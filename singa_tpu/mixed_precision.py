"""Precision policy: bf16 compute against fp32 master parameters.

On TPU the MXU's native matmul precision is bf16 — feeding it bf16
operands roughly doubles dense throughput and halves the HBM traffic of
activations and gradient communication. What must NOT be bf16 is the
canonical training state: parameters drift by updates ~1e-4 of their
magnitude, below bf16's 8 mantissa bits, so masters stay fp32 and only
the *compute* is cast down.

A :class:`Policy` names the three dtypes of that contract:

- ``param_dtype`` — what parameters are created and updated in (the
  masters; what every checkpoint route saves);
- ``compute_dtype`` — what matmul/conv/attention operands are cast to
  inside the traced step;
- ``output_dtype`` — what floating output leaves of the compiled step
  are cast back to at the step boundary.

The policy is threaded through ``Model.compile(policy=...)``: the model
enters :func:`policy_scope` inside its jitted train/eval builders, so
every cast is part of ONE fused XLA program (params are cast at their
use sites; XLA dedups the converts and the backward casts gradients back
up through the same boundary — the optimizer always sees fp32 gradients
against fp32 masters). Numerically fragile ops opt out by construction:
BatchNorm/LayerNorm statistics, softmax/logsumexp accumulations and loss
reductions run in fp32 regardless of policy (see ops/batchnorm.py,
autograd losses, ops/losses.py), and :func:`fp32_accumulate` is the
escape hatch for user code that needs a full-precision region inside a
policy scope.

No reference counterpart (the reference's closest knob is fp16 wire
format in Communicator::fusedSynchHalf); the design follows the standard
mixed-precision recipe the TPU literature attributes most of the bf16
cost advantage to.
"""

from __future__ import annotations

import contextlib
from contextvars import ContextVar

import jax.numpy as jnp

__all__ = ["Policy", "QuantPolicy", "resolve", "active_policy",
           "policy_scope",
           "fp32_accumulate", "cast_compute", "compute_dtype",
           "param_dtype", "accum_f32"]

# canonical named policies; aliases normalise below
_NAMED = {
    "float32": ("float32", "float32", "float32"),
    "bf16_mixed": ("float32", "bfloat16", "float32"),
    "float16_mixed": ("float32", "float16", "float32"),
    "bfloat16": ("bfloat16", "bfloat16", "bfloat16"),
}
# quantized presets (singa_tpu.quant): base float dtypes + the quant
# axes layered on top. Fields: (param, compute, output, weight_quant,
# compute_quant, grad_quant, cache_quant, quantize_checkpoints,
# loss_scaling_default). Resolved to QuantPolicy by resolve().
_QUANT_NAMED = {
    # weight-only int8 inference/serving: int8 payloads + per-channel
    # scales, dequantized in graph at the matmul/conv boundary; ring KV
    # cache in int8; checkpoints persist the int8 bytes (~4x smaller)
    "int8_weight_only": ("float32", "float32", "float32",
                         "int8", None, None, "int8", True, False),
    # fp8 serving: e4m3 weight emulation over bf16 compute, int8 cache
    "fp8_serving": ("float32", "bfloat16", "float32",
                    None, "e4m3", None, "int8", False, None),
    # fp8 training: e4m3 fake-quant compute (STE), e5m2 gradient
    # emulation through the GuardedOptimizer driver, dynamic loss
    # scaling on (bf16 compute underneath)
    "fp8_mixed": ("float32", "bfloat16", "float32",
                  None, "e4m3", "e5m2", None, False, None),
    # int8 QAT: fp32 masters/compute with int8 fake-quant at every op
    # boundary; loss scaling stays armed so the guard rides along
    "int8_qat": ("float32", "float32", "float32",
                 None, "int8", None, None, False, True),
}
_ALIASES = {"fp32": "float32", "f32": "float32",
            "bf16": "bfloat16", "mixed_bf16": "bf16_mixed",
            "fp16_mixed": "float16_mixed", "f16_mixed": "float16_mixed",
            "int8": "int8_weight_only", "fp8": "fp8_mixed"}

_LOW_BITS = (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16))


def _dt(x):
    return None if x is None else jnp.dtype(x)


class Policy:
    """One precision contract for a compiled model (see module doc).

    ``Policy("bf16_mixed")`` is the TPU production setting: fp32
    masters, bf16 compute, fp32 outputs. Explicit dtype kwargs override
    the named preset; ``loss_scaling`` overrides whether
    ``Model.compile`` pairs the policy with a dynamic-loss-scaling
    :class:`~singa_tpu.resilience.GuardedOptimizer` by default (on for
    every 16-bit compute dtype, off for float32).
    """

    def __init__(self, name="bf16_mixed", *, param_dtype=None,
                 compute_dtype=None, output_dtype=None, loss_scaling=None):
        key = _ALIASES.get(str(name).lower(), str(name).lower())
        if key in _QUANT_NAMED and type(self) is Policy:
            raise ValueError(
                f"{name!r} is a quantized preset: construct it via "
                f"QuantPolicy({name!r}) or mixed_precision.resolve")
        if key not in _NAMED and key not in _QUANT_NAMED:
            raise ValueError(
                f"unknown precision policy {name!r}; expected one of "
                f"{sorted(_NAMED) + sorted(_QUANT_NAMED)} (or aliases "
                f"{sorted(_ALIASES)})")
        self.name = key
        p, c, o = _NAMED[key] if key in _NAMED else _QUANT_NAMED[key][:3]
        self.param_dtype = _dt(param_dtype if param_dtype is not None
                               else p)
        self.compute_dtype = _dt(compute_dtype if compute_dtype is not None
                                 else c)
        self.output_dtype = _dt(output_dtype if output_dtype is not None
                                else o)
        self._loss_scaling = loss_scaling

    # -- derived contract --------------------------------------------------
    @property
    def is_mixed(self):
        """True when compute happens below the masters' precision."""
        return self.compute_dtype != self.param_dtype

    @property
    def comm_dtype(self):
        """Wire dtype for gradient collectives under this policy (None =
        reduce in the gradients' own dtype). A 16-bit compute dtype
        makes the comm 16-bit too: the psum'd values were just computed
        at that precision, so the wire loses nothing extra while the
        all-reduce moves half the bytes."""
        return self.compute_dtype if self.compute_dtype in _LOW_BITS \
            else None

    @property
    def wants_loss_scaling(self):
        if self._loss_scaling is not None:
            return bool(self._loss_scaling)
        return self.compute_dtype in _LOW_BITS

    @property
    def default_loss_scale(self):
        """Initial dynamic-loss-scale: fp16's narrow exponent needs the
        classic 2^15 underflow shield; bf16 shares fp32's exponent range
        so scaling starts neutral and only moves if the guard's dynamic
        backoff/growth finds a reason."""
        return 2.0 ** 15 if self.compute_dtype == jnp.dtype(jnp.float16) \
            else 1.0

    def describe(self):
        return {"name": self.name,
                "param_dtype": str(self.param_dtype),
                "compute_dtype": str(self.compute_dtype),
                "output_dtype": str(self.output_dtype)}

    def __repr__(self):
        return (f"Policy({self.name!r}: params={self.param_dtype}, "
                f"compute={self.compute_dtype}, out={self.output_dtype})")

    def __eq__(self, other):
        # loss scaling is part of the contract: a recompile that only
        # flips the opt-out must still register as a policy change
        return isinstance(other, Policy) and \
            self.describe() == other.describe() and \
            self.wants_loss_scaling == other.wants_loss_scaling

    def __hash__(self):
        return hash(tuple(sorted(self.describe().items()))
                    + (self.wants_loss_scaling,))

    # -- casts -------------------------------------------------------------
    def cast_output(self, x):
        """Step-boundary cast of one output leaf (floats only: integer
        outputs — predictions, counts — are never touched)."""
        if self.output_dtype is None or not hasattr(x, "dtype"):
            return x
        if jnp.issubdtype(x.dtype, jnp.floating) and \
                x.dtype != self.output_dtype:
            return x.astype(self.output_dtype)
        return x


class QuantPolicy(Policy):
    """A precision policy with quantized numerics layered on top
    (the ``singa_tpu.quant`` subsystem's compile-time contract).

    Named presets (see ``_QUANT_NAMED``):

    - ``"int8_weight_only"`` — inference/serving: weights are int8
      payloads + per-channel fp32 scales, dequantized in graph at
      their use sites; the serving ring KV cache runs int8; checkpoint
      routes persist the int8 bytes (~4x smaller);
    - ``"fp8_serving"`` — e4m3 weight emulation over bf16 compute with
      an int8 KV cache;
    - ``"fp8_mixed"`` — fp8 training: e4m3 fake-quant compute (STE)
      inside the compiled step, e5m2 gradient emulation through the
      ``GuardedOptimizer`` driver, dynamic loss scaling on;
    - ``"int8_qat"`` — int8 quantization-aware training over fp32
      masters (fake-quant at every op boundary, guard armed).

    ``scales`` (usually via :meth:`with_scales` /
    ``quant.Calibrator.freeze``) freezes per-op-position activation
    scales into the policy: the traced program bakes them in as
    constants instead of deriving a scale from each batch's amax.
    """

    def __init__(self, name="int8_weight_only", *, scales=None,
                 loss_scaling=None, **kw):
        key = _ALIASES.get(str(name).lower(), str(name).lower())
        if key not in _QUANT_NAMED:
            raise ValueError(
                f"unknown quantized policy {name!r}; expected one of "
                f"{sorted(_QUANT_NAMED)} (plain presets go through "
                "Policy/resolve)")
        (_p, _c, _o, self.weight_quant, self.compute_quant,
         self.grad_quant, self.cache_quant, self.quantize_checkpoints,
         ls_default) = _QUANT_NAMED[key]
        if loss_scaling is None:
            loss_scaling = ls_default
        super().__init__(key, loss_scaling=loss_scaling, **kw)
        self.scales = dict(scales) if scales else None

    def describe(self):
        d = super().describe()
        d.update({"weight_quant": self.weight_quant,
                  "compute_quant": self.compute_quant,
                  "grad_quant": self.grad_quant,
                  "cache_quant": self.cache_quant})
        if self.scales:
            # the frozen scales ARE numerics: two policies with
            # different calibrations must not compare (or hash) equal,
            # so a content digest of the scale table rides describe()
            import zlib
            blob = ",".join(f"{k}={self.scales[k]!r}"
                            for k in sorted(self.scales))
            d["calibrated_ops"] = len(self.scales)
            d["scales_crc"] = f"{zlib.crc32(blob.encode()):08x}"
        return d

    def with_scales(self, scales):
        """A copy of this policy with calibration scales frozen in."""
        return type(self)(self.name, scales=scales,
                          loss_scaling=self._loss_scaling,
                          param_dtype=self.param_dtype,
                          compute_dtype=self.compute_dtype,
                          output_dtype=self.output_dtype)

    def apply_compute_quant(self, a, pos):
        """Fake-quantize one compute operand (op position ``pos`` in
        the forward's trace order — how frozen calibration scales find
        their operand). Called by :func:`cast_compute` inside the
        traced step; STE keeps backward an identity."""
        kind = self.compute_quant
        if kind is None:
            return a
        from .quant import core as _qcore   # lazy: quant imports us
        scale = self.scales.get(f"act{pos}") if self.scales else None
        if kind == "int8":
            return _qcore.fake_quant_int8(a, scale=scale)
        return _qcore.fake_quant_fp8(a, kind, scale)


def resolve(policy):
    """str | dict | Policy | None -> Policy | None. Strings resolve
    named presets (quantized ones to :class:`QuantPolicy`); a dict is
    a ``describe()`` document — the ``meta/precision_policy`` stamp a
    checkpoint carries — whose name AND per-dtype overrides both
    round-trip (a ``Policy("bf16_mixed", compute_dtype="float32")``
    stamp must not come back as stock bf16_mixed). Frozen calibration
    scales are NOT in the stamp (only their CRC): resolving a
    calibrated stamp warns that the policy needs re-calibrating."""
    if policy is None or isinstance(policy, Policy):
        return policy
    kw = {}
    if isinstance(policy, dict):
        doc = policy
        policy = doc.get("name")
        kw = {f: doc[f] for f in ("param_dtype", "compute_dtype",
                                  "output_dtype") if doc.get(f)}
        if doc.get("calibrated_ops") or doc.get("scales_crc"):
            import warnings
            warnings.warn(
                f"precision-policy stamp {policy!r} records "
                f"{doc.get('calibrated_ops')} calibrated scales (crc "
                f"{doc.get('scales_crc')}) but the scales themselves "
                "are not stored in the stamp: the resolved policy "
                "falls back to dynamic per-batch scales — re-run "
                "quant.Calibrator to restore frozen numerics",
                stacklevel=2)
    key = _ALIASES.get(str(policy).lower(), str(policy).lower())
    if key in _QUANT_NAMED:
        return QuantPolicy(key, **kw)
    return Policy(policy, **kw)


# Per-context scope stack (same pattern as ops/layout.py): a ContextVar
# so a policy entered while one model's step traces can never leak into
# another thread's trace; ``None`` entries are fp32_accumulate escapes.
_stack: ContextVar[tuple] = ContextVar("singa_tpu_precision_policy",
                                       default=())

# quantization hooks riding the cast_compute chokepoint:
# - _observer: a `(tag, array)` callback the quant.Calibrator installs
#   to record activation ranges during an eager calibration pass;
# - _qpos: the per-scope op-position counter ([next_index]) that tags
#   operands in trace order (`act0, act1, ...`) — reset at every
#   policy-scope entry so the eager calibration pass and the traced
#   step number the same operands identically.
_observer: ContextVar = ContextVar("singa_tpu_quant_observer",
                                   default=None)
_qpos: ContextVar = ContextVar("singa_tpu_quant_pos", default=None)


def active_policy():
    """The innermost active Policy, or None (no policy / inside an
    :func:`fp32_accumulate` escape)."""
    s = _stack.get()
    return s[-1] if s else None


@contextlib.contextmanager
def policy_scope(policy):
    """Activate a policy for ops traced within (model step builders
    enter this inside their jit bodies, so the casts land in the one
    fused program). ``None`` is a no-op scope."""
    if policy is None:
        yield
        return
    token = _stack.set(_stack.get() + (resolve(policy),))
    # fresh op-position counter per scope entry: one forward/step body
    # numbers its compute operands 0..N in trace order (calibration
    # tags and frozen quant scales key off these positions)
    qtok = _qpos.set([0])
    try:
        yield
    finally:
        _qpos.reset(qtok)
        _stack.reset(token)


@contextlib.contextmanager
def fp32_accumulate():
    """Escape hatch: suspend compute-dtype casting for ops built inside
    — the fp32-accumulate region for numerically fragile user code
    (custom reductions, cumulative sums, metric math) under a 16-bit
    policy. Params created inside still honor the *outer* policy's
    param story only if created via an explicit dtype; compute casts are
    simply off."""
    token = _stack.set(_stack.get() + (None,))
    try:
        yield
    finally:
        _stack.reset(token)


def compute_dtype():
    """Active compute dtype, or None when no policy applies."""
    p = active_policy()
    return p.compute_dtype if p is not None else None


def cast_compute(*arrays):
    """Cast floating operands to the active policy's compute dtype (the
    per-op discipline matmul/conv/attention/bias ops apply to their
    inputs). Integers, bools and ``None`` pass through; with no active
    policy this is the identity. Returns a single value for a single
    argument.

    This is also the quantization chokepoint: each floating operand is
    (a) reported to an active calibration observer and (b) fake-
    quantized when the active policy is a :class:`QuantPolicy` with a
    ``compute_quant`` kind — both keyed by the operand's position in
    the scope's trace order, so calibration-frozen scales land on
    exactly the operands they were measured from."""
    stack = _stack.get()
    if stack and stack[-1] is None:
        # inside fp32_accumulate: no casts, AND no position counting /
        # observation — the escape region must be invisible to the
        # quantization op-order in BOTH the eager calibration pass and
        # the policied run, or every later act{i} tag would shift and
        # frozen scales would land on the wrong operands
        return arrays[0] if len(arrays) == 1 else arrays
    p = stack[-1] if stack else None
    obs = _observer.get()
    if (p is None or p.compute_dtype is None) and obs is None:
        return arrays[0] if len(arrays) == 1 else arrays
    ct = p.compute_dtype if p is not None else None
    fq = p if getattr(p, "compute_quant", None) else None
    pos = _qpos.get() if (obs is not None or fq is not None) else None
    out = []
    for a in arrays:
        if a is not None and hasattr(a, "dtype") and \
                jnp.issubdtype(a.dtype, jnp.floating):
            if ct is not None and a.dtype != ct:
                a = a.astype(ct)
            if pos is not None:
                i = pos[0]
                pos[0] += 1
                if obs is not None:
                    obs(f"act{i}", a)
                if fq is not None:
                    a = fq.apply_compute_quant(a, i)
        out.append(a)
    return out[0] if len(out) == 1 else tuple(out)


def accum_f32(x):
    """Accumulate in f32: upcast a 16-bit float before a numerically
    fragile reduction (softmax/logsumexp, loss means, norm statistics).
    The cast fuses into the reduction under XLA, so the fp32 discipline
    is free; f32 inputs pass through untouched. The op-level sibling of
    the :func:`fp32_accumulate` scope."""
    return x.astype(jnp.float32) if x.dtype in _LOW_BITS else x


def param_dtype(dtype=None):
    """Dtype a NEW trainable parameter should be created in: the active
    policy's master dtype for floating params (deferred layer inits pass
    the input's dtype here — under a policy the masters must not follow
    a 16-bit activation), the requested dtype otherwise."""
    p = active_policy()
    if p is None or p.param_dtype is None:
        return dtype
    if dtype is not None and not jnp.issubdtype(jnp.dtype(dtype),
                                                jnp.floating):
        return dtype
    return p.param_dtype
