"""Precision policy: bf16 compute against fp32 master parameters.

On TPU the MXU's native matmul precision is bf16 — feeding it bf16
operands roughly doubles dense throughput and halves the HBM traffic of
activations and gradient communication. What must NOT be bf16 is the
canonical training state: parameters drift by updates ~1e-4 of their
magnitude, below bf16's 8 mantissa bits, so masters stay fp32 and only
the *compute* is cast down.

A :class:`Policy` names the three dtypes of that contract:

- ``param_dtype`` — what parameters are created and updated in (the
  masters; what every checkpoint route saves);
- ``compute_dtype`` — what matmul/conv/attention operands are cast to
  inside the traced step;
- ``output_dtype`` — what floating output leaves of the compiled step
  are cast back to at the step boundary.

The policy is threaded through ``Model.compile(policy=...)``: the model
enters :func:`policy_scope` inside its jitted train/eval builders, so
every cast is part of ONE fused XLA program (params are cast at their
use sites; XLA dedups the converts and the backward casts gradients back
up through the same boundary — the optimizer always sees fp32 gradients
against fp32 masters). Numerically fragile ops opt out by construction:
BatchNorm/LayerNorm statistics, softmax/logsumexp accumulations and loss
reductions run in fp32 regardless of policy (see ops/batchnorm.py,
autograd losses, ops/losses.py), and :func:`fp32_accumulate` is the
escape hatch for user code that needs a full-precision region inside a
policy scope.

No reference counterpart (the reference's closest knob is fp16 wire
format in Communicator::fusedSynchHalf); the design follows the standard
mixed-precision recipe the TPU literature attributes most of the bf16
cost advantage to.
"""

from __future__ import annotations

import contextlib
from contextvars import ContextVar

import jax.numpy as jnp

__all__ = ["Policy", "resolve", "active_policy", "policy_scope",
           "fp32_accumulate", "cast_compute", "compute_dtype",
           "param_dtype", "accum_f32"]

# canonical named policies; aliases normalise below
_NAMED = {
    "float32": ("float32", "float32", "float32"),
    "bf16_mixed": ("float32", "bfloat16", "float32"),
    "float16_mixed": ("float32", "float16", "float32"),
    "bfloat16": ("bfloat16", "bfloat16", "bfloat16"),
}
_ALIASES = {"fp32": "float32", "f32": "float32",
            "bf16": "bfloat16", "mixed_bf16": "bf16_mixed",
            "fp16_mixed": "float16_mixed", "f16_mixed": "float16_mixed"}

_LOW_BITS = (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16))


def _dt(x):
    return None if x is None else jnp.dtype(x)


class Policy:
    """One precision contract for a compiled model (see module doc).

    ``Policy("bf16_mixed")`` is the TPU production setting: fp32
    masters, bf16 compute, fp32 outputs. Explicit dtype kwargs override
    the named preset; ``loss_scaling`` overrides whether
    ``Model.compile`` pairs the policy with a dynamic-loss-scaling
    :class:`~singa_tpu.resilience.GuardedOptimizer` by default (on for
    every 16-bit compute dtype, off for float32).
    """

    def __init__(self, name="bf16_mixed", *, param_dtype=None,
                 compute_dtype=None, output_dtype=None, loss_scaling=None):
        key = _ALIASES.get(str(name).lower(), str(name).lower())
        if key not in _NAMED:
            raise ValueError(
                f"unknown precision policy {name!r}; expected one of "
                f"{sorted(_NAMED)} (or aliases {sorted(_ALIASES)})")
        self.name = key
        p, c, o = _NAMED[key]
        self.param_dtype = _dt(param_dtype if param_dtype is not None
                               else p)
        self.compute_dtype = _dt(compute_dtype if compute_dtype is not None
                                 else c)
        self.output_dtype = _dt(output_dtype if output_dtype is not None
                                else o)
        self._loss_scaling = loss_scaling

    # -- derived contract --------------------------------------------------
    @property
    def is_mixed(self):
        """True when compute happens below the masters' precision."""
        return self.compute_dtype != self.param_dtype

    @property
    def comm_dtype(self):
        """Wire dtype for gradient collectives under this policy (None =
        reduce in the gradients' own dtype). A 16-bit compute dtype
        makes the comm 16-bit too: the psum'd values were just computed
        at that precision, so the wire loses nothing extra while the
        all-reduce moves half the bytes."""
        return self.compute_dtype if self.compute_dtype in _LOW_BITS \
            else None

    @property
    def wants_loss_scaling(self):
        if self._loss_scaling is not None:
            return bool(self._loss_scaling)
        return self.compute_dtype in _LOW_BITS

    @property
    def default_loss_scale(self):
        """Initial dynamic-loss-scale: fp16's narrow exponent needs the
        classic 2^15 underflow shield; bf16 shares fp32's exponent range
        so scaling starts neutral and only moves if the guard's dynamic
        backoff/growth finds a reason."""
        return 2.0 ** 15 if self.compute_dtype == jnp.dtype(jnp.float16) \
            else 1.0

    def describe(self):
        return {"name": self.name,
                "param_dtype": str(self.param_dtype),
                "compute_dtype": str(self.compute_dtype),
                "output_dtype": str(self.output_dtype)}

    def __repr__(self):
        return (f"Policy({self.name!r}: params={self.param_dtype}, "
                f"compute={self.compute_dtype}, out={self.output_dtype})")

    def __eq__(self, other):
        # loss scaling is part of the contract: a recompile that only
        # flips the opt-out must still register as a policy change
        return isinstance(other, Policy) and \
            self.describe() == other.describe() and \
            self.wants_loss_scaling == other.wants_loss_scaling

    def __hash__(self):
        return hash(tuple(sorted(self.describe().items()))
                    + (self.wants_loss_scaling,))

    # -- casts -------------------------------------------------------------
    def cast_output(self, x):
        """Step-boundary cast of one output leaf (floats only: integer
        outputs — predictions, counts — are never touched)."""
        if self.output_dtype is None or not hasattr(x, "dtype"):
            return x
        if jnp.issubdtype(x.dtype, jnp.floating) and \
                x.dtype != self.output_dtype:
            return x.astype(self.output_dtype)
        return x


def resolve(policy):
    """str | Policy | None -> Policy | None."""
    if policy is None or isinstance(policy, Policy):
        return policy
    return Policy(policy)


# Per-context scope stack (same pattern as ops/layout.py): a ContextVar
# so a policy entered while one model's step traces can never leak into
# another thread's trace; ``None`` entries are fp32_accumulate escapes.
_stack: ContextVar[tuple] = ContextVar("singa_tpu_precision_policy",
                                       default=())


def active_policy():
    """The innermost active Policy, or None (no policy / inside an
    :func:`fp32_accumulate` escape)."""
    s = _stack.get()
    return s[-1] if s else None


@contextlib.contextmanager
def policy_scope(policy):
    """Activate a policy for ops traced within (model step builders
    enter this inside their jit bodies, so the casts land in the one
    fused program). ``None`` is a no-op scope."""
    if policy is None:
        yield
        return
    token = _stack.set(_stack.get() + (resolve(policy),))
    try:
        yield
    finally:
        _stack.reset(token)


@contextlib.contextmanager
def fp32_accumulate():
    """Escape hatch: suspend compute-dtype casting for ops built inside
    — the fp32-accumulate region for numerically fragile user code
    (custom reductions, cumulative sums, metric math) under a 16-bit
    policy. Params created inside still honor the *outer* policy's
    param story only if created via an explicit dtype; compute casts are
    simply off."""
    token = _stack.set(_stack.get() + (None,))
    try:
        yield
    finally:
        _stack.reset(token)


def compute_dtype():
    """Active compute dtype, or None when no policy applies."""
    p = active_policy()
    return p.compute_dtype if p is not None else None


def cast_compute(*arrays):
    """Cast floating operands to the active policy's compute dtype (the
    per-op discipline matmul/conv/attention/bias ops apply to their
    inputs). Integers, bools and ``None`` pass through; with no active
    policy this is the identity. Returns a single value for a single
    argument."""
    p = active_policy()
    if p is None or p.compute_dtype is None:
        return arrays[0] if len(arrays) == 1 else arrays
    ct = p.compute_dtype
    out = []
    for a in arrays:
        if a is not None and hasattr(a, "dtype") and \
                jnp.issubdtype(a.dtype, jnp.floating) and a.dtype != ct:
            a = a.astype(ct)
        out.append(a)
    return out[0] if len(out) == 1 else tuple(out)


def accum_f32(x):
    """Accumulate in f32: upcast a 16-bit float before a numerically
    fragile reduction (softmax/logsumexp, loss means, norm statistics).
    The cast fuses into the reduction under XLA, so the fp32 discipline
    is free; f32 inputs pass through untouched. The op-level sibling of
    the :func:`fp32_accumulate` scope."""
    return x.astype(jnp.float32) if x.dtype in _LOW_BITS else x


def param_dtype(dtype=None):
    """Dtype a NEW trainable parameter should be created in: the active
    policy's master dtype for floating params (deferred layer inits pass
    the input's dtype here — under a policy the masters must not follow
    a 16-bit activation), the requested dtype otherwise."""
    p = active_policy()
    if p is None or p.param_dtype is None:
        return dtype
    if dtype is not None and not jnp.issubdtype(jnp.dtype(dtype),
                                                jnp.floating):
        return dtype
    return p.param_dtype
