"""VGG family (the capability behind reference examples/onnx/vgg16.py /
vgg19.py, built natively on the TPU-native layer API rather than imported
from an ONNX zoo file).

Standard VGG-A/B/D/E configurations with optional batch norm. All convs are
3x3 stride 1 — each lowers to one MXU matmul after im2col by XLA; with
graph (jit) mode the whole stack fuses into a single compiled step.
"""

from .. import layer, model
from . import TrainStepMixin

CFGS = {
    11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
         512, 512, "M"],
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
         "M", 512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
         512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(model.Model, TrainStepMixin):

    def __init__(self, depth=16, num_classes=10, num_channels=3,
                 batch_norm=False):
        super().__init__()
        self.num_classes = num_classes
        self.input_size = 224
        self.dimension = 4
        feats = []
        for v in CFGS[depth]:
            if v == "M":
                feats.append(layer.MaxPool2d(2, 2))
            else:
                feats.append(layer.Conv2d(v, 3, padding=1,
                                          bias=not batch_norm))
                if batch_norm:
                    feats.append(layer.BatchNorm2d())
                feats.append(layer.ReLU())
        self.features = feats
        self.flatten = layer.Flatten()
        self.fc1 = layer.Linear(4096)
        self.relu1 = layer.ReLU()
        self.drop1 = layer.Dropout(0.5)
        self.fc2 = layer.Linear(4096)
        self.relu2 = layer.ReLU()
        self.drop2 = layer.Dropout(0.5)
        self.fc3 = layer.Linear(num_classes)
        self.softmax_cross_entropy = layer.SoftMaxCrossEntropy()

    def forward(self, x):
        for f in self.features:
            x = f(x)
        x = self.flatten(x)
        x = self.drop1(self.relu1(self.fc1(x)))
        x = self.drop2(self.relu2(self.fc2(x)))
        return self.fc3(x)

    def train_one_batch(self, x, y, dist_option="plain", spars=None,
                    rotation=None):
        out = self.forward(x)
        loss = self.softmax_cross_entropy(out, y)
        self._apply_optimizer(loss, dist_option, spars, rotation)
        return out, loss


def create_model(pretrained=False, depth=16, batch_norm=False, **kwargs):
    return VGG(depth=depth, batch_norm=batch_norm, **kwargs)


__all__ = ["VGG", "create_model"]
