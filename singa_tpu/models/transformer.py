"""Decoder-only Transformer LM — the flagship long-context model.

TPU-first design (no reference equivalent; the reference's only attention
is composed from primitive ops in examples/qabot): pre-norm GPT-style
blocks whose attention is the fused flash kernel (ops/attention.py), with
three composable parallelism modes driven by the mesh:

- data parallel: batch over 'data' (DistOpt psum, like every model here);
- tensor parallel (``tp=True``): qkv and MLP-up as ColumnParallelLinear,
  out-proj and MLP-down as RowParallelLinear — heads shard over 'model',
  two all-reduces per block (Megatron layout); the vocab ends shard too:
  token embedding rows (VocabParallelEmbedding) and LM-head columns
  (ColumnParallelLinear), and with ``fused_head_chunk`` the chunked CE
  loss reduces across vocab shards online so per-rank head memory is
  V/tp without ever materialising logits;
- sequence parallel (``seq_axis='seq'``): tokens shard over 'seq'; the
  attention switches to ring attention (k/v rotate over ICI) and the
  caller sets ``Model.input_specs = [P('data', 'seq'), ...]``.
"""

from __future__ import annotations

import math

import numpy as np

from .. import autograd, layer, model
from ..parallel import tensor_parallel as tp_mod
from ..ops.attention import attention
from ..tensor import Tensor


class _Positions(autograd.Operator):
    """Global position ids for a (possibly sequence-sharded) token block."""

    differentiable = False

    def __init__(self, seq_axis=None):
        super().__init__()
        self.seq_axis = seq_axis

    def forward(self, ids):
        import jax.numpy as jnp
        from jax import lax
        from ..parallel.communicator import active_axis
        S = ids.shape[1]
        pos = jnp.arange(S)
        if self.seq_axis and active_axis(self.seq_axis):
            pos = pos + lax.axis_index(self.seq_axis) * S
        return jnp.broadcast_to(pos[None, :], ids.shape).astype(jnp.float32)


class MultiHeadAttention(layer.Layer):
    """Fused-attention MHA; optionally tensor-parallel over heads and/or
    sequence-parallel (ring) over tokens."""

    def __init__(self, d_model, n_heads, causal=True, tp=True,
                 seq_axis=None, axis_name="model", seq_mode="ring"):
        """``tp`` is accepted for API compatibility but the layout is
        mesh-driven: the parallel layers degrade to plain Linear on a
        size-1 'model' axis (or outside any mesh), so there is exactly one
        code path — and one state-dict layout — for every topology."""
        super().__init__()
        assert d_model % n_heads == 0
        self.d_model = d_model
        self.n_heads = n_heads
        self.head_dim = d_model // n_heads
        self.causal = causal
        self.seq_axis = seq_axis
        self.seq_mode = seq_mode
        # three separate column-parallel projections: a fused qkv matrix
        # would shard its columns across the [q|k|v] boundary
        self.q_proj = tp_mod.ColumnParallelLinear(d_model,
                                                  axis_name=axis_name)
        self.k_proj = tp_mod.ColumnParallelLinear(d_model,
                                                  axis_name=axis_name)
        self.v_proj = tp_mod.ColumnParallelLinear(d_model,
                                                  axis_name=axis_name)
        self.proj = tp_mod.RowParallelLinear(d_model, axis_name=axis_name)

    def forward(self, x):
        B, S = x.shape[0], x.shape[1]
        q = self.q_proj(x)                      # (B, S, d_local)
        k = self.k_proj(x)
        v = self.v_proj(x)
        d_local = q.shape[-1]
        h_local = d_local // self.head_dim      # heads on this shard

        def split_heads(t):
            t = autograd.reshape(t, (B, S, h_local, self.head_dim))
            return autograd.transpose(t, (0, 2, 1, 3))  # (B, H, S, D)

        out = attention(split_heads(q), split_heads(k), split_heads(v),
                        causal=self.causal, seq_axis=self.seq_axis,
                        seq_mode=self.seq_mode)
        out = autograd.transpose(out, (0, 2, 1, 3))
        out = autograd.reshape(out, (B, S, d_local))
        return self.proj(out)


class TransformerBlock(layer.Layer):
    def __init__(self, d_model, n_heads, d_ff=None, causal=True, tp=True,
                 seq_axis=None, moe=None, moe_top_k=None,
                 moe_capacity_factor=1.25, seq_mode="ring"):
        """``moe``: number of experts; replaces the dense FFN with a
        :class:`~singa_tpu.parallel.moe.MoEFFN` sharded over the mesh
        'expert' axis (``self.mlp.aux_loss`` is valid only inside the
        same train_one_batch trace). ``moe_top_k`` defaults to 2 clamped
        to the expert count (so moe=1 means Switch-style top-1)."""
        super().__init__()
        d_ff = d_ff or 4 * d_model
        self.ln1 = layer.LayerNorm()
        self.attn = MultiHeadAttention(d_model, n_heads, causal, tp,
                                       seq_axis, seq_mode=seq_mode)
        self.ln2 = layer.LayerNorm()
        if moe:
            from ..parallel.moe import MoEFFN
            top_k = moe_top_k if moe_top_k is not None else min(2, moe)
            self.mlp = MoEFFN(moe, d_ff, top_k=top_k,
                              capacity_factor=moe_capacity_factor)
        else:
            self.mlp = tp_mod.TPMLP(d_ff, d_model, activation="gelu")

    def forward(self, x):
        x = autograd.add(x, self.attn(self.ln1(x)))
        return autograd.add(x, self.mlp(self.ln2(x)))


class TransformerLM(model.Model):
    """GPT-style language model with next-token loss.

    ``train_one_batch(ids, targets)`` takes float tensors of token ids and
    target ids, both (B, S) ((B, S/n) per shard under sequence parallel).
    """

    def __init__(self, vocab_size, d_model=128, n_heads=4, n_layers=2,
                 max_len=1024, causal=True, tp=True, seq_axis=None,
                 remat=False, moe=None, moe_aux_weight=0.01,
                 moe_top_k=None, moe_capacity_factor=1.25,
                 seq_mode="ring", fused_head_chunk=None,
                 compute_dtype=None):
        """``moe``: experts per block (MoE FFN over the 'expert' mesh
        axis); the blocks' load-balance aux losses join the training loss
        scaled by ``moe_aux_weight``. ``moe_top_k`` defaults to
        min(2, moe).

        ``compute_dtype`` (e.g. ``jnp.bfloat16``): cast the summed
        embeddings to this dtype, so every downstream layer initialises
        its params in it and the whole transformer stack (attention
        matmuls included) runs in the MXU's native precision — the LM
        counterpart of feeding a bf16 input to the CNN zoo. Embedding
        tables stay f32 (the gather is bandwidth-, not MXU-bound), norm
        stats compute in f32 as always, and both loss paths upcast to
        f32 before the softmax."""
        super().__init__()
        self.compute_dtype = compute_dtype
        self.vocab_size = vocab_size
        self.d_model = d_model
        # remat: rematerialize each block in backward (jax.checkpoint) —
        # activation memory O(n_layers * block-boundary) instead of
        # O(n_layers * everything), the standard long-context trade
        self.remat = remat
        self.moe = moe
        self.moe_aux_weight = moe_aux_weight
        self.fused_head_chunk = fused_head_chunk
        # vocab-parallel ends: token embedding rows and head columns
        # shard over 'model' (Megatron layout) — at real vocab sizes the
        # head is the single largest tensor, so it must not replicate.
        # Both degrade to plain layers outside a mesh with the SAME
        # full-shape state dict, so there is one layout everywhere.
        # pos_emb stays replicated: max_len·D is small and every rank
        # reads every row.
        self.tok_emb = tp_mod.VocabParallelEmbedding(vocab_size, d_model)
        self.pos_emb = layer.Embedding(max_len, d_model)
        self._pos = _Positions(seq_axis)
        self.blocks = [TransformerBlock(
            d_model, n_heads, causal=causal, tp=tp, seq_axis=seq_axis,
            moe=moe, moe_top_k=moe_top_k,
            moe_capacity_factor=moe_capacity_factor, seq_mode=seq_mode)
            for i in range(n_layers)]
        self.ln_f = layer.LayerNorm()
        self.head = tp_mod.ColumnParallelLinear(vocab_size,
                                                gather_output=True)
        self.loss_fn = layer.SoftMaxCrossEntropy()

    def _hidden(self, ids):
        pos = self._pos(ids)
        x = autograd.add(self.tok_emb(ids), self.pos_emb(pos))
        if self.compute_dtype is not None:
            x = autograd.astype(x, self.compute_dtype)
        for blk in self.blocks:
            x = autograd.checkpoint(blk, x) if self.remat else blk(x)
        return self.ln_f(x)

    def forward(self, ids):
        return self.head(self._hidden(ids))     # (B, S, vocab)

    def train_one_batch(self, ids, targets):
        if self.fused_head_chunk:
            # large-vocab mode: loss straight from the hidden states via
            # the chunked fused CE head — the (B,S,V) logits are never
            # materialised in the TRAINING step (forward/eval still
            # produces them through the same shared head params).
            from ..ops.losses import fused_softmax_cross_entropy
            h = self._hidden(ids)
            # params only, no forward: running head(h) here would
            # materialise the full (B,S,V) logits the fused mode exists
            # to avoid
            self.head.ensure_initialized(h)
            # the layer's own sharded-check decides whether to turn on
            # the cross-shard reduction (one source of truth)
            ax = self.head.axis_name if self.head._sharded() else None
            loss = fused_softmax_cross_entropy(
                h, self.head.W, self.head.b, targets,
                self.fused_head_chunk, axis_name=ax)
            out = None
        else:
            logits = self.forward(ids)
            if self.compute_dtype is not None:
                # softmax over a 32k vocab needs f32 range
                logits = autograd.astype(logits, np.float32)
            B, S, V = logits.shape
            flat = autograd.reshape(logits, (B * S, V))
            onehot = autograd.onehot(-1, targets, self.vocab_size)
            oh_flat = autograd.reshape(onehot, (B * S, V))
            loss = autograd.softmax_cross_entropy(flat, oh_flat)
            out = logits
        if self.moe:
            w = Tensor(data=np.asarray(self.moe_aux_weight, np.float32),
                       device=ids.device, requires_grad=False)
            for blk in self.blocks:
                loss = autograd.add(loss, autograd.mul(blk.mlp.aux_loss, w))
        self.optimizer(loss)
        # fused mode has no logits to return: the TOTAL loss (incl. moe
        # aux) fills the predictions slot so both outputs agree with
        # what the optimizer stepped on
        if out is None:
            out = loss
        return out, loss


def create_model(vocab_size=256, **kwargs):
    return TransformerLM(vocab_size, **kwargs)


__all__ = ["TransformerLM", "TransformerBlock", "MultiHeadAttention",
           "create_model"]


def _lm_decode_tensors(m):
    """Ordered (name, Tensor) leaves the decode functions need."""
    out = []
    for i, blk in enumerate(m.blocks):
        at = blk.attn
        leaves = [("ln1_s", blk.ln1.scale), ("ln1_b", blk.ln1.bias),
                  ("wq", at.q_proj.W), ("bq", at.q_proj.b),
                  ("wk", at.k_proj.W), ("bk", at.k_proj.b),
                  ("wv", at.v_proj.W), ("bv", at.v_proj.b),
                  ("wo", at.proj.W), ("bo", at.proj.b),
                  ("ln2_s", blk.ln2.scale), ("ln2_b", blk.ln2.bias)]
        if hasattr(blk.mlp, "up"):
            leaves += [("w_up", blk.mlp.up.W), ("b_up", blk.mlp.up.b),
                       ("w_dn", blk.mlp.down.W), ("b_dn", blk.mlp.down.b)]
        else:
            # MoE FFN: all expert groups gathered to host like the rest
            # of the decode state; "wg" flags the MoE path downstream
            leaves += [("wg", blk.mlp.wg), ("w1", blk.mlp.w1),
                       ("b1", blk.mlp.b1), ("w2", blk.mlp.w2),
                       ("b2", blk.mlp.b2)]
        out.append(leaves)
    return out


def _lm_decode_params(m):
    """Pull the trained weights into one host-gathered pytree of jnp
    arrays for the pure decode functions (mesh-sharded state is gathered
    once here — generation is a single-device inference convenience).

    The gathered tree is CACHED against the identity of the live param
    arrays (jax arrays are immutable, so a train step rebinds every
    leaf): a serving loop pays the host round-trip once, not per call.
    The cache holds references to the arrays it was built from, so after
    a train step one stale weight copy lives until the next generate()
    call refreshes it — an inference-convenience tradeoff, documented
    here."""
    import jax
    import jax.numpy as jnp

    per_block = _lm_decode_tensors(m)
    live = [t.data for leaves in per_block for _, t in leaves] \
        + [m.tok_emb.W.data, m.pos_emb.W.data, m.ln_f.scale.data,
           m.ln_f.bias.data, m.head.W.data, m.head.b.data]
    pin = getattr(m, "_decode_params_pin", None)
    if pin is not None and len(pin[0]) == len(live) and \
            all(a is b for a, b in zip(pin[0], live)):
        return pin[1]

    def a(t):
        return jnp.asarray(np.asarray(jax.device_get(t.data)))

    blocks = [{name: a(t) for name, t in leaves} for leaves in per_block]
    P = dict(tok=a(m.tok_emb.W), pos=a(m.pos_emb.W),
             lnf_s=a(m.ln_f.scale), lnf_b=a(m.ln_f.bias),
             head_w=a(m.head.W), head_b=a(m.head.b),
             blocks=blocks)
    m._decode_params_pin = (live, P)
    return P


def _ln(x, s, b, eps=1e-5):
    import jax
    import jax.numpy as jnp
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, -1, keepdims=True)
    var = jnp.var(xf, -1, keepdims=True)
    return ((xf - mean) * jax.lax.rsqrt(var + eps) * s + b).astype(x.dtype)


def _split_heads(t, n_heads):
    B, S, D = t.shape
    return t.reshape(B, S, n_heads, D // n_heads).transpose(0, 2, 1, 3)


def _merge_heads(t):
    B, H, S, hd = t.shape
    return t.transpose(0, 2, 1, 3).reshape(B, S, H * hd)


def _generate(self, ids, max_new_tokens, temperature=1.0, top_k=None,
              seed=0):
    """Autoregressive decoding with a static-shape KV cache.

    One causal prefill pass encodes the prompt and fills per-layer
    key/value caches; a ``lax.scan`` then emits one token per tick,
    attending against the cache — O(L) per new token instead of
    re-running the full O(L²) forward (no reference counterpart; its
    rnn examples re-run full forwards).

    ``ids``: Tensor or array (B, S0) of prompt token ids (float or int).
    ``temperature=0`` is greedy argmax; otherwise softmax sampling with
    optional ``top_k``. Returns a (B, S0 + max_new_tokens) numpy array.
    Single-device inference path: mesh-sharded weights are host-gathered
    per call (so freshly trained values are always used), but the
    compiled decode program is CACHED per shape signature — repeated
    calls pay no retrace. Causal models only (AR decoding is undefined
    for bidirectional attention). MoE models decode through the training
    MoE kernel (same routing/combine math, expert axis inactive) with
    DROP-FREE capacity; greedy decode equals the full forward exactly
    whenever the forward itself drops no tokens.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    if not self.blocks[0].attn.causal:
        raise NotImplementedError(
            "generate() requires a causal model; this TransformerLM was "
            "built with causal=False")
    arr = ids.data if isinstance(ids, Tensor) else ids
    prompt = jnp.asarray(np.asarray(jax.device_get(arr)), jnp.int32)
    if max_new_tokens <= 0:
        return np.asarray(prompt)
    B, S0 = prompt.shape
    P = _lm_decode_params(self)
    n_heads = self.blocks[0].attn.n_heads
    hd = self.d_model // n_heads
    L = S0 + max_new_tokens
    assert L <= P["pos"].shape[0], \
        f"prompt+new tokens ({L}) exceeds max_len {P['pos'].shape[0]}"
    scale = 1.0 / math.sqrt(hd)
    mlp0 = self.blocks[0].mlp
    act = jax.nn.gelu \
        if getattr(mlp0, "activation", "gelu") == "gelu" else jax.nn.relu
    if self.moe:
        # decode reuses the training MoE kernel (same routing/combine
        # math) with the expert axis inactive — the host-gathered params
        # hold every expert — but with DROP-FREE capacity: cf=E makes
        # C = k*T, so no token of the tiny per-step set is ever dropped
        # (training's cf is tuned for joint batches; applied to T=B
        # decode steps it would silently zero some tokens' FFN output).
        # Exact greedy parity with a full forward therefore holds
        # whenever the forward itself drops nothing.
        from ..parallel.moe import _MoEFFN
        moe_op = _MoEFFN(mlp0.n_experts, mlp0.top_k,
                         float(mlp0.n_experts), None, ())

    def mlp_apply(p, h2):
        if "wg" in p:
            Bq, Sq, Dq = h2.shape
            y, _aux = moe_op.forward(h2.reshape(-1, Dq), p["wg"],
                                     p["w1"], p["b1"], p["w2"], p["b2"])
            return y.reshape(h2.shape)
        return act(h2 @ p["w_up"] + p["b_up"]) @ p["w_dn"] + p["b_dn"]

    sig = (B, S0, max_new_tokens, float(temperature), top_k)
    cache = getattr(self, "_decode_cache", None)
    if cache is None:
        cache = self._decode_cache = {}
    run = cache.get(sig)
    if run is None:
        def embed(Pq, tok_ids, pos_ids):
            return (jnp.take(Pq["tok"], tok_ids, axis=0)
                    + jnp.take(Pq["pos"], pos_ids, axis=0))

        def block_prefill(p, x):
            h = _ln(x, p["ln1_s"], p["ln1_b"])
            q = _split_heads(h @ p["wq"] + p["bq"], n_heads)
            k = _split_heads(h @ p["wk"] + p["bk"], n_heads)
            v = _split_heads(h @ p["wv"] + p["bv"], n_heads)
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
            mask = jnp.tril(jnp.ones((S0, S0), bool))
            att = jax.nn.softmax(jnp.where(mask, s, -jnp.inf), -1)
            o = _merge_heads(jnp.einsum("bhqk,bhkd->bhqd", att, v))
            x = x + (o @ p["wo"] + p["bo"])
            h2 = _ln(x, p["ln2_s"], p["ln2_b"])
            x = x + mlp_apply(p, h2)
            return x, k, v

        def block_decode(p, x, kc, vc, pos):
            h = _ln(x, p["ln1_s"], p["ln1_b"])          # (B, 1, D)
            q = _split_heads(h @ p["wq"] + p["bq"], n_heads)
            k = _split_heads(h @ p["wk"] + p["bk"], n_heads)
            v = _split_heads(h @ p["wv"] + p["bv"], n_heads)
            kc = lax.dynamic_update_slice(kc, k, (0, 0, pos, 0))
            vc = lax.dynamic_update_slice(vc, v, (0, 0, pos, 0))
            s = jnp.einsum("bhqd,bhkd->bhqk", q, kc) * scale
            valid = jnp.arange(L)[None, None, None, :] <= pos
            att = jax.nn.softmax(jnp.where(valid, s, -jnp.inf), -1)
            o = _merge_heads(jnp.einsum("bhqk,bhkd->bhqd", att, vc))
            x = x + (o @ p["wo"] + p["bo"])
            h2 = _ln(x, p["ln2_s"], p["ln2_b"])
            x = x + mlp_apply(p, h2)
            return x, kc, vc

        def sample(logits, key):
            # the ONE shared sampling path (models/decode.py): greedy /
            # temperature / top-k math lives there, tested once, shared
            # with char_rnn.sample and the serving engine
            from .decode import sample_logits_jax
            return sample_logits_jax(logits, temperature, top_k, key)

        @jax.jit
        def run(Pq, prompt, key):
            x = embed(Pq, prompt, jnp.arange(S0)[None, :])
            caches = []
            for p in Pq["blocks"]:
                x, k, v = block_prefill(p, x)
                kc = jnp.zeros((B, n_heads, L, hd), k.dtype)
                vc = jnp.zeros_like(kc)
                kc = lax.dynamic_update_slice(kc, k, (0, 0, 0, 0))
                vc = lax.dynamic_update_slice(vc, v, (0, 0, 0, 0))
                caches.append((kc, vc))
            hN = _ln(x, Pq["lnf_s"], Pq["lnf_b"])
            logits0 = hN[:, -1] @ Pq["head_w"] + Pq["head_b"]
            key, sub = jax.random.split(key)
            tok0 = sample(logits0, sub)

            def step(carry, _):
                tok, pos, caches, key = carry
                x = embed(Pq, tok[:, None], pos.reshape(1, 1))
                new_caches = []
                for p, (kc, vc) in zip(Pq["blocks"], caches):
                    x, kc, vc = block_decode(p, x, kc, vc, pos[0])
                    new_caches.append((kc, vc))
                hN = _ln(x, Pq["lnf_s"], Pq["lnf_b"])
                logits = hN[:, -1] @ Pq["head_w"] + Pq["head_b"]
                key, sub = jax.random.split(key)
                nxt = sample(logits, sub)
                return (nxt, pos + 1, tuple(new_caches), key), tok

            init = (tok0, jnp.asarray([S0]), tuple(caches), key)
            (last, _, _, _), toks = lax.scan(
                step, init, None, length=max_new_tokens - 1)
            toks = jnp.concatenate([toks.transpose(1, 0), last[:, None]],
                                   1)
            return toks

        cache[sig] = run

    key = jax.random.PRNGKey(seed)
    new = run(P, prompt, key)
    return np.concatenate([np.asarray(prompt), np.asarray(new)], axis=1)


TransformerLM.generate = _generate


class _LMServeAdapter:
    """Ring-cache prefill/decode adapter: the TransformerLM half of the
    ``singa_tpu.serving.ServingEngine`` contract.

    Exposes the two pure fixed-shape functions the engine AOT-compiles —

    - ``prefill_fn``: ``(P, cache, tokens (B,S), lengths, slot_ids,
      valid) -> (cache, logits (B,V))`` — a fixed-width batch of padded
      prompts runs ONE causal forward and writes each prompt's k/v rows
      into its assigned slot of the ring cache (``valid=False`` rows are
      batch padding: computed, never written);
    - ``decode_fn``: ``(P, cache, tokens (W,), positions (W,),
      active (W,)) -> (cache, logits (W,V))`` — one token for every slot
      in O(1): write the new k/v at ``pos % max_len``, attend over the
      ring (``serving.kv_cache``), return next-token logits.

    Freed-slot hygiene is arithmetic, not bookkeeping: a dead slot's
    stale rows sit at ring indices the position mask only reaches once
    the NEW occupant has overwritten them (prefill covers ``[0, len)``,
    decode writes index ``p`` in the same tick the mask first admits
    ``p``), so no cross-request leakage is possible by construction.

    Mixed precision follows the training policy's contract: embeddings
    and the head stay f32, block weights and the cache run in the
    policy's compute dtype (bf16 serving out of the box), attention
    softmax and the returned logits are f32.

    Quantized serving (``singa_tpu.quant`` presets): under
    ``"int8_weight_only"`` every block matmul weight is quantized ONCE
    at engine build into an int8 payload + per-output-channel fp32
    scale and dequantized in graph at its use site (embeddings and the
    head stay f32 — they are the parity-critical ends); under
    ``"fp8_serving"`` block weights are rounded through the e4m3 grid
    inside the compiled programs. Either way a ``cache_quant`` policy
    runs the ring KV cache in int8 with per-(slot, ring-index) scale
    rows — ``kv_cache`` dequantizes into the unchanged f32 softmax.
    """

    # block weights eligible for int8 weight-only quantization (2-D
    # matmul operands; biases/LN stay f32, MoE expert banks pass
    # through untouched)
    _QUANT_KEYS = ("wq", "wk", "wv", "wo", "w_up", "w_dn")
    # build_engine's honored-or-refused contract for quantized policies
    supports_weight_quant = True
    supports_cache_quant = True
    # the transformer's KV state is pure per-position rows — exactly
    # what the paged block pool holds; the char-rnn's (h,c) carry is
    # not, so ITS adapter leaves this False and the engine declines
    # kv_layout="paged" loudly back to the ring
    supports_paged = True
    # GSPMD sharded serving (parallel/gspmd.py): the adapter can map
    # its param/cache trees to NamedSharding specs and emit
    # argmax-in-graph program variants; the char-rnn's (h,c) adapter
    # cannot, so compile_serving(model_shards=) on it is a typed decline
    supports_sharded = True

    def __init__(self, m, policy=None):
        self.m = m
        self.policy = policy
        at = m.blocks[0].attn
        if not at.causal:
            raise NotImplementedError(
                "serving needs a causal model; this TransformerLM was "
                "built with causal=False")
        self.n_heads = at.n_heads
        self.head_dim = m.d_model // self.n_heads
        self.scale = 1.0 / math.sqrt(self.head_dim)

    def _compute_dtype(self):
        import jax.numpy as jnp
        if self.policy is not None and \
                self.policy.compute_dtype is not None:
            return jnp.dtype(self.policy.compute_dtype)
        cd = self.m.compute_dtype
        return jnp.dtype(cd) if cd is not None else jnp.dtype(jnp.float32)

    def params(self):
        from ..quant.core import dequant_params_scope
        with dequant_params_scope(self.m):
            # a model already weight-quantized in place hands the
            # engine its DEQUANTIZED weights here (concrete arrays at
            # build time; re-quantized below under an int8 policy)
            P = _lm_decode_params(self.m)
        if getattr(self.policy, "weight_quant", None) == "int8":
            from ..quant import core as _qcore
            import jax.numpy as jnp
            blocks = []
            for p in P["blocks"]:
                bp = dict(p)
                for key in self._QUANT_KEYS:
                    w = bp.get(key)
                    if w is not None and w.ndim == 2 and \
                            jnp.issubdtype(w.dtype, jnp.floating):
                        q, s = _qcore.quantize_int8(
                            w, _qcore.channel_axis(w.shape))
                        bp[key] = {"q": q, "s": s}
                blocks.append(bp)
            P = dict(P, blocks=blocks)
        return P

    def _cache_dtype(self):
        import jax.numpy as jnp
        if getattr(self.policy, "cache_quant", None) == "int8":
            return jnp.dtype(jnp.int8)
        return self._compute_dtype()

    def validate(self, prefill_len, max_len):
        """Engine-construction-time limits the engine itself can't see:
        a prompt longer than the positional-embedding table would crash
        the first compiled prefill with a shape error; fail typed and
        early instead. (decode clips positions to the table — the ring
        has made attention sliding-window by then — but prefill indexes
        ``pos[:S]`` directly.)"""
        table = int(self.m.pos_emb.input_dim)
        if int(prefill_len) > table:
            raise ValueError(
                f"prefill_len {prefill_len} exceeds this model's "
                f"positional-embedding table ({table} rows): rebuild "
                f"the model with max_len >= {prefill_len} or lower "
                "prefill_len")

    def init_cache(self, slots, max_len):
        from ..serving import kv_cache
        return [kv_cache.init_cache(slots, self.n_heads, max_len,
                                    self.head_dim, self._cache_dtype())
                for _ in self.m.blocks]

    def _mlp_apply(self):
        import jax
        mlp0 = self.m.blocks[0].mlp
        act = jax.nn.gelu \
            if getattr(mlp0, "activation", "gelu") == "gelu" \
            else jax.nn.relu
        if self.m.moe:
            from ..parallel.moe import _MoEFFN
            # drop-free capacity, expert axis inactive — the same decode
            # convention generate() documents
            moe_op = _MoEFFN(mlp0.n_experts, mlp0.top_k,
                             float(mlp0.n_experts), None, ())
        else:
            moe_op = None

        def mlp_apply(p, h2, c):
            if "wg" in p:
                Bq, Sq, Dq = h2.shape
                y, _aux = moe_op.forward(h2.reshape(-1, Dq), p["wg"],
                                         p["w1"], p["b1"], p["w2"],
                                         p["b2"])
                return y.reshape(h2.shape).astype(h2.dtype)
            return (act(h2 @ c(p["w_up"]) + c(p["b_up"]))
                    @ c(p["w_dn"]) + c(p["b_dn"]))

        return mlp_apply

    def _block(self):
        """The ONE transformer-block body both serve programs share
        (LN → QKV → attend → out-proj → LN → MLP). Only the
        attention+cache step differs between prefill and decode, so it
        is injected: ``attend(q, k, v, level) -> (merged_out, level)``.
        One copy means the two compiled programs cannot drift from each
        other."""
        import jax.numpy as jnp
        n_heads = self.n_heads
        cdt = self._compute_dtype()
        mlp_apply = self._mlp_apply()
        fp8_w = getattr(self.policy, "compute_quant", None) \
            if getattr(self.policy, "weight_quant", None) is None else None
        if fp8_w is not None and fp8_w not in ("e4m3", "e5m2"):
            fp8_w = None        # int8 fake-quant policies serve as-is

        def c(a):
            if isinstance(a, dict):
                # int8 weight-only payload from params(): the in-graph
                # dequant XLA fuses into the consuming matmul — the
                # threaded params stay int8, only this use site is fp
                from ..quant import core as _qcore
                return _qcore.dequantize_int8(a["q"], a["s"], cdt)
            if not jnp.issubdtype(a.dtype, jnp.floating):
                return a
            a = a.astype(cdt)
            if fp8_w is not None and a.ndim == 2:
                # fp8_serving: matmul weights rounded through the e4m3
                # grid inside the compiled programs (biases/LN stay in
                # the compute dtype — tiny and fragile)
                from ..quant import core as _qcore
                a = _qcore.fake_cast(a, fp8_w)
            return a

        def block(p, x, level, attend):
            h = _ln(x, p["ln1_s"], p["ln1_b"])
            q = _split_heads(h @ c(p["wq"]) + c(p["bq"]), n_heads)
            k = _split_heads(h @ c(p["wk"]) + c(p["bk"]), n_heads)
            v = _split_heads(h @ c(p["wv"]) + c(p["bv"]), n_heads)
            o, level = attend(q, k, v, level)
            x = x + (o.astype(x.dtype) @ c(p["wo"]) + c(p["bo"]))
            return x + mlp_apply(p, _ln(x, p["ln2_s"], p["ln2_b"]), c), \
                level

        return block, c, cdt

    def prefill_fn(self):
        import jax
        import jax.numpy as jnp
        from ..serving import kv_cache
        scale = self.scale
        block, _c, cdt = self._block()

        def fn(P, cache, tokens, lengths, slot_ids, valid):
            B, S = tokens.shape
            x = (jnp.take(P["tok"], tokens, axis=0)
                 + P["pos"][None, :S]).astype(cdt)
            causal = jnp.tril(jnp.ones((S, S), bool))[None, None]

            def attend(q, k, v, level):
                s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                               k.astype(jnp.float32)) * scale
                att = jax.nn.softmax(jnp.where(causal, s, -jnp.inf), -1)
                o = _merge_heads(jnp.einsum("bhqk,bhkd->bhqd", att,
                                            v.astype(jnp.float32)))
                # B is a static prefill-batch width: this unrolls into
                # B masked slot writes inside the ONE compiled program
                for b in range(B):
                    level = kv_cache.write_prompt(
                        level, slot_ids[b], k[b], v[b], valid[b])
                return o, level

            new_cache = []
            for p, level in zip(P["blocks"], cache):
                x, level = block(p, x, level, attend)
                new_cache.append(level)
            hN = _ln(x, P["lnf_s"], P["lnf_b"])
            h_last = jnp.take_along_axis(
                hN, (lengths - 1).astype(jnp.int32)[:, None, None]
                .clip(0), axis=1)[:, 0]
            logits = (h_last.astype(jnp.float32) @ P["head_w"]
                      + P["head_b"])
            return new_cache, logits

        return fn

    # -- paged block-pool programs ------------------------------------------
    def init_pool(self, n_blocks, block_size):
        from ..serving import kv_cache
        return [kv_cache.init_pool(n_blocks, self.n_heads, block_size,
                                   self.head_dim, self._cache_dtype())
                for _ in self.m.blocks]

    def _paged_core(self):
        """The ONE paged transformer pass both paged programs share:
        embed ``(R, Q)`` tokens at absolute positions ``pos_abs``,
        write each layer's fresh k/v rows into the pool through the
        per-row block tables (``wmask`` drops padding/inactive rows),
        attend position-exactly (``cache position <= query position`` —
        a query sees the cached prefix, earlier fresh tokens, and
        itself), and return the final-LN hidden states. Chunked prefill
        and the K-token speculative verify are the SAME math at
        different (R, Q); one body means they cannot drift."""
        import jax.numpy as jnp
        from ..serving import kv_cache
        scale = self.scale
        block, _c, cdt = self._block()

        def core(P, pool, tables, tokens, pos_abs, wmask):
            pos_ids = jnp.minimum(pos_abs,
                                  P["pos"].shape[0] - 1)
            x = (jnp.take(P["tok"], tokens, axis=0)
                 + jnp.take(P["pos"], pos_ids, axis=0)).astype(cdt)

            def attend(q, k, v, level):
                level = kv_cache.write_rows(level, tables, k, v,
                                            pos_abs, wmask)
                o = kv_cache.attend_pages(q, level, tables, pos_abs,
                                          scale)
                return _merge_heads(o), level

            new_pool = []
            for p, level in zip(P["blocks"], pool):
                x, level = block(p, x, level, attend)
                new_pool.append(level)
            return new_pool, _ln(x, P["lnf_s"], P["lnf_b"])

        return core

    def paged_prefill_fn(self):
        """Chunked paged prefill: ``(P, pool, tables (B, n_pages),
        tokens (B, S) SUFFIX tokens, starts (B,) prefix-hit lengths,
        lengths (B,) suffix lengths, valid (B,)) -> (pool,
        logits (B, V))`` — a prefix-cache hit enters here with
        ``starts > 0`` and its suffix attending to the shared blocks
        it never recomputed."""
        import jax.numpy as jnp

        core = self._paged_core()

        def fn(P, pool, tables, tokens, starts, lengths, valid):
            B, S = tokens.shape
            pos_abs = starts.astype(jnp.int32)[:, None] \
                + jnp.arange(S, dtype=jnp.int32)[None, :]
            wmask = (jnp.arange(S, dtype=jnp.int32)[None, :]
                     < lengths.astype(jnp.int32)[:, None]) \
                & valid[:, None]
            pool, hN = core(P, pool, tables, tokens, pos_abs, wmask)
            h_last = jnp.take_along_axis(
                hN, (lengths - 1).astype(jnp.int32)[:, None, None]
                .clip(0), axis=1)[:, 0]
            logits = (h_last.astype(jnp.float32) @ P["head_w"]
                      + P["head_b"])
            return pool, logits

        return fn

    def paged_decode_fn(self):
        """Paged decode/verify: ``(P, pool, tables (W, n_pages),
        tokens (W, K), positions (W,) first-token positions,
        counts (W,) real tokens per row) -> (pool,
        logits (W, K, V))``. ``K == 1`` is plain one-token decode;
        ``K > 1`` scores a speculative draft row in ONE tick —
        ``logits[:, i]`` is the exact next-token distribution after
        token ``i``, which is what makes the host accept/reject walk
        token-identical to sequential greedy."""
        import jax.numpy as jnp

        core = self._paged_core()

        def fn(P, pool, tables, tokens, positions, counts):
            W, K = tokens.shape
            pos_abs = positions.astype(jnp.int32)[:, None] \
                + jnp.arange(K, dtype=jnp.int32)[None, :]
            wmask = jnp.arange(K, dtype=jnp.int32)[None, :] \
                < counts.astype(jnp.int32)[:, None]
            pool, hN = core(P, pool, tables, tokens, pos_abs, wmask)
            logits = (hN.astype(jnp.float32) @ P["head_w"]
                      + P["head_b"])
            return pool, logits

        return fn

    def decode_fn(self):
        import jax.numpy as jnp
        from ..serving import kv_cache
        scale = self.scale
        block, _c, cdt = self._block()

        def fn(P, cache, tokens, positions, active):
            positions = positions.astype(jnp.int32)
            # the learned position table is finite; a sequence decoding
            # past it holds the last embedding (the ring has already
            # made attention sliding-window by then)
            pos_ids = jnp.minimum(positions, P["pos"].shape[0] - 1)
            x = (jnp.take(P["tok"], tokens, axis=0)
                 + jnp.take(P["pos"], pos_ids, axis=0))[:, None, :] \
                .astype(cdt)

            def attend(q, k, v, level):
                level = kv_cache.write_token(
                    level, k[:, :, 0], v[:, :, 0], positions)
                return _merge_heads(kv_cache.attend(
                    q, level, positions, scale)), level

            new_cache = []
            for p, level in zip(P["blocks"], cache):
                x, level = block(p, x, level, attend)
                new_cache.append(level)
            hN = _ln(x, P["lnf_s"], P["lnf_b"])[:, 0]
            logits = (hN.astype(jnp.float32) @ P["head_w"]
                      + P["head_b"])
            return new_cache, logits

        return fn


    # -- GSPMD sharded serving ----------------------------------------------
    def sharding_specs(self, part, P, cache, kv_layout):
        """PartitionSpec trees for this adapter's param dict and KV
        state over a (batch × model) partitioner — the ONE gspmd rule
        table; raises a typed
        :class:`~singa_tpu.parallel.gspmd.ShardingDecline` for any
        dimension the mesh cannot split honestly (heads, vocab, MLP
        hidden, MoE expert banks)."""
        from ..parallel import gspmd
        param_specs = gspmd.lm_param_specs(part, P, self.n_heads)
        cache_specs = gspmd.pool_specs(part, cache) \
            if kv_layout == "paged" else \
            gspmd.ring_cache_specs(part, cache)
        return param_specs, cache_specs

    def _argmax_wrap(self, base):
        """Token-returning twin of a logits-returning serve program:
        ``argmax`` runs IN GRAPH over the vocab-sharded logits (XLA
        combines per-shard partial argmaxes — ties break to the lowest
        id, the exact semantics of the host sampler's np.argmax), so
        the full (rows, V) logits never leave the program and no
        full-vocab gather exists anywhere in it."""
        import jax.numpy as jnp

        def fn(*args):
            state, logits = base(*args)
            return state, jnp.argmax(logits, -1).astype(jnp.int32)

        return fn

    def greedy_prefill_fn(self):
        return self._argmax_wrap(self.prefill_fn())

    def greedy_decode_fn(self):
        return self._argmax_wrap(self.decode_fn())

    def greedy_paged_prefill_fn(self):
        return self._argmax_wrap(self.paged_prefill_fn())

    def greedy_paged_decode_fn(self):
        # (W, K, V) logits -> (W, K) tokens: the speculative accept
        # walk only ever compares draft tokens against argmax, so the
        # verify program loses nothing by returning tokens
        return self._argmax_wrap(self.paged_decode_fn())


def _decode_adapter(self, policy=None):
    """The serving engine's entry point (``Model.compile_serving``
    routes autoregressive models here): a :class:`_LMServeAdapter` over
    this model's live (host-gathered) weights."""
    return _LMServeAdapter(self, policy=policy)


TransformerLM.decode_adapter = _decode_adapter
