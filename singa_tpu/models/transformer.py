"""Decoder-only Transformer LM — the flagship long-context model.

TPU-first design (no reference equivalent; the reference's only attention
is composed from primitive ops in examples/qabot): pre-norm GPT-style
blocks whose attention is the fused flash kernel (ops/attention.py), with
three composable parallelism modes driven by the mesh:

- data parallel: batch over 'data' (DistOpt psum, like every model here);
- tensor parallel (``tp=True``): qkv and MLP-up as ColumnParallelLinear,
  out-proj and MLP-down as RowParallelLinear — heads shard over 'model',
  two all-reduces per block (Megatron layout);
- sequence parallel (``seq_axis='seq'``): tokens shard over 'seq'; the
  attention switches to ring attention (k/v rotate over ICI) and the
  caller sets ``Model.input_specs = [P('data', 'seq'), ...]``.
"""

from __future__ import annotations

import math

import numpy as np

from .. import autograd, layer, model
from ..parallel import tensor_parallel as tp_mod
from ..ops.attention import attention
from ..tensor import Tensor


class _Positions(autograd.Operator):
    """Global position ids for a (possibly sequence-sharded) token block."""

    differentiable = False

    def __init__(self, seq_axis=None):
        super().__init__()
        self.seq_axis = seq_axis

    def forward(self, ids):
        import jax.numpy as jnp
        from jax import lax
        from ..parallel.communicator import active_axis
        S = ids.shape[1]
        pos = jnp.arange(S)
        if self.seq_axis and active_axis(self.seq_axis):
            pos = pos + lax.axis_index(self.seq_axis) * S
        return jnp.broadcast_to(pos[None, :], ids.shape).astype(jnp.float32)


class MultiHeadAttention(layer.Layer):
    """Fused-attention MHA; optionally tensor-parallel over heads and/or
    sequence-parallel (ring) over tokens."""

    def __init__(self, d_model, n_heads, causal=True, tp=True,
                 seq_axis=None, axis_name="model", seq_mode="ring"):
        """``tp`` is accepted for API compatibility but the layout is
        mesh-driven: the parallel layers degrade to plain Linear on a
        size-1 'model' axis (or outside any mesh), so there is exactly one
        code path — and one state-dict layout — for every topology."""
        super().__init__()
        assert d_model % n_heads == 0
        self.d_model = d_model
        self.n_heads = n_heads
        self.head_dim = d_model // n_heads
        self.causal = causal
        self.seq_axis = seq_axis
        self.seq_mode = seq_mode
        # three separate column-parallel projections: a fused qkv matrix
        # would shard its columns across the [q|k|v] boundary
        self.q_proj = tp_mod.ColumnParallelLinear(d_model,
                                                  axis_name=axis_name)
        self.k_proj = tp_mod.ColumnParallelLinear(d_model,
                                                  axis_name=axis_name)
        self.v_proj = tp_mod.ColumnParallelLinear(d_model,
                                                  axis_name=axis_name)
        self.proj = tp_mod.RowParallelLinear(d_model, axis_name=axis_name)

    def forward(self, x):
        B, S = x.shape[0], x.shape[1]
        q = self.q_proj(x)                      # (B, S, d_local)
        k = self.k_proj(x)
        v = self.v_proj(x)
        d_local = q.shape[-1]
        h_local = d_local // self.head_dim      # heads on this shard

        def split_heads(t):
            t = autograd.reshape(t, (B, S, h_local, self.head_dim))
            return autograd.transpose(t, (0, 2, 1, 3))  # (B, H, S, D)

        out = attention(split_heads(q), split_heads(k), split_heads(v),
                        causal=self.causal, seq_axis=self.seq_axis,
                        seq_mode=self.seq_mode)
        out = autograd.transpose(out, (0, 2, 1, 3))
        out = autograd.reshape(out, (B, S, d_local))
        return self.proj(out)


class TransformerBlock(layer.Layer):
    def __init__(self, d_model, n_heads, d_ff=None, causal=True, tp=True,
                 seq_axis=None, moe=None, moe_top_k=None,
                 moe_capacity_factor=1.25, seq_mode="ring"):
        """``moe``: number of experts; replaces the dense FFN with a
        :class:`~singa_tpu.parallel.moe.MoEFFN` sharded over the mesh
        'expert' axis (``self.mlp.aux_loss`` is valid only inside the
        same train_one_batch trace). ``moe_top_k`` defaults to 2 clamped
        to the expert count (so moe=1 means Switch-style top-1)."""
        super().__init__()
        d_ff = d_ff or 4 * d_model
        self.ln1 = layer.LayerNorm()
        self.attn = MultiHeadAttention(d_model, n_heads, causal, tp,
                                       seq_axis, seq_mode=seq_mode)
        self.ln2 = layer.LayerNorm()
        if moe:
            from ..parallel.moe import MoEFFN
            top_k = moe_top_k if moe_top_k is not None else min(2, moe)
            self.mlp = MoEFFN(moe, d_ff, top_k=top_k,
                              capacity_factor=moe_capacity_factor)
        else:
            self.mlp = tp_mod.TPMLP(d_ff, d_model, activation="gelu")

    def forward(self, x):
        x = autograd.add(x, self.attn(self.ln1(x)))
        return autograd.add(x, self.mlp(self.ln2(x)))


class TransformerLM(model.Model):
    """GPT-style language model with next-token loss.

    ``train_one_batch(ids, targets)`` takes float tensors of token ids and
    target ids, both (B, S) ((B, S/n) per shard under sequence parallel).
    """

    def __init__(self, vocab_size, d_model=128, n_heads=4, n_layers=2,
                 max_len=1024, causal=True, tp=True, seq_axis=None,
                 remat=False, moe=None, moe_aux_weight=0.01,
                 moe_top_k=None, moe_capacity_factor=1.25,
                 seq_mode="ring", fused_head_chunk=None):
        """``moe``: experts per block (MoE FFN over the 'expert' mesh
        axis); the blocks' load-balance aux losses join the training loss
        scaled by ``moe_aux_weight``. ``moe_top_k`` defaults to
        min(2, moe)."""
        super().__init__()
        self.vocab_size = vocab_size
        self.d_model = d_model
        # remat: rematerialize each block in backward (jax.checkpoint) —
        # activation memory O(n_layers * block-boundary) instead of
        # O(n_layers * everything), the standard long-context trade
        self.remat = remat
        self.moe = moe
        self.moe_aux_weight = moe_aux_weight
        self.fused_head_chunk = fused_head_chunk
        self.tok_emb = layer.Embedding(vocab_size, d_model)
        self.pos_emb = layer.Embedding(max_len, d_model)
        self._pos = _Positions(seq_axis)
        self.blocks = [TransformerBlock(
            d_model, n_heads, causal=causal, tp=tp, seq_axis=seq_axis,
            moe=moe, moe_top_k=moe_top_k,
            moe_capacity_factor=moe_capacity_factor, seq_mode=seq_mode)
            for i in range(n_layers)]
        self.ln_f = layer.LayerNorm()
        self.head = layer.Linear(vocab_size)
        self.loss_fn = layer.SoftMaxCrossEntropy()

    def _hidden(self, ids):
        pos = self._pos(ids)
        x = autograd.add(self.tok_emb(ids), self.pos_emb(pos))
        for blk in self.blocks:
            x = autograd.checkpoint(blk, x) if self.remat else blk(x)
        return self.ln_f(x)

    def forward(self, ids):
        return self.head(self._hidden(ids))     # (B, S, vocab)

    def train_one_batch(self, ids, targets):
        if self.fused_head_chunk:
            # large-vocab mode: loss straight from the hidden states via
            # the chunked fused CE head — the (B,S,V) logits are never
            # materialised in the TRAINING step (forward/eval still
            # produces them through the same shared head params).
            from ..ops.losses import fused_softmax_cross_entropy
            h = self._hidden(ids)
            if not self._initialized_head():
                # compile()'s dry forward normally initializes the head;
                # direct train_one_batch calls get it here
                self.head(h)
            loss = fused_softmax_cross_entropy(
                h, self.head.W, self.head.b, targets,
                self.fused_head_chunk)
            out = None
        else:
            logits = self.forward(ids)
            B, S, V = logits.shape
            flat = autograd.reshape(logits, (B * S, V))
            onehot = autograd.onehot(-1, targets, self.vocab_size)
            oh_flat = autograd.reshape(onehot, (B * S, V))
            loss = autograd.softmax_cross_entropy(flat, oh_flat)
            out = logits
        if self.moe:
            w = Tensor(data=np.asarray(self.moe_aux_weight, np.float32),
                       device=ids.device, requires_grad=False)
            for blk in self.blocks:
                loss = autograd.add(loss, autograd.mul(blk.mlp.aux_loss, w))
        self.optimizer(loss)
        # fused mode has no logits to return: the TOTAL loss (incl. moe
        # aux) fills the predictions slot so both outputs agree with
        # what the optimizer stepped on
        if out is None:
            out = loss
        return out, loss

    def _initialized_head(self):
        return getattr(self.head, "_initialized", False) and \
            hasattr(self.head, "W")


def create_model(vocab_size=256, **kwargs):
    return TransformerLM(vocab_size, **kwargs)


__all__ = ["TransformerLM", "TransformerBlock", "MultiHeadAttention",
           "create_model"]
