"""Character-level LSTM language model.

Capability parity with the reference char-rnn example
(examples/rnn/char_rnn.py:39-90): a stateful LSTM over per-timestep
one-hot inputs whose hidden/cell states persist across batches (truncated
BPTT), a shared dense decoder over all timesteps, and a sampler.
"""

from __future__ import annotations

import numpy as np

from .. import autograd, layer, model, opt
from ..tensor import Tensor


class CharRNN(model.Model):
    """(reference char_rnn.py CharRNN)"""

    def __init__(self, vocab_size, hidden_size=32):
        super().__init__()
        self.rnn = layer.LSTM(vocab_size, hidden_size)
        self.dense = layer.Linear(hidden_size, vocab_size)
        self.optimizer = opt.SGD(0.01)
        self.hidden_size = hidden_size
        self.vocab_size = vocab_size
        self._states_ready = False
        self._pending_states = None  # checkpointed hx/cx awaiting creation

    def reset_states(self, dev=None):
        """Zero the recurrent state; safe before the first forward
        (states are created lazily)."""
        if self._states_ready:
            self.hx.set_value(0.0)
            self.cx.set_value(0.0)

    def _ensure_states(self, inputs):
        if not self._states_ready:
            batch = inputs[0].shape[0]
            dev = inputs[0].device
            self.hx = Tensor(shape=(batch, self.hidden_size), device=dev,
                             requires_grad=False)
            self.cx = Tensor(shape=(batch, self.hidden_size), device=dev,
                             requires_grad=False)
            self.hx.name, self.cx.name = "hx", "cx"
            self._states_ready = True
            if self._pending_states is not None:
                hx, cx = self._pending_states
                if hx is not None:
                    self.hx.copy_from(hx)
                if cx is not None:
                    self.cx.copy_from(cx)
                self._pending_states = None

    def forward(self, inputs):
        """inputs: list of (batch, vocab) one-hot tensors, one per step."""
        self._ensure_states(inputs)
        out, (hx, cx) = self.rnn(inputs, (self.hx, self.cx))
        # persist the running state for truncated BPTT across batches
        self.hx.copy_data(hx)
        self.cx.copy_data(cx)
        x = autograd.cat(out, axis=0)          # (steps*batch, hidden)
        return self.dense(x)

    def train_one_batch(self, inputs, labels):
        """labels: list of (batch,) class-id tensors, one per step."""
        out = self.forward(inputs)
        y = autograd.cat(labels, axis=0)
        onehot = autograd.onehot(-1, y, self.vocab_size)
        loss = autograd.softmax_cross_entropy(out, onehot)
        self.optimizer(loss)
        return out, loss

    def get_states(self):
        ret = super().get_states()
        if self._states_ready:
            ret["hx"] = self.hx
            ret["cx"] = self.cx
        return ret

    def set_states(self, states):
        if self._states_ready:
            if "hx" in states:
                self.hx.copy_from(states["hx"])
            if "cx" in states:
                self.cx.copy_from(states["cx"])
        elif "hx" in states or "cx" in states:
            # fresh model: stash the recurrent state until the lazily
            # created hx/cx exist (checkpoint-resume must not drop it)
            self._pending_states = (states.get("hx"), states.get("cx"))
        super().set_states(states)


def sample(model, start_ids, vocab_size, nsamples=100, use_max=False,
           seed=0, temperature=1.0, top_k=None):
    """Autoregressive sampling (reference char_rnn.py sample:164).

    The token draw routes through the ONE shared sampling helper
    (:func:`singa_tpu.models.decode.sample_logits`) — the same math the
    transformer's ``generate()`` and the serving engine use.
    ``use_max=True`` is greedy (``temperature=0``)."""
    from . import decode as _decode
    rng = np.random.RandomState(seed)
    ids = list(start_ids)
    out_ids = []
    # re-run with batch 1; borrow the layer weights via step_forward —
    # under the model's OWN scope (this drives layers directly, not
    # Model.__call__): a precision policy is honored and a weight-
    # quantized model's int8 payloads are dequantized, exactly as in
    # every other forward path
    with model._policy_scope():
        h = Tensor(data=np.zeros((1, model.hidden_size), np.float32),
                   requires_grad=False)
        c = Tensor(data=np.zeros((1, model.hidden_size), np.float32),
                   requires_grad=False)
        for i in ids:
            x = Tensor(data=np.eye(vocab_size, dtype=np.float32)[[i]],
                       requires_grad=False)
            h, c = model.rnn.step_forward(x, h, c)
        temp = 0 if use_max else temperature
        for _ in range(nsamples):
            logits = np.asarray(model.dense(h).numpy()).ravel()
            cur = _decode.sample_logits(logits, temperature=temp,
                                        top_k=top_k, rng=rng)
            out_ids.append(cur)
            x = Tensor(data=np.eye(vocab_size,
                                   dtype=np.float32)[[cur]],
                       requires_grad=False)
            h, c = model.rnn.step_forward(x, h, c)
    return out_ids


class _CharRNNServeAdapter:
    """Serving-engine adapter for the stateful LSTM LM: the "cache" is
    just each slot's ``(h, c)`` recurrent state — O(1) per token by
    construction, no ring needed (``max_len`` is accepted and ignored).
    Same prefill/decode signatures as the transformer adapter, so the
    engine is model-agnostic. A mixed-precision policy is HONORED, not
    just reported: gates and state run in the policy's compute dtype,
    logits return f32 (what ``compiled_step_info()["policy"]`` claims
    must be what executes)."""

    def __init__(self, m, policy=None):
        self.m = m
        self.policy = policy
        if getattr(m.rnn, "Wx", None) is None:
            raise RuntimeError(
                "CharRNN serving needs initialized weights: run one "
                "forward (or restore a checkpoint) before "
                "compile_serving")

    def _compute_dtype(self):
        import jax.numpy as jnp
        if self.policy is not None and \
                self.policy.compute_dtype is not None:
            return jnp.dtype(self.policy.compute_dtype)
        return jnp.dtype(jnp.float32)

    def params(self):
        import jax
        import jax.numpy as jnp
        from ..quant.core import dequant_params_scope

        def a(t):
            return jnp.asarray(np.asarray(jax.device_get(t.data)))

        m = self.m
        with dequant_params_scope(m):
            # an in-place-quantized model (quant.quantize_params) hands
            # the engine dequantized fp32 weights — raw int8 payloads
            # consumed as floats would be garbage logits
            return {"Wx": a(m.rnn.Wx), "Wh": a(m.rnn.Wh),
                    "b": a(m.rnn.b), "dense_w": a(m.dense.W),
                    "dense_b": a(m.dense.b)}

    def init_cache(self, slots, max_len):
        import jax.numpy as jnp
        H = self.m.hidden_size
        cdt = self._compute_dtype()
        return {"h": jnp.zeros((int(slots), H), cdt),
                "c": jnp.zeros((int(slots), H), cdt)}

    def _cell(self):
        import jax
        import jax.numpy as jnp
        cdt = self._compute_dtype()

        def cell(P, x, h, c):
            H = h.shape[-1]
            g = (x @ P["Wx"].astype(cdt) + h @ P["Wh"].astype(cdt)
                 + P["b"].astype(cdt))
            i = jax.nn.sigmoid(g[:, :H])
            f = jax.nn.sigmoid(g[:, H:2 * H])
            gg = jnp.tanh(g[:, 2 * H:3 * H])
            o = jax.nn.sigmoid(g[:, 3 * H:])
            c_new = f * c + i * gg
            return o * jnp.tanh(c_new), c_new

        return cell

    @staticmethod
    def _logits(P, h):
        import jax.numpy as jnp
        # the softmax-side output is f32 regardless of compute dtype
        # (the transformer adapter's head convention)
        return (h.astype(jnp.float32) @ P["dense_w"] + P["dense_b"])

    def prefill_fn(self):
        import jax
        import jax.numpy as jnp
        V = self.m.vocab_size
        cell = self._cell()
        cdt = self._compute_dtype()
        logits_of = self._logits

        def fn(P, cache, tokens, lengths, slot_ids, valid):
            B, S = tokens.shape
            H = cache["h"].shape[-1]
            h0 = jnp.zeros((B, H), cdt)

            def step(hc, t):
                h, c = hc
                x = jax.nn.one_hot(tokens[:, t], V, dtype=cdt)
                h2, c2 = cell(P, x, h, c)
                live = (t < lengths)[:, None]    # padded tail: freeze
                return (jnp.where(live, h2, h),
                        jnp.where(live, c2, c)), None

            (h, c), _ = jax.lax.scan(step, (h0, h0), jnp.arange(S))
            ch, cc = cache["h"], cache["c"]
            for b in range(B):          # static width, masked writes
                keep = valid[b]
                ch = jnp.where(keep, ch.at[slot_ids[b]].set(h[b]), ch)
                cc = jnp.where(keep, cc.at[slot_ids[b]].set(c[b]), cc)
            return {"h": ch, "c": cc}, logits_of(P, h)

        return fn

    def decode_fn(self):
        import jax
        import jax.numpy as jnp
        cell = self._cell()
        V = self.m.vocab_size
        cdt = self._compute_dtype()
        logits_of = self._logits

        def fn(P, cache, tokens, positions, active):
            x = jax.nn.one_hot(tokens, V, dtype=cdt)
            h2, c2 = cell(P, x, cache["h"], cache["c"])
            live = active[:, None]
            h = jnp.where(live, h2, cache["h"])
            c = jnp.where(live, c2, cache["c"])
            return {"h": h, "c": c}, logits_of(P, h)

        return fn


def _decode_adapter(self, policy=None):
    """Serving entry point (``Model.compile_serving``): adapter over
    this CharRNN's live weights."""
    return _CharRNNServeAdapter(self, policy=policy)


CharRNN.decode_adapter = _decode_adapter


def create_model(vocab_size=101, hidden_size=32, **kwargs):
    return CharRNN(vocab_size, hidden_size, **kwargs)


__all__ = ["CharRNN", "sample", "create_model"]
