"""Character-level LSTM language model.

Capability parity with the reference char-rnn example
(examples/rnn/char_rnn.py:39-90): a stateful LSTM over per-timestep
one-hot inputs whose hidden/cell states persist across batches (truncated
BPTT), a shared dense decoder over all timesteps, and a sampler.
"""

from __future__ import annotations

import numpy as np

from .. import autograd, layer, model, opt, tensor
from ..tensor import Tensor


class CharRNN(model.Model):
    """(reference char_rnn.py CharRNN)"""

    def __init__(self, vocab_size, hidden_size=32):
        super().__init__()
        self.rnn = layer.LSTM(vocab_size, hidden_size)
        self.dense = layer.Linear(hidden_size, vocab_size)
        self.optimizer = opt.SGD(0.01)
        self.hidden_size = hidden_size
        self.vocab_size = vocab_size
        self._states_ready = False
        self._pending_states = None  # checkpointed hx/cx awaiting creation

    def reset_states(self, dev=None):
        """Zero the recurrent state; safe before the first forward
        (states are created lazily)."""
        if self._states_ready:
            self.hx.set_value(0.0)
            self.cx.set_value(0.0)

    def _ensure_states(self, inputs):
        if not self._states_ready:
            batch = inputs[0].shape[0]
            dev = inputs[0].device
            self.hx = Tensor(shape=(batch, self.hidden_size), device=dev,
                             requires_grad=False)
            self.cx = Tensor(shape=(batch, self.hidden_size), device=dev,
                             requires_grad=False)
            self.hx.name, self.cx.name = "hx", "cx"
            self._states_ready = True
            if self._pending_states is not None:
                hx, cx = self._pending_states
                if hx is not None:
                    self.hx.copy_from(hx)
                if cx is not None:
                    self.cx.copy_from(cx)
                self._pending_states = None

    def forward(self, inputs):
        """inputs: list of (batch, vocab) one-hot tensors, one per step."""
        self._ensure_states(inputs)
        out, (hx, cx) = self.rnn(inputs, (self.hx, self.cx))
        # persist the running state for truncated BPTT across batches
        self.hx.copy_data(hx)
        self.cx.copy_data(cx)
        x = autograd.cat(out, axis=0)          # (steps*batch, hidden)
        return self.dense(x)

    def train_one_batch(self, inputs, labels):
        """labels: list of (batch,) class-id tensors, one per step."""
        out = self.forward(inputs)
        y = autograd.cat(labels, axis=0)
        onehot = autograd.onehot(-1, y, self.vocab_size)
        loss = autograd.softmax_cross_entropy(out, onehot)
        self.optimizer(loss)
        return out, loss

    def get_states(self):
        ret = super().get_states()
        if self._states_ready:
            ret["hx"] = self.hx
            ret["cx"] = self.cx
        return ret

    def set_states(self, states):
        if self._states_ready:
            if "hx" in states:
                self.hx.copy_from(states["hx"])
            if "cx" in states:
                self.cx.copy_from(states["cx"])
        elif "hx" in states or "cx" in states:
            # fresh model: stash the recurrent state until the lazily
            # created hx/cx exist (checkpoint-resume must not drop it)
            self._pending_states = (states.get("hx"), states.get("cx"))
        super().set_states(states)


def sample(model, start_ids, vocab_size, nsamples=100, use_max=False,
           seed=0):
    """Autoregressive sampling (reference char_rnn.py sample:164)."""
    rng = np.random.RandomState(seed)
    ids = list(start_ids)
    out_ids = []
    # re-run with batch 1; borrow the layer weights via step_forward
    h = Tensor(data=np.zeros((1, model.hidden_size), np.float32),
               requires_grad=False)
    c = Tensor(data=np.zeros((1, model.hidden_size), np.float32),
               requires_grad=False)
    for i in ids:
        x = Tensor(data=np.eye(vocab_size, dtype=np.float32)[[i]],
                   requires_grad=False)
        h, c = model.rnn.step_forward(x, h, c)
    for _ in range(nsamples):
        logits = model.dense(h)
        probs = np.asarray(
            tensor.softmax(logits).numpy()).ravel()
        cur = int(np.argmax(probs)) if use_max else \
            int(rng.choice(vocab_size, p=probs / probs.sum()))
        out_ids.append(cur)
        x = Tensor(data=np.eye(vocab_size, dtype=np.float32)[[cur]],
                   requires_grad=False)
        h, c = model.rnn.step_forward(x, h, c)
    return out_ids


def create_model(vocab_size=101, hidden_size=32, **kwargs):
    return CharRNN(vocab_size, hidden_size, **kwargs)


__all__ = ["CharRNN", "sample", "create_model"]
