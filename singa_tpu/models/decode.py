"""The ONE token-sampling path every decode surface shares.

Greedy / temperature / top-k sampling used to live three times — the
char-rnn sampler, the transformer's KV-cache ``generate()``, and (with
this PR) the serving engine would have made a fourth. One wrong-by-one
top-k cut in any copy silently changes what a model says, so the rule
is: the math lives HERE, tested once, and every caller — the examples,
``CharRNN.sample``, ``TransformerLM.generate``, and
``singa_tpu.serving`` — routes through it.

Two variants with identical semantics:

- :func:`sample_logits` — host-side numpy, one logits vector -> one
  token id. What the serving engine uses per slot (per-request
  temperature/top_k/rng without retracing the decode program) and what
  the char-rnn sampler uses.
- :func:`sample_logits_jax` — the traced form for in-graph decode loops
  (``TransformerLM.generate``'s ``lax.scan``). ``temperature``/``top_k``
  are static python values there (they key the jit cache, as before).

``temperature == 0`` is greedy argmax in both. Ties break toward the
lowest id (argmax semantics) in both, so greedy host and traced decode
agree exactly.
"""

from __future__ import annotations

import numpy as np


def apply_top_k(logits, top_k):
    """Mask everything below the k-th largest logit to -inf (numpy,
    last axis). ``top_k`` of None/0 or >= vocab is a no-op."""
    logits = np.asarray(logits, np.float64)
    k = int(top_k or 0)
    if k <= 0 or k >= logits.shape[-1]:
        return logits
    kth = np.sort(logits, axis=-1)[..., -k][..., None]
    return np.where(logits < kth, -np.inf, logits)


def sample_logits(logits, temperature=1.0, top_k=None, rng=None):
    """Sample ONE token id from a 1-D logits vector (host side).

    ``temperature == 0`` -> greedy argmax (``rng`` unused). Otherwise
    softmax sampling at ``temperature`` over the ``top_k`` largest
    logits (None/0 = full vocab), drawing from ``rng`` (a
    ``numpy.random.RandomState``; a fresh seed-0 state when omitted, so
    callers wanting reproducibility pass their own)."""
    logits = np.asarray(logits, np.float64).reshape(-1)
    if temperature == 0:
        return int(np.argmax(logits))
    lg = apply_top_k(logits / float(temperature), top_k)
    lg = lg - np.max(lg)
    p = np.exp(lg)
    p = p / p.sum()
    if rng is None:
        rng = np.random.RandomState(0)
    return int(rng.choice(len(p), p=p))


def sample_logits_jax(logits, temperature, top_k, key):
    """Traced twin of :func:`sample_logits` over the LAST axis of
    ``logits`` (any leading batch dims). ``temperature``/``top_k`` are
    static python values; ``key`` a jax PRNG key. Returns int32 ids."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    if temperature == 0:
        return jnp.argmax(logits, -1).astype(jnp.int32)
    lg = logits / temperature
    if top_k and int(top_k) < logits.shape[-1]:
        kth = lax.top_k(lg, int(top_k))[0][..., -1:]
        lg = jnp.where(lg < kth, -jnp.inf, lg)
    return jax.random.categorical(key, lg, -1).astype(jnp.int32)


def ngram_propose(history, k, n=2):
    """Self-drafting n-gram proposer for speculative decoding: up to
    ``k`` draft tokens guessed from the sequence's OWN history (prompt
    + generated so far), zero model calls.

    Finds the most recent earlier occurrence of the trailing ``n``-gram
    and proposes its continuation; pads by repeating the last proposed
    (or last history) token. Pure and deterministic — draft quality
    only moves the speculative accept RATE, never the output: the
    verify program's accept/reject walk guarantees token-for-token
    identity with sequential greedy decoding regardless of what is
    proposed here."""
    k = int(k)
    if k <= 0:
        return []
    h = [int(t) for t in history]
    out = []
    if len(h) > n:
        tail = tuple(h[-n:])
        for i in range(len(h) - n - 1, -1, -1):
            if tuple(h[i:i + n]) == tail:
                out = h[i + n:i + n + k]
                break
    while len(out) < k:
        out.append(out[-1] if out else h[-1])
    return out[:k]


__all__ = ["apply_top_k", "sample_logits", "sample_logits_jax",
           "ngram_propose"]
