"""Xception (reference examples/cnn/model/xceptionnet.py, the standard
Xception architecture built from SeparableConv2d blocks)."""

from .. import layer, model
from . import TrainStepMixin


class Block(layer.Layer):

    def __init__(self, out_filters, reps, strides=1,
                 padding=0, start_with_relu=True, grow_first=True):
        super().__init__()
        self.out_filters = out_filters
        self.reps = reps
        self.strides = strides
        self.padding = padding
        self.start_with_relu = start_with_relu
        self.grow_first = grow_first
        self.skip = None
        self.skipbn = None
        self._need_skip = None

    def initialize(self, x):
        in_filters = x.shape[1]
        self._need_skip = (self.out_filters != in_filters
                           or self.strides != 1)
        if self._need_skip:
            self.skip = layer.Conv2d(self.out_filters, 1,
                                     stride=self.strides, bias=False)
            self.skipbn = layer.BatchNorm2d()
        seq = []
        filters = in_filters
        if self.grow_first:
            seq.append(layer.ReLU())
            seq.append(layer.SeparableConv2d(self.out_filters, 3,
                                             stride=1, padding=1,
                                             bias=False))
            seq.append(layer.BatchNorm2d())
            filters = self.out_filters
        for _ in range(self.reps - 1):
            seq.append(layer.ReLU())
            seq.append(layer.SeparableConv2d(filters, 3, stride=1,
                                             padding=1, bias=False))
            seq.append(layer.BatchNorm2d())
        if not self.grow_first:
            seq.append(layer.ReLU())
            seq.append(layer.SeparableConv2d(self.out_filters, 3,
                                             stride=1, padding=1,
                                             bias=False))
            seq.append(layer.BatchNorm2d())
        if not self.start_with_relu:
            seq = seq[1:]
        else:
            seq[0] = layer.ReLU()
        if self.strides != 1:
            seq.append(layer.MaxPool2d(3, self.strides, self.padding + 1))
        self.seq = seq
        self.add = layer.Add()

    def forward(self, x):
        y = x
        for s in self.seq:
            y = s(y)
        if self._need_skip:
            skip = self.skipbn(self.skip(x))
        else:
            skip = x
        return self.add(y, skip)


class Xception(model.Model, TrainStepMixin):
    """Xception V1 (10.5281/zenodo.4012456 architecture; reference
    examples/cnn/model/xceptionnet.py:113-294)."""

    def __init__(self, num_classes=10, num_channels=3):
        super().__init__()
        self.num_classes = num_classes
        self.input_size = 299
        self.dimension = 4

        self.conv1 = layer.Conv2d(32, 3, stride=2, padding=0, bias=False)
        self.bn1 = layer.BatchNorm2d()
        self.relu1 = layer.ReLU()
        self.conv2 = layer.Conv2d(64, 3, stride=1, padding=1, bias=False)
        self.bn2 = layer.BatchNorm2d()
        self.relu2 = layer.ReLU()

        self.block1 = Block(128, 2, 2, padding=0, start_with_relu=False)
        self.block2 = Block(256, 2, 2, padding=0)
        self.block3 = Block(728, 2, 2, padding=0)
        self.block4 = Block(728, 3, 1)
        self.block5 = Block(728, 3, 1)
        self.block6 = Block(728, 3, 1)
        self.block7 = Block(728, 3, 1)
        self.block8 = Block(728, 3, 1)
        self.block9 = Block(728, 3, 1)
        self.block10 = Block(728, 3, 1)
        self.block11 = Block(728, 3, 1)
        self.block12 = Block(1024, 2, 2, grow_first=False)

        self.conv3 = layer.SeparableConv2d(1536, 3, stride=1, padding=1)
        self.bn3 = layer.BatchNorm2d()
        self.relu3 = layer.ReLU()
        self.conv4 = layer.SeparableConv2d(2048, 3, stride=1, padding=1)
        self.bn4 = layer.BatchNorm2d()
        self.relu4 = layer.ReLU()
        self.globalpooling = layer.MaxPool2d(10, 1)
        self.flatten = layer.Flatten()
        self.fc = layer.Linear(num_classes)
        self.softmax_cross_entropy = layer.SoftMaxCrossEntropy()

    def features(self, x):
        y = self.relu1(self.bn1(self.conv1(x)))
        y = self.relu2(self.bn2(self.conv2(y)))
        for i in range(1, 13):
            y = getattr(self, f"block{i}")(y)
        y = self.relu3(self.bn3(self.conv3(y)))
        y = self.relu4(self.bn4(self.conv4(y)))
        return y

    def logits(self, features):
        return self.fc(self.flatten(self.globalpooling(features)))

    def forward(self, x):
        return self.logits(self.features(x))

    def train_one_batch(self, x, y, dist_option="plain", spars=None,
                    rotation=None):
        out = self.forward(x)
        loss = self.softmax_cross_entropy(out, y)
        self._apply_optimizer(loss, dist_option, spars, rotation)
        return out, loss


def create_model(pretrained=False, **kwargs):
    return Xception(**kwargs)


__all__ = ["Xception", "Block", "create_model"]
