"""MLP model (reference examples/mlp/model.py)."""

from .. import layer, model
from . import TrainStepMixin


class MLP(model.Model, TrainStepMixin):

    def __init__(self, data_size=10, perceptron_size=100, num_classes=10):
        super().__init__()
        self.num_classes = num_classes
        self.dimension = 2
        self.relu = layer.ReLU()
        self.linear1 = layer.Linear(perceptron_size)
        self.linear2 = layer.Linear(num_classes)
        self.softmax_cross_entropy = layer.SoftMaxCrossEntropy()

    def forward(self, inputs):
        y = self.linear1(inputs)
        y = self.relu(y)
        return self.linear2(y)

    def train_one_batch(self, x, y, dist_option="plain", spars=None,
                    rotation=None):
        out = self.forward(x)
        loss = self.softmax_cross_entropy(out, y)
        self._apply_optimizer(loss, dist_option, spars, rotation)
        return out, loss


def create_model(pretrained=False, **kwargs):
    return MLP(**kwargs)


__all__ = ["MLP", "create_model"]
