"""Restricted Boltzmann Machine trained with contrastive divergence.

Capability parity with the reference RBM example (examples/rbm/train.py:
60-120): CD-1 — positive phase, Bernoulli hidden sample, negative
(reconstruction) phase, and manual gradient assembly applied through the
optimizer — expressed on our tensor surface. TPU-first: the whole CD-1
step is one jittable function of (weights, visible batch, rng), so it
compiles to a single XLA program instead of the reference's per-op
kernel launches.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .. import tensor
from ..tensor import Tensor


class RBM:
    """Bernoulli-Bernoulli RBM (vdim visible, hdim hidden units)."""

    def __init__(self, vdim=784, hdim=1000, device=None):
        self.vdim, self.hdim = vdim, hdim
        self.w = Tensor(shape=(vdim, hdim), device=device,
                        requires_grad=False)
        self.w.gaussian(0.0, 0.1)
        self.vb = Tensor(shape=(vdim,), device=device, requires_grad=False)
        self.hb = Tensor(shape=(hdim,), device=device, requires_grad=False)
        self.w.name, self.vb.name, self.hb.name = "w", "vb", "hb"
        self._jit_cd1 = None

    # -- phases (reference train.py:80-104) --------------------------------
    def _cd1(self, w, vb, hb, data, key):
        poshidprob = jax.nn.sigmoid(data @ w + hb)
        rand = jax.random.uniform(key, poshidprob.shape)
        poshidsample = (poshidprob > rand).astype(jnp.float32)

        negdata = jax.nn.sigmoid(poshidsample @ w.T + vb)
        neghidprob = jax.nn.sigmoid(negdata @ w + hb)

        gw = negdata.T @ neghidprob - data.T @ poshidprob
        gvb = jnp.sum(negdata, 0) - jnp.sum(data, 0)
        ghb = jnp.sum(neghidprob, 0) - jnp.sum(poshidprob, 0)
        err = jnp.sum(jnp.square(data - negdata))
        return gw, gvb, ghb, err

    def train_on_batch(self, optimizer, data):
        """One CD-1 update; returns the reconstruction error
        (reference train.py:78-107)."""
        arr = data.data if isinstance(data, Tensor) else jnp.asarray(data)
        if self._jit_cd1 is None:
            self._jit_cd1 = jax.jit(self._cd1)
        key = self.w.device.rand_key() if self.w.device else \
            jax.random.PRNGKey(np.random.randint(1 << 31))
        gw, gvb, ghb, err = self._jit_cd1(self.w.data, self.vb.data,
                                          self.hb.data, arr, key)
        optimizer.apply("w", self.w, Tensor(data=gw, requires_grad=False))
        optimizer.apply("vb", self.vb,
                        Tensor(data=gvb, requires_grad=False))
        optimizer.apply("hb", self.hb,
                        Tensor(data=ghb, requires_grad=False))
        optimizer.step()
        return float(err)

    def reconstruct(self, data):
        """v -> h sample -> v' (the validation pass, train.py:111-124)."""
        tdata = data if isinstance(data, Tensor) else \
            Tensor(data=np.asarray(data, np.float32), requires_grad=False)
        prob = tensor.sigmoid(tensor.mult(tdata, self.w) + self.hb)
        rnd = Tensor(shape=prob.shape, device=prob.device,
                     requires_grad=False)
        rnd.uniform(0.0, 1.0)
        sample = tensor.gt(prob, rnd)
        recon = tensor.sigmoid(tensor.mult(sample, self.w.T()) + self.vb)
        return recon

    def reconstruction_error(self, data):
        recon = self.reconstruct(data)
        arr = data.data if isinstance(data, Tensor) else jnp.asarray(data)
        return float(jnp.sum(jnp.square(arr - recon.data)))

    # -- persistence --------------------------------------------------------
    def get_states(self):
        return {"w": self.w, "vb": self.vb, "hb": self.hb}

    def set_states(self, states):
        for k, t in self.get_states().items():
            if k in states:
                t.copy_from(states[k])


def create_model(vdim=784, hdim=1000, **kwargs):
    return RBM(vdim=vdim, hdim=hdim, **kwargs)


__all__ = ["RBM", "create_model"]
