"""AlexNet (reference examples/cnn/model/alexnet.py)."""

from .. import layer, model
from . import TrainStepMixin


class AlexNet(model.Model, TrainStepMixin):

    def __init__(self, num_classes=10, num_channels=1):
        super().__init__()
        self.num_classes = num_classes
        self.input_size = 224
        self.dimension = 4
        self.conv1 = layer.Conv2d(64, 11, stride=4, padding=2)
        self.conv2 = layer.Conv2d(192, 5, padding=2)
        self.conv3 = layer.Conv2d(384, 3, padding=1)
        self.conv4 = layer.Conv2d(256, 3, padding=1)
        self.conv5 = layer.Conv2d(256, 3, padding=1)
        self.linear1 = layer.Linear(4096)
        self.linear2 = layer.Linear(4096)
        self.linear3 = layer.Linear(num_classes)
        self.pooling1 = layer.MaxPool2d(2, 2, padding=0)
        self.pooling2 = layer.MaxPool2d(2, 2, padding=0)
        self.pooling3 = layer.MaxPool2d(2, 2, padding=0)
        self.avg_pooling1 = layer.AvgPool2d(3, 2, padding=0)
        self.relu1 = layer.ReLU()
        self.relu2 = layer.ReLU()
        self.relu3 = layer.ReLU()
        self.relu4 = layer.ReLU()
        self.relu5 = layer.ReLU()
        self.relu6 = layer.ReLU()
        self.relu7 = layer.ReLU()
        self.flatten = layer.Flatten()
        self.dropout1 = layer.Dropout()
        self.dropout2 = layer.Dropout()
        self.softmax_cross_entropy = layer.SoftMaxCrossEntropy()

    def forward(self, x):
        y = self.pooling1(self.relu1(self.conv1(x)))
        y = self.pooling2(self.relu2(self.conv2(y)))
        y = self.relu3(self.conv3(y))
        y = self.relu4(self.conv4(y))
        y = self.avg_pooling1(self.relu5(self.conv5(y)))
        y = self.flatten(y)
        y = self.dropout1(y)
        y = self.relu6(self.linear1(y))
        y = self.dropout2(y)
        y = self.relu7(self.linear2(y))
        return self.linear3(y)

    def train_one_batch(self, x, y, dist_option="plain", spars=None,
                    rotation=None):
        out = self.forward(x)
        loss = self.softmax_cross_entropy(out, y)
        self._apply_optimizer(loss, dist_option, spars, rotation)
        return out, loss


def create_model(pretrained=False, **kwargs):
    return AlexNet(**kwargs)


__all__ = ["AlexNet", "create_model"]
