"""ShuffleNetV2 (the capability behind reference
examples/onnx/shufflenetv1.py / shufflenetv2.py, built natively on the
TPU-native layer API).

Channel split + shuffle units: the shuffle is a reshape/transpose pair that
XLA compiles to a free layout change; depthwise 3x3 convs use
``Conv2d(group=channels)``.
"""

from .. import autograd, layer, model
from . import TrainStepMixin

# width multiplier -> (stage repeats, stage out-channels, final conv)
CFGS = {
    "0.5": ((4, 8, 4), (48, 96, 192), 1024),
    "1.0": ((4, 8, 4), (116, 232, 464), 1024),
    "1.5": ((4, 8, 4), (176, 352, 704), 1024),
    "2.0": ((4, 8, 4), (244, 488, 976), 2048),
}


def channel_shuffle(x, groups=2):
    b, c, h, w = x.shape
    x = autograd.reshape(x, (b, groups, c // groups, h, w))
    x = autograd.transpose(x, (0, 2, 1, 3, 4))
    return autograd.reshape(x, (b, c, h, w))


class ShuffleUnit(layer.Layer):
    """Stride-1 unit: split channels in half, transform one branch,
    concat, shuffle."""

    def __init__(self, channels):
        super().__init__()
        half = channels // 2
        self.conv1 = layer.Conv2d(half, 1, bias=False)
        self.bn1 = layer.BatchNorm2d()
        self.relu1 = layer.ReLU()
        self.dwconv = layer.Conv2d(half, 3, padding=1, group=half,
                                   bias=False)
        self.bn2 = layer.BatchNorm2d()
        self.conv3 = layer.Conv2d(half, 1, bias=False)
        self.bn3 = layer.BatchNorm2d()
        self.relu3 = layer.ReLU()
        self.cat = layer.Cat(axis=1)

    def forward(self, x):
        x1, x2 = autograd.split(x, 1, num_output=2)
        y = self.relu1(self.bn1(self.conv1(x2)))
        y = self.bn2(self.dwconv(y))
        y = self.relu3(self.bn3(self.conv3(y)))
        return channel_shuffle(self.cat([x1, y]))


class ShuffleDownUnit(layer.Layer):
    """Stride-2 unit: both branches transform, spatial size halves,
    channels grow to ``out_channels``."""

    def __init__(self, out_channels):
        super().__init__()
        half = out_channels // 2
        # branch 1 (shortcut): dw3x3 s2 + 1x1
        self.b1_dw = None  # depthwise needs in_channels; deferred
        self.half = half
        self.b1_bn1 = layer.BatchNorm2d()
        self.b1_conv = layer.Conv2d(half, 1, bias=False)
        self.b1_bn2 = layer.BatchNorm2d()
        self.b1_relu = layer.ReLU()
        # branch 2: 1x1 + dw3x3 s2 + 1x1
        self.b2_conv1 = layer.Conv2d(half, 1, bias=False)
        self.b2_bn1 = layer.BatchNorm2d()
        self.b2_relu1 = layer.ReLU()
        self.b2_dw = layer.Conv2d(half, 3, stride=2, padding=1,
                                  group=half, bias=False)
        self.b2_bn2 = layer.BatchNorm2d()
        self.b2_conv3 = layer.Conv2d(half, 1, bias=False)
        self.b2_bn3 = layer.BatchNorm2d()
        self.b2_relu3 = layer.ReLU()
        self.cat = layer.Cat(axis=1)

    def initialize(self, x):
        inp = x.shape[1]
        self.b1_dw = layer.Conv2d(inp, 3, stride=2, padding=1, group=inp,
                                  bias=False)

    def forward(self, x):
        s = self.b1_relu(self.b1_bn2(self.b1_conv(
            self.b1_bn1(self.b1_dw(x)))))
        y = self.b2_relu1(self.b2_bn1(self.b2_conv1(x)))
        y = self.b2_bn2(self.b2_dw(y))
        y = self.b2_relu3(self.b2_bn3(self.b2_conv3(y)))
        return channel_shuffle(self.cat([s, y]))


class ShuffleNetV2(model.Model, TrainStepMixin):

    def __init__(self, width="1.0", num_classes=10, num_channels=3):
        super().__init__()
        self.num_classes = num_classes
        self.input_size = 224
        self.dimension = 4
        repeats, channels, final_ch = CFGS[str(width)]
        self.stem_conv = layer.Conv2d(24, 3, stride=2, padding=1,
                                      bias=False)
        self.stem_bn = layer.BatchNorm2d()
        self.stem_relu = layer.ReLU()
        self.stem_pool = layer.MaxPool2d(3, 2, 1)
        blocks = []
        for n, ch in zip(repeats, channels):
            blocks.append(ShuffleDownUnit(ch))
            for _ in range(n - 1):
                blocks.append(ShuffleUnit(ch))
        self.blocks = blocks
        self.head_conv = layer.Conv2d(final_ch, 1, bias=False)
        self.head_bn = layer.BatchNorm2d()
        self.head_relu = layer.ReLU()
        self.fc = layer.Linear(num_classes)
        self.softmax_cross_entropy = layer.SoftMaxCrossEntropy()

    def forward(self, x):
        x = self.stem_pool(self.stem_relu(self.stem_bn(self.stem_conv(x))))
        for b in self.blocks:
            x = b(x)
        x = self.head_relu(self.head_bn(self.head_conv(x)))
        x = autograd.reduce_mean(x, axes=[2, 3], keepdims=0)
        return self.fc(x)

    def train_one_batch(self, x, y, dist_option="plain", spars=None,
                    rotation=None):
        out = self.forward(x)
        loss = self.softmax_cross_entropy(out, y)
        self._apply_optimizer(loss, dist_option, spars, rotation)
        return out, loss


def create_model(pretrained=False, width="1.0", **kwargs):
    return ShuffleNetV2(width=width, **kwargs)


__all__ = ["ShuffleNetV2", "ShuffleUnit", "ShuffleDownUnit",
           "create_model", "channel_shuffle"]
