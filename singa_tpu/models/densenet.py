"""DenseNet family (the capability behind reference
examples/onnx/densenet121.py, built natively on the TPU-native layer API).

Dense blocks concatenate every preceding feature map on the channel axis
(``layer.Cat``); transitions halve channels with a 1x1 conv and 2x2 average
pool. BN-ReLU-Conv ordering throughout (pre-activation).
"""

from .. import autograd, layer, model
from . import TrainStepMixin

CFGS = {
    121: (32, (6, 12, 24, 16)),
    169: (32, (6, 12, 32, 32)),
    201: (32, (6, 12, 48, 32)),
    161: (48, (6, 12, 36, 24)),
}


class DenseLayer(layer.Layer):
    """BN-ReLU-Conv1x1(bn_size*growth) -> BN-ReLU-Conv3x3(growth)."""

    def __init__(self, growth_rate, bn_size=4):
        super().__init__()
        self.bn1 = layer.BatchNorm2d()
        self.relu1 = layer.ReLU()
        self.conv1 = layer.Conv2d(bn_size * growth_rate, 1, bias=False)
        self.bn2 = layer.BatchNorm2d()
        self.relu2 = layer.ReLU()
        self.conv2 = layer.Conv2d(growth_rate, 3, padding=1, bias=False)
        self.cat = layer.Cat(axis=1)

    def forward(self, x):
        y = self.conv1(self.relu1(self.bn1(x)))
        y = self.conv2(self.relu2(self.bn2(y)))
        return self.cat([x, y])


class Transition(layer.Layer):

    def __init__(self, out_channels):
        super().__init__()
        self.bn = layer.BatchNorm2d()
        self.relu = layer.ReLU()
        self.conv = layer.Conv2d(out_channels, 1, bias=False)
        self.pool = layer.AvgPool2d(2, 2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(model.Model, TrainStepMixin):

    def __init__(self, depth=121, num_classes=10, num_channels=3,
                 num_init_features=None, bn_size=4, block_config=None,
                 growth_rate=None):
        super().__init__()
        self.num_classes = num_classes
        self.input_size = 224
        self.dimension = 4
        growth, block_cfg = CFGS[depth]
        if block_config is not None:
            block_cfg = block_config
        if growth_rate is not None:
            growth = growth_rate
        if num_init_features is None:
            num_init_features = 96 if depth == 161 else 64
        self.conv0 = layer.Conv2d(num_init_features, 7, stride=2,
                                  padding=3, bias=False)
        self.bn0 = layer.BatchNorm2d()
        self.relu0 = layer.ReLU()
        self.pool0 = layer.MaxPool2d(3, 2, 1)
        blocks = []
        ch = num_init_features
        for i, n in enumerate(block_cfg):
            for _ in range(n):
                blocks.append(DenseLayer(growth, bn_size))
                ch += growth
            if i != len(block_cfg) - 1:
                ch = ch // 2
                blocks.append(Transition(ch))
        self.blocks = blocks
        self.bn_final = layer.BatchNorm2d()
        self.relu_final = layer.ReLU()
        self.fc = layer.Linear(num_classes)
        self.softmax_cross_entropy = layer.SoftMaxCrossEntropy()

    def forward(self, x):
        x = self.pool0(self.relu0(self.bn0(self.conv0(x))))
        for b in self.blocks:
            x = b(x)
        x = self.relu_final(self.bn_final(x))
        x = autograd.reduce_mean(x, axes=[2, 3], keepdims=0)
        return self.fc(x)

    def train_one_batch(self, x, y, dist_option="plain", spars=None,
                    rotation=None):
        out = self.forward(x)
        loss = self.softmax_cross_entropy(out, y)
        self._apply_optimizer(loss, dist_option, spars, rotation)
        return out, loss


def create_model(pretrained=False, depth=121, **kwargs):
    return DenseNet(depth=depth, **kwargs)


__all__ = ["DenseNet", "DenseLayer", "Transition", "create_model"]
