"""Question-answer ranking models (answer selection).

Capability parity with the reference QAbot example
(examples/qabot/qabot_model.py): encode a question and a batch of
candidate answers with (bi)LSTMs, score by cosine similarity, and train
with margin ranking loss over (positive, negative) answer pairs. Three
encoder variants, as in the reference: last-state, mean-pool, max-pool.
"""

from __future__ import annotations

from .. import autograd, layer, model


class QAModelBase(model.Model):
    def train_one_batch(self, q, a_batch):
        sim_pos, sim_neg = self.forward(q, a_batch)
        loss = autograd.ranking_loss(sim_pos, sim_neg)
        self.optimizer(loss)
        return sim_pos, sim_neg, loss

    def _score(self, q_enc, a_enc):
        bs = q_enc.shape[0]
        a_pos, a_neg = autograd.split(a_enc, 0, [bs, bs])
        return (autograd.cossim(q_enc, a_pos),
                autograd.cossim(q_enc, a_neg))


class QAModel(QAModelBase):
    """Last-hidden-state encoders (reference qabot_model.py:46-73)."""

    def __init__(self, hidden_size, num_layers=1, bidirectional=True,
                 return_sequences=False):
        super().__init__()
        self.hidden_size = hidden_size
        self.lstm_q = layer.CudnnRNN(hidden_size=hidden_size,
                                     bidirectional=bidirectional,
                                     rnn_mode="lstm",
                                     return_sequences=return_sequences,
                                     batch_first=True)
        self.lstm_a = layer.CudnnRNN(hidden_size=hidden_size,
                                     bidirectional=bidirectional,
                                     rnn_mode="lstm",
                                     return_sequences=return_sequences,
                                     batch_first=True)

    def forward(self, q, a_batch):
        q_enc = self.lstm_q(q)[0]          # (bs, 2*hidden)
        a_enc = self.lstm_a(a_batch)[0]    # (2*bs, 2*hidden)
        return self._score(q_enc, a_enc)


class QAModel_mean(QAModelBase):
    """Mean-pool over sequence outputs (reference qabot_model.py:75-104)."""

    def __init__(self, hidden_size, bidirectional=True,
                 return_sequences=True):
        super().__init__()
        self.lstm_q = layer.CudnnRNN(hidden_size=hidden_size,
                                     bidirectional=bidirectional,
                                     rnn_mode="lstm",
                                     return_sequences=True,
                                     batch_first=True)
        self.lstm_a = layer.CudnnRNN(hidden_size=hidden_size,
                                     bidirectional=bidirectional,
                                     rnn_mode="lstm",
                                     return_sequences=True,
                                     batch_first=True)

    def forward(self, q, a_batch):
        q_seq = self.lstm_q(q)[0]          # (bs, S, 2*hidden)
        a_seq = self.lstm_a(a_batch)[0]
        q_enc = autograd.reduce_mean(q_seq, axes=[1], keepdims=0)
        a_enc = autograd.reduce_mean(a_seq, axes=[1], keepdims=0)
        return self._score(q_enc, a_enc)


class QAModel_maxpooling(QAModelBase):
    """Max-pool over sequence outputs (reference qabot_model.py:106+)."""

    def __init__(self, hidden_size, bidirectional=True,
                 return_sequences=True):
        super().__init__()
        self.lstm_q = layer.CudnnRNN(hidden_size=hidden_size,
                                     bidirectional=bidirectional,
                                     rnn_mode="lstm",
                                     return_sequences=True,
                                     batch_first=True)
        self.lstm_a = layer.CudnnRNN(hidden_size=hidden_size,
                                     bidirectional=bidirectional,
                                     rnn_mode="lstm",
                                     return_sequences=True,
                                     batch_first=True)

    def forward(self, q, a_batch):
        q_seq = self.lstm_q(q)[0]
        a_seq = self.lstm_a(a_batch)[0]
        q_enc = autograd.reduce_max(q_seq, axes=[1], keepdims=0)
        a_enc = autograd.reduce_max(a_seq, axes=[1], keepdims=0)
        return self._score(q_enc, a_enc)


class QAModel_mlp(QAModelBase):
    """Flatten + MLP encoders (reference qabot_model.py:23-44)."""

    def __init__(self, hidden_size):
        super().__init__()
        self.flat_q = layer.Flatten()
        self.flat_a = layer.Flatten()
        self.enc_q = layer.Linear(hidden_size)
        self.enc_a = layer.Linear(hidden_size)

    def forward(self, q, a_batch):
        q_enc = self.enc_q(self.flat_q(q))
        a_enc = self.enc_a(self.flat_a(a_batch))
        return self._score(q_enc, a_enc)


def create_model(kind="lstm", hidden_size=64, **kwargs):
    return {"lstm": QAModel, "mean": QAModel_mean,
            "max": QAModel_maxpooling, "mlp": QAModel_mlp}[kind](
                hidden_size, **kwargs)


__all__ = ["QAModel", "QAModel_mean", "QAModel_maxpooling", "QAModel_mlp",
           "create_model"]
