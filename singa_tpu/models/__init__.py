"""Model zoo — the reference's example model families, rebuilt on the
TPU-native layer/model API (reference examples/cnn/model/*.py,
examples/mlp/model.py).

Every model exposes ``create_model(**kwargs)`` and a ``train_one_batch``
supporting the reference's distributed options
(examples/cnn/model/cnn.py:52-70): plain | half | partialUpdate |
sparseTopK | sparseThreshold.
"""


class TrainStepMixin:
    """Shared dist-option dispatch for train_one_batch
    (reference examples/cnn/model/cnn.py:52-70)."""

    def _apply_optimizer(self, loss, dist_option="plain", spars=None,
                         rotation=None):
        if dist_option == "plain" or not hasattr(
                self.optimizer, "backward_and_update_half"):
            self.optimizer(loss)
        elif dist_option == "half":
            self.optimizer.backward_and_update_half(loss)
        elif dist_option == "fp16":
            # IEEE-fp16 wire format (reference synchHalf,
            # src/io/communicator.cc:262-299) with its overflow clip
            self.optimizer.backward_and_update_half(
                loss, clipping=True, dtype="float16")
        elif dist_option == "partialUpdate":
            # ``rotation`` (a STATIC python int, normally
            # step % world_size) keys the Model's compiled-step cache: n
            # small specializations, each issuing the all-reduce ONLY for
            # its parameter partition — the reference's communication
            # saving (opt.py:922-992). Without it the traced fallback
            # reduces every gradient and merely masks the application.
            self.optimizer.backward_and_partial_update(
                loss, rotation=rotation)
        elif dist_option == "sparseTopK":
            self.optimizer.backward_and_sparse_update(
                loss, topK=True, spars=spars)
        elif dist_option == "sparseThreshold":
            self.optimizer.backward_and_sparse_update(
                loss, topK=False, spars=spars)
        else:
            raise ValueError(f"unknown dist_option {dist_option!r}")


from . import (mlp, cnn, alexnet, resnet, xceptionnet,  # noqa: F401,E402
               transformer, gan, rbm, char_rnn, qabot,
               vgg, squeezenet, mobilenet, densenet, shufflenet,
               decode)
