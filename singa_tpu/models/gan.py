"""Generative adversarial networks (vanilla + least-squares).

Capability parity with the reference GAN examples (examples/gan/model/
gan_mlp.py GAN_MLP and lsgan.py): a cascaded generator/discriminator MLP
whose two training steps update disjoint parameter subsets by filtering
the lazily-yielded (param, grad) stream from ``autograd.backward`` on the
parameter name prefix — the same selective-update pattern, on our tape.
"""

from __future__ import annotations

from .. import autograd, layer, model


class GAN_MLP(model.Model):
    """Vanilla GAN with BCE losses (reference gan_mlp.py:25-95)."""

    loss_cls = layer.BinaryCrossEntropy

    def __init__(self, noise_size=100, feature_size=784, hidden_size=128):
        super().__init__()
        self.noise_size = noise_size
        self.feature_size = feature_size
        self.hidden_size = hidden_size

        self.gen_net_fc_0 = layer.Linear(hidden_size)
        self.gen_net_relu_0 = layer.ReLU()
        self.gen_net_fc_1 = layer.Linear(feature_size)
        self.gen_net_sigmoid_1 = layer.Sigmoid()

        self.dis_net_fc_0 = layer.Linear(hidden_size)
        self.dis_net_relu_0 = layer.ReLU()
        self.dis_net_fc_1 = layer.Linear(1)
        self.dis_net_sigmoid_1 = layer.Sigmoid()
        self.loss_fn = self.loss_cls()

    # -- nets --------------------------------------------------------------
    def forward_gen(self, x):
        y = self.gen_net_relu_0(self.gen_net_fc_0(x))
        return self.gen_net_sigmoid_1(self.gen_net_fc_1(y))

    def forward_dis(self, x):
        y = self.dis_net_relu_0(self.dis_net_fc_0(x))
        return self.dis_net_sigmoid_1(self.dis_net_fc_1(y))

    def forward(self, x):
        return self.forward_dis(self.forward_gen(x))

    # -- selective-update training steps -----------------------------------
    def _update_subset(self, loss, prefix):
        for p, g in autograd.backward(loss):
            if prefix in (p.name or ""):
                self.optimizer.apply(p.name, p, g)
        self.optimizer.step()

    def train_one_batch(self, x, y):
        """Generator step: push D(G(noise)) toward the real label, updating
        only gen_net params (reference gan_mlp.py:68-76)."""
        out = self.forward(x)
        loss = self.loss_fn(out, y)
        self._update_subset(loss, "gen_net")
        return out, loss

    def train_one_batch_dis(self, x, y):
        """Discriminator step on a real+fake batch, updating only dis_net
        params (reference gan_mlp.py:78-88)."""
        out = self.forward_dis(x)
        loss = self.loss_fn(out, y)
        self._update_subset(loss, "dis_net")
        return out, loss

    def compile_gan(self, noise, real=None):
        """Initialise + name all params so the prefix filters work.
        ``compile``'s dry forward already runs D(G(noise)), which builds
        and names both nets; ``real`` is accepted for API symmetry."""
        self.compile([noise], is_train=True, use_graph=False)


class LSGAN_MLP(GAN_MLP):
    """Least-squares GAN: MSE in place of BCE (reference lsgan.py)."""

    loss_cls = layer.MeanSquareError


def create_model(model_type="vanilla", **kwargs):
    if model_type in ("vanilla", "gan"):
        return GAN_MLP(**kwargs)
    if model_type in ("lsgan", "ls"):
        return LSGAN_MLP(**kwargs)
    raise ValueError(f"unknown GAN type {model_type!r}")


__all__ = ["GAN_MLP", "LSGAN_MLP", "create_model"]
