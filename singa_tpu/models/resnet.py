"""ResNet family (reference examples/cnn/model/resnet.py, itself the
standard torchvision ResNet architecture) on the TPU-native layer API.

This is the flagship benchmark model: ResNet-50 at batch 32, 224x224 is the
reference's headline throughput harness (examples/cnn/benchmark.py:85-87).
All convs/GEMMs lower to single MXU ops via lax; with graph (jit) mode the
whole train step is one fused XLA computation.
"""

from .. import autograd, layer, model
from ..ops.layout import use_layout
from . import TrainStepMixin


def conv3x3(planes, stride=1):
    return layer.Conv2d(planes, 3, stride=stride, padding=1, bias=False)


class BasicBlock(layer.Layer):
    expansion = 1

    def __init__(self, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = conv3x3(planes, stride)
        self.bn1 = layer.BatchNorm2d()
        self.relu1 = layer.ReLU()
        self.conv2 = conv3x3(planes)
        self.bn2 = layer.BatchNorm2d()
        self.add = layer.Add()
        self.relu2 = layer.ReLU()
        self.downsample = downsample

    def forward(self, x):
        residual = x
        out = self.relu1(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            residual = self.downsample(x)
        return self.relu2(self.add(out, residual))


class Bottleneck(layer.Layer):
    expansion = 4

    def __init__(self, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = layer.Conv2d(planes, 1, bias=False)
        self.bn1 = layer.BatchNorm2d()
        self.relu1 = layer.ReLU()
        self.conv2 = layer.Conv2d(planes, 3, stride=stride, padding=1,
                                  bias=False)
        self.bn2 = layer.BatchNorm2d()
        self.relu2 = layer.ReLU()
        self.conv3 = layer.Conv2d(planes * self.expansion, 1, bias=False)
        self.bn3 = layer.BatchNorm2d()
        self.add = layer.Add()
        self.relu3 = layer.ReLU()
        self.downsample = downsample

    def forward(self, x):
        residual = x
        out = self.relu1(self.bn1(self.conv1(x)))
        out = self.relu2(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            residual = self.downsample(x)
        return self.relu3(self.add(out, residual))


class Downsample(layer.Layer):
    """1x1 strided conv + BN on the shortcut path."""

    def __init__(self, planes, stride):
        super().__init__()
        self.conv = layer.Conv2d(planes, 1, stride=stride, bias=False)
        self.bn = layer.BatchNorm2d()

    def forward(self, x):
        return self.bn(self.conv(x))


class ResNet(model.Model, TrainStepMixin):

    def __init__(self, block, layers, num_classes=10, num_channels=3,
                 layout="NCHW", stem="conv7"):
        super().__init__()
        self.num_classes = num_classes
        self.input_size = 224
        self.dimension = 4
        # activation layout of the conv trunk. The public interface stays
        # NCHW either way; "NHWC" transposes once at the stem and runs
        # channels-last (TPU 128-lane minor dim — see ops/layout.py).
        # Weights are OIHW in both modes, so checkpoints are identical.
        self.layout = str(layout).upper()
        # stem="space_to_depth": the exact MXU-friendly reformulation of
        # the 7x7/s2 stem conv (ops/conv.py _space_to_depth_conv) —
        # same weights, same math, 12 input channels instead of 3
        if stem not in ("conv7", "space_to_depth"):
            raise ValueError(f"stem must be 'conv7' or 'space_to_depth', "
                             f"got {stem!r}")
        self.inplanes = 64
        self.conv1 = layer.Conv2d(64, 7, stride=2, padding=3, bias=False,
                                  space_to_depth=(stem == "space_to_depth"))
        self.bn1 = layer.BatchNorm2d()
        self.relu = layer.ReLU()
        self.maxpool = layer.MaxPool2d(kernel_size=3, stride=2, padding=1)
        self.layer1, l1 = self._make_layer(block, 64, layers[0])
        self.layer2, l2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3, l3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4, l4 = self._make_layer(block, 512, layers[3], stride=2)
        self.avgpool = layer.AvgPool2d(7, stride=1)
        self.flatten = layer.Flatten()
        self.fc = layer.Linear(num_classes)
        self.softmax_cross_entropy = layer.SoftMaxCrossEntropy()
        self.register_layers(*l1, *l2, *l3, *l4)

    def _make_layer(self, block, planes, num_blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = Downsample(planes * block.expansion, stride)
        blocks = [block(planes, stride, downsample)]
        self.inplanes = planes * block.expansion
        for _ in range(1, num_blocks):
            blocks.append(block(planes))

        def forward(x):
            for b in blocks:
                x = b(x)
            return x

        return forward, blocks

    def forward(self, x):
        if self.layout == "NHWC":
            # one transpose at the stem; the trunk then runs channels-last
            # end-to-end (handles capture NHWC at their deferred init).
            # After global avg-pool the spatial dims are 1x1, so flatten
            # yields the same (N, C) features as the NCHW path.
            x = autograd.transpose(x, (0, 2, 3, 1))
            with use_layout("NHWC"):
                return self._trunk(x)
        return self._trunk(x)

    def _trunk(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        x = self.flatten(self.avgpool(x))
        return self.fc(x)

    def train_one_batch(self, x, y, dist_option="plain", spars=None,
                    rotation=None):
        out = self.forward(x)
        loss = self.softmax_cross_entropy(out, y)
        self._apply_optimizer(loss, dist_option, spars, rotation)
        return out, loss

    # registered block lists live in self._registered; expose their params
    def _sublayers(self):
        subs = super()._sublayers()
        for i, b in enumerate(getattr(self, "_registered", [])):
            b.name = b.name if b.name != type(b).__name__ \
                else f"block{self.sep}{i}"
            subs.append((b.name, b))
        return subs


def resnet18(**kw):
    return ResNet(BasicBlock, [2, 2, 2, 2], **kw)


def resnet34(**kw):
    return ResNet(BasicBlock, [3, 4, 6, 3], **kw)


def resnet50(**kw):
    return ResNet(Bottleneck, [3, 4, 6, 3], **kw)


def resnet101(**kw):
    return ResNet(Bottleneck, [3, 4, 23, 3], **kw)


def resnet152(**kw):
    return ResNet(Bottleneck, [3, 8, 36, 3], **kw)


def create_model(pretrained=False, depth=50, **kwargs):
    zoo = {18: resnet18, 34: resnet34, 50: resnet50, 101: resnet101,
           152: resnet152}
    return zoo[depth](**kwargs)


__all__ = ["ResNet", "BasicBlock", "Bottleneck", "resnet18", "resnet34",
           "resnet50", "resnet101", "resnet152", "create_model"]
