"""LeNet-style CNN for MNIST (reference examples/cnn/model/cnn.py)."""

from .. import layer, model
from . import TrainStepMixin


class CNN(model.Model, TrainStepMixin):

    def __init__(self, num_classes=10, num_channels=1):
        super().__init__()
        self.num_classes = num_classes
        self.input_size = 28
        self.dimension = 4
        self.conv1 = layer.Conv2d(20, 5, padding=0, activation="RELU")
        self.conv2 = layer.Conv2d(50, 5, padding=0, activation="RELU")
        self.linear1 = layer.Linear(500)
        self.linear2 = layer.Linear(num_classes)
        self.pooling1 = layer.MaxPool2d(2, 2, padding=0)
        self.pooling2 = layer.MaxPool2d(2, 2, padding=0)
        self.relu = layer.ReLU()
        self.flatten = layer.Flatten()
        self.softmax_cross_entropy = layer.SoftMaxCrossEntropy()

    def forward(self, x):
        y = self.conv1(x)
        y = self.pooling1(y)
        y = self.conv2(y)
        y = self.pooling2(y)
        y = self.flatten(y)
        y = self.linear1(y)
        y = self.relu(y)
        return self.linear2(y)

    def train_one_batch(self, x, y, dist_option="plain", spars=None,
                    rotation=None):
        out = self.forward(x)
        loss = self.softmax_cross_entropy(out, y)
        self._apply_optimizer(loss, dist_option, spars, rotation)
        return out, loss


def create_model(pretrained=False, **kwargs):
    return CNN(**kwargs)


__all__ = ["CNN", "create_model"]
