"""SqueezeNet 1.0/1.1 (the capability behind reference
examples/onnx/squeezenet.py, built natively on the TPU-native layer API).

Fire modules: a 1x1 squeeze conv followed by parallel 1x1 and 3x3 expand
convs concatenated on channels. The final classifier is a 1x1 conv + global
average pool (no fully-connected layer).
"""

from .. import autograd, layer, model
from . import TrainStepMixin


class Fire(layer.Layer):

    def __init__(self, squeeze_planes, expand1x1_planes, expand3x3_planes):
        super().__init__()
        self.squeeze = layer.Conv2d(squeeze_planes, 1)
        self.squeeze_relu = layer.ReLU()
        self.expand1x1 = layer.Conv2d(expand1x1_planes, 1)
        self.expand1x1_relu = layer.ReLU()
        self.expand3x3 = layer.Conv2d(expand3x3_planes, 3, padding=1)
        self.expand3x3_relu = layer.ReLU()
        self.cat = layer.Cat(axis=1)

    def forward(self, x):
        x = self.squeeze_relu(self.squeeze(x))
        return self.cat([self.expand1x1_relu(self.expand1x1(x)),
                         self.expand3x3_relu(self.expand3x3(x))])


class SqueezeNet(model.Model, TrainStepMixin):

    def __init__(self, version="1.1", num_classes=10, num_channels=3):
        super().__init__()
        self.num_classes = num_classes
        self.input_size = 224
        self.dimension = 4
        if version == "1.0":
            self.stem = [layer.Conv2d(96, 7, stride=2), layer.ReLU(),
                         layer.MaxPool2d(3, 2)]
            self.blocks = [
                Fire(16, 64, 64), Fire(16, 64, 64), Fire(32, 128, 128),
                layer.MaxPool2d(3, 2),
                Fire(32, 128, 128), Fire(48, 192, 192),
                Fire(48, 192, 192), Fire(64, 256, 256),
                layer.MaxPool2d(3, 2),
                Fire(64, 256, 256),
            ]
        elif version == "1.1":
            self.stem = [layer.Conv2d(64, 3, stride=2), layer.ReLU(),
                         layer.MaxPool2d(3, 2)]
            self.blocks = [
                Fire(16, 64, 64), Fire(16, 64, 64),
                layer.MaxPool2d(3, 2),
                Fire(32, 128, 128), Fire(32, 128, 128),
                layer.MaxPool2d(3, 2),
                Fire(48, 192, 192), Fire(48, 192, 192),
                Fire(64, 256, 256), Fire(64, 256, 256),
            ]
        else:
            raise ValueError(f"unknown SqueezeNet version {version!r}")
        self.dropout = layer.Dropout(0.5)
        self.final_conv = layer.Conv2d(num_classes, 1)
        self.final_relu = layer.ReLU()
        self.softmax_cross_entropy = layer.SoftMaxCrossEntropy()

    def forward(self, x):
        for f in self.stem:
            x = f(x)
        for b in self.blocks:
            x = b(x)
        x = self.final_relu(self.final_conv(self.dropout(x)))
        # global average pool over the remaining spatial extent
        return autograd.reduce_mean(x, axes=[2, 3], keepdims=0)

    def train_one_batch(self, x, y, dist_option="plain", spars=None,
                    rotation=None):
        out = self.forward(x)
        loss = self.softmax_cross_entropy(out, y)
        self._apply_optimizer(loss, dist_option, spars, rotation)
        return out, loss


def create_model(pretrained=False, version="1.1", **kwargs):
    return SqueezeNet(version=version, **kwargs)


__all__ = ["SqueezeNet", "Fire", "create_model"]
