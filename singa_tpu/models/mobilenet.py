"""MobileNetV2 (the capability behind reference examples/onnx/mobilenet.py,
built natively on the TPU-native layer API).

Inverted-residual bottlenecks with depthwise 3x3 convolutions
(``Conv2d(group=channels)`` lowers to ``lax.conv_general_dilated`` with
``feature_group_count``) and ReLU6 activations (``autograd.clip(x, 0, 6)``).
"""

from .. import autograd, layer, model
from . import TrainStepMixin


class ReLU6(layer.Layer):

    def forward(self, x):
        return autograd.clip(x, 0.0, 6.0)


class ConvBNReLU(layer.Layer):

    def __init__(self, planes, kernel_size=3, stride=1, group=1):
        super().__init__()
        pad = (kernel_size - 1) // 2
        self.conv = layer.Conv2d(planes, kernel_size, stride=stride,
                                 padding=pad, group=group, bias=False)
        self.bn = layer.BatchNorm2d()
        self.relu = ReLU6()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class InvertedResidual(layer.Layer):

    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        seq = []
        if expand_ratio != 1:
            seq.append(ConvBNReLU(hidden, kernel_size=1))
        seq.append(ConvBNReLU(hidden, stride=stride, group=hidden))
        self.seq = seq
        self.project = layer.Conv2d(oup, 1, bias=False)
        self.project_bn = layer.BatchNorm2d()
        self.add = layer.Add()

    def forward(self, x):
        y = x
        for s in self.seq:
            y = s(y)
        y = self.project_bn(self.project(y))
        return self.add(y, x) if self.use_res else y


# (expand_ratio t, out channels c, repeats n, first stride s)
INVERTED_RESIDUAL_CFG = [
    (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
    (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
]


class MobileNetV2(model.Model, TrainStepMixin):

    def __init__(self, num_classes=10, num_channels=3, width_mult=1.0):
        super().__init__()
        self.num_classes = num_classes
        self.input_size = 224
        self.dimension = 4

        def c(ch):  # round channels to a multiple of 8 (hardware-friendly)
            ch = int(ch * width_mult)
            return max(8, (ch + 4) // 8 * 8)

        self.stem = ConvBNReLU(c(32), stride=2)
        blocks = []
        inp = c(32)
        for t, ch, n, s in INVERTED_RESIDUAL_CFG:
            for i in range(n):
                blocks.append(InvertedResidual(inp, c(ch),
                                               s if i == 0 else 1, t))
                inp = c(ch)
        self.blocks = blocks
        self.head = ConvBNReLU(max(1280, c(1280)), kernel_size=1)
        self.dropout = layer.Dropout(0.2)
        self.fc = layer.Linear(num_classes)
        self.softmax_cross_entropy = layer.SoftMaxCrossEntropy()

    def forward(self, x):
        x = self.stem(x)
        for b in self.blocks:
            x = b(x)
        x = self.head(x)
        x = autograd.reduce_mean(x, axes=[2, 3], keepdims=0)
        return self.fc(self.dropout(x))

    def train_one_batch(self, x, y, dist_option="plain", spars=None,
                    rotation=None):
        out = self.forward(x)
        loss = self.softmax_cross_entropy(out, y)
        self._apply_optimizer(loss, dist_option, spars, rotation)
        return out, loss


def create_model(pretrained=False, **kwargs):
    return MobileNetV2(**kwargs)


__all__ = ["MobileNetV2", "InvertedResidual", "ReLU6", "create_model"]
