"""Collective communication over the device mesh.

TPU-native equivalent of the reference Communicator
(src/io/communicator.cc:54-260): the NCCL ring becomes XLA collectives over
ICI, MPI/NcclIdHolder process bootstrap becomes ``jax.distributed``, and the
dedicated comm streams (c1/c2/s) plus the ``wait`` stream-join op disappear —
XLA schedules and overlaps async collectives itself.

A Communicator's ops are *context sensitive*: inside a compiled step that
the Model layer has shard_map'd over the mesh, ``all_reduce`` lowers to
``lax.psum`` on the 'data' axis; outside any mesh context it degrades to the
identity (a world of one), so single-chip scripts run unchanged.

Deprecation boundary: this module is the LEGACY explicit-collective
mechanism. The GSPMD train step (``Model.compile(mesh=...)``) traces the
same step body OUTSIDE any collective context — the identity degradation
above is exactly what lets one body serve both generations — and lets XLA
insert the gradient collectives from ``NamedSharding`` annotations. The
shard_map driver, the pipeline schedules, and sync-BN's in-graph pmeans
still run through here; new sharded code should not add collectives here
(see :func:`partitioner` and docs/distributed.md "One sharding
vocabulary").
"""

from __future__ import annotations

import contextlib
import os

import jax
import jax.numpy as jnp
from jax import lax

from .mesh import make_mesh, MeshConfig

# Axis names currently live inside a shard_map body (set by the Model layer).
_ACTIVE_AXES: list[str] = []


@contextlib.contextmanager
def collective_context(*axis_names):
    """Marks that the code within runs inside shard_map over these axes."""
    _ACTIVE_AXES.extend(axis_names)
    try:
        yield
    finally:
        for _ in axis_names:
            _ACTIVE_AXES.pop()


def active_axis(axis_name: str) -> bool:
    return axis_name in _ACTIVE_AXES


def axis_size(axis_name: str) -> int:
    """Size of a bound mesh axis. ``jax.lax.axis_size`` only exists on
    newer jax; on older versions ``psum(1, axis)`` is the idiom — and
    it constant-folds to a python int at trace time, so callers can use
    the result in static control flow either way."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


# Mesh axes the BATCH dimension is sharded over inside the current
# shard_map'd step. Cross-replica statistics (sync-BN) must reduce over
# exactly these — not a hardcoded ("data",), which silently computes
# shard-local stats when the batch also shards over 'expert'/'seq' or a
# renamed axis. The Model's step body sets this from its input specs.
_BATCH_SHARD_AXES: list[tuple] = []


@contextlib.contextmanager
def batch_shard_axes(axes):
    """Declare the mesh axes sharding the batch dim for the enclosed
    trace (normally entered by Model's compiled step body)."""
    axes = tuple(axes) if isinstance(axes, (tuple, list)) else (axes,)
    _BATCH_SHARD_AXES.append(axes)
    try:
        yield
    finally:
        _BATCH_SHARD_AXES.pop()


def active_batch_axes() -> tuple:
    """Axes cross-replica batch statistics should reduce over: the
    declared batch-shard axes (default 'data') filtered to those
    actually active."""
    axes = _BATCH_SHARD_AXES[-1] if _BATCH_SHARD_AXES else ("data",)
    return tuple(a for a in axes if a is not None and active_axis(a))


_global_mesh = None


def get_mesh(config: MeshConfig | None = None, devices=None):
    """Process-wide default mesh (built over all visible devices)."""
    global _global_mesh
    if _global_mesh is None or config is not None or devices is not None:
        _global_mesh = make_mesh(devices, config)
    return _global_mesh


def set_mesh(mesh):
    global _global_mesh
    _global_mesh = mesh


def partitioner(mesh=None, batch_axis="data", model_axis="model"):
    """Deprecation-boundary shim onto the ONE sharding vocabulary.

    The communicator's explicit-collective mechanism (shard_map +
    psum/ppermute) stays for the LEGACY training driver and the
    pipeline schedules, but layouts belong to :mod:`.gspmd`: this
    returns the shared :class:`~singa_tpu.parallel.gspmd.Partitioner`
    over the given (or process-default) mesh so code still living on
    this mechanism expresses shardings through the same specs the
    GSPMD train step and serving path use. New sharded code should
    annotate with NamedSharding via gspmd and jit — not add
    hand-rolled collectives here."""
    from .gspmd import Partitioner
    return Partitioner(mesh if mesh is not None else get_mesh(),
                       batch_axis=batch_axis, model_axis=model_axis)


class NcclIdHolder:
    """Parity stub for the reference's NcclIdHolder
    (include/singa/io/communicator.h:69): with jax.distributed the
    coordinator address plays this role."""

    def __init__(self, coordinator_address: str | None = None):
        self.coordinator_address = coordinator_address or \
            os.environ.get("JAX_COORDINATOR_ADDRESS", "localhost:12345")


def init_process(nccl_id: NcclIdHolder | None = None, rank: int = 0,
                 world: int = 1):
    """Multi-host bootstrap (replaces the reference's MPI_Bcast rank
    exchange, communicator.cc:73-103).

    On TPU pods the collectives ride ICI/DCN natively; on the CPU backend
    cross-process collectives need an explicit transport, so gloo is
    enabled best-effort (this is what makes the multi-process examples and
    tests runnable on any machine — the reference needs real GPUs+NCCL)."""
    if world > 1:
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:            # unknown option on this jax version
            pass
        jax.distributed.initialize(
            coordinator_address=(nccl_id or NcclIdHolder()).
            coordinator_address,
            num_processes=world, process_id=rank)


def rescale_batch(manifest, new_world):
    """Data-parallel batch accounting across an elastic restart.

    A checkpoint's manifest (``DistributedCheckpointManager`` commit
    marker) records the world size it was saved at plus, when the
    caller provided them, ``per_replica_batch`` / ``global_batch``. On
    resume at a different world size the invariant kept is the
    PER-REPLICA batch — each surviving host keeps its compiled step and
    its memory footprint — so the global batch scales with the world:
    ``global = per_replica * new_world``. Returns ``(per_replica,
    new_global)`` (``(None, None)`` when the manifest carries no batch
    info). Callers that instead want fixed global batch semantics can
    derive ``per_replica = global // new_world`` themselves; that
    changes the compiled step shape, which is why it is not the default.
    """
    saved_world = max(1, int(manifest.get("world", 1)))
    per = manifest.get("per_replica_batch")
    if per is None:
        gb = manifest.get("global_batch")
        if gb is None:
            return None, None
        per = max(1, int(gb) // saved_world)
    return int(per), int(per) * int(new_world)


def replica_fingerprint(arrays, axis_name="data"):
    """In-graph cross-replica parameter fingerprint (integrity layer).

    Each replica reduces every array to two cheap scalars — sum and
    sum-of-squares in f32 — stacks them into one small vector, and
    all-gathers that vector over ``axis_name``. On healthy hardware the
    gathered rows are IDENTICAL (data-parallel params are replicated
    and every replica ran the same program); a row that differs is
    silent data corruption or a non-deterministic kernel on that
    replica. Returns ``(gathered, agree)``: ``gathered`` has shape
    ``(axis_size, 2 * len(arrays))`` and ``agree`` is a scalar bool
    (all rows BITWISE-equal the first — the vectors are compared as
    int32 bit patterns, so identical computations agree even through a
    NaN, and SDC does not need a large epsilon to be seen). Outside a
    mesh context (or on an inactive axis) there is nothing to compare
    with: the local vector comes back with ``agree=True``.

    Cost: one tiny all-gather of ``2 * n_params`` f32 scalars riding
    the step's existing collectives — cheap enough to run on a cadence.
    Limitation of the lossy reduction: two replicas whose sums both
    saturate (e.g. to the same inf) from DIFFERENT values compare
    equal; the host-side counterpart for cross-PROCESS agreement —
    :func:`singa_tpu.integrity.state_fingerprint` over the cluster
    control plane — digests every byte and has no such blind spot."""
    parts = []
    for a in arrays:
        x = jnp.asarray(getattr(a, "data", a)).astype(jnp.float32)
        parts.append(jnp.sum(x))
        parts.append(jnp.sum(x * x))
    vec = jnp.stack(parts) if parts else jnp.zeros((0,), jnp.float32)
    if active_axis(axis_name):
        gathered = lax.all_gather(vec, axis_name)
        # bitwise comparison: float == would call bit-identical NaN
        # rows "divergent" (NaN != NaN) on perfectly healthy replicas
        bits = lax.bitcast_convert_type(gathered, jnp.int32)
        agree = jnp.all(bits == bits[0:1])
        return gathered, agree
    return vec[None], jnp.asarray(True)


class Communicator:
    """All-reduce (and friends) over the mesh 'data' axis.

    Reference op mapping (src/io/communicator.cc):
      synch            -> all_reduce (lax.psum)
      fusedSynch       -> unnecessary (XLA fuses/overlaps collectives)
      synchHalf        -> all_reduce of a bf16-cast value (DistOpt does it)
      sparsification   -> masked dense psum (DistOpt does it)
      wait             -> unnecessary (async collectives are data-flow
                          ordered by XLA)
    """

    def __init__(self, axis_name: str = "data", world_size=None,
                 mesh=None, reduce_axes=None):
        self.axis_name = axis_name
        # axes gradients are summed over: the data axis plus any other
        # batch-like axis (sequence parallelism splits the token batch, so
        # 'seq' joins the reduction there)
        self.reduce_axes = tuple(reduce_axes) if reduce_axes is not None \
            else (axis_name,)
        self.mesh = mesh
        self.local_rank = jax.process_index()
        self.global_rank = jax.process_index()
        if world_size is None:
            world_size = jax.device_count()
        self.world_size = int(world_size)

    def _active_reduce_axes(self, exclude=()):
        return tuple(a for a in self.reduce_axes
                     if active_axis(a) and a not in exclude)

    def effective_world_size(self, exclude=()):
        """Replica count actually participating in the current context.
        ``exclude``: axes a parameter is SHARDED over (its per-shard values
        are distinct, not replicas — e.g. expert weights on 'expert')."""
        axes = self._active_reduce_axes(exclude)
        size = 1
        for a in axes:
            size *= axis_size(a)
        return size

    # -- collectives (identity outside a mesh context) ---------------------
    def all_reduce(self, arr, exclude=()):
        axes = self._active_reduce_axes(exclude)
        if axes:
            return lax.psum(arr, axes)
        return arr

    def all_gather(self, arr, axis=0):
        if active_axis(self.axis_name):
            return lax.all_gather(arr, self.axis_name, axis=axis,
                                  tiled=True)
        return arr

    def reduce_scatter(self, arr, axis=0):
        if active_axis(self.axis_name):
            return lax.psum_scatter(arr, self.axis_name,
                                    scatter_dimension=axis, tiled=True)
        return arr

    def broadcast(self, arr, root=0):
        if active_axis(self.axis_name):
            mask = (lax.axis_index(self.axis_name) == root)
            return lax.psum(jnp.where(mask, arr, jnp.zeros_like(arr)),
                            self.axis_name)
        return arr

    def ppermute(self, arr, perm):
        if active_axis(self.axis_name):
            return lax.ppermute(arr, self.axis_name, perm)
        return arr

    def rank(self):
        if active_axis(self.axis_name):
            return lax.axis_index(self.axis_name)
        return 0

    def wait(self):
        """Parity no-op (reference communicator.cc:169-186): XLA's async
        collectives are ordered by data flow, not stream joins."""
