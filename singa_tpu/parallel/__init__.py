"""Distributed execution: device meshes + XLA collectives over ICI/DCN.

TPU-native replacement for the reference's NCCL/MPI communicator stack
(src/io/communicator.cc, include/singa/io/communicator.h): process bootstrap
via ``jax.distributed`` (replacing MPI rank exchange / NcclIdHolder), and
data movement via mesh collectives (psum/all_gather/ppermute/reduce_scatter)
that XLA schedules over ICI.
"""

from .communicator import (Communicator, NcclIdHolder, get_mesh,
                           collective_context, active_axis)
from .mesh import make_mesh, MeshConfig

__all__ = ["Communicator", "NcclIdHolder", "get_mesh", "collective_context",
           "active_axis", "make_mesh", "MeshConfig"]
