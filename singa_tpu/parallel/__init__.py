"""Distributed execution: device meshes + XLA collectives over ICI/DCN.

TPU-native replacement for the reference's NCCL/MPI communicator stack
(src/io/communicator.cc, include/singa/io/communicator.h): process bootstrap
via ``jax.distributed`` (replacing MPI rank exchange / NcclIdHolder), and
data movement via mesh collectives (psum/all_gather/ppermute/reduce_scatter)
that XLA schedules over ICI.
"""

from .communicator import (Communicator, NcclIdHolder, get_mesh,
                           collective_context, active_axis)
from .mesh import make_mesh, MeshConfig
from .ops import (all_reduce, all_gather, reduce_scatter, pmean,
                  copy_to_parallel, all_to_all)
from .tensor_parallel import (ColumnParallelLinear, RowParallelLinear,
                              TPMLP)
from .pipeline import pipeline_spmd, stack_stage_params, microbatch
from .moe import MoEFFN
from .gspmd import (Partitioner, ShardingDecline, serving_mesh,
                    serving_partitioner)

__all__ = ["Communicator", "NcclIdHolder", "get_mesh", "collective_context",
           "active_axis", "make_mesh", "MeshConfig",
           "all_reduce", "all_gather", "reduce_scatter", "pmean",
           "copy_to_parallel", "all_to_all", "MoEFFN",
           "ColumnParallelLinear", "RowParallelLinear", "TPMLP",
           "pipeline_spmd", "stack_stage_params", "microbatch",
           "Partitioner", "ShardingDecline", "serving_mesh",
           "serving_partitioner"]
