"""Tensor (model) parallel layers — Megatron-style column/row splits.

No reference equivalent (the reference is data-parallel only, SURVEY.md
§2.4); this is the TPU-native extension that shards the weight matmuls
over the mesh 'model' axis so a layer larger than one chip's HBM still
runs, with exactly one psum per column→row pair riding the ICI.

How it composes with the Model layer: weights are created full-size and
announce their layout via ``Tensor.spec``; the compiled step's shard_map
passes each device its shard, the tape traces local-shape matmuls, and
the `RowParallelLinear` output all-reduce is the only cross-chip traffic.
Outside shard_map (eager or single chip) the collectives degrade to
identity and the same code computes the full matmul.
"""

from __future__ import annotations

import math

from .. import autograd
from ..layer import Layer, _param
from . import ops as collective
# layouts come from the ONE sharding vocabulary (parallel/gspmd.py) so
# the shard_map training mechanism and GSPMD serving can never disagree
# about what "column/row/vocab-parallel" means
from .gspmd import col_bias_spec, col_spec, row_spec, vocab_spec


class ColumnParallelLinear(Layer):
    """y_local = x @ W[:, shard] — output features sharded over 'model'.

    Feed its output into a :class:`RowParallelLinear` (no gather needed)
    or set ``gather_output=True`` to return the full feature dim.
    """

    def __init__(self, out_features, bias=True, gather_output=False,
                 axis_name="model"):
        super().__init__()
        self.out_features = out_features
        self.bias = bias
        self.gather_output = gather_output
        self.axis_name = axis_name

    def initialize(self, x):
        in_features = x.shape[-1]
        # params follow the input dtype (bf16 activations -> bf16 W),
        # same contract as layer.Linear
        self.W = _param((in_features, self.out_features), x.device,
                        dtype=x.dtype)
        std = math.sqrt(2.0 / (in_features + self.out_features))
        self.W.gaussian(0.0, std)
        self.W.spec = col_spec(self.axis_name)
        if self.bias:
            self.b = _param((self.out_features,), x.device, dtype=x.dtype)
            self.b.spec = col_bias_spec(self.axis_name)

    def _sharded(self):
        # inside shard_map the payload is the LOCAL shard; a full-width W
        # means the spec was dropped (no mesh, or out_features does not
        # divide the axis — Model._fit_state_spec) and every collective
        # here must vanish or it would double-count
        return self.W.shape[-1] < self.out_features

    def forward(self, x):
        if self._sharded():
            # Megatron "f": identity fwd, all-reduce bwd — each shard
            # produces only its slice's contribution to dx
            x = collective.copy_to_parallel(x, self.axis_name)
        y = autograd.matmul(x, self.W)
        if self.bias:
            y = autograd.add_bias(y, self.b, axis=0)
        if self.gather_output and self._sharded():
            y = collective.all_gather(y, self.axis_name, concat_axis=-1)
        return y

    def _own_params(self):
        p = {"W": self.W}
        if self.bias:
            p["b"] = self.b
        return p


class RowParallelLinear(Layer):
    """y = psum_model(x_local @ W[shard, :]) + b — input features sharded.

    Takes the sharded activations a ColumnParallelLinear produced; the
    single all-reduce here completes the logical full matmul.
    """

    def __init__(self, out_features, bias=True, axis_name="model"):
        super().__init__()
        self.out_features = out_features
        self.bias = bias
        self.axis_name = axis_name

    def initialize(self, x):
        # x carries the LOCAL shard width when tracing inside shard_map,
        # but initialize runs on the eager (full) pass, so this is the
        # full input width
        in_features = x.shape[-1]
        self.in_features = in_features
        self.W = _param((in_features, self.out_features), x.device,
                        dtype=x.dtype)
        std = math.sqrt(2.0 / (in_features + self.out_features))
        self.W.gaussian(0.0, std)
        self.W.spec = row_spec(self.axis_name)
        if self.bias:
            # replicated
            self.b = _param((self.out_features,), x.device, dtype=x.dtype)

    def forward(self, x):
        y = autograd.matmul(x, self.W)
        if self.W.shape[0] < self.in_features:   # rows actually sharded
            y = collective.all_reduce(y, self.axis_name)
        if self.bias:
            y = autograd.add_bias(y, self.b, axis=0)
        return y

    def _own_params(self):
        p = {"W": self.W}
        if self.bias:
            p["b"] = self.b
        return p


class _MaskedLookup(autograd.Operator):
    """Rank-local slice of an embedding lookup: rows of the LOCAL vocab
    shard for ids that land in this rank's range, zeros elsewhere. The
    enclosing all-reduce (pinned identity backward) completes the lookup;
    this op's own vjp scatter-adds only into the local rows, so no psum
    ever appears inside a transposed region."""

    def __init__(self, axis_name, full_rows):
        super().__init__()
        self.axis_name = axis_name
        self.full_rows = full_rows

    def forward(self, ids, W):
        import jax
        from jax import lax as jlax
        import jax.numpy as jnp
        from .communicator import active_axis
        idi = jax.lax.stop_gradient(ids).astype(jnp.int32)
        # W at full row count means the spec was dropped (no mesh, or an
        # indivisible vocab): offset 0 and no masking — a plain lookup
        if active_axis(self.axis_name) and W.shape[0] < self.full_rows:
            idi = idi - jlax.axis_index(self.axis_name) * W.shape[0]
        hit = (idi >= 0) & (idi < W.shape[0])
        rows = jnp.take(W, jnp.clip(idi, 0, W.shape[0] - 1), axis=0)
        return jnp.where(hit[..., None], rows, 0.0)


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab rows sharded over the 'model' axis —
    Megatron's VocabParallelEmbedding, the input-side twin of a
    vocab-sharded LM head. Each rank stores V/tp rows; a lookup is a
    masked local take + one all-reduce. Degrades to a plain
    :class:`~singa_tpu.layer.Embedding` outside a mesh (same state-dict
    layout: one full-shape ``W``). Scales the capability at reference
    python/singa/layer.py Embedding to vocabularies larger than one
    chip's HBM slice."""

    def __init__(self, input_dim, output_dim, axis_name="model"):
        super().__init__()
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.axis_name = axis_name

    def initialize(self, x):
        self.W = _param((self.input_dim, self.output_dim), x.device)
        self.W.gaussian(0.0, 0.02)
        self.W.spec = vocab_spec(self.axis_name)

    def _sharded(self):
        return self.W.shape[0] < self.input_dim  # rows actually sharded

    def forward(self, x):
        y = _MaskedLookup(self.axis_name, self.input_dim)(x, self.W)
        if self._sharded():
            y = collective.all_reduce(y, self.axis_name)
        return y

    def _own_params(self):
        return {"W": self.W}


class TPMLP(Layer):
    """Column→activation→Row two-layer MLP: one all-reduce total."""

    def __init__(self, hidden_features, out_features, activation="relu",
                 axis_name="model"):
        super().__init__()
        self.up = ColumnParallelLinear(hidden_features,
                                       axis_name=axis_name)
        self.down = RowParallelLinear(out_features, axis_name=axis_name)
        self.activation = activation

    def forward(self, x):
        h = self.up(x)
        h = getattr(autograd, self.activation)(h)
        return self.down(h)
