"""Tensor (model) parallel layers — Megatron-style column/row splits.

No reference equivalent (the reference is data-parallel only, SURVEY.md
§2.4); this is the TPU-native extension that shards the weight matmuls
over the mesh 'model' axis so a layer larger than one chip's HBM still
runs, with exactly one psum per column→row pair riding the ICI.

How it composes with the Model layer: weights are created full-size and
announce their layout via ``Tensor.spec``; the compiled step's shard_map
passes each device its shard, the tape traces local-shape matmuls, and
the `RowParallelLinear` output all-reduce is the only cross-chip traffic.
Outside shard_map (eager or single chip) the collectives degrade to
identity and the same code computes the full matmul.
"""

from __future__ import annotations

import math

from jax.sharding import PartitionSpec as P

from .. import autograd
from ..layer import Layer, _param
from . import ops as collective


class ColumnParallelLinear(Layer):
    """y_local = x @ W[:, shard] — output features sharded over 'model'.

    Feed its output into a :class:`RowParallelLinear` (no gather needed)
    or set ``gather_output=True`` to return the full feature dim.
    """

    def __init__(self, out_features, bias=True, gather_output=False,
                 axis_name="model"):
        super().__init__()
        self.out_features = out_features
        self.bias = bias
        self.gather_output = gather_output
        self.axis_name = axis_name

    def initialize(self, x):
        in_features = x.shape[-1]
        self.W = _param((in_features, self.out_features), x.device)
        std = math.sqrt(2.0 / (in_features + self.out_features))
        self.W.gaussian(0.0, std)
        self.W.spec = P(None, self.axis_name)
        if self.bias:
            self.b = _param((self.out_features,), x.device)
            self.b.spec = P(self.axis_name)

    def forward(self, x):
        # Megatron "f": identity fwd, all-reduce bwd — each shard produces
        # only its slice's contribution to dx
        x = collective.copy_to_parallel(x, self.axis_name)
        y = autograd.matmul(x, self.W)
        if self.bias:
            y = autograd.add_bias(y, self.b, axis=0)
        if self.gather_output:
            y = collective.all_gather(y, self.axis_name, concat_axis=-1)
        return y

    def _own_params(self):
        p = {"W": self.W}
        if self.bias:
            p["b"] = self.b
        return p


class RowParallelLinear(Layer):
    """y = psum_model(x_local @ W[shard, :]) + b — input features sharded.

    Takes the sharded activations a ColumnParallelLinear produced; the
    single all-reduce here completes the logical full matmul.
    """

    def __init__(self, out_features, bias=True, axis_name="model"):
        super().__init__()
        self.out_features = out_features
        self.bias = bias
        self.axis_name = axis_name

    def initialize(self, x):
        # x carries the LOCAL shard width when tracing inside shard_map,
        # but initialize runs on the eager (full) pass, so this is the
        # full input width
        in_features = x.shape[-1]
        self.W = _param((in_features, self.out_features), x.device)
        std = math.sqrt(2.0 / (in_features + self.out_features))
        self.W.gaussian(0.0, std)
        self.W.spec = P(self.axis_name, None)
        if self.bias:
            self.b = _param((self.out_features,), x.device)  # replicated

    def forward(self, x):
        y = autograd.matmul(x, self.W)
        y = collective.all_reduce(y, self.axis_name)
        if self.bias:
            y = autograd.add_bias(y, self.b, axis=0)
        return y

    def _own_params(self):
        p = {"W": self.W}
        if self.bias:
            p["b"] = self.b
        return p


class TPMLP(Layer):
    """Column→activation→Row two-layer MLP: one all-reduce total."""

    def __init__(self, hidden_features, out_features, activation="relu",
                 axis_name="model"):
        super().__init__()
        self.up = ColumnParallelLinear(hidden_features,
                                       axis_name=axis_name)
        self.down = RowParallelLinear(out_features, axis_name=axis_name)
        self.activation = activation

    def forward(self, x):
        h = self.up(x)
        h = getattr(autograd, self.activation)(h)
        return self.down(h)
