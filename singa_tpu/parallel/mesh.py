"""Device-mesh construction for dp/tp/pp/sp parallelism axes.

The reference supports data parallelism only (SURVEY §2.4); the mesh here is
the superset TPU-native form: named axes over which shardings and
collectives are expressed (scaling-book recipe: pick a mesh, annotate
shardings, let XLA insert collectives).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np
import jax
from jax.sharding import Mesh


@dataclass
class MeshConfig:
    """Logical parallelism degrees; -1 on ``data`` means "everything left"."""

    data: int = -1      # dp replicas
    model: int = 1      # tp shards
    pipe: int = 1       # pp stages
    seq: int = 1        # sp shards (long-context)
    expert: int = 1     # ep shards (MoE experts)

    axis_order: tuple = ("data", "expert", "seq", "pipe", "model")

    def degrees(self, n_devices: int):
        fixed = {"model": self.model, "pipe": self.pipe, "seq": self.seq,
                 "expert": self.expert}
        rest = n_devices
        for v in fixed.values():
            assert rest % v == 0, \
                f"{n_devices} devices not divisible by {fixed}"
            rest //= v
        data = self.data if self.data != -1 else rest
        assert (data * self.model * self.pipe * self.seq * self.expert
                == n_devices), \
            f"mesh {self} does not cover {n_devices} devices"
        return {"data": data, "expert": self.expert, "seq": self.seq,
                "pipe": self.pipe, "model": self.model}


def make_mesh(devices=None, config: MeshConfig | None = None) -> Mesh:
    """Build a named mesh. Axes with degree 1 are kept (size-1 axes are free
    and let sharding rules stay uniform across configurations)."""
    if devices is None:
        devices = jax.devices()
    if config is None:
        config = MeshConfig()
    deg = config.degrees(len(devices))
    shape = tuple(deg[a] for a in config.axis_order)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, config.axis_order)
