"""Device-mesh construction for dp/tp/pp/sp parallelism axes.

The reference supports data parallelism only (SURVEY §2.4); the mesh here is
the superset TPU-native form: named axes over which shardings and
collectives are expressed (scaling-book recipe: pick a mesh, annotate
shardings, let XLA insert collectives).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np
import jax
from jax.sharding import Mesh


@dataclass
class MeshConfig:
    """Logical parallelism degrees; -1 on ``data`` means "everything left".

    ONE axis table for both train-step generations: the GSPMD path's
    ``parallel.gspmd.train_mesh(data=, model=, stage=)`` builds through
    this config with its ``stage`` vocabulary bound to the existing
    ``pipe`` axis NAME, so pipeline layouts, ``elastic_mesh``
    resharding and checkpoint live-sharding keep speaking identical
    axis names across the migration (a rename would silently orphan
    every announced PartitionSpec)."""

    data: int = -1      # dp replicas
    model: int = 1      # tp shards
    pipe: int = 1       # pp stages ('stage' in the gspmd train vocabulary)
    seq: int = 1        # sp shards (long-context)
    expert: int = 1     # ep shards (MoE experts)

    axis_order: tuple = ("data", "expert", "seq", "pipe", "model")

    def degrees(self, n_devices: int):
        fixed = {"model": self.model, "pipe": self.pipe, "seq": self.seq,
                 "expert": self.expert}
        rest = n_devices
        for v in fixed.values():
            assert rest % v == 0, \
                f"{n_devices} devices not divisible by {fixed}"
            rest //= v
        data = self.data if self.data != -1 else rest
        assert (data * self.model * self.pipe * self.seq * self.expert
                == n_devices), \
            f"mesh {self} does not cover {n_devices} devices"
        return {"data": data, "expert": self.expert, "seq": self.seq,
                "pipe": self.pipe, "model": self.model}


def make_mesh(devices=None, config: MeshConfig | None = None) -> Mesh:
    """Build a named mesh. Axes with degree 1 are kept (size-1 axes are free
    and let sharding rules stay uniform across configurations)."""
    if devices is None:
        devices = jax.devices()
    if config is None:
        config = MeshConfig()
    deg = config.degrees(len(devices))
    shape = tuple(deg[a] for a in config.axis_order)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, config.axis_order)


def elastic_mesh(devices=None, config: MeshConfig | None = None,
                 saved_world: int | None = None) -> Mesh:
    """Mesh for a (possibly world-size-changed) restart.

    Built over whatever devices THIS incarnation of the job has: the
    ``data`` axis defaults to -1 ("everything left"), so a run restarted
    with fewer or more hosts gets a mesh whose dp degree simply absorbs
    the change while every axis NAME stays fixed — shardings and
    collectives written against names re-land unchanged, and the restore
    template re-shards checkpointed state onto the new degrees.

    ``saved_world`` (from a checkpoint manifest) makes the transition
    loud: a mismatch with the current world is warned, not an error —
    elastic resume is exactly the case where they differ.
    """
    import warnings
    if devices is None:
        devices = jax.devices()
    cfg = config or MeshConfig()
    fixed = cfg.model * cfg.pipe * cfg.seq * cfg.expert
    world = len(devices) // max(1, fixed)
    if saved_world is not None and int(saved_world) != world:
        warnings.warn(
            f"elastic mesh: data-parallel degree is now {world} "
            f"(checkpoint was saved at {saved_world}); state will be "
            "re-sharded onto the new mesh on restore", stacklevel=2)
    return make_mesh(devices, cfg)
