"""Mixture-of-Experts FFN with expert parallelism (GShard-style).

No reference equivalent (the reference is data-parallel only, SURVEY.md
§2.4); this is the TPU-native 'ep' axis: expert weights shard over the
mesh 'expert' axis (one expert group per peer), tokens shard over the
batch-like axes, and two tiled ``lax.all_to_all`` exchanges carry each
token to its expert's peer and back — the canonical MoE layout where the
dispatch rides the ICI.

Capacity-factor token dropping, top-1/top-2 gating with normalized
combine weights, and the load-balance auxiliary loss follow the GShard
formulation (einsum dispatch/combine over static shapes, so the whole
layer jits into one XLA computation). Outside an active mesh context the
all-to-alls degrade to identity and the same code computes the dense
(single-device) MoE.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from ..autograd_base import Operator
from ..layer import Layer, _param
from ..tensor import Tensor
from .communicator import active_axis, axis_size
from .gspmd import expert_spec


class _MoEFFN(Operator):
    """(T, D) tokens -> (T, D) expert-mixed output + scalar aux loss."""

    def __init__(self, n_experts, top_k, capacity_factor, axis_name,
                 batch_axes):
        super().__init__()
        self.E = n_experts
        self.k = top_k
        self.cf = capacity_factor
        self.axis_name = axis_name
        self.batch_axes = batch_axes

    def forward(self, x, wg, w1, b1, w2, b2):
        T, D = x.shape
        E, k = self.E, self.k
        C = max(1, math.ceil(k * T * self.cf / E))
        f32 = jnp.float32
        gates = jax.nn.softmax(jnp.dot(x.astype(f32), wg.astype(f32)))

        # iterative top-k: pick, reserve capacity, mask out, repeat;
        # dispatch and (unnormalized) combine accumulate per round from
        # the same keep/slot increment
        masked = gates
        count = jnp.zeros((E,), f32)          # tokens already queued
        dispatch = jnp.zeros((T, E, C), f32)
        combine = jnp.zeros((T, E, C), f32)
        picked_gates = []
        first_mask = None
        for _ in range(k):
            idx = jnp.argmax(masked, axis=1)              # (T,)
            hot = jax.nn.one_hot(idx, E, dtype=f32)       # (T, E)
            if first_mask is None:
                first_mask = hot
            pos = jnp.cumsum(hot, axis=0) - hot + count   # queue position
            keep = (pos < C).astype(f32) * hot
            count = count + keep.sum(axis=0)
            chot = jax.nn.one_hot(
                (pos * hot).sum(axis=1).astype(jnp.int32), C,
                dtype=f32)                                # (T, C)
            inc = keep[:, :, None] * chot[:, None, :]     # (T, E, C)
            dispatch = dispatch + inc
            g = (gates * hot).sum(axis=1)                 # (T,)
            combine = combine + g[:, None, None] * inc
            picked_gates.append(g)
            masked = masked * (1.0 - hot)

        # combine weights: raw gate for top-1 (Switch — the gate gradient
        # flows through the output scale), normalized across picks for
        # top-k>=2 (GShard)
        if k > 1:
            denom = sum(picked_gates) + 1e-9              # (T,)
            combine = combine / denom[:, None, None]

        # dispatch -> expert-major buffer, exchange over the expert axis
        ein = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
        if active_axis(self.axis_name):
            ep = axis_size(self.axis_name)
            if E % ep != 0:
                raise ValueError(
                    f"n_experts={E} must divide by the '{self.axis_name}' "
                    f"mesh degree {ep}")
            ein = lax.all_to_all(ein, self.axis_name, 0, 1, tiled=True)
        # expert FFN on the local expert group (g = local experts)
        h = jnp.einsum("gcd,gdf->gcf", ein, w1) + b1[:, None, :]
        h = jax.nn.gelu(h)
        out_e = jnp.einsum("gcf,gfd->gcd", h, w2) + b2[:, None, :]
        if active_axis(self.axis_name):
            out_e = lax.all_to_all(out_e, self.axis_name, 1, 0, tiled=True)
        y = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), out_e)

        # load-balance aux (GShard): E * sum_e mean_t(gate_e)*mean_t(pick1_e)
        # — the means must be GLOBAL over the token batch: under sharding,
        # a mean of per-shard products is not the product of global means
        gmean = gates.mean(axis=0)
        mmean = first_mask.mean(axis=0)
        for ax in self.batch_axes:
            if active_axis(ax):
                gmean = lax.pmean(gmean, ax)
                mmean = lax.pmean(mmean, ax)
        aux = E * jnp.sum(gmean * mmean)
        return y, aux.astype(x.dtype)


class MoEFFN(Layer):
    """Drop-in FFN block whose experts shard over the mesh 'expert' axis.

    ``forward`` returns the mixed output; the load-balance auxiliary loss
    of the call is exposed as ``self.aux_loss`` — a tape Tensor that is
    only valid INSIDE the same ``train_one_batch`` (add
    ``alpha * aux_loss`` to the loss there; under graph mode it is a
    traced value that dies with the trace, so it cannot be read for
    logging after a compiled step).

    ``n_experts`` must divide by the expert-axis degree; with no active
    mesh the same layer computes the dense MoE on one device.
    """

    def __init__(self, n_experts, d_ff, top_k=2, capacity_factor=1.25,
                 axis_name="expert", batch_axes=("data", "expert", "seq")):
        super().__init__()
        if top_k > n_experts:
            raise ValueError(
                f"top_k={top_k} cannot exceed n_experts={n_experts}")
        self.n_experts = n_experts
        self.d_ff = d_ff
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.axis_name = axis_name
        self.batch_axes = batch_axes
        self.aux_loss = None

    def initialize(self, x):
        D, F, E = x.shape[-1], self.d_ff, self.n_experts
        dev = x.device
        # router stays f32 (softmax gating needs the range; x bf16 @ wg
        # f32 promotes to f32 so routing is full-precision either way);
        # experts follow the input dtype like every other matmul layer
        self.wg = _param((D, E), dev)
        self.wg.gaussian(0.0, math.sqrt(1.0 / D))
        self.w1 = _param((E, D, F), dev, dtype=x.dtype)
        self.w1.gaussian(0.0, math.sqrt(2.0 / (D + F)))
        self.b1 = _param((E, F), dev, dtype=x.dtype)
        self.w2 = _param((E, F, D), dev, dtype=x.dtype)
        self.w2.gaussian(0.0, math.sqrt(2.0 / (D + F)))
        self.b2 = _param((E, D), dev, dtype=x.dtype)
        if self.axis_name:
            # expert banks announce their layout through the shared
            # gspmd vocabulary, like every other sharded layer
            for t in (self.w1, self.b1, self.w2, self.b2):
                t.spec = expert_spec(self.axis_name)

    def forward(self, x):
        from .. import autograd
        shape = x.shape
        if len(shape) > 2:
            x = autograd.reshape(x, (-1, shape[-1]))
        y, aux = _MoEFFN(self.n_experts, self.top_k, self.capacity_factor,
                         self.axis_name, self.batch_axes)(
            x, self.wg, self.w1, self.b1, self.w2, self.b2)
        self.aux_loss = aux
        if len(shape) > 2:
            y = autograd.reshape(y, shape)
        return y

    def _own_params(self):
        return {"wg": self.wg, "w1": self.w1, "b1": self.b1,
                "w2": self.w2, "b2": self.b2}


__all__ = ["MoEFFN"]
