"""GSPMD-native partitioner: ONE sharding vocabulary for the whole stack.

Every PartitionSpec in this codebase — tensor/vocab-parallel training
layers, MoE expert banks, the serving engine's params and KV state
(ring AND paged), checkpoint live-sharding templates — is constructed
HERE, over a named mesh whose two serving axes are ``batch`` (data-like:
slots, request rows) and ``model`` (tensor-parallel: attention heads,
MLP hidden, vocab). The execution model is the scaling-book /
SNIPPETS.md [2] recipe: annotate inputs with
:class:`~jax.sharding.NamedSharding`, ``jax.jit`` the UNCHANGED pure
function, and let XLA's SPMD partitioner insert the collectives — no
hand-written ``psum`` anywhere on the compiled path, and the same
program text runs on 1 chip or 6000.

Two mechanisms coexist during the migration:

- **GSPMD (this module)** — serving AND the train step
  (``Model.compile(mesh=...)``): one jitted program over
  NamedSharding-annotated arrays. The train program's state shardings
  come from :func:`fit_state_spec` (and :func:`fsdp_state_spec` under
  ZeRO/FSDP), its batch inputs from the 'data' axis; XLA inserts the
  gradient all-reduces (or reduce-scatter/all-gather under FSDP).
- **shard_map + explicit collectives** (``communicator.py``,
  ``ops.py``, ``pipeline.py``) — the train step's LEGACY mechanism,
  still the default when ``compile`` is called without ``mesh=``. It
  remains the bitwise-parity reference the GSPMD path is pinned
  against, but it is a deprecation boundary: its layers announce their
  layouts through this module's spec vocabulary (so the two mechanisms
  can never disagree about what "column-parallel" means), and new
  sharded code should not add hand-rolled collectives.

Declines are TYPED, never silent: a config the mesh cannot honor
(heads that don't divide the model axis, a vocab that doesn't split, a
mesh smaller than the requested shards) raises
:class:`ShardingDecline` naming the offender — GSPMD would otherwise
fall back to replication and serve a "sharded" model that isn't.
"""

from __future__ import annotations

import numpy as np

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

BATCH_AXIS = "batch"
MODEL_AXIS = "model"
# the TRAINING batch axis (serving uses BATCH_AXIS; training meshes come
# from parallel.mesh whose dp axis has always been named 'data')
DATA_AXIS = "data"


class ShardingDecline(ValueError):
    """A sharding request the mesh cannot honor. Raised at build time,
    naming the offending dimension — never a silently replicated
    "sharded" program."""


# ---------------------------------------------------------------------------
# the spec vocabulary: every layer/serving rule speaks these
# ---------------------------------------------------------------------------

def replicated_spec():
    """Fully replicated (LN scale/bias, small biases, scalars)."""
    return P()


def col_spec(axis=MODEL_AXIS):
    """Column-parallel 2-D weight ``(in, out)``: OUT features sharded
    (Megatron column split — qkv projections, MLP up, LM head)."""
    return P(None, axis)


def col_bias_spec(axis=MODEL_AXIS):
    """Bias of a column-parallel layer: sharded like its out features."""
    return P(axis)


def row_spec(axis=MODEL_AXIS):
    """Row-parallel 2-D weight ``(in, out)``: IN features sharded
    (Megatron row split — attention out-proj, MLP down). The bias of a
    row-parallel layer is replicated (:func:`replicated_spec`)."""
    return P(axis, None)


def vocab_spec(axis=MODEL_AXIS):
    """Embedding table ``(vocab, d)``: vocab ROWS sharded — the
    input-side twin of a column-sharded LM head."""
    return P(axis, None)


def expert_spec(axis="expert"):
    """Expert-banked weight ``(E, ...)``: leading expert dim sharded
    over the expert-parallel axis."""
    return P(axis)


def batch_spec(axis=BATCH_AXIS, rank=1):
    """Leading-dim batch sharding for an activation/IO array of
    ``rank`` dims (slots, request rows, token batches)."""
    return P(axis, *([None] * (rank - 1)))


def fit_state_spec(spec, shape, mesh):
    """A parameter's announced PartitionSpec, with any dim that does not
    divide its mesh axes replicated instead (e.g. a vocab of 31 over
    'model'=2: the layer announces P('model', None) unconditionally
    because it cannot know the mesh at init; sharding such a dim would
    make shard_map reject the whole step, so the dim falls back to
    replication and the layers' offset math detects the full-width
    tensor). The checkpoint live-sharding template and the compiled
    step both resolve layouts through this ONE function."""
    if spec is None:
        return P()
    fitted = []
    for dim, names in enumerate(spec):
        if names is None:
            fitted.append(None)
            continue
        tup = names if isinstance(names, tuple) else (names,)
        size = 1
        for n in tup:
            size *= mesh.shape[n]
        fitted.append(names if dim < len(shape) and
                      shape[dim] % size == 0 else None)
    while fitted and fitted[-1] is None:
        fitted.pop()
    return P(*fitted)


def fsdp_state_spec(spec, shape, mesh, axis=DATA_AXIS):
    """ZeRO/FSDP layout for ONE param / optimizer-aux / master tensor:
    the announced spec (mesh-fitted through :func:`fit_state_spec`)
    with the first still-replicated dim that divides the ``axis``
    degree additionally sharded over it. Params never announce the
    data axis themselves, so this composes with tensor/expert layouts
    instead of double-sharding a dim. Scalars (step counter, loss
    scale) and tensors with no divisible dim stay replicated — an
    honest fallback, not a decline: FSDP is a memory layout, and a
    handful of tiny replicated leaves does not change the N× headroom
    the big buffers provide."""
    if axis not in mesh.shape:
        raise ShardingDecline(
            f"fsdp axis {axis!r} is not in the mesh "
            f"{dict(mesh.shape)}: build the train mesh with a "
            f"{axis!r} axis (parallel.mesh.MeshConfig names it)")
    base = fit_state_spec(spec, shape, mesh)
    deg = int(mesh.shape[axis])
    if deg <= 1 or not shape:
        return base
    entries = list(base) + [None] * (len(shape) - len(base))
    for dim, names in enumerate(entries):
        if names is None and shape[dim] % deg == 0:
            entries[dim] = axis
            break
    else:
        return base
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


# ---------------------------------------------------------------------------
# mesh construction
# ---------------------------------------------------------------------------

def serving_mesh(devices=None, model_shards=1, batch_shards=None):
    """A named ``(batch × model)`` serving mesh.

    ``model_shards`` tensor-parallel degree; ``batch_shards`` defaults
    to "every remaining device". Typed declines when the device count
    cannot cover the request."""
    import jax
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    m = int(model_shards)
    if m < 1:
        raise ShardingDecline(f"model_shards must be >= 1, got {m}")
    if m > n:
        raise ShardingDecline(
            f"model_shards={m} exceeds the {n} available devices: the "
            "mesh cannot be built — lower model_shards or add devices")
    if batch_shards:
        # an explicit batch degree only needs the device set to COVER
        # the mesh (trailing devices may idle — the caller chose)
        b = int(batch_shards)
        if b * m > n:
            raise ShardingDecline(
                f"batch_shards={b} × model_shards={m} exceeds the "
                f"{n} available devices")
    else:
        if n % m != 0:
            raise ShardingDecline(
                f"{n} devices do not divide into model_shards={m}: "
                "the default (batch × model) mesh must tile the "
                "device set exactly — pass batch_shards to use a "
                "subset deliberately")
        b = n // m
    arr = np.asarray(devices[:b * m]).reshape(b, m)
    return Mesh(arr, (BATCH_AXIS, MODEL_AXIS))


def train_mesh(devices=None, data=-1, model=1, stage=1):
    """A named training mesh over the (data × model × stage)
    vocabulary. ONE table with the shard_map world: ``stage`` binds to
    ``parallel.mesh``'s existing ``pipe`` axis name (pipeline stages),
    so pipeline layouts, ``elastic_mesh`` resharding, and checkpoint
    live-sharding all keep speaking the same axis names across the
    GSPMD migration. ``data=-1`` means "everything left" — the elastic
    default. Fully explicit degrees may use a leading device SUBSET
    (trailing devices idle — the caller chose, same contract as
    :func:`serving_mesh` with an explicit batch degree). Typed
    declines for device counts the degrees cannot tile."""
    import jax
    from . import mesh as mesh_mod
    if devices is None:
        devices = jax.devices()
    d, m, s = int(data), int(model), int(stage)
    if m < 1 or s < 1:
        raise ShardingDecline(
            f"model={m} / stage={s} degrees must be >= 1")
    n = len(devices)
    need = m * s * (d if d != -1 else 1)
    if need > n or n % (m * s) != 0:
        raise ShardingDecline(
            f"train mesh data={d} model={m} stage={s} cannot tile the "
            f"{n} available devices: degrees must cover the device "
            "set exactly")
    if d != -1:
        devices = list(devices)[:d * m * s]
    cfg = mesh_mod.MeshConfig(data=d, model=m, pipe=s)
    return mesh_mod.make_mesh(devices, cfg)


def serving_partitioner(mesh=None, model_shards=None, devices=None,
                        max_batch=None):
    """Resolve ``compile_serving(mesh=..., model_shards=...)`` into a
    :class:`Partitioner`. An explicit mesh must carry the named
    ``batch``/``model`` axes (extra axes must be size 1) and is taken
    as pinned — indivisible geometry against it refuses typed. With
    only ``model_shards`` a fresh mesh is built over the devices, its
    ``batch`` degree auto-fitted: the largest divisor of ``max_batch``
    (the engine passes its slot count) that the remaining devices
    cover, so a 2-slot engine on 8 chips gets a (2 × model) mesh
    instead of a refusal."""
    if mesh is None:
        import jax
        devs = devices if devices is not None else jax.devices()
        m = int(model_shards or 1)
        b = None
        if max_batch is not None and 1 <= m <= len(devs):
            # largest divisor of the slot count the remaining devices
            # cover: a 6-slot engine on 8 chips at model_shards=2 gets
            # batch=3 (6 devices), not gcd's 2
            fits = [d for d in range(1, int(max_batch) + 1)
                    if int(max_batch) % d == 0 and d * m <= len(devs)]
            b = max(fits) if fits else None
        return Partitioner(serving_mesh(
            devices=devs, model_shards=m, batch_shards=b))
    if not isinstance(mesh, Mesh):
        raise ShardingDecline(
            f"mesh must be a jax.sharding.Mesh, got {type(mesh).__name__}")
    if BATCH_AXIS not in mesh.shape or MODEL_AXIS not in mesh.shape:
        raise ShardingDecline(
            f"serving mesh needs named axes ({BATCH_AXIS!r}, "
            f"{MODEL_AXIS!r}); got {tuple(mesh.axis_names)}")
    extra = [a for a in mesh.axis_names
             if a not in (BATCH_AXIS, MODEL_AXIS) and mesh.shape[a] != 1]
    if extra:
        raise ShardingDecline(
            f"serving mesh has extra non-unit axes {extra}; only "
            f"{BATCH_AXIS!r} and {MODEL_AXIS!r} partition the serve "
            "programs")
    if model_shards and int(model_shards) != mesh.shape[MODEL_AXIS]:
        raise ShardingDecline(
            f"model_shards={model_shards} disagrees with the mesh's "
            f"'{MODEL_AXIS}' degree {mesh.shape[MODEL_AXIS]}")
    return Partitioner(mesh)


class Partitioner:
    """NamedSharding factory over one mesh: spec→sharding resolution,
    tree placement, divisibility checks, and per-device accounting."""

    def __init__(self, mesh, batch_axis=BATCH_AXIS,
                 model_axis=MODEL_AXIS):
        for ax in (batch_axis, model_axis):
            if ax not in mesh.shape:
                raise ShardingDecline(
                    f"mesh {dict(mesh.shape)} has no '{ax}' axis")
        self.mesh = mesh
        self.batch_axis = batch_axis
        self.model_axis = model_axis

    @property
    def batch_shards(self):
        return int(self.mesh.shape[self.batch_axis])

    @property
    def model_shards(self):
        return int(self.mesh.shape[self.model_axis])

    @property
    def n_devices(self):
        return int(np.prod(list(self.mesh.shape.values())))

    def describe(self):
        """The mesh stamp /healthz, heartbeats, and manifests carry."""
        return {"batch": self.batch_shards, "model": self.model_shards,
                "devices": self.n_devices}

    # -- spec resolution ----------------------------------------------------
    def sharding(self, spec):
        return NamedSharding(self.mesh, spec)

    def sharding_tree(self, spec_tree):
        """Same-structure tree of NamedShardings (PartitionSpec leaves)."""
        import jax
        return jax.tree_util.tree_map(
            self.sharding, spec_tree,
            is_leaf=lambda x: isinstance(x, P))

    def shard(self, tree, spec_tree):
        """device_put every leaf onto its NamedSharding — the one
        placement chokepoint for params and KV state."""
        import jax
        import jax.numpy as jnp
        return jax.tree_util.tree_map(
            lambda a, s: jax.device_put(jnp.asarray(a),
                                        self.sharding(s)),
            tree, spec_tree,
            is_leaf=lambda x: isinstance(x, P))

    # -- typed declines -----------------------------------------------------
    def require_divisible(self, what, size, axis=None):
        """``size % axis-degree == 0`` or a :class:`ShardingDecline`
        naming the offender — the guard that keeps "sharded" honest
        (GSPMD would silently replicate an indivisible dim)."""
        axis = axis or self.model_axis
        deg = int(self.mesh.shape[axis])
        if int(size) % deg != 0:
            raise ShardingDecline(
                f"{what} = {size} does not divide the '{axis}' mesh "
                f"axis (degree {deg}): the mesh cannot shard it — "
                "XLA would silently replicate instead, so this config "
                "is refused")

    # -- accounting ---------------------------------------------------------
    @staticmethod
    def per_device_bytes(tree):
        """Per-device bytes of a (possibly sharded) array tree — what
        one chip actually holds, the honest HBM number for fleet
        gauges. Unsharded arrays count full size."""
        import jax
        total = 0
        for a in jax.tree_util.tree_leaves(tree):
            shape = tuple(a.shape)
            sh = getattr(a, "sharding", None)
            if sh is not None and hasattr(sh, "shard_shape"):
                shape = sh.shard_shape(shape)
            total += int(np.prod(shape, dtype=np.int64)) * \
                np.dtype(a.dtype).itemsize
        return int(total)

    @staticmethod
    def global_bytes(tree):
        import jax
        return int(sum(
            int(np.prod(a.shape, dtype=np.int64)) *
            np.dtype(a.dtype).itemsize
            for a in jax.tree_util.tree_leaves(tree)))


# ---------------------------------------------------------------------------
# serving rule tables: the LM decode-param tree, ring caches, block pools
# ---------------------------------------------------------------------------

def _weight_entry_spec(w, spec):
    """Spec for one weight entry of the serve param tree: a float array
    gets ``spec`` directly; an int8 weight-only payload ``{"q","s"}``
    shards the payload like the weight and its rank-preserving
    per-out-channel scale along the same out axis."""
    if isinstance(w, dict):
        # scale keeps the payload's rank (quant.core.quantize_int8), so
        # it shards along exactly the axes the payload does that it has
        # size > 1 in; for the (1, out) 2-D scales that is the out axis.
        s_spec = P(*[ax if int(d) > 1 else None
                     for ax, d in zip(tuple(spec) +
                                      (None,) * len(w["s"].shape),
                                      w["s"].shape)])
        return {"q": spec, "s": s_spec}
    return spec


def lm_param_specs(part, params, n_heads):
    """PartitionSpec tree for the transformer serve-param dict
    (``models.transformer._lm_decode_params`` layout): attention heads
    and MLP hidden split over ``model``, vocab-sharded embedding rows
    and head columns, everything small replicated. Typed declines for
    every dimension the mesh cannot split honestly."""
    ax = part.model_axis
    part.require_divisible("n_heads", n_heads, ax)
    vocab = int(params["tok"].shape[0])
    part.require_divisible("vocab_size", vocab, ax)
    blocks = []
    for i, p in enumerate(params["blocks"]):
        if "wg" in p:
            raise ShardingDecline(
                "MoE decode blocks are not mesh-shardable yet: the "
                "expert banks would silently replicate per device "
                f"(block {i}); serve MoE models single-device, or "
                "train with the 'expert' axis")
        d_ff = int((p["w_up"]["q"] if isinstance(p["w_up"], dict)
                    else p["w_up"]).shape[1])
        part.require_divisible("d_ff (MLP hidden)", d_ff, ax)
        spec = {
            "ln1_s": P(), "ln1_b": P(), "ln2_s": P(), "ln2_b": P(),
            # qkv columns = heads × head_dim: whole heads per shard
            # (n_heads % m checked above keeps the reshape honest)
            "wq": _weight_entry_spec(p["wq"], col_spec(ax)),
            "bq": col_bias_spec(ax),
            "wk": _weight_entry_spec(p["wk"], col_spec(ax)),
            "bk": col_bias_spec(ax),
            "wv": _weight_entry_spec(p["wv"], col_spec(ax)),
            "bv": col_bias_spec(ax),
            "wo": _weight_entry_spec(p["wo"], row_spec(ax)),
            "bo": P(),
            "w_up": _weight_entry_spec(p["w_up"], col_spec(ax)),
            "b_up": col_bias_spec(ax),
            "w_dn": _weight_entry_spec(p["w_dn"], row_spec(ax)),
            "b_dn": P(),
        }
        blocks.append(spec)
    return dict(
        tok=vocab_spec(ax),          # vocab rows sharded
        pos=P(),                     # tiny, every rank reads every row
        lnf_s=P(), lnf_b=P(),
        head_w=col_spec(ax),         # vocab columns sharded
        head_b=col_bias_spec(ax),
        blocks=blocks)


def ring_cache_specs(part, cache):
    """Ring KV levels ``(W, H, L, D)``: slots over ``batch``, heads
    over ``model``; int8 scale rows ``(W, L)`` ride the slot axis."""
    out = []
    for level in cache:
        spec = {"k": P(part.batch_axis, part.model_axis, None, None),
                "v": P(part.batch_axis, part.model_axis, None, None)}
        if "k_scale" in level:
            spec["k_scale"] = P(part.batch_axis, None)
            spec["v_scale"] = P(part.batch_axis, None)
        out.append(spec)
    return out


def pool_specs(part, pool):
    """Paged KV pools ``(N, H, bs, D)``: heads over ``model``, blocks
    REPLICATED over ``batch`` — prefix-shared blocks are referenced by
    slots on every batch shard, so the pool is per-device-whole with a
    per-device head slice (the per-chip HBM win is H/model_shards);
    int8 scale planes ``(N, bs)`` are head-less, hence replicated."""
    out = []
    for level in pool:
        spec = {"k": P(None, part.model_axis, None, None),
                "v": P(None, part.model_axis, None, None)}
        if "k_scale" in level:
            spec["k_scale"] = P()
            spec["v_scale"] = P()
        out.append(spec)
    return out


def serving_arg_specs(part, kv_layout):
    """PartitionSpecs for the serve programs' HOST-ARRAY arguments and
    token outputs, per KV layout.

    Decode's per-slot rows ride the ``batch`` axis (``slots`` divides
    it — checked at engine build); prefill's small fixed-width batch
    arrays are replicated (``prefill_batch`` need not divide the mesh,
    and a handful of prompt rows is not where sharding pays). Token
    outputs are replicated — the host scheduler reads every slot's
    token each tick."""
    b = part.batch_axis
    if kv_layout == "paged":
        return {
            # (tables, tokens, starts, lengths, valid)
            "prefill": (P(), P(), P(), P(), P()),
            # (tables (W,n_pages), tokens (W,K), positions, counts)
            "decode": (P(b, None), P(b, None), P(b), P(b)),
            "tokens_out": P(),
        }
    return {
        # (tokens, lengths, slot_ids, valid)
        "prefill": (P(), P(), P(), P()),
        # (tokens (W,), positions (W,), active (W,))
        "decode": (P(b), P(b), P(b)),
        "tokens_out": P(),
    }


__all__ = ["BATCH_AXIS", "MODEL_AXIS", "DATA_AXIS", "ShardingDecline",
           "replicated_spec", "col_spec", "col_bias_spec", "row_spec",
           "vocab_spec", "expert_spec", "batch_spec", "fit_state_spec",
           "fsdp_state_spec", "serving_mesh", "train_mesh",
           "serving_partitioner", "Partitioner",
           "lm_param_specs", "ring_cache_specs", "pool_specs",
           "serving_arg_specs"]
