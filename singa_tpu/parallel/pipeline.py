"""Pipeline parallelism: GPipe and 1F1B microbatch schedules over a mesh
axis.

No reference equivalent (the reference is data-parallel only, SURVEY.md
§2.4). TPU-native design: every pipeline stage is the same jitted program
(SPMD over the 'pipe' mesh axis inside ``shard_map``); activations hop to
the next stage with `lax.ppermute` over ICI each schedule tick, and the
whole schedule is a `lax.scan` — so XLA sees one static program.

Two schedules:
- GPipe (:func:`pipeline_spmd` / :class:`PipelineModule`): forward only;
  backward falls out of `jax.grad` of the scan (the transpose of
  `ppermute` is the reverse-direction `ppermute`) — simple, but autodiff
  stores every tick's activations, O(n_micro).
- 1F1B (:func:`pipeline_1f1b` / :class:`PipelineModule1F1B`): forward and
  backward micro-steps interleave in ONE scan with the per-microbatch
  loss inside the schedule; backward recomputes each stage from a saved
  input-activation ring of depth 2(S-1)+1, so activation memory is
  bounded by the pipe depth, not the microbatch count.

Prefer 1F1B for training: in the SPMD GPipe form every pipe member also
recomputes the downstream (post-pipeline) loss redundantly — inherent to
one-program-per-mesh SPMD, harmless for inference, but wasted compute
per training step that the in-schedule 1F1B loss avoids entirely.

Heterogeneous stages (different params AND different activation shapes
per stage — embedding -> blocks -> head) are first-class via
:class:`HeteroPipeline1F1B`.

Deprecation boundary: this module (like ``communicator.py``) is the
explicit-collective MECHANISM layer — it stays for the compiled train
step, but sharding LAYOUTS belong to :mod:`.gspmd` (the one
NamedSharding vocabulary training and serving share; see
``communicator.partitioner`` for the shim). New sharded code should
annotate arrays with NamedSharding and jit, not add ppermute schedules
here.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..autograd_base import Operator
from .communicator import axis_size as _axis_size
from ..layer import Layer
from ..tensor import Tensor


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _pipe_descale(x, axis_name):
    """Identity whose transpose divides the cotangent by the pipe degree.

    In the Model's shard_map (replication checks off) every pipe member
    computes the downstream loss redundantly and injects a full cotangent;
    the last-stage psum broadcast's transpose then sums them, inflating
    every in-pipeline gradient by the pipe degree. This normalises at the
    pipeline boundary so stage-param and upstream grads equal the
    single-program values."""
    return x


def _pipe_descale_fwd(x, axis_name):
    return x, None


def _pipe_descale_bwd(axis_name, _res, g):
    return (g / _axis_size(axis_name),)


_pipe_descale.defvjp(_pipe_descale_fwd, _pipe_descale_bwd)


def _mark_varying(v, axis_name):
    """Mark a value device-varying over ``axis_name`` for shard_map's
    vma typecheck (API renamed across JAX versions)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(v, (axis_name,), to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(v, (axis_name,))
    return v


def _pipeline_fwd_core(dispatch, stage_params, x_microbatches, wire_shape,
                       wire_dtype, axis_name):
    """Generic GPipe forward scan. ``dispatch(params, a_wire, mb) ->
    a_wire`` is this device's stage applied to the wire activation (or,
    on stage 0, to the injected microbatch ``mb``). Returns the last
    stage's wire outputs (n_micro, *wire_shape), broadcast to all
    stages."""
    n = _axis_size(axis_name)
    sid = lax.axis_index(axis_name)
    n_micro = x_microbatches.shape[0]
    steps = n_micro + n - 1
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        prev_y = carry
        # activation produced upstream last tick arrives over the ring
        recv = lax.ppermute(prev_y, axis_name, fwd_perm)
        mb = lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
        y = dispatch(stage_params, recv, mb)
        return y, y

    # the carry becomes device-varying (stage params differ per pipe
    # member); mark the init accordingly for shard_map's vma typecheck
    init = _mark_varying(jnp.zeros(wire_shape, wire_dtype), axis_name)
    _, ys = lax.scan(step, init, jnp.arange(steps))

    # last stage's outputs at ticks n-1 .. steps-1 are microbatches 0..M-1
    outs = lax.dynamic_slice_in_dim(ys, n - 1, n_micro, axis=0)
    # broadcast them from the last stage to everyone
    return lax.psum(jnp.where(sid == n - 1, outs, jnp.zeros_like(outs)),
                    axis_name)


def pipeline_spmd(stage_fn, stage_params, x_microbatches, axis_name="pipe"):
    """Run a GPipe forward inside ``shard_map`` over ``axis_name``.

    Args:
      stage_fn: ``(params, activation) -> activation`` — this device's
        pipeline stage (all stages must preserve the activation shape).
      stage_params: this device's stage parameters (pytree; under
        shard_map give the global stacked params a P(axis_name, ...) spec
        so each device holds its own stage's slice).
      x_microbatches: (n_micro, mb, ...) — the microbatched global input
        (replicated; only stage 0 reads it).

    Returns (n_micro, mb, ...) outputs of the LAST stage, broadcast to all
    stages (so a replicated loss can follow).

    Schedule: t = 0..n_micro+n_stages-2; stage 0 injects microbatch t,
    stage s>0 consumes the activation stage s-1 produced at t-1.
    """

    def dispatch(params, a_wire, mb):
        a = jnp.where(lax.axis_index(axis_name) == 0, mb, a_wire)
        return stage_fn(params, a)

    return _pipeline_fwd_core(dispatch, stage_params, x_microbatches,
                              x_microbatches.shape[1:],
                              x_microbatches.dtype, axis_name)


def pipeline_1f1b(stage_fn, loss_fn, stage_params, x_microbatches,
                  y_microbatches, axis_name="pipe"):
    """One-forward-one-backward schedule inside ``shard_map``: loss and
    gradients in ONE pass with activation memory bounded by the pipe
    depth, not the microbatch count (GPipe autodiff stores every tick).

    Each scan tick runs one forward micro-step and one backward
    micro-step per stage. Stage ``s`` forwards microbatch ``t - s`` and
    backwards microbatch ``t - 2(S-1) + s``; activations hop forward and
    cotangents hop backward over the ICI ring each tick, and the backward
    recomputes the stage forward from the saved *input* activation (vjp
    residuals are never carried across ticks) — so the live state per
    stage is a ring of at most ``2(S-1)+1`` input activations.

    Args:
      stage_fn: ``(params, a) -> a`` shape-preserving stage.
      loss_fn: ``(a, y_mb) -> scalar`` applied at the LAST stage per
        microbatch (mean-reduced over microbatches in the result).
      stage_params: this device's stage params (pytree).
      x_microbatches / y_microbatches: (M, mb, ...) replicated inputs.

    Returns ``(loss, param_grads, dx_microbatches)`` — loss is the mean
    over microbatches (broadcast to all stages), ``param_grads`` is the
    gradient of that mean loss wrt THIS stage's params, and
    ``dx_microbatches`` is the cotangent reaching the pipeline input
    (nonzero on every stage after the final psum) for upstream layers.
    """
    def dispatch(params, a_wire, mb, _y_mb, _m_idx):
        a = jnp.where(lax.axis_index(axis_name) == 0, mb, a_wire)
        return stage_fn(params, a)

    return _pipeline_1f1b_core(
        dispatch, loss_fn, stage_params, x_microbatches, y_microbatches,
        x_microbatches.shape[1:], x_microbatches.dtype, axis_name)


def _pipeline_1f1b_core(dispatch, loss_fn, stage_params, x_microbatches,
                        y_microbatches, wire_shape, wire_dtype, axis_name):
    """Generic 1F1B scan shared by the homogeneous and heterogeneous
    APIs.

    ``dispatch(params, a_wire, mb, y_mb, m_idx) -> a_wire`` applies this
    device's stage: stage 0 reads the injected microbatch ``mb``, later
    stages read the wire activation, and a heterogeneous last stage may
    fold the per-microbatch loss into its wire output (with ``loss_fn``
    then just extracting it). ``m_idx`` is the microbatch index — the
    SAME value reaches the forward tick and that microbatch's backward
    recompute, so RNG-consuming stages (dropout) can fold a key from it
    and see identical draws in both (a stateful trace-time key would
    bake a DIFFERENT mask into the recompute, silently corrupting
    gradients). The ring stores WIRE inputs only — stage 0's input is
    re-read from ``x_microbatches`` at backward time, so heterogeneous
    input shapes never touch the ring.
    """
    S = _axis_size(axis_name)
    sid = lax.axis_index(axis_name)
    M = x_microbatches.shape[0]
    R = 2 * (S - 1) + 1                       # max in-flight per stage
    steps = M + 2 * (S - 1)
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [((i + 1) % S, i) for i in range(S)]

    x_shape = x_microbatches.shape[1:]
    is_last = sid == S - 1

    def step(carry, t):
        fwd_out, cot_out, ring, gacc, lacc, dxbuf = carry

        # ---- forward tick: mb (t - sid) -----------------------------
        recv_act = lax.ppermute(fwd_out, axis_name, fwd_perm)
        m_f = t - sid
        f_on = (m_f >= 0) & (m_f < M)
        mb = lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(m_f, 0, M - 1), 0, keepdims=False)
        y_f = lax.dynamic_index_in_dim(
            y_microbatches, jnp.clip(m_f, 0, M - 1), 0, keepdims=False)
        slot_f = jnp.clip(m_f, 0, M - 1) % R
        ring = jnp.where(
            f_on,
            lax.dynamic_update_index_in_dim(ring, recv_act, slot_f, 0),
            ring)
        y_new = dispatch(stage_params, recv_act, mb, y_f,
                         jnp.clip(m_f, 0, M - 1))
        fwd_out = jnp.where(f_on, y_new, fwd_out)

        # ---- backward tick: mb (t - 2(S-1) + sid) -------------------
        recv_cot = lax.ppermute(cot_out, axis_name, bwd_perm)
        m_b = t - 2 * (S - 1) + sid
        b_on = (m_b >= 0) & (m_b < M)
        slot_b = jnp.clip(m_b, 0, M - 1) % R
        a_saved = lax.dynamic_index_in_dim(ring, slot_b, 0, keepdims=False)
        mb_b = lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(m_b, 0, M - 1), 0, keepdims=False)
        y_mb = lax.dynamic_index_in_dim(
            y_microbatches, jnp.clip(m_b, 0, M - 1), 0, keepdims=False)

        mi_b = jnp.clip(m_b, 0, M - 1)
        out, vjp_fn = jax.vjp(
            lambda p, a, x: dispatch(p, a, x, y_mb, mi_b),
            stage_params, a_saved, mb_b)
        loss_mb, dout = jax.value_and_grad(loss_fn)(out, y_mb)
        cot_eff = jnp.where(is_last, dout, recv_cot)
        dp, da, dmb = vjp_fn(cot_eff)

        gacc = jax.tree_util.tree_map(
            lambda g, d: g + jnp.where(b_on, d, jnp.zeros_like(d)),
            gacc, dp)
        lacc = lacc + jnp.where(is_last & b_on, loss_mb, 0.0)
        dxbuf = jnp.where(
            (sid == 0) & b_on,
            lax.dynamic_update_index_in_dim(dxbuf, dmb, mi_b, 0), dxbuf)
        cot_out = jnp.where(b_on, da, jnp.zeros_like(da))

        return (fwd_out, cot_out, ring, gacc, lacc, dxbuf), None

    init = (
        _mark_varying(jnp.zeros(wire_shape, wire_dtype), axis_name),
        _mark_varying(jnp.zeros(wire_shape, wire_dtype), axis_name),
        _mark_varying(jnp.zeros((R,) + tuple(wire_shape), wire_dtype),
                      axis_name),
        jax.tree_util.tree_map(
            lambda p: _mark_varying(jnp.zeros_like(p), axis_name),
            stage_params),
        _mark_varying(jnp.asarray(0.0, jnp.float32), axis_name),
        _mark_varying(jnp.zeros((M,) + x_shape, x_microbatches.dtype),
                      axis_name),
    )
    (fwd_out, cot_out, ring, gacc, lacc, dxbuf), _ = \
        lax.scan(step, init, jnp.arange(steps))

    loss = lax.psum(jnp.where(is_last, lacc, 0.0), axis_name) / M
    grads = jax.tree_util.tree_map(lambda g: g / M, gacc)
    dx = lax.psum(jnp.where(sid == 0, dxbuf, jnp.zeros_like(dxbuf)),
                  axis_name) / M
    return loss, grads, dx


def stack_stage_params(per_stage_params):
    """[stage0_pytree, stage1_pytree, ...] -> stacked pytree with a leading
    stage axis, ready for a P('pipe', ...) sharding."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *per_stage_params)


def microbatch(x, n_micro):
    """(B, ...) -> (n_micro, B/n_micro, ...)"""
    B = x.shape[0]
    assert B % n_micro == 0, f"batch {B} not divisible by {n_micro}"
    return x.reshape((n_micro, B // n_micro) + x.shape[1:])


# ---------------------------------------------------------------------------
# Layer/Model API integration
# ---------------------------------------------------------------------------

class _Pipeline(Operator):
    """Tape op running the GPipe schedule. Inside the compiled shard_map'd
    step (mesh 'pipe' axis active) each pipe member holds its stage's
    (1, ...) slice of the stacked params and activations ride the ring;
    outside a mesh (the eager first step, eval, single-device) the stages
    run sequentially — identical math, so eager/compiled parity holds."""

    def __init__(self, stage_apply, n_stages, n_micro, axis="pipe"):
        super().__init__()
        self.stage_apply = stage_apply
        self.n_stages = n_stages
        self.n_micro = n_micro
        self.axis = axis
        self._mesh_branch = False

    def forward(self, x, *stacked):
        from .communicator import active_axis
        if active_axis(self.axis):
            self._mesh_branch = True
            assert stacked[0].shape[0] == 1, \
                f"mesh 'pipe' axis must have degree n_stages=" \
                f"{self.n_stages}; got param slice {stacked[0].shape}"
            local = [s[0] for s in stacked]
            x_mb = microbatch(x, self.n_micro)
            out = pipeline_spmd(
                lambda params, a: self.stage_apply(params, a),
                local, x_mb, self.axis)
            return _pipe_descale(out.reshape((-1,) + out.shape[2:]),
                                 self.axis)
        self._mesh_branch = False
        a = x
        for i in range(self.n_stages):
            a = self.stage_apply([s[i] for s in stacked], a)
        return a


def _make_1f1b_loss(stage_fn, loss_fn, axis_name):
    """Wrap the 1F1B schedule as a custom-vjp scalar-loss function, so
    differentiating it hands back the schedule's OWN gradients instead of
    autodiffing through the scan (which would re-materialise every tick's
    activations — the exact cost 1F1B exists to avoid)."""

    @jax.custom_vjp
    def f(params_local, x_mb, y_mb):
        loss, _, _ = pipeline_1f1b(stage_fn, loss_fn, params_local,
                                   x_mb, y_mb, axis_name)
        return loss

    def f_fwd(params_local, x_mb, y_mb):
        loss, grads, dx = pipeline_1f1b(stage_fn, loss_fn, params_local,
                                        x_mb, y_mb, axis_name)
        return loss, (grads, dx, y_mb)

    def f_bwd(res, ct):
        grads, dx, y_mb = res
        return (jax.tree_util.tree_map(lambda g: g * ct, grads),
                dx * ct, jnp.zeros_like(y_mb))

    f.defvjp(f_fwd, f_bwd)
    return f


class _Pipeline1F1B(Operator):
    """Tape op: (x, y, *stacked_params) -> scalar loss via the 1F1B
    schedule when the 'pipe' mesh axis is active; sequential identical
    math otherwise (eager first step / single device)."""

    def __init__(self, stage_apply, loss_fn, n_stages, n_micro,
                 axis="pipe"):
        super().__init__()
        self.stage_apply = stage_apply
        self.loss_fn = loss_fn
        self.n_stages = n_stages
        self.n_micro = n_micro
        self.axis = axis

    def forward(self, x, y, *stacked):
        from .communicator import active_axis
        x_mb = microbatch(x, self.n_micro)
        y_mb = microbatch(y, self.n_micro)
        if active_axis(self.axis):
            assert stacked[0].shape[0] == 1, \
                f"mesh 'pipe' axis must have degree n_stages=" \
                f"{self.n_stages}; got param slice {stacked[0].shape}"
            local = tuple(s[0] for s in stacked)
            f = _make_1f1b_loss(self.stage_apply, self.loss_fn, self.axis)
            return f(local, x_mb, y_mb)
        def one(xm, ym):
            a = xm
            for i in range(self.n_stages):
                a = self.stage_apply(tuple(s[i] for s in stacked), a)
            return self.loss_fn(a, ym)
        # vmap over microbatches: trace size stays O(n_stages)
        return jnp.mean(jax.vmap(one)(x_mb, y_mb))


class PipelineModule(Layer):
    """A pipeline-parallel stack of ``n_stages`` structurally identical
    stages, reachable from the Layer/Model API: drop it into a Model's
    forward and give the DistOpt mesh a 'pipe' axis of degree n_stages.

    ``stage_init(rng, x_shape) -> [arrays]`` builds one stage's params;
    ``stage_apply(params, a) -> a`` applies a stage (must preserve the
    activation shape — the GPipe ring rotates a fixed-shape buffer).
    Stage params are stacked on a leading axis and sharded P('pipe', ...),
    so each pipe member materialises only its own stage (optimizer
    moments inherit the spec and shard the same way).
    """

    def __init__(self, stage_apply, stage_init, n_stages, n_micro,
                 axis="pipe"):
        super().__init__()
        self.stage_apply = stage_apply
        self.stage_init = stage_init
        self.n_stages = n_stages
        self.n_micro = n_micro
        self.axis = axis

    def initialize(self, x):
        rng = np.random.RandomState(0)
        per_stage = [list(self.stage_init(rng, x.shape))
                     for _ in range(self.n_stages)]
        self._params = []
        for j in range(len(per_stage[0])):
            stacked = jnp.stack([jnp.asarray(per_stage[i][j])
                                 for i in range(self.n_stages)])
            t = Tensor(data=stacked, device=x.device, requires_grad=True)
            t.stores_grad = True
            t.spec = P(self.axis)
            self._params.append(t)

    def forward(self, x):
        return _Pipeline(self.stage_apply, self.n_stages, self.n_micro,
                         self.axis)(x, *self._params)

    def _own_params(self):
        return {f"stage_param{j}": t for j, t in enumerate(self._params)}


class PipelineModule1F1B(PipelineModule):
    """Pipeline stack trained with the 1F1B schedule: the per-microbatch
    loss lives INSIDE the schedule, so ``forward(x, y)`` returns the mean
    loss directly (activation memory bounded by pipe depth). ``forward(x)``
    without targets falls back to the GPipe forward for inference."""

    def __init__(self, stage_apply, stage_init, loss_fn, n_stages, n_micro,
                 axis="pipe"):
        super().__init__(stage_apply, stage_init, n_stages, n_micro, axis)
        self.loss_fn = loss_fn

    def initialize(self, x, y=None):
        super().initialize(x)

    def forward(self, x, y=None):
        if y is None:
            return super().forward(x)
        return _Pipeline1F1B(self.stage_apply, self.loss_fn,
                             self.n_stages, self.n_micro,
                             self.axis)(x, y, *self._params)


# ---------------------------------------------------------------------------
# heterogeneous stages: embedding -> blocks -> head
# ---------------------------------------------------------------------------

class _StagePack:
    """Flat-packing metadata for one stage's params. Each stage's Layer
    tensors are absorbed into one float32 row of a (S, Lmax) stack
    (sharded P('pipe'), so a pipe member materialises only its own
    stage), and unpacked back into the live tensors inside the traced
    stage apply — different stages may have entirely different param
    pytrees."""

    def __init__(self, tensors, row_dtype=jnp.float32):
        self.tensors = tensors
        self.row_dtype = jnp.dtype(row_dtype)
        self.shapes = [tuple(t.shape) for t in tensors]
        self.dtypes = [jnp.asarray(t.data).dtype for t in tensors]
        self.sizes = [int(np.prod(s)) if s else 1 for s in self.shapes]
        self.offsets = np.cumsum([0] + self.sizes[:-1]).tolist()
        self.size = int(sum(self.sizes))

    def pack(self):
        if not self.tensors:
            return jnp.zeros((0,), self.row_dtype)
        # via host: freshly-initialized params may sit on DIFFERENT
        # device sets (rng-derived ones inherit a mesh-replicated key's
        # devices, zeros-inits sit on the default device) and a device
        # concatenate across those sets is an error. One-time init cost.
        return jnp.asarray(np.concatenate([
            np.asarray(jax.device_get(t.data), np.float32).reshape(-1)
            for t in self.tensors])).astype(self.row_dtype)

    def unpack_into(self, flat):
        for t, shape, dtype, off, size in zip(
                self.tensors, self.shapes, self.dtypes, self.offsets,
                self.sizes):
            t.data = flat[off:off + size].reshape(shape).astype(dtype)


def _feat(shape):
    return int(np.prod(shape[1:])) if len(shape) > 1 else 1


class HeteroPipeline1F1B(Layer):
    """1F1B pipeline over HETEROGENEOUS stages: a list of per-stage Layer
    stacks with different parameters and different activation shapes at
    every boundary (embedding -> transformer blocks -> head, or a ResNet
    with downsampling at stage boundaries).

    TPU-native design: the program stays SPMD over the 'pipe' mesh axis —
    activations cross stage boundaries as flat padded (mb, wire) float32
    buffers riding `lax.ppermute` over ICI, and a `lax.switch` on the
    stage index applies this member's stage, unflattening its own static
    shapes. The last stage folds the per-microbatch loss into its wire
    output, so the schedule core never materialises logits on the wire.

    ``stages``: Layers (or Layer-like callables Tensor -> Tensor), one
    per pipe member, initialized lazily at microbatch shape.
    ``loss_fn(out_array, y_mb_array) -> scalar`` applies at the last
    stage. ``forward(x, y)`` returns the mean microbatch loss;
    ``forward(x)`` runs the GPipe forward for inference.

    The training input x must be float (LM token ids as float work; the
    embedding gather's index cast handles them) — integer inputs would
    need float0 cotangent plumbing.
    """

    def __init__(self, stages, loss_fn, n_micro, axis="pipe",
                 wire_dtype="float32", param_dtype="float32"):
        super().__init__()
        self._stages = list(stages)   # underscore: NOT sublayers — the
        self._loss_fn = loss_fn       # packed stack is the only state
        self.n_micro = n_micro
        self.axis = axis
        # "bfloat16" halves the ICI bytes of every activation AND
        # cotangent hop (the pipeline analogue of the 'half' dist
        # option); loss accumulation stays float32.
        # NOTE on the wire width: one max-over-boundaries width is a
        # DESIGN requirement, not laziness — the wire is a single SPMD
        # array ppermuted around the ring while different members sit at
        # different boundaries in the same tick, so per-boundary widths
        # cannot exist without per-member array shapes (not expressible
        # under shard_map). wire_dtype is the lever that actually
        # shrinks hop bytes.
        self._wire_dtype = jnp.dtype(wire_dtype)
        # "bfloat16" also halves the packed param stack's HBM (a
        # bf16-param model otherwise pays 2x for f32 rows). The rows ARE
        # the master copy, so optimizer updates quantize to bf16 — the
        # same trade as bf16 training anywhere else.
        self._param_dtype = jnp.dtype(param_dtype)

    def initialize(self, x, y=None):
        B = x.shape[0]
        assert B % self.n_micro == 0, \
            f"batch {B} not divisible by n_micro={self.n_micro}"
        mb = B // self.n_micro
        self._dev = x.device
        self._in_shapes, self._out_shapes, self._act_dtypes = [], [], []

        # thread a microbatch ABSTRACTLY through the stages to learn each
        # boundary's shape: stage param creation still executes concretely
        # (Layer.__call__ wraps initialize in ensure_compile_time_eval)
        # but the inter-stage forwards trace with zero device compute —
        # a concrete rehearsal would also mix devices when the rng key is
        # mesh-replicated from an earlier compiled step
        def thread(ab):
            a = Tensor(data=ab, device=x.device, requires_grad=False)
            for stage in self._stages:
                self._in_shapes.append(tuple(a.shape))
                a = stage(a)
                self._out_shapes.append(tuple(a.shape))
                self._act_dtypes.append(jnp.asarray(a.data).dtype)
            return a.data

        jax.eval_shape(thread, jax.ShapeDtypeStruct(
            (mb,) + tuple(x.shape[1:]), jnp.asarray(x.data).dtype))
        self._packs = [_StagePack(list(stage.get_params().values()),
                                  self._param_dtype)
                       if isinstance(stage, Layer)
                       else _StagePack([], self._param_dtype)
                       for stage in self._stages]
        lmax = max([p.size for p in self._packs] + [1])
        rows = [jnp.pad(p.pack(), (0, lmax - p.size))
                for p in self._packs]
        t = Tensor(data=jnp.stack(rows), device=x.device,
                   requires_grad=True)
        t.stores_grad = True
        t.spec = P(self.axis)
        self._stacked = t
        # wire width: largest INTER-stage boundary (the last stage's
        # output never rides the wire in 1F1B) + one slot for the
        # per-microbatch loss scalar
        self._wire_train = max(
            [_feat(s) for s in self._out_shapes[:-1]] + [1]) + 1
        # inference wire must carry the last stage's output too
        self._wire_fwd = max(_feat(s) for s in self._out_shapes)

    def _apply_stage(self, s, a_array):
        out = self._stages[s](Tensor(data=a_array, device=self._dev,
                                     requires_grad=False))
        return out.data

    def _stage_in(self, s, a_wire, mb_x):
        """This stage's input: the injected microbatch for stage 0, else
        the wire buffer unflattened to the boundary's shape. Only FEATURE
        dims are static — under dp the local microbatch is smaller than
        at init time."""
        if s == 0:
            return mb_x
        in_shape = self._in_shapes[s]
        return a_wire[:, :_feat(in_shape)] \
            .reshape((a_wire.shape[0],) + in_shape[1:]) \
            .astype(self._act_dtypes[s - 1])

    def _to_wire(self, o, n_rows, wire):
        of = o.reshape(o.shape[0], -1).astype(self._wire_dtype)
        return jnp.zeros((n_rows, wire), self._wire_dtype) \
            .at[:, :of.shape[1]].set(of)

    def _branch_train(self, s, n_stages):
        wire = self._wire_train

        def fn(flat, a_wire, mb_x, y_mb, key_m):
            # deterministic per-(microbatch, stage) stream: the SAME key
            # reaches this branch at the forward tick and at that
            # microbatch's backward recompute, so RNG layers (dropout)
            # draw identical masks in both — a stateful trace-time key
            # would bake a different mask into the recompute and
            # silently corrupt gradients
            self._dev._set_rng_state(jax.random.fold_in(key_m, s))
            self._packs[s].unpack_into(flat)
            o = self._apply_stage(s, self._stage_in(s, a_wire, mb_x))
            if s == n_stages - 1:
                loss = self._loss_fn(o, y_mb)
                return jnp.zeros((a_wire.shape[0], wire),
                                 self._wire_dtype) \
                    .at[0, -1].set(loss.astype(self._wire_dtype))
            return self._to_wire(o, a_wire.shape[0], wire)

        return fn

    def _branch_fwd(self, s, n_stages):
        wire = self._wire_fwd

        def fn(flat, a_wire, mb_x):
            self._packs[s].unpack_into(flat)
            o = self._apply_stage(s, self._stage_in(s, a_wire, mb_x))
            return self._to_wire(o, a_wire.shape[0], wire)

        return fn

    def _sequential(self, stacked, x_mb, y_mb=None, base_key=None):
        """Identical math without a mesh (eager first step, single
        device): unpack every stage once, then vmap over microbatches,
        folding the SAME per-(microbatch, stage) rng keys as the mesh
        schedule so dropout draws match across paths."""
        for row, pack in zip(stacked, self._packs):
            pack.unpack_into(row)
        if base_key is None:
            base_key = self._dev._get_rng_state()

        def stage_seq(xm, idx):
            a = xm
            for s in range(len(self._stages)):
                self._dev._set_rng_state(
                    jax.random.fold_in(jax.random.fold_in(base_key, idx),
                                       s))
                a = self._apply_stage(s, a)
            return a

        idxs = jnp.arange(x_mb.shape[0])
        if y_mb is None:
            return jax.vmap(stage_seq)(x_mb, idxs)

        def one(xm, ym, idx):
            return self._loss_fn(stage_seq(xm, idx), ym)

        return jnp.mean(jax.vmap(one)(x_mb, y_mb, idxs))

    def forward(self, x, y=None):
        if y is None:
            return _PipelineHetFwd(self)(x, self._stacked)
        return _PipelineHet1F1B(self)(x, y, self._stacked)

    def _own_params(self):
        return {"stages_packed": self._stacked}


def _make_het_1f1b_loss(make_dispatch, wire_shape, axis_name,
                        wire_dtype=jnp.float32):
    """custom-vjp wrapper: differentiating the scalar loss hands back the
    1F1B schedule's OWN gradients instead of autodiffing the scan. The
    rng base key is an explicit argument (custom_vjp forbids closing
    over tracers) with a float0 cotangent."""
    def extract(w, _y):
        return w[0, -1].astype(jnp.float32)

    def run(flat_local, x_mb, y_mb, base_key):
        return _pipeline_1f1b_core(
            make_dispatch(base_key), extract, flat_local, x_mb, y_mb,
            wire_shape, wire_dtype, axis_name)

    @jax.custom_vjp
    def f(flat_local, x_mb, y_mb, base_key):
        return run(flat_local, x_mb, y_mb, base_key)[0]

    def f_fwd(flat_local, x_mb, y_mb, base_key):
        loss, grads, dx = run(flat_local, x_mb, y_mb, base_key)
        return loss, (grads, dx, y_mb, base_key)

    def f_bwd(res, ct):
        grads, dx, y_mb, base_key = res
        return (jax.tree_util.tree_map(lambda g: g * ct, grads),
                dx * ct, jnp.zeros_like(y_mb),
                np.zeros(np.shape(base_key), jax.dtypes.float0))

    f.defvjp(f_fwd, f_bwd)
    return f


class _PipelineHet1F1B(Operator):
    """Tape op: (x, y, stacked_flat) -> scalar loss via the 1F1B schedule
    over heterogeneous stages when the 'pipe' axis is active; sequential
    identical math otherwise."""

    def __init__(self, module):
        super().__init__()
        self.m = module

    def forward(self, x, y, stacked):
        from .communicator import active_axis
        m = self.m
        if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            raise TypeError(
                "HeteroPipeline1F1B training input must be float "
                f"(got {jnp.asarray(x).dtype}); cast token ids to float")
        x_mb = microbatch(x, m.n_micro)
        y_mb = microbatch(y, m.n_micro)
        if active_axis(m.axis):
            S = len(m._stages)
            assert stacked.shape[0] == 1, \
                f"mesh '{m.axis}' axis degree must equal " \
                f"n_stages={S}; got param slice {stacked.shape}"
            branches = [m._branch_train(s, S) for s in range(S)]

            def make_dispatch(base_key):
                def dispatch(flat, a_wire, mb_x, y_m, m_idx):
                    key_m = jax.random.fold_in(base_key, m_idx)
                    return lax.switch(lax.axis_index(m.axis), branches,
                                      flat, a_wire, mb_x, y_m, key_m)
                return dispatch

            base_key = m._dev._get_rng_state()
            f = _make_het_1f1b_loss(
                make_dispatch, (x_mb.shape[1], m._wire_train), m.axis,
                m._wire_dtype)
            out = f(stacked[0], x_mb, y_mb, base_key)
            # branch traces left the device key holding inner tracers;
            # restore a deterministic continuation of the stream
            m._dev._set_rng_state(jax.random.fold_in(base_key, 0x8157))
            return out
        base_key = m._dev._get_rng_state()
        out = m._sequential(stacked, x_mb, y_mb, base_key)
        m._dev._set_rng_state(jax.random.fold_in(base_key, 0x8157))
        return out


class _PipelineHetFwd(Operator):
    """Tape op: (x, stacked_flat) -> last-stage output via the GPipe
    forward over heterogeneous stages (inference path)."""

    def __init__(self, module):
        super().__init__()
        self.m = module

    def forward(self, x, stacked):
        from .communicator import active_axis
        m = self.m
        x_mb = microbatch(x, m.n_micro)
        if active_axis(m.axis):
            S = len(m._stages)
            assert stacked.shape[0] == 1
            branches = [m._branch_fwd(s, S) for s in range(S)]

            def dispatch(flat, a_wire, mb_x):
                return lax.switch(lax.axis_index(m.axis), branches,
                                  flat, a_wire, mb_x)

            w = _pipeline_fwd_core(dispatch, stacked[0], x_mb,
                                   (x_mb.shape[1], m._wire_fwd),
                                   m._wire_dtype, m.axis)
            w = _pipe_descale(w, m.axis)
            out_shape = m._out_shapes[-1]
            o = w[:, :, :_feat(out_shape)].reshape(
                (m.n_micro, x_mb.shape[1]) + out_shape[1:]) \
                .astype(m._act_dtypes[-1])
            return o.reshape((-1,) + out_shape[1:])
        out = m._sequential(stacked, x_mb)
        return out.reshape((-1,) + out.shape[2:])
