"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

No reference equivalent (the reference is data-parallel only, SURVEY.md
§2.4). TPU-native design: every pipeline stage is the same jitted program
(SPMD over the 'pipe' mesh axis inside ``shard_map``); activations hop to
the next stage with `lax.ppermute` over ICI each schedule tick, and the
whole schedule is a `lax.scan` — so XLA sees one static program and
backward-through-the-pipeline falls out of `jax.grad` (the transpose of
`ppermute` is the reverse-direction `ppermute`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_spmd(stage_fn, stage_params, x_microbatches, axis_name="pipe"):
    """Run a GPipe forward inside ``shard_map`` over ``axis_name``.

    Args:
      stage_fn: ``(params, activation) -> activation`` — this device's
        pipeline stage (all stages must preserve the activation shape).
      stage_params: this device's stage parameters (pytree; under
        shard_map give the global stacked params a P(axis_name, ...) spec
        so each device holds its own stage's slice).
      x_microbatches: (n_micro, mb, ...) — the microbatched global input
        (replicated; only stage 0 reads it).

    Returns (n_micro, mb, ...) outputs of the LAST stage, broadcast to all
    stages (so a replicated loss can follow).

    Schedule: t = 0..n_micro+n_stages-2; stage 0 injects microbatch t,
    stage s>0 consumes the activation stage s-1 produced at t-1.
    """
    n = lax.axis_size(axis_name)
    sid = lax.axis_index(axis_name)
    n_micro = x_microbatches.shape[0]
    steps = n_micro + n - 1
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]

    mb_shape = x_microbatches.shape[1:]

    def step(carry, t):
        prev_y = carry
        # activation produced upstream last tick arrives over the ring
        recv = lax.ppermute(prev_y, axis_name, fwd_perm)
        mb = lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
        a = jnp.where(sid == 0, mb, recv)
        y = stage_fn(stage_params, a)
        return y, y

    # the carry becomes device-varying (stage params differ per pipe
    # member); mark the init accordingly for shard_map's vma typecheck
    init = jnp.zeros(mb_shape, x_microbatches.dtype)
    if hasattr(jax.lax, "pcast"):
        init = jax.lax.pcast(init, (axis_name,), to="varying")
    elif hasattr(jax.lax, "pvary"):
        init = jax.lax.pvary(init, (axis_name,))
    _, ys = lax.scan(step, init, jnp.arange(steps))

    # last stage's outputs at ticks n-1 .. steps-1 are microbatches 0..M-1
    outs = lax.dynamic_slice_in_dim(ys, n - 1, n_micro, axis=0)
    # broadcast them from the last stage to everyone
    return lax.psum(jnp.where(sid == n - 1, outs, jnp.zeros_like(outs)),
                    axis_name)


def stack_stage_params(per_stage_params):
    """[stage0_pytree, stage1_pytree, ...] -> stacked pytree with a leading
    stage axis, ready for a P('pipe', ...) sharding."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *per_stage_params)


def microbatch(x, n_micro):
    """(B, ...) -> (n_micro, B/n_micro, ...)"""
    B = x.shape[0]
    assert B % n_micro == 0, f"batch {B} not divisible by {n_micro}"
    return x.reshape((n_micro, B // n_micro) + x.shape[1:])
