"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

No reference equivalent (the reference is data-parallel only, SURVEY.md
§2.4). TPU-native design: every pipeline stage is the same jitted program
(SPMD over the 'pipe' mesh axis inside ``shard_map``); activations hop to
the next stage with `lax.ppermute` over ICI each schedule tick, and the
whole schedule is a `lax.scan` — so XLA sees one static program and
backward-through-the-pipeline falls out of `jax.grad` (the transpose of
`ppermute` is the reverse-direction `ppermute`).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..autograd_base import Operator
from ..layer import Layer
from ..tensor import Tensor


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _pipe_descale(x, axis_name):
    """Identity whose transpose divides the cotangent by the pipe degree.

    In the Model's shard_map (replication checks off) every pipe member
    computes the downstream loss redundantly and injects a full cotangent;
    the last-stage psum broadcast's transpose then sums them, inflating
    every in-pipeline gradient by the pipe degree. This normalises at the
    pipeline boundary so stage-param and upstream grads equal the
    single-program values."""
    return x


def _pipe_descale_fwd(x, axis_name):
    return x, None


def _pipe_descale_bwd(axis_name, _res, g):
    return (g / lax.axis_size(axis_name),)


_pipe_descale.defvjp(_pipe_descale_fwd, _pipe_descale_bwd)


def pipeline_spmd(stage_fn, stage_params, x_microbatches, axis_name="pipe"):
    """Run a GPipe forward inside ``shard_map`` over ``axis_name``.

    Args:
      stage_fn: ``(params, activation) -> activation`` — this device's
        pipeline stage (all stages must preserve the activation shape).
      stage_params: this device's stage parameters (pytree; under
        shard_map give the global stacked params a P(axis_name, ...) spec
        so each device holds its own stage's slice).
      x_microbatches: (n_micro, mb, ...) — the microbatched global input
        (replicated; only stage 0 reads it).

    Returns (n_micro, mb, ...) outputs of the LAST stage, broadcast to all
    stages (so a replicated loss can follow).

    Schedule: t = 0..n_micro+n_stages-2; stage 0 injects microbatch t,
    stage s>0 consumes the activation stage s-1 produced at t-1.
    """
    n = lax.axis_size(axis_name)
    sid = lax.axis_index(axis_name)
    n_micro = x_microbatches.shape[0]
    steps = n_micro + n - 1
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]

    mb_shape = x_microbatches.shape[1:]

    def step(carry, t):
        prev_y = carry
        # activation produced upstream last tick arrives over the ring
        recv = lax.ppermute(prev_y, axis_name, fwd_perm)
        mb = lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
        a = jnp.where(sid == 0, mb, recv)
        y = stage_fn(stage_params, a)
        return y, y

    # the carry becomes device-varying (stage params differ per pipe
    # member); mark the init accordingly for shard_map's vma typecheck
    init = jnp.zeros(mb_shape, x_microbatches.dtype)
    if hasattr(jax.lax, "pcast"):
        init = jax.lax.pcast(init, (axis_name,), to="varying")
    elif hasattr(jax.lax, "pvary"):
        init = jax.lax.pvary(init, (axis_name,))
    _, ys = lax.scan(step, init, jnp.arange(steps))

    # last stage's outputs at ticks n-1 .. steps-1 are microbatches 0..M-1
    outs = lax.dynamic_slice_in_dim(ys, n - 1, n_micro, axis=0)
    # broadcast them from the last stage to everyone
    return lax.psum(jnp.where(sid == n - 1, outs, jnp.zeros_like(outs)),
                    axis_name)


def stack_stage_params(per_stage_params):
    """[stage0_pytree, stage1_pytree, ...] -> stacked pytree with a leading
    stage axis, ready for a P('pipe', ...) sharding."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *per_stage_params)


def microbatch(x, n_micro):
    """(B, ...) -> (n_micro, B/n_micro, ...)"""
    B = x.shape[0]
    assert B % n_micro == 0, f"batch {B} not divisible by {n_micro}"
    return x.reshape((n_micro, B // n_micro) + x.shape[1:])


# ---------------------------------------------------------------------------
# Layer/Model API integration
# ---------------------------------------------------------------------------

class _Pipeline(Operator):
    """Tape op running the GPipe schedule. Inside the compiled shard_map'd
    step (mesh 'pipe' axis active) each pipe member holds its stage's
    (1, ...) slice of the stacked params and activations ride the ring;
    outside a mesh (the eager first step, eval, single-device) the stages
    run sequentially — identical math, so eager/compiled parity holds."""

    def __init__(self, stage_apply, n_stages, n_micro, axis="pipe"):
        super().__init__()
        self.stage_apply = stage_apply
        self.n_stages = n_stages
        self.n_micro = n_micro
        self.axis = axis
        self._mesh_branch = False

    def forward(self, x, *stacked):
        from .communicator import active_axis
        if active_axis(self.axis):
            self._mesh_branch = True
            assert stacked[0].shape[0] == 1, \
                f"mesh 'pipe' axis must have degree n_stages=" \
                f"{self.n_stages}; got param slice {stacked[0].shape}"
            local = [s[0] for s in stacked]
            x_mb = microbatch(x, self.n_micro)
            out = pipeline_spmd(
                lambda params, a: self.stage_apply(params, a),
                local, x_mb, self.axis)
            return _pipe_descale(out.reshape((-1,) + out.shape[2:]),
                                 self.axis)
        self._mesh_branch = False
        a = x
        for i in range(self.n_stages):
            a = self.stage_apply([s[i] for s in stacked], a)
        return a


class PipelineModule(Layer):
    """A pipeline-parallel stack of ``n_stages`` structurally identical
    stages, reachable from the Layer/Model API: drop it into a Model's
    forward and give the DistOpt mesh a 'pipe' axis of degree n_stages.

    ``stage_init(rng, x_shape) -> [arrays]`` builds one stage's params;
    ``stage_apply(params, a) -> a`` applies a stage (must preserve the
    activation shape — the GPipe ring rotates a fixed-shape buffer).
    Stage params are stacked on a leading axis and sharded P('pipe', ...),
    so each pipe member materialises only its own stage (optimizer
    moments inherit the spec and shard the same way).
    """

    def __init__(self, stage_apply, stage_init, n_stages, n_micro,
                 axis="pipe"):
        super().__init__()
        self.stage_apply = stage_apply
        self.stage_init = stage_init
        self.n_stages = n_stages
        self.n_micro = n_micro
        self.axis = axis

    def initialize(self, x):
        rng = np.random.RandomState(0)
        per_stage = [list(self.stage_init(rng, x.shape))
                     for _ in range(self.n_stages)]
        self._params = []
        for j in range(len(per_stage[0])):
            stacked = jnp.stack([jnp.asarray(per_stage[i][j])
                                 for i in range(self.n_stages)])
            t = Tensor(data=stacked, device=x.device, requires_grad=True)
            t.stores_grad = True
            t.spec = P(self.axis)
            self._params.append(t)

    def forward(self, x):
        return _Pipeline(self.stage_apply, self.n_stages, self.n_micro,
                         self.axis)(x, *self._params)

    def _own_params(self):
        return {f"stage_param{j}": t for j, t in enumerate(self._params)}
