"""Collective operations as tape ops with explicit transposes.

The TPU-native replacement for the reference Communicator's op surface
(src/io/communicator.cc synch/fusedSynch/...): collectives are ordinary
differentiable autograd ops that lower to XLA collectives over the mesh
when tracing inside ``shard_map`` (the Model layer arms the axis context),
and degrade to identity in single-device eager execution.

Every op pins its own backward (Megatron-style f/g duality) instead of
relying on ``jax.vjp``: under ``shard_map(..., check_vma=False)`` the
autodiff transpose of ``lax.psum`` is another ``psum``, which double-counts
by the axis size when the cotangent is already replicated. The correct
pairs are:

    AllReduce        fwd psum       bwd identity        ("g")
    CopyToParallel   fwd identity   bwd psum            ("f")
    AllGather        fwd gather     bwd take-own-shard
    ReduceScatter    fwd psum_scatter  bwd all_gather
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..autograd_base import Operator
from .communicator import active_axis, axis_size as _axis_size


class AllReduce(Operator):
    """psum over a mesh axis (reference Communicator::synch). Backward is
    identity: the summed output's cotangent is replicated already."""

    def __init__(self, axis_name="data"):
        super().__init__()
        self.axis_name = axis_name

    def forward(self, x):
        if active_axis(self.axis_name):
            return lax.psum(x, self.axis_name)
        return x

    def backward(self, dy):
        return dy


class CopyToParallel(Operator):
    """Identity forward into a model-parallel region; backward all-reduces
    the partial input-gradients the shards produce (Megatron's ``f``)."""

    def __init__(self, axis_name="model"):
        super().__init__()
        self.axis_name = axis_name

    def forward(self, x):
        return x

    def backward(self, dy):
        if active_axis(self.axis_name):
            return lax.psum(dy, self.axis_name)
        return dy


class AllGather(Operator):
    """Concatenate shards along ``concat_axis``; backward hands each shard
    the slice of the cotangent it contributed."""

    def __init__(self, axis_name="model", concat_axis=-1):
        super().__init__()
        self.axis_name = axis_name
        self.concat_axis = concat_axis

    def forward(self, x):
        self._local = x.shape[self.concat_axis % x.ndim]
        if active_axis(self.axis_name):
            return lax.all_gather(x, self.axis_name,
                                  axis=self.concat_axis % x.ndim,
                                  tiled=True)
        return x

    def backward(self, dy):
        if active_axis(self.axis_name):
            idx = lax.axis_index(self.axis_name)
            ax = self.concat_axis % dy.ndim
            return lax.dynamic_slice_in_dim(dy, idx * self._local,
                                            self._local, axis=ax)
        return dy


class ReduceScatter(Operator):
    """psum + scatter along ``scatter_axis``; backward all-gathers."""

    def __init__(self, axis_name="model", scatter_axis=-1):
        super().__init__()
        self.axis_name = axis_name
        self.scatter_axis = scatter_axis

    def forward(self, x):
        if active_axis(self.axis_name):
            ax = self.scatter_axis % x.ndim
            return lax.psum_scatter(x, self.axis_name,
                                    scatter_dimension=ax, tiled=True)
        return x

    def backward(self, dy):
        if active_axis(self.axis_name):
            ax = self.scatter_axis % dy.ndim
            return lax.all_gather(dy, self.axis_name, axis=ax, tiled=True)
        return dy


class PMean(Operator):
    """pmean over a mesh axis (metric averaging)."""

    def __init__(self, axis_name="data"):
        super().__init__()
        self.axis_name = axis_name

    def forward(self, x):
        if active_axis(self.axis_name):
            return lax.pmean(x, self.axis_name)
        return x

    def backward(self, dy):
        if active_axis(self.axis_name):
            return dy / _axis_size(self.axis_name)
        return dy


class AllToAll(Operator):
    """Tiled all-to-all over a mesh axis (expert-parallel token dispatch:
    split ``split_axis`` across the axis peers, concatenate what each peer
    sends back along ``concat_axis``). Backward is the reverse exchange.
    Identity outside an active mesh context (world of 1)."""

    def __init__(self, axis_name="expert", split_axis=0, concat_axis=1):
        super().__init__()
        self.axis_name = axis_name
        self.split_axis = split_axis
        self.concat_axis = concat_axis

    def forward(self, x):
        if active_axis(self.axis_name):
            return lax.all_to_all(x, self.axis_name, self.split_axis,
                                  self.concat_axis, tiled=True)
        return x

    def backward(self, dy):
        if active_axis(self.axis_name):
            return lax.all_to_all(dy, self.axis_name, self.concat_axis,
                                  self.split_axis, tiled=True)
        return dy


def all_reduce(x, axis_name="data"):
    return AllReduce(axis_name)(x)


def copy_to_parallel(x, axis_name="model"):
    return CopyToParallel(axis_name)(x)


def all_gather(x, axis_name="model", concat_axis=-1):
    return AllGather(axis_name, concat_axis)(x)


def reduce_scatter(x, axis_name="model", scatter_axis=-1):
    return ReduceScatter(axis_name, scatter_axis)(x)


def pmean(x, axis_name="data"):
    return PMean(axis_name)(x)


def all_to_all(x, axis_name="expert", split_axis=0, concat_axis=1):
    return AllToAll(axis_name, split_axis, concat_axis)(x)
