"""Chrome-trace / Perfetto export of the flight-recorder ring.

The recorder's JSONL records (spans, events, in-flight ``span_open``
records, dump headers, metrics snapshots) render into ONE Chrome Trace
Event Format document (``{"traceEvents": [...]}``) that opens directly
in ``ui.perfetto.dev`` or ``chrome://tracing`` — the whole train-and-
serve session on a timeline instead of a JSONL scroll.

Mapping:

- each distinct ``rank`` attribution becomes a **process** row
  (``pid``), named ``rank N``;
- within a rank, records WITHOUT a request id share the ``runtime``
  thread (``tid`` 0); records carrying a ``request`` attr (the serving
  engine's per-request trace: ``request.queued`` → ``request.prefill``
  → ``request.decode_tick``... → ``request.delivered``) each get their
  own named thread lane, so one gateway request reads as one row;
- ``span`` / ``span_open`` records are complete (``ph: "X"``) events —
  start timestamp from ``ts_start`` (falling back to ``ts - dur_s``
  for pre-PR-9 records), duration from ``dur_s``/``age_s``;
- ``event`` records are instant (``ph: "i"``) events; the full attr
  dict rides ``args`` (so a ``retrace`` event's signature diff and a
  ``profile.sample``'s fusion table are clickable in the UI);
- a ``metrics`` record (the snapshot a blackbox dump closes with)
  becomes an instant event whose ``args`` carry the per-fusion
  ``profile_fusion_seconds`` table and the snapshot's metric names.

Timestamps are microseconds relative to the earliest record, which is
what the viewers expect. :func:`validate_chrome_trace` is the schema
gate the CLI selftest and the gateway endpoint run before replying.
"""

from __future__ import annotations

import json

# span attrs that are structural (consumed by the mapping), not args
_STRUCTURAL = ("kind", "name", "ts", "ts_start", "dur_s", "age_s",
               "rank")


def _start_ts(rec):
    if rec.get("ts_start") is not None:
        return float(rec["ts_start"])
    ts = rec.get("ts")
    if ts is None:
        return None
    if rec.get("kind") == "span":
        return float(ts) - float(rec.get("dur_s") or 0.0)
    return float(ts)


def _fusion_args(snapshot):
    """Pull the per-fusion gauge table out of one metrics snapshot —
    the 'fusion tables' part of the export contract."""
    args = {"metrics": sorted(m.get("name", "?")
                              for m in snapshot.get("metrics", []))}
    for m in snapshot.get("metrics", []):
        if m.get("name") == "profile_fusion_seconds":
            rows = []
            for s in m.get("series", []):
                labels = s.get("labels") or {}
                rows.append([labels.get("fusion", "?"),
                             s.get("value")])
            rows.sort(key=lambda r: -(r[1] or 0.0))
            args["profile_fusion_seconds"] = rows[:32]
    return args


def to_chrome_trace(records):
    """Render recorder records (dicts, recorder/JSONL order) into a
    Chrome Trace Event Format document. Unknown record kinds are
    skipped; an empty input renders an empty (still valid) trace."""
    recs = [r for r in records if isinstance(r, dict)]
    tvals = [t for t in (_start_ts(r) for r in recs) if t is not None]
    t0 = min(tvals) if tvals else 0.0

    pids = {}           # rank value -> pid
    tids = {}           # (pid, lane key) -> tid
    meta, events = [], []
    # dump headers and metrics snapshots are process-global, not any
    # one rank's work — they get their own "recorder" row instead of
    # landing in whichever rank happened to claim pid 1 first
    recorder_pid = [None]

    def pid_recorder():
        if recorder_pid[0] is None:
            recorder_pid[0] = 1_000_000
            meta.append({"ph": "M", "name": "process_name",
                         "pid": recorder_pid[0], "tid": 0,
                         "args": {"name": "recorder"}})
        return recorder_pid[0]

    def pid_of(rec):
        rank = rec.get("rank", 0)
        key = str(rank)
        if key not in pids:
            pids[key] = len(pids) + 1
            meta.append({"ph": "M", "name": "process_name",
                         "pid": pids[key], "tid": 0,
                         "args": {"name": f"rank {rank}"}})
            meta.append({"ph": "M", "name": "thread_name",
                         "pid": pids[key], "tid": 0,
                         "args": {"name": "runtime"}})
        return pids[key]

    def named_tid(pid, key, label):
        k = (pid, str(key))
        if k not in tids:
            tids[k] = len(tids) + 1
            meta.append({"ph": "M", "name": "thread_name",
                         "pid": pid, "tid": tids[k],
                         "args": {"name": label}})
        return tids[k]

    def tid_of(pid, rec):
        rid = rec.get("request")
        if not rid:
            return 0
        return named_tid(pid, rid, f"request {rid}")

    def timeline_lanes(rec, pid, ts_us):
        """Extra Perfetto rows for one ``timeline.sample`` event: the
        profiled step's bucketized device timeline (compute /
        collective / memcpy / host / idle intervals), placed so the
        window ENDS at the sample event — one named lane per bucket,
        so 'where did the step go' is visible on the same trace as the
        spans that asked."""
        lanes = rec.get("lanes")
        window_s = rec.get("window_s")
        if not isinstance(lanes, dict) or not window_s:
            return
        site = rec.get("site", "train")
        base = max(0.0, ts_us - float(window_s) * 1e6)
        for bucket, intervals in lanes.items():
            if not intervals:
                continue
            tid = named_tid(pid, f"timeline:{site}:{bucket}",
                            f"timeline {bucket}")
            for iv in intervals:
                try:
                    rel, dur = float(iv[0]), float(iv[1])
                except (TypeError, ValueError, IndexError):
                    continue
                events.append({
                    "ph": "X", "name": bucket, "cat": "timeline",
                    "pid": pid, "tid": tid,
                    "ts": base + max(0.0, rel) * 1e6,
                    "dur": max(0.0, dur) * 1e6,
                    "args": {"bucket": bucket, "site": site}})

    for rec in recs:
        kind = rec.get("kind")
        ts = _start_ts(rec)
        if ts is None:
            continue
        ts_us = max(0.0, (ts - t0) * 1e6)
        if kind == "metrics":
            events.append({"ph": "i", "name": "metrics_snapshot",
                           "cat": "metrics", "pid": pid_recorder(),
                           "tid": 0, "ts": ts_us, "s": "g",
                           "args": _fusion_args(
                               rec.get("snapshot") or {})})
            continue
        if kind == "dump":
            events.append({"ph": "i", "name": "blackbox_dump",
                           "cat": "dump", "pid": pid_recorder(),
                           "tid": 0, "ts": ts_us, "s": "g",
                           "args": {k: v for k, v in rec.items()
                                    if k not in ("kind", "ts")}})
            continue
        if kind not in ("span", "span_open", "event"):
            continue
        pid = pid_of(rec)
        tid = tid_of(pid, rec)
        args = {k: v for k, v in rec.items() if k not in _STRUCTURAL}
        if kind == "event":
            if rec.get("name") == "timeline.sample":
                # the bucket lanes render as their own rows; the
                # instant event keeps the fractions/waterfall args but
                # not the raw interval list (it would bloat every
                # click)
                args.pop("lanes", None)
                timeline_lanes(rec, pid, ts_us)
            events.append({"ph": "i", "name": rec.get("name", "event"),
                           "cat": "event", "pid": pid, "tid": tid,
                           "ts": ts_us, "s": "t", "args": args})
        else:
            dur_s = rec.get("dur_s", rec.get("age_s", 0.0)) or 0.0
            if kind == "span_open":
                args["open"] = True
            events.append({"ph": "X", "name": rec.get("name", "span"),
                           "cat": kind, "pid": pid, "tid": tid,
                           "ts": ts_us,
                           "dur": max(float(dur_s) * 1e6, 0.0),
                           "args": args})
    events.sort(key=lambda e: e["ts"])
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def validate_chrome_trace(doc, check_serializable=True):
    """Structural gate over an exported trace document: raises
    ValueError naming the first problem, returns the doc for chaining.
    Checks what the viewers actually require — every event has a phase
    and pid/tid, non-metadata events have numeric non-negative
    timestamps, complete events have numeric durations — plus a JSON
    round-trip (an unserializable arg must fail HERE, not in the
    browser). A caller about to serialize the doc itself passes
    ``check_serializable=False`` — its own ``json.dumps`` IS that
    check, and the doc can hold the whole recorder ring (dumping it
    twice doubles the endpoint's cost for nothing)."""
    if not isinstance(doc, dict):
        raise ValueError("trace is not a dict")
    evs = doc.get("traceEvents")
    if not isinstance(evs, list):
        raise ValueError("traceEvents is not a list")
    for i, e in enumerate(evs):
        if not isinstance(e, dict):
            raise ValueError(f"traceEvents[{i}] is not a dict")
        ph = e.get("ph")
        if not isinstance(ph, str) or not ph:
            raise ValueError(f"traceEvents[{i}]: missing phase 'ph'")
        if not isinstance(e.get("name"), str):
            raise ValueError(f"traceEvents[{i}]: missing name")
        for f in ("pid", "tid"):
            if not isinstance(e.get(f), int):
                raise ValueError(f"traceEvents[{i}]: missing {f}")
        if ph == "M":
            if not isinstance(e.get("args"), dict):
                raise ValueError(
                    f"traceEvents[{i}]: metadata without args")
            continue
        ts = e.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            raise ValueError(
                f"traceEvents[{i}] ({e['name']}): bad ts {ts!r}")
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                raise ValueError(
                    f"traceEvents[{i}] ({e['name']}): bad dur {dur!r}")
    if check_serializable:
        try:
            json.dumps(doc)
        except (TypeError, ValueError) as e:
            raise ValueError(f"trace is not JSON-serializable: {e}") \
                from None
    return doc


def records_from_jsonl(path):
    """Parse one recorder file (a blackbox dump or a live
    ``spans.jsonl`` sink) back into record dicts, skipping unparseable
    lines (a torn final line must not void the rest of a post-mortem)."""
    out = []
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if not ln:
                continue
            try:
                out.append(json.loads(ln))
            except json.JSONDecodeError:
                continue
    return out


def export_records(records, path):
    """Render + validate + write ``records`` as ``path`` (a
    ``.trace.json`` that opens in ui.perfetto.dev). Returns the doc."""
    doc = validate_chrome_trace(to_chrome_trace(records))
    with open(path, "w") as f:
        json.dump(doc, f)
    return doc


def live_records(recorder=None, registry=None):
    """The LIVE process trace as records: the flight-recorder ring,
    in-flight (still-open) spans, and a closing metrics snapshot (the
    fusion tables ride it). The one composition both live consumers —
    the gateway's ``GET /trace.json`` and :func:`export_recorder` —
    render, so they cannot drift."""
    import time

    from . import metrics as _metrics
    from . import spans as _spans
    rec = recorder if recorder is not None else _spans.recorder()
    records = list(rec.records()) + _spans.open_spans()
    dropped = getattr(rec, "dropped_records", 0)
    if dropped:
        # loud partiality: the ring evicted records, so this trace
        # starts mid-story — say so IN the trace instead of letting an
        # empty-looking prefix read as "nothing happened"
        records.append({
            "kind": "event", "name": "recorder.dropped",
            "ts": time.time(), "dropped_records": dropped,
            "note": "flight-recorder ring evicted older records; "
                    "this trace is partial"})
    reg = registry if registry is not None \
        else _metrics.default_registry()
    try:
        records.append({"kind": "metrics", "ts": time.time(),
                        "snapshot": reg.snapshot()})
    except Exception:   # noqa: BLE001 — spans alone still export
        pass
    return records


def export_recorder(path, recorder=None, registry=None):
    """Export the LIVE default flight recorder (:func:`live_records`)
    to ``path`` as a Perfetto-openable trace."""
    return export_records(live_records(recorder, registry), path)


__all__ = ["to_chrome_trace", "validate_chrome_trace",
           "records_from_jsonl", "export_records", "live_records",
           "export_recorder"]
