"""Unified telemetry for the training runtime.

Three cooperating pieces (see each module's docstring):

- :mod:`.metrics` — the process-wide metrics registry (counters, gauges,
  histograms with labels), snapshot-first serialization, heartbeat
  summaries and the fleet aggregation the coordinator publishes.
- :mod:`.spans` — nested wall-clock trace spans (``compile``, ``step``,
  ``checkpoint.save``, ``restore``, ``barrier``, ``data.next``) with
  run/rank/step attribution, and the bounded flight-recorder ring the
  resilient trainer dumps to ``telemetry/blackbox-<rank>.jsonl`` on
  every abnormal exit path.
- :mod:`.export` — Prometheus-text rendering, snapshot schema
  validation, and the optional localhost HTTP endpoint. The
  ``tools/metrics_dump.py`` CLI drives these.
- :mod:`.perf` — the performance-observability layer on top: HBM
  gauges + OOM post-mortems, compile/retrace attribution
  (``compile_seconds`` + ``retrace`` events naming the changed arg),
  the sampling step profiler, and the step-time anomaly sentinel.
- :mod:`.trace_export` — renders the flight-recorder ring into a
  Chrome-trace ``.trace.json`` that opens in ui.perfetto.dev
  (``tools/trace_export.py`` is the CLI, the serving gateway serves it
  at ``/trace.json``).
- :mod:`.timeline` — step-timeline attribution: buckets the profiler's
  device trace into compute / collective / memcpy / host / idle,
  computes exposed-communication seconds and the MFU-loss waterfall,
  publishes ``timeline_*`` gauges, and labels stragglers with a cause
  (``comm_bound | data_bound | compute_bound | compile_bound``).

Host-side only: nothing here imports jax at module scope or runs
inside a compiled step — ``compiled_step_info()["n_traces"]`` stays 1
with telemetry on, and per-step instrumentation cost is microseconds
(both pinned by ``tests/test_observability.py`` and
``tests/test_perf_observability.py``).
"""

from . import metrics     # noqa: F401
from . import spans       # noqa: F401
from . import export      # noqa: F401
from . import perf        # noqa: F401
from . import trace_export  # noqa: F401
from . import timeline    # noqa: F401

from .metrics import (MetricsRegistry, default_registry,  # noqa: F401
                      heartbeat_summary, aggregate_summaries,
                      device_peak_flops)
from .spans import (FlightRecorder, span, event, context,  # noqa: F401
                    recorder, configure, open_spans)
from .export import (render_prometheus, validate_snapshot,  # noqa: F401
                     serve_metrics)
from .perf import (hbm_stats, record_hbm,                 # noqa: F401
                   live_array_report, record_compile,
                   SamplingProfiler, AnomalySentinel)
from .trace_export import (to_chrome_trace,               # noqa: F401
                           validate_chrome_trace)
