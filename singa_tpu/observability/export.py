"""Exporters over metric snapshots: Prometheus text, schema validation,
and an optional localhost HTTP endpoint.

Everything renders from the SNAPSHOT dict (``MetricsRegistry.snapshot``,
schema ``singa-tpu-metrics/1``), never from live registry internals — so
``tools/metrics_dump.py`` can convert a metrics.json written by a dead
run exactly like a live scrape, and the HTTP endpoint is a thin loop
around ``registry.snapshot()``.
"""

from __future__ import annotations

import json
import threading

from .metrics import SNAPSHOT_SCHEMA, default_registry


# the quantile summaries every histogram series exports (serving SLOs
# read p99 token latency straight off the snapshot)
QUANTILES = {"p50": 0.5, "p95": 0.95, "p99": 0.99}


def bucket_quantile(buckets, count, q, lo=None, hi=None):
    """Estimate the ``q``-quantile of one histogram series from its
    CUMULATIVE ``[le, count]`` buckets (Prometheus ``histogram_quantile``
    style: linear interpolation inside the containing bucket), clamped
    to the series' exact observed ``[lo, hi]`` extrema when given — so a
    single-observation histogram reports the exact value and no
    quantile can stray outside what was actually seen. Returns None for
    an empty series."""
    count = int(count or 0)
    if count <= 0:
        return None
    target = float(q) * count
    prev_le, prev_cum = None, 0
    val = None
    for le, cum in buckets:
        if cum >= target and cum > prev_cum:
            if le == "+Inf":
                # the overflow bucket has no upper edge; the exact max
                # (when known) is the honest answer, else the last
                # finite edge
                val = hi if hi is not None else prev_le
            else:
                lower = prev_le if prev_le is not None \
                    else (lo if lo is not None else 0.0)
                lower = min(float(lower), float(le))
                frac = (target - prev_cum) / (cum - prev_cum)
                val = lower + (float(le) - lower) * frac
            break
        if le != "+Inf":
            prev_le = float(le)
        prev_cum = cum
    if val is None:
        return None
    if lo is not None:
        val = max(val, float(lo))
    if hi is not None:
        val = min(val, float(hi))
    return val


def series_quantiles(series, quantiles=None):
    """``{"p50": v, "p95": v, "p99": v}`` for one histogram series doc
    (``buckets``/``count`` plus optional exact ``min``/``max``).
    Values are None when the series is empty."""
    qs = quantiles if quantiles is not None else QUANTILES
    return {name: bucket_quantile(series.get("buckets") or [],
                                  series.get("count"), q,
                                  lo=series.get("min"),
                                  hi=series.get("max"))
            for name, q in qs.items()}


def _prom_escape(v):
    return str(v).replace("\\", r"\\").replace('"', r'\"') \
        .replace("\n", r"\n")


def _labels_text(labels, extra=None):
    items = list((labels or {}).items()) + list((extra or {}).items())
    if not items:
        return ""
    body = ",".join(f'{k}="{_prom_escape(v)}"' for k, v in items)
    return "{" + body + "}"


def render_prometheus(snapshot):
    """Prometheus exposition text for one snapshot dict."""
    validate_snapshot(snapshot)
    lines = []
    for m in snapshot["metrics"]:
        name, kind = m["name"], m["kind"]
        if m.get("help"):
            lines.append(f"# HELP {name} {_prom_escape(m['help'])}")
        lines.append(f"# TYPE {name} {kind}")
        for s in m["series"]:
            labels = s.get("labels") or {}
            if kind == "histogram":
                for le, c in s["buckets"]:
                    lines.append(
                        f"{name}_bucket"
                        f"{_labels_text(labels, {'le': le})} {c}")
                lines.append(f"{name}_sum{_labels_text(labels)} "
                             f"{s['sum']}")
                lines.append(f"{name}_count{_labels_text(labels)} "
                             f"{s['count']}")
                # quantile summaries as sibling untyped samples
                # (`<name>_p99`, not `<name>{quantile=}` — the latter
                # is reserved for TYPE summary and would make the
                # histogram exposition invalid)
                for qname, qv in (s.get("quantiles") or {}).items():
                    if qv is not None:
                        lines.append(
                            f"{name}_{qname}{_labels_text(labels)} "
                            f"{qv}")
            else:
                lines.append(f"{name}{_labels_text(labels)} "
                             f"{s['value']}")
    return "\n".join(lines) + "\n"


def validate_snapshot(doc):
    """Structural check of a snapshot dict (the CLI selftest's and any
    snapshot reader's gate). Raises ValueError naming the first problem;
    returns the doc for chaining."""
    if not isinstance(doc, dict):
        raise ValueError("snapshot is not a dict")
    if doc.get("schema") != SNAPSHOT_SCHEMA:
        raise ValueError(
            f"snapshot schema {doc.get('schema')!r} is not "
            f"{SNAPSHOT_SCHEMA!r}")
    metrics = doc.get("metrics")
    if not isinstance(metrics, list):
        raise ValueError("snapshot.metrics is not a list")
    for m in metrics:
        name = m.get("name")
        if not name or not isinstance(name, str):
            raise ValueError("metric without a name")
        if m.get("kind") not in ("counter", "gauge", "histogram"):
            raise ValueError(f"metric {name}: unknown kind {m.get('kind')!r}")
        if not isinstance(m.get("series"), list):
            raise ValueError(f"metric {name}: series is not a list")
        for s in m["series"]:
            if not isinstance(s.get("labels", {}), dict):
                raise ValueError(f"metric {name}: series labels not a dict")
            if m["kind"] == "histogram":
                for field in ("count", "sum", "buckets"):
                    if field not in s:
                        raise ValueError(
                            f"metric {name}: histogram series missing "
                            f"{field!r}")
                counts = [c for _le, c in s["buckets"]]
                if counts != sorted(counts):
                    raise ValueError(
                        f"metric {name}: bucket counts not cumulative")
                if counts and counts[-1] != s["count"]:
                    raise ValueError(
                        f"metric {name}: +Inf bucket {counts[-1]} != "
                        f"count {s['count']}")
                if "quantiles" in s and \
                        not isinstance(s["quantiles"], dict):
                    raise ValueError(
                        f"metric {name}: quantiles is not a dict")
            elif "value" not in s:
                raise ValueError(f"metric {name}: series missing value")
    return doc


def serve_metrics(registry=None, host="127.0.0.1", port=0):
    """Start a daemon-thread HTTP endpoint serving the live registry:
    ``/metrics`` (Prometheus text) and ``/metrics.json`` (snapshot).
    Returns ``(server, port)``; ``server.shutdown()`` stops it. Binds
    localhost by default — this is a debugging/scrape endpoint, not a
    public service."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    reg = registry if registry is not None else default_registry()

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            try:
                if self.path.startswith("/metrics.json"):
                    body = json.dumps(reg.snapshot()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/metrics"):
                    body = render_prometheus(reg.snapshot()).encode()
                    ctype = "text/plain; version=0.0.4"
                else:
                    self.send_error(404)
                    return
            except Exception as e:      # a scrape must not crash the job
                self.send_error(500, str(e)[:100])
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):      # silence per-request stderr spam
            pass

    server = ThreadingHTTPServer((host, int(port)), Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="metrics-http")
    t.start()
    return server, server.server_address[1]


__all__ = ["render_prometheus", "validate_snapshot", "serve_metrics",
           "bucket_quantile", "series_quantiles", "QUANTILES"]
