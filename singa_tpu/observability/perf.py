"""Continuous performance observability over the telemetry spine.

PR 6 built the *what happened* layer (metrics registry, spans, the
flight recorder); this module is the *why is it slow / where did the
memory go / why did it retrace* layer the MFU push and the cold-start
work are measured with:

- **HBM telemetry** (:func:`hbm_stats`, :func:`record_hbm`): the one
  shared reader of ``jax_device.memory_stats()`` — normalized dict in,
  ``hbm_*`` gauges out — sampled at training step boundaries and
  serving ticks. :func:`live_array_report` is the OOM post-mortem: a
  bounded ``jax.live_arrays()`` allocation breakdown grouped by
  (shape, dtype), dumped into crash blackboxes.
- **Compile/retrace attribution** (:func:`step_signature`,
  :func:`diff_signatures`, :func:`record_compile`): every trace of a
  compiled step/serving program lands its wall-clock in the
  ``compile_seconds{program}`` histogram and a ``compile``/``retrace``
  flight-recorder event carrying the arg-shape/dtype signature — a
  retrace event NAMES the argument whose signature changed (old vs
  new), so "why did it retrace" is answerable from the blackbox.
- **Sampling step profiler** (:class:`SamplingProfiler`): every Nth
  step runs under the existing ``measure_step_fusions`` machinery
  (``Model.profile_step``), refreshing the ``profile_fusion_*`` gauges
  continuously instead of on demand. Non-sample steps pay one integer
  check; the compiled step never retraces (the profiler wraps the
  already-compiled dispatch).
- **Anomaly sentinel** (:class:`AnomalySentinel`): a rolling (EMA)
  per-rank step-time baseline; a sustained spike fires an attributed
  ``step_anomaly`` event and tells the caller to capture a one-shot
  profile and dump the blackbox. Cross-rank straggler attribution
  rides the heartbeat summaries
  (``metrics.aggregate_summaries -> step_time_stragglers``).

Contract unchanged from PR 6: nothing here imports jax at module
level, everything is host-side (dict updates + ``perf_counter``), and
``compiled_step_info()["n_traces"]`` stays 1 with every feature on —
pinned by ``tests/test_perf_observability.py`` together with a
measured non-sample-step overhead bound.
"""

from __future__ import annotations

import time

import numpy as np

from . import metrics as _metrics
from . import spans as _spans

# memory_stats keys promoted to their own named gauge (the three the
# HBM dashboards and the bench legs read); everything else numeric the
# backend reports lands in the labeled ``hbm_stat_bytes{kind}`` gauge
_HBM_NAMED = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
_HBM_EXTRA = ("bytes_reserved", "largest_alloc_size", "pool_bytes",
              "bytes_reservable_limit")

# devices whose memory_stats() came back unusable — probed once, then
# every later sample is a set lookup (the CPU/emulator fast path on the
# per-step and per-tick call sites)
_HBM_UNAVAILABLE = set()


# ---------------------------------------------------------------------------
# HBM telemetry
# ---------------------------------------------------------------------------

def hbm_stats(jax_device, raise_errors=False):
    """Normalized ``memory_stats()`` of one jax device: the known byte
    counters as ints plus a derived ``peak_gib``, or None when the
    backend has no stats (CPU, emulators) or the read fails.

    ``raise_errors=True`` propagates a FAILING ``memory_stats()`` call
    instead of folding it into None — diagnostic callers (the HBM
    probe children) must report "the TPU runtime errored: <why>", not
    the same silence a stats-less CPU produces.

    NOTE: ``peak_bytes_in_use`` is a process-lifetime high-water mark —
    within one process it is monotonic across workloads. A precise
    per-model peak needs a fresh process (what
    ``tools/tpu_probe_extra.py``'s HBM children do); in-process samples
    are an upper bound."""
    ms = getattr(jax_device, "memory_stats", None)
    if ms is None:
        return None
    try:
        stats = ms()
    except Exception:       # noqa: BLE001 — telemetry is best-effort
        if raise_errors:
            raise
        return None
    if not stats:
        return None
    out = {}
    for k in _HBM_NAMED + _HBM_EXTRA:
        v = stats.get(k)
        if v is not None:
            try:
                out[k] = int(v)
            except (TypeError, ValueError):
                continue
    if not out:
        return None
    if out.get("peak_bytes_in_use"):
        out["peak_gib"] = round(out["peak_bytes_in_use"] / 2**30, 3)
    return out


def record_hbm(jax_device, registry=None, site="train"):
    """Sample one device's HBM stats into gauges — the step-boundary /
    serving-tick call site. Returns the stats dict (or None).

    Gauges: ``hbm_bytes_in_use``, ``hbm_peak_bytes_in_use``,
    ``hbm_bytes_limit`` (labels: ``site`` = ``train``/``serve``/...),
    plus ``hbm_stat_bytes{site, kind}`` for any further counter the
    backend reports. A device without stats is probed ONCE and then
    skipped by a set lookup, so off-accelerator call sites cost
    nothing."""
    if jax_device is None or id(jax_device) in _HBM_UNAVAILABLE:
        return None
    stats = hbm_stats(jax_device)
    if stats is None:
        _HBM_UNAVAILABLE.add(id(jax_device))
        return None
    reg = registry if registry is not None else _metrics.default_registry()
    for k in _HBM_NAMED:
        if k in stats:
            reg.gauge(f"hbm_{k}",
                      f"device memory_stats {k} at the newest sample",
                      labels=("site",)).set(stats[k], site=site)
    extra = reg.gauge("hbm_stat_bytes",
                      "further device memory_stats counters",
                      labels=("site", "kind"))
    for k in _HBM_EXTRA:
        if k in stats:
            extra.set(stats[k], site=site, kind=k)
    return stats


def live_array_report(top=15):
    """Bounded ``jax.live_arrays()`` allocation breakdown — the OOM
    post-mortem the crash blackbox carries: arrays grouped by
    (dtype, shape) with per-group count/bytes, biggest first, plus the
    total. Returns None when jax (or the walk) is unavailable; never
    raises — this runs on paths where the process is already dying."""
    try:
        import jax
        arrs = jax.live_arrays()
    except Exception:       # noqa: BLE001 — post-mortem is best-effort
        return None
    groups = {}
    total = 0
    n = 0
    for a in arrs:
        try:
            shape = tuple(int(d) for d in a.shape)
            dtype = str(a.dtype)
            nbytes = int(np.prod(shape or (1,))) * \
                int(np.dtype(a.dtype).itemsize) if shape is not None else 0
        except Exception:   # noqa: BLE001 — skip exotic leaves
            continue
        n += 1
        total += nbytes
        key = (dtype, shape)
        cnt, byt = groups.get(key, (0, 0))
        groups[key] = (cnt + 1, byt + nbytes)
    rows = sorted(groups.items(), key=lambda kv: -kv[1][1])[:int(top)]
    return {"n_arrays": n, "total_bytes": total,
            "total_gib": round(total / 2**30, 3),
            "top": [{"dtype": d, "shape": list(s), "count": c,
                     "bytes": b}
                    for (d, s), (c, b) in rows]}


def first_jax_device(tree):
    """First jax array's device found in a nested structure (the
    serving engines hold their cache/state, not a Device object).
    Returns None when nothing device-backed is found."""
    stack = [tree]
    seen = 0
    while stack and seen < 256:
        obj = stack.pop()
        seen += 1
        if isinstance(obj, dict):
            stack.extend(obj.values())
            continue
        if isinstance(obj, (list, tuple)):
            stack.extend(obj)
            continue
        devs = getattr(obj, "devices", None)
        if callable(devs):
            try:
                ds = devs()
                if ds:
                    return next(iter(ds))
            except Exception:   # noqa: BLE001 — keep walking
                pass
        d = getattr(obj, "device", None)
        if d is not None and not callable(d):
            return d
    return None


# ---------------------------------------------------------------------------
# compile / retrace attribution
# ---------------------------------------------------------------------------

def step_signature(arrays, names=None):
    """JSON-able shape/dtype signature of one call's traced arguments:
    ``[[label, [dims...], dtype], ...]`` — what the retrace event diffs
    against."""
    sig = []
    for i, a in enumerate(arrays):
        label = names[i] if names is not None and i < len(names) \
            else f"arg{i}"
        sig.append([str(label), [int(d) for d in np.shape(a)],
                    str(getattr(a, "dtype", type(a).__name__))])
    return sig


def diff_signatures(old, new):
    """Structured diff of two :func:`step_signature` lists: one entry
    per argument whose shape or dtype changed (or that appeared/
    vanished), each carrying the old and new ``[shape, dtype]``."""
    changed = []
    old = old or []
    new = new or []
    for i in range(max(len(old), len(new))):
        o = old[i] if i < len(old) else None
        n = new[i] if i < len(new) else None
        if o is not None and n is not None and o[1:] == n[1:]:
            continue
        changed.append({
            "arg": (n or o)[0],
            "old": None if o is None else [o[1], o[2]],
            "new": None if n is None else [n[1], n[2]]})
    return changed


def record_compile(program, seconds, signature, prev_signature=None,
                   registry=None, source="fresh", **attrs):
    """Attribute one trace of a compiled program: observe its wall-time
    in the ``compile_seconds{program, source}`` histogram and leave a
    flight-recorder event — ``compile`` for a first trace (or a
    re-lower with an identical signature), ``retrace`` when the
    signature changed, naming the changed argument(s) old vs new.

    ``source`` labels where the executable came from: ``"fresh"`` (XLA
    compiled it now), ``"cache"`` (served whole from the persistent
    compilation cache — ``singa_tpu.aot.cache.classify`` is the
    judge), or ``"aot"`` (a deserialized exported executable; no trace
    happened at all and ``seconds`` is the verify+load cost). The
    cold-start acceptance gate is "zero ``source="fresh"``
    observations on a warm restart".

    ``seconds`` is the dispatch wall-clock of the call that traced
    (trace + XLA compile + the step's own dispatch — on a first call
    compile dominates). Returns the structured diff (empty/None when
    nothing changed)."""
    reg = registry if registry is not None else _metrics.default_registry()
    reg.histogram(
        "compile_seconds",
        "wall-clock of a dispatch that traced+compiled, by program "
        "and executable source (fresh | cache | aot)",
        labels=("program", "source")).observe(
            float(seconds), program=str(program), source=str(source))
    changed = diff_signatures(prev_signature, signature) \
        if prev_signature is not None else None
    if changed:
        _spans.event("retrace", program=str(program),
                     compile_s=round(float(seconds), 4), source=source,
                     changed=changed, signature=signature, **attrs)
    else:
        _spans.event("compile", program=str(program),
                     compile_s=round(float(seconds), 4), source=source,
                     signature=signature, **attrs)
    return changed


def compile_source_counts(registry=None):
    """{source: observation count} over the ``compile_seconds``
    histogram — the warm-restart gate reads this (zero ``fresh`` on a
    warm path). Empty dict when nothing compiled yet."""
    reg = registry if registry is not None \
        else _metrics.default_registry()
    hist = reg.get("compile_seconds")
    if hist is None:
        return {}
    out = {}
    for series in hist.to_doc()["series"]:
        src = series.get("labels", {}).get("source", "fresh")
        out[src] = out.get(src, 0) + int(series.get("count", 0))
    return out


# ---------------------------------------------------------------------------
# sampling step profiler
# ---------------------------------------------------------------------------

class SamplingProfiler:
    """Every-Nth-step measured per-fusion profiling.

    The trainer asks :meth:`should_sample` per step (one int check on
    non-sample steps); on a sample step it routes the step through
    ``Model.profile_step`` (the existing ``measure_step_fusions``
    machinery — ``n_traces`` untouched, one profiler trace per sample)
    and hands the table to :meth:`record`, which refreshes the
    ``profile_fusion_*`` gauges, counts the sample, observes the
    capture cost, and leaves a ``profile.sample`` event with the top
    fusions. ``every=0`` disables sampling; :meth:`force_next` arms a
    one-shot sample regardless (the anomaly sentinel's capture
    trigger)."""

    def __init__(self, every=0, registry=None):
        self.every = int(every or 0)
        self._force = False
        self.last_timeline = None     # newest analyzed step timeline
        self._reg = registry if registry is not None \
            else _metrics.default_registry()
        self._samples = self._reg.counter(
            "profile_samples_total",
            "sampled profiled steps (sampling profiler + one-shot "
            "anomaly captures)")
        self._capture = self._reg.histogram(
            "profile_capture_seconds",
            "wall-clock of one sampled profiled step (profiler trace "
            "+ parse included — the sampling overhead bound)")
        self._last = self._reg.gauge(
            "profile_last_sample_step",
            "global step of the newest profile sample")

    def should_sample(self, step):
        if self._force:
            return True
        return bool(self.every) and step > 0 and \
            step % self.every == 0

    def force_next(self):
        """Arm a one-shot sample (the sentinel's profile capture)."""
        self._force = True

    def record(self, step, table, capture_s=None, events=None,
               step_flops=None, peak_flops=None, site="train"):
        from .. import profiling as _profiling
        from . import timeline as _timeline
        self._force = False
        self._samples.inc()
        self._last.set(step)
        if capture_s is not None:
            self._capture.observe(capture_s)
        _profiling.record_fusion_metrics(table, registry=self._reg)
        _spans.event("profile.sample", step=step, fusions=len(table),
                     top=_profiling.summarize_table(table, top=3),
                     **({"capture_s": round(capture_s, 4)}
                        if capture_s is not None else {}))
        # the step-timeline decomposition rides the SAME capture (no
        # second trace): bucket the raw events, refresh the timeline_*
        # gauges, and leave a timeline.sample event whose bounded
        # per-bucket lanes the Perfetto exporter renders as extra rows
        if events:
            tl = _timeline.analyze(events)
            if tl is not None:
                wf = _timeline.waterfall(tl, step_flops, peak_flops)
                _timeline.record_timeline(tl, registry=self._reg,
                                          site=site, waterfall_doc=wf)
                self.last_timeline = tl
                _spans.event(
                    "timeline.sample", step=step, site=site,
                    lanes=tl["lanes"], **_timeline.compact(tl),
                    **({"achieved_mfu": round(wf["achieved_mfu"], 4),
                        "mfu_loss": {k: round(v, 4)
                                     for k, v in wf["loss"].items()}}
                       if wf else {}))


# ---------------------------------------------------------------------------
# anomaly sentinel
# ---------------------------------------------------------------------------

class AnomalySentinel:
    """Rolling step-time baseline with sustained-spike detection.

    Feed every completed step's wall-clock to :meth:`observe`; it
    maintains an EMA baseline (spike-clipped, so an incident does not
    teach the baseline to expect incidents) and, after ``warmup``
    samples, fires when ``sustain`` consecutive steps exceed
    ``factor``× the baseline: a ``step_anomaly`` flight-recorder event
    (step, measured, baseline, factor), a ``perf_anomalies_total``
    count, and a True return — the caller's cue to capture a one-shot
    profile and dump the blackbox. A ``cooldown`` keeps one incident
    from firing every step while it lasts."""

    def __init__(self, factor=3.0, sustain=3, warmup=10, alpha=0.2,
                 min_baseline_s=1e-4, cooldown=20, registry=None):
        self.factor = float(factor)
        self.sustain = int(sustain)
        self.warmup = int(warmup)
        self.alpha = float(alpha)
        self.min_baseline_s = float(min_baseline_s)
        self.cooldown = int(cooldown)
        self._ema = None
        self._seen = 0
        self._streak = 0
        self._cool = 0
        reg = registry if registry is not None \
            else _metrics.default_registry()
        self._fired = reg.counter(
            "perf_anomalies_total",
            "sustained step-time spikes the sentinel fired on")
        self._baseline = reg.gauge(
            "perf_step_baseline_seconds",
            "the sentinel's rolling step-time baseline (EMA)")

    def observe(self, step, step_s):
        """Returns True when a sustained spike fires this step."""
        step_s = float(step_s)
        base = self._ema
        fired = False
        floor = max(base or 0.0, self.min_baseline_s)
        spike = (base is not None and self._seen >= self.warmup
                 and step_s > self.factor * floor)
        if spike and self._cool == 0:
            self._streak += 1
            if self._streak >= self.sustain:
                fired = True
                self._streak = 0
                self._cool = self.cooldown
                self._fired.inc()
                _spans.event("step_anomaly", step=step,
                             step_s=round(step_s, 6),
                             baseline_s=round(base, 6),
                             factor=self.factor)
        elif not spike:
            self._streak = 0
        if self._cool:
            self._cool -= 1
        # clip the update so a spike streak drags the baseline up only
        # slowly; a genuine regime change still converges
        clip = step_s if base is None \
            else min(step_s, self.factor * floor)
        self._ema = clip if base is None \
            else (1.0 - self.alpha) * base + self.alpha * clip
        self._seen += 1
        self._baseline.set(self._ema)
        return fired


__all__ = ["hbm_stats", "record_hbm", "live_array_report",
           "first_jax_device", "step_signature", "diff_signatures",
           "record_compile", "compile_source_counts",
           "SamplingProfiler", "AnomalySentinel"]
