"""Nested wall-clock trace spans and the crash flight recorder.

Spans are the narrative counterpart of the metrics registry: where a
histogram says "step time p50 is 42 ms", the span stream says "step 317
took 1.9 s, and inside it checkpoint.save took 1.7 s". Each span is one
JSON record::

    {"kind": "span", "name": "step", "ts": <end, epoch s>,
     "ts_start": <start, epoch s>, "dur_s": 0.042,
     "parent": "run", "rank": 0, "step": 317, ...}

- **Attribution** (run id, rank, step) comes from two places: explicit
  keyword attrs on the span, and an ambient :func:`context` carried in a
  ``contextvars.ContextVar`` — so two in-process ranks (threaded tests,
  the in-process cluster suite) stamp their own rank on every record
  even though they share the process-global recorder, and the trainer's
  watchdog worker (which copies its caller's context) inherits it.
- **Nesting** rides the same contextvar mechanism: a span records the
  name of the innermost enclosing span as ``parent``.
- **The flight recorder** is a bounded ring (``deque(maxlen=...)``) of
  the most recent records. It costs one append per span — nothing is
  written anywhere until :meth:`FlightRecorder.dump` is called, which
  the resilient trainer does on every ABNORMAL exit path (preemption,
  divergence, watchdog kill, membership loss, rollback), writing
  ``telemetry/blackbox-<rank>.jsonl``: a dump header naming the reason,
  the ring contents (the last N seconds of spans), and a final metrics
  snapshot. A post-mortem then shows what the job was doing when it
  died, not just an exit code.
- Optionally a live JSONL sink (:meth:`FlightRecorder.attach_jsonl`)
  mirrors every record to disk as it happens — what
  ``examples/train_cnn.py --telemetry`` turns on.

Everything here is host-side stdlib; nothing imports jax, so span cost
is a couple of ``perf_counter`` calls plus a dict build (~µs) and the
compiled step's ``n_traces`` pin is untouched.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
from collections import deque

# ambient attrs merged into every record (rank, run id); per-context so
# in-process multi-rank tests attribute correctly
_CTX = contextvars.ContextVar("singa_tpu_span_ctx", default=None)
# innermost-enclosing-span name, for the ``parent`` field
_STACK = contextvars.ContextVar("singa_tpu_span_stack", default=())

# spans currently INSIDE their ``with`` body, keyed by object id: a
# blackbox written while the process is dying must show what it was
# inside (the hung step, the restore that never returned), not only
# what already finished — FlightRecorder.dump appends these as
# ``span_open`` records
_OPEN_LOCK = threading.Lock()
_OPEN = {}

DEFAULT_CAPACITY = 1024


@contextlib.contextmanager
def context(**attrs):
    """Scope ambient attribution: every record made inside the ``with``
    (in this thread/context, workers that copy it included) carries
    ``attrs``. Nests by merging."""
    merged = dict(_CTX.get() or {})
    merged.update(attrs)
    token = _CTX.set(merged)
    try:
        yield
    finally:
        _CTX.reset(token)


class FlightRecorder:
    """Bounded in-memory ring of telemetry records + optional live
    JSONL sink (see module docstring)."""

    def __init__(self, capacity=DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._sink_lock = threading.Lock()  # serializes sink I/O only
        self._ring = deque(maxlen=int(capacity))
        self._jsonl = None
        self._jsonl_path = None
        # records the bounded ring pushed out (oldest-first): a
        # beheaded blackbox/trace must SAY it is partial, not read as
        # "nothing else happened" — dump() stamps this into its
        # header, and a process-wide counter tracks it
        self._evicted = 0
        self._evict_counter = None      # lazy metrics handle

    @property
    def dropped_records(self):
        """Ring evictions since this recorder was created — how many
        records any dump/trace built from it is missing."""
        with self._lock:
            return self._evicted

    def _count_eviction(self, n=1):
        # lazy get-or-create OUTSIDE the ring lock; metrics is a lazy
        # import here (it never imports spans, but keep the edge soft)
        c = self._evict_counter
        if c is None:
            try:
                from . import metrics as _metrics
                c = self._evict_counter = \
                    _metrics.default_registry().counter(
                        "recorder_evicted_total",
                        "flight-recorder ring records pushed out by "
                        "newer ones (dumps built after evictions are "
                        "partial and say so)")
            except Exception:   # noqa: BLE001 — telemetry of telemetry
                return
        try:
            c.inc(n)
        except Exception:       # noqa: BLE001
            pass

    def record(self, rec):
        evicted = False
        with self._lock:
            if self._ring.maxlen is not None and \
                    len(self._ring) == self._ring.maxlen:
                self._evicted += 1
                evicted = True
            self._ring.append(rec)
        if evicted:
            self._count_eviction()
        if self._jsonl is not None:
            # serialize + write OUTSIDE the ring lock: a slow disk may
            # stall sink writers, never every span-recording thread
            with self._sink_lock:
                try:
                    if self._jsonl is not None:
                        self._jsonl.write(json.dumps(rec) + "\n")
                except (OSError, ValueError, TypeError):
                    # a full disk or closed sink must never take down
                    # training; the ring still holds the record
                    pass

    def records(self):
        with self._lock:
            return list(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()

    # -- live JSONL sink ---------------------------------------------------
    def attach_jsonl(self, path):
        """Mirror every record to ``path`` as it is made (line-buffered
        append). Returns the absolute path."""
        path = os.path.abspath(str(path))
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with self._sink_lock:
            if self._jsonl is not None:
                self._jsonl.close()
            self._jsonl = open(path, "a", buffering=1)
            self._jsonl_path = path
        return path

    def detach_jsonl(self):
        with self._sink_lock:
            if self._jsonl is not None:
                self._jsonl.close()
            self._jsonl = None
            self._jsonl_path = None

    @property
    def jsonl_path(self):
        return self._jsonl_path

    # -- the blackbox dump -------------------------------------------------
    def dump(self, path, reason, rank=None, step=None, extra=None,
             registry=None):
        """Write the blackbox: header (reason/rank/step/extra), the ring
        contents, then a final metrics snapshot. Atomic (tmp + rename)
        and OVERWRITING — the newest incident is the one the post-mortem
        wants, and a half-written dump must never pass for a whole one.
        Returns the absolute path."""
        from . import metrics as _metrics
        path = os.path.abspath(str(path))
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with self._lock:
            dropped = self._evicted
            capacity = self._ring.maxlen
        header = {"kind": "dump", "ts": time.time(),
                  "reason": str(reason),
                  # loud partiality: a ring that evicted is a beheaded
                  # blackbox — the post-mortem must know the N records
                  # before this window are gone, not conclude they
                  # never happened
                  "dropped_records": dropped,
                  "ring_capacity": capacity}
        if rank is not None:
            header["rank"] = rank
        if step is not None:
            header["step"] = step
        if extra:
            header["extra"] = extra
        reg = registry if registry is not None \
            else _metrics.default_registry()
        try:
            snap = reg.snapshot()
        except Exception:       # the spans must land even if metrics fail
            snap = None
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(json.dumps(header) + "\n")
            for rec in self.records():
                f.write(json.dumps(rec) + "\n")
            # spans still open at dump time (the hung step, the restore
            # that never returned): without these the blackbox shows
            # everything EXCEPT what the process died inside
            for rec in open_spans():
                try:
                    f.write(json.dumps(rec, default=str) + "\n")
                except (TypeError, ValueError):
                    continue
            if snap is not None:
                f.write(json.dumps({"kind": "metrics",
                                    "snapshot": snap}) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path


# the process-wide default recorder (the trainer, the span context
# manager, and the --telemetry example all share it)
_RECORDER = FlightRecorder()


def recorder():
    return _RECORDER


def configure(capacity=None, jsonl_path=None):
    """Adjust the default recorder: ring capacity and/or a live JSONL
    sink path. Returns the recorder."""
    if capacity is not None:
        with _RECORDER._lock:
            before = len(_RECORDER._ring)
            _RECORDER._ring = deque(_RECORDER._ring,
                                    maxlen=int(capacity))
            # shrinking below the current length drops the OLDEST
            # records — counted like any other eviction (header AND
            # metrics counter, so the two can never disagree)
            dropped = max(0, before - len(_RECORDER._ring))
            _RECORDER._evicted += dropped
        if dropped:
            _RECORDER._count_eviction(dropped)
    if jsonl_path is not None:
        _RECORDER.attach_jsonl(jsonl_path)
    return _RECORDER


class span:
    """Context manager recording one nested wall-clock span::

        with span("checkpoint.save", step=42):
            mgr.save(...)

    On exit a record lands in the default recorder, stamped with the
    ambient :func:`context` attrs, the enclosing span's name, and — when
    the body raised — the exception type under ``error``."""

    __slots__ = ("name", "attrs", "_t0", "_token", "_wall0", "_ctx")

    def __init__(self, name, **attrs):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        self._token = _STACK.set(_STACK.get() + (self.name,))
        self._wall0 = time.time()
        self._ctx = _CTX.get()
        with _OPEN_LOCK:
            _OPEN[id(self)] = self
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        with _OPEN_LOCK:
            _OPEN.pop(id(self), None)
        stack = _STACK.get()
        _STACK.reset(self._token)
        rec = {"kind": "span", "name": self.name, "ts": time.time(),
               "ts_start": self._wall0, "dur_s": dur}
        if len(stack) > 1:
            rec["parent"] = stack[-2]
        ctx = _CTX.get()
        if ctx:
            rec.update(ctx)
        if self.attrs:
            rec.update(self.attrs)
        if exc_type is not None:
            rec["error"] = exc_type.__name__
        _RECORDER.record(rec)
        return False


def event(name, **attrs):
    """Record a point-in-time event (no duration) — rollbacks, loss-
    scale backoffs, quarantines."""
    rec = {"kind": "event", "name": name, "ts": time.time()}
    ctx = _CTX.get()
    if ctx:
        rec.update(ctx)
    if attrs:
        rec.update(attrs)
    _RECORDER.record(rec)


def open_spans(now=None):
    """``span_open`` records for every span currently inside its
    ``with`` body (any thread), oldest first: name, start timestamp,
    age, and the attribution it was entered under. What a post-mortem
    reads to learn what the process was INSIDE when it died."""
    now = now if now is not None else time.time()
    with _OPEN_LOCK:
        items = list(_OPEN.values())
    out = []
    for s in items:
        rec = {"kind": "span_open", "name": s.name, "ts": now,
               "ts_start": s._wall0,
               "age_s": max(0.0, now - s._wall0)}
        if s._ctx:
            rec.update(s._ctx)
        if s.attrs:
            rec.update(s.attrs)
        out.append(rec)
    out.sort(key=lambda r: r["ts_start"])
    return out


__all__ = ["FlightRecorder", "context", "span", "event", "open_spans",
           "recorder", "configure", "DEFAULT_CAPACITY"]
