"""Step-timeline attribution: where did the hardware go?

The sampling profiler (PR 9) already captures a device trace of one
compiled step (``profiling.measure_step_fusions`` — the same capture
``Model.profile_step`` makes; no second tracing mechanism) and sums the
per-fusion costs. This module keeps the TIMELINE that sum used to throw
away and buckets every device-lane event into:

- **compute** — fusions, dot_generals, convolutions: the MXU/VPU doing
  model math;
- **collective** — all-reduce / all-gather / reduce-scatter /
  all-to-all / collective-permute (``ppermute``/``psum`` lower to
  these) / cross-program send/recv: cross-chip communication;
- **memcpy** — HBM↔host traffic: infeed/outfeed, copy-start/done,
  host transfers;
- **host** — device idle while a HOST lane is busy (the runtime
  feeding/blocking the device — the data-stall signature);
- **idle** — device idle with nothing measurable on the host either.

Two numbers fall out that the ROADMAP's MFU push is steered by:

- **exposed communication**: collective time NOT overlapped with
  compute — the quantity DistOpt gradient-bucketing must drive to
  zero. Overlapped collectives are free; exposed ones are the bill.
- the **MFU-loss waterfall**: peak FLOPs → achieved, with the gap
  attributed per bucket (:func:`waterfall`) — so "MFU is 0.31" becomes
  "0.19 of peak went to exposed collectives, 0.08 to input stalls,
  0.42 to compute inefficiency (HBM-bound fusions)".

The bucket fractions are EXACT over the step window: compute +
exposed-collective + exposed-memcpy + host + idle == 1.0 (overlap is
resolved by precedence compute > collective > memcpy; the committed
trace fixture pins this to 1e-6 in tier-1, CPU-only).

Publication: :func:`record_timeline` sets the ``timeline_*`` gauges
(labels ``site=train|serve`` and ``bucket``); the sampling profiler
(``ResilientTrainer(profile_every=N)``) refreshes them continuously
and its ``timeline.sample`` flight-recorder event carries bounded
per-bucket interval lanes that ``trace_export`` renders as extra
Perfetto rows. :func:`classify_cause` turns a rank's fractions into
the ``comm_bound | data_bound | compute_bound | compile_bound`` label
the coordinator's fleet health report attaches to each straggler
(``metrics.aggregate_summaries -> straggler_causes``).

Everything here is host-side stdlib math over already-parsed events —
nothing imports jax, and the compiled step's ``n_traces`` pin is
untouched (the capture wraps the already-compiled dispatch).
"""

from __future__ import annotations

BUCKETS = ("compute", "collective", "memcpy", "host", "idle")

# substring markers over the (lowercased) event symbol — checked on
# each "|"-separated part, so an enriched "fusion.3|all-reduce.1"
# classifies by its HLO long name too. Order matters: collective wins
# over memcpy (a "collective-permute-start" contains neither memcpy
# marker, but be explicit anyway).
_COLLECTIVE_MARKERS = (
    "all-reduce", "allreduce", "all-gather", "allgather",
    "reduce-scatter", "reducescatter", "all-to-all", "alltoall",
    "collective-permute", "collective-broadcast", "ppermute", "psum",
    "send", "recv")
_MEMCPY_MARKERS = ("infeed", "outfeed", "memcpy", "host-transfer",
                   "transfertodevice", "transferfromdevice", "copy-start",
                   "copy-done", "copy.")


def classify_op(name):
    """Bucket one device-lane op symbol: ``collective`` / ``memcpy`` /
    ``compute``. (``host``/``idle`` are gap buckets — they exist only
    relative to a step window, see :func:`analyze`.)"""
    low = str(name).lower()
    for part in low.split("|"):
        for m in _COLLECTIVE_MARKERS:
            if m in part:
                return "collective"
        for m in _MEMCPY_MARKERS:
            if m in part:
                return "memcpy"
        if part == "copy" or part.startswith("copy."):
            return "memcpy"
    return "compute"


# ---------------------------------------------------------------------------
# interval arithmetic (half-open [start, end) µs pairs)
# ---------------------------------------------------------------------------

def merge_intervals(intervals):
    """Sort + merge overlapping/touching intervals."""
    ivs = sorted((float(a), float(b)) for a, b in intervals if b > a)
    out = []
    for a, b in ivs:
        if out and a <= out[-1][1]:
            out[-1][1] = max(out[-1][1], b)
        else:
            out.append([a, b])
    return [(a, b) for a, b in out]


def subtract_intervals(base, cut):
    """``base - cut`` (both merged): the parts of ``base`` not covered
    by ``cut``."""
    out = []
    ci = 0
    cut = list(cut)
    for a, b in base:
        pos = a
        while ci < len(cut) and cut[ci][1] <= pos:
            ci += 1
        j = ci
        while j < len(cut) and cut[j][0] < b:
            ca, cb = cut[j]
            if ca > pos:
                out.append((pos, min(ca, b)))
            pos = max(pos, cb)
            if pos >= b:
                break
            j += 1
        if pos < b:
            out.append((pos, b))
    return [iv for iv in out if iv[1] > iv[0]]


def intersect_intervals(a, b):
    """Overlap of two merged interval lists."""
    out = []
    i = j = 0
    a, b = list(a), list(b)
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            out.append((lo, hi))
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return out


def _span(intervals):
    return sum(b - a for a, b in intervals)


def _clip(intervals, t0, t1):
    return [(max(a, t0), min(b, t1)) for a, b in intervals
            if min(b, t1) > max(a, t0)]


# ---------------------------------------------------------------------------
# the analyzer
# ---------------------------------------------------------------------------

_MAX_LANE_INTERVALS = 128


def analyze(events, window=None):
    """Bucket a step's trace events (``profiling.parse_trace_events``
    dicts) into the compute/collective/memcpy/host/idle decomposition.

    Device lanes are the op timeline; on a backend without device lanes
    (CPU CI) the host lane's XLA-op events stand in (and the ``host``
    bucket is then empty — it cannot be told apart from compute there).
    ``window`` is an optional ``(t0_us, t1_us)`` override; by default
    the window spans the first op start to the last op end.

    Returns None when nothing timestamped was captured, else a dict::

        {"window_s", "compute_s", "collective_s",
         "exposed_collective_s", "memcpy_s", "exposed_memcpy_s",
         "host_s", "idle_s", "fractions": {bucket: f, ...},  # sums to 1
         "overlapped_collective_s", "events": n,
         "lanes": {bucket: [[rel_start_s, dur_s], ...], ...}}

    The ``fractions`` partition the window exactly (precedence
    compute > collective > memcpy over overlapping device time), so
    ``sum(fractions.values()) == 1.0`` to float precision —
    exposed-communication seconds are ``exposed_collective_s``, while
    ``collective_s`` is the TOTAL collective time (overlap included:
    ``collective_s - exposed_collective_s`` is what the DistOpt
    bucketing successfully hid under compute)."""
    evs = [e for e in (events or [])
           if e.get("ts") is not None and e.get("dur")]
    device = [e for e in evs if e.get("lane") == "device"]
    if device:
        ops = device
        host = [e for e in evs if e.get("lane") == "host"]
    else:
        # CPU fallback: host XLA-op events are the op timeline; there
        # is no separate runtime lane to attribute gaps to
        ops = [e for e in evs if e.get("xla_op", True)]
        host = []
    if not ops:
        return None

    by_bucket = {"compute": [], "collective": [], "memcpy": []}
    for e in ops:
        by_bucket[classify_op(e["name"])].append(
            (e["ts"], e["ts"] + e["dur"]))
    if window is not None:
        t0, t1 = float(window[0]), float(window[1])
    else:
        t0 = min(a for ivs in by_bucket.values() for a, _b in ivs)
        t1 = max(b for ivs in by_bucket.values() for _a, b in ivs)
    if t1 <= t0:
        return None

    compute = merge_intervals(_clip(by_bucket["compute"], t0, t1))
    coll = merge_intervals(_clip(by_bucket["collective"], t0, t1))
    memcpy = merge_intervals(_clip(by_bucket["memcpy"], t0, t1))
    exposed_coll = subtract_intervals(coll, compute)
    busy_cc = merge_intervals(compute + coll)
    exposed_memcpy = subtract_intervals(memcpy, busy_cc)
    busy = merge_intervals(busy_cc + memcpy)
    gaps = subtract_intervals([(t0, t1)], busy)
    host_busy = merge_intervals(
        _clip([(e["ts"], e["ts"] + e["dur"]) for e in host], t0, t1))
    host_iv = intersect_intervals(gaps, host_busy)
    idle_iv = subtract_intervals(gaps, host_iv)

    window_us = t1 - t0
    us = 1e-6

    def lane(ivs):
        return [[round((a - t0) * us, 9), round((b - a) * us, 9)]
                for a, b in ivs[:_MAX_LANE_INTERVALS]]

    secs = {
        "compute_s": _span(compute) * us,
        "collective_s": _span(coll) * us,
        "exposed_collective_s": _span(exposed_coll) * us,
        "memcpy_s": _span(memcpy) * us,
        "exposed_memcpy_s": _span(exposed_memcpy) * us,
        "host_s": _span(host_iv) * us,
        "idle_s": _span(idle_iv) * us,
    }
    w = window_us * us
    fractions = {
        "compute": secs["compute_s"] / w,
        "collective": secs["exposed_collective_s"] / w,
        "memcpy": secs["exposed_memcpy_s"] / w,
        "host": secs["host_s"] / w,
        "idle": secs["idle_s"] / w,
    }
    return dict(
        secs, window_s=w, fractions=fractions,
        overlapped_collective_s=(secs["collective_s"]
                                 - secs["exposed_collective_s"]),
        events=len(ops),
        lanes={"compute": lane(compute), "collective": lane(coll),
               "memcpy": lane(memcpy), "host": lane(host_iv),
               "idle": lane(idle_iv)})


def waterfall(tl, step_flops, peak_flops):
    """The MFU-loss waterfall over one analyzed timeline: peak (1.0)
    → achieved, the gap attributed per bucket. Each non-compute
    bucket's window fraction is directly that fraction of peak lost;
    what remains of the gap happened INSIDE the compute bucket
    (HBM-bound fusions, low-occupancy kernels) and lands in
    ``compute_inefficiency``. Returns None when the FLOP counts are
    unknown (no cost analysis / unknown chip)."""
    if not (tl and step_flops and peak_flops and tl.get("window_s")):
        return None
    achieved = float(step_flops) / float(tl["window_s"]) / \
        float(peak_flops)
    f = tl["fractions"]
    loss = {
        "collective": f["collective"],
        "memcpy": f["memcpy"],
        "host": f["host"],
        "idle": f["idle"],
        "compute_inefficiency": max(0.0, f["compute"] - achieved),
    }
    return {"achieved_mfu": achieved, "loss": loss}


# ---------------------------------------------------------------------------
# gauge publication + readback (heartbeats)
# ---------------------------------------------------------------------------

def record_timeline(tl, registry=None, site="train", waterfall_doc=None):
    """Publish one analyzed timeline as ``timeline_*`` gauges (SET, not
    accumulated — each sample replaces the previous decomposition,
    like the ``profile_fusion_*`` gauges):

    - ``timeline_fraction{site, bucket}`` — the exact partition;
    - ``timeline_seconds{site, bucket}`` — the same in seconds
      (bucket ``collective`` is EXPOSED seconds; the total rides
      ``timeline_collective_total_seconds``);
    - ``timeline_exposed_collective_seconds{site}`` — the headline
      exposed-communication number;
    - ``timeline_window_seconds{site}``;
    - ``timeline_mfu_loss{site, bucket}`` + ``timeline_mfu{site}`` when
      a :func:`waterfall` doc is given.

    Returns the registry."""
    from . import metrics as _metrics
    reg = registry if registry is not None \
        else _metrics.default_registry()
    if tl is None:
        return reg
    frac = reg.gauge(
        "timeline_fraction",
        "step-window fraction per bucket of the newest profiled "
        "step/tick (compute | collective(exposed) | memcpy(exposed) | "
        "host | idle; sums to 1)", labels=("site", "bucket"))
    secs = reg.gauge(
        "timeline_seconds",
        "seconds per bucket over the newest profiled step window "
        "(collective/memcpy are EXPOSED time)",
        labels=("site", "bucket"))
    sec_by_bucket = {
        "compute": tl["compute_s"],
        "collective": tl["exposed_collective_s"],
        "memcpy": tl["exposed_memcpy_s"],
        "host": tl["host_s"], "idle": tl["idle_s"]}
    for bucket in BUCKETS:
        frac.set(tl["fractions"][bucket], site=site, bucket=bucket)
        secs.set(sec_by_bucket[bucket], site=site, bucket=bucket)
    reg.gauge("timeline_exposed_collective_seconds",
              "collective time NOT overlapped with compute in the "
              "newest profiled step — the number DistOpt bucketing "
              "must drive to zero", labels=("site",)).set(
                  tl["exposed_collective_s"], site=site)
    reg.gauge("timeline_collective_total_seconds",
              "TOTAL collective time (overlapped + exposed) in the "
              "newest profiled step", labels=("site",)).set(
                  tl["collective_s"], site=site)
    reg.gauge("timeline_window_seconds",
              "device-active window of the newest profiled step",
              labels=("site",)).set(tl["window_s"], site=site)
    if waterfall_doc:
        reg.gauge("timeline_mfu",
                  "achieved/peak FLOP fraction over the newest "
                  "profiled step's device window",
                  labels=("site",)).set(
                      waterfall_doc["achieved_mfu"], site=site)
        loss = reg.gauge(
            "timeline_mfu_loss",
            "MFU-loss waterfall: fraction of peak lost per bucket "
            "(collective | memcpy | host | idle | "
            "compute_inefficiency)", labels=("site", "bucket"))
        for bucket, v in waterfall_doc["loss"].items():
            loss.set(v, site=site, bucket=bucket)
    return reg


def compact(tl):
    """The ONE compact serialized form of an analyzed timeline —
    rounded bucket fractions + exposed/total collective seconds + the
    window — shared by every emitter (the bench legs' banked records,
    the ``timeline.sample`` flight-recorder events) so their schemas
    cannot drift. Returns None for None."""
    if not tl:
        return None
    return {
        "fractions": {k: round(v, 4)
                      for k, v in tl["fractions"].items()},
        "exposed_collective_s": round(tl["exposed_collective_s"], 6),
        "collective_total_s": round(tl["collective_s"], 6),
        "window_s": round(tl["window_s"], 6),
    }


def timeline_summary(registry=None, site="train"):
    """The compact per-rank timeline view that rides cluster
    heartbeats: newest bucket fractions + exposed-comm seconds, read
    back off the ``timeline_*`` gauges. None before the first profiled
    sample (the heartbeat then simply omits the field)."""
    from . import metrics as _metrics
    reg = registry if registry is not None \
        else _metrics.default_registry()
    g = reg.get("timeline_fraction")
    if g is None:
        return None
    fractions = {}
    for s in g.to_doc()["series"]:
        labels = s.get("labels") or {}
        if labels.get("site") == site:
            fractions[labels.get("bucket")] = s.get("value")
    if not fractions:
        return None
    out = {"fractions": fractions}
    for key, name in (("exposed_collective_s",
                       "timeline_exposed_collective_seconds"),
                      ("window_s", "timeline_window_seconds")):
        m = reg.get(name)
        if m is not None:
            try:
                out[key] = m.value(site=site)
            except Exception:   # noqa: BLE001 — label-shape drift
                pass
    return out


# ---------------------------------------------------------------------------
# straggler cause classification
# ---------------------------------------------------------------------------

# a bucket must claim at least this fraction of the step window before
# it is blamed for a straggler (below it, "slow compute" is the honest
# default)
CAUSE_THRESHOLD = 0.2
# compile share of step wall-time above which a rank is compile-bound
# (retraces / cold compiles dominating its steps)
COMPILE_BOUND_SHARE = 0.25

CAUSES = ("comm_bound", "data_bound", "compute_bound", "compile_bound")


def classify_cause(fractions, compile_share=None,
                   threshold=CAUSE_THRESHOLD,
                   compile_threshold=COMPILE_BOUND_SHARE):
    """One straggler's cause label from its timeline fractions (and
    compile share of step wall-time):

    - ``compile_bound`` — compiling/retracing ate ≥ ``compile_threshold``
      of its step time (checked FIRST: a retracing rank also looks
      idle on the device timeline);
    - ``comm_bound``   — exposed collectives ≥ ``threshold`` of the
      window and at least as large as the data-stall share;
    - ``data_bound``   — host + idle + exposed memcpy (input pipeline /
      host stalls) ≥ ``threshold``;
    - ``compute_bound`` — everything else: the device is busy doing
      math, just slowly.

    Returns None when there is nothing to judge (no timeline AND no
    compile share) — the aggregation then labels the rank "unknown"."""
    share = float(compile_share or 0.0)
    if share >= compile_threshold:
        return "compile_bound"
    if not fractions:
        return None if not share else "compute_bound"
    comm = float(fractions.get("collective") or 0.0)
    data = float(fractions.get("host") or 0.0) \
        + float(fractions.get("idle") or 0.0) \
        + float(fractions.get("memcpy") or 0.0)
    if comm >= threshold and comm >= data:
        return "comm_bound"
    if data >= threshold:
        return "data_bound"
    return "compute_bound"


__all__ = ["BUCKETS", "CAUSES", "CAUSE_THRESHOLD",
           "COMPILE_BOUND_SHARE", "classify_op", "merge_intervals",
           "subtract_intervals", "intersect_intervals", "analyze",
           "waterfall", "record_timeline", "compact",
           "timeline_summary", "classify_cause"]
