"""Process-wide metrics registry: counters, gauges, histograms.

The reference scheduler prints MEASURED per-node accounting of the graph
it actually runs (src/core/scheduler/scheduler.cc:240-298); a production
TPU job needs the same honesty one level up — step time, throughput,
MFU, guard skips, checkpoint/restore durations, cluster health — in ONE
place every layer reports through, instead of per-module print
statements that scroll away.

Design constraints (why this is not a prometheus_client dependency):

- **Host-side only, never inside jit.** Every operation here is a dict
  update under a lock — a few microseconds. Nothing in this module may
  import jax or touch device values; callers hand in plain floats they
  already had (the retrace-guard CI pin ``n_traces == 1`` stays the
  step-path invariant).
- **Snapshot-first.** ``MetricsRegistry.snapshot()`` is the canonical
  serialized form (a JSON-able dict, schema ``singa-tpu-metrics/1``);
  the Prometheus text rendering and the CLI/HTTP exporters
  (:mod:`.export`, ``tools/metrics_dump.py``) all work from snapshots,
  so a metrics file written at the end of a run is exactly as
  exportable as a live registry.
- **Get-or-create.** ``registry.counter(name)`` returns the existing
  series on repeat calls (kind-checked), so instrumented layers never
  need to coordinate creation order.

Usage::

    from singa_tpu.observability import metrics
    reg = metrics.default_registry()
    reg.counter("train_steps_total", "completed training steps").inc()
    reg.histogram("train_step_seconds").observe(dt)
    doc = reg.snapshot()             # JSON-able
    text = reg.to_prometheus()       # exposition text
"""

from __future__ import annotations

import math
import os
import threading
import time

SNAPSHOT_SCHEMA = "singa-tpu-metrics/1"

# process start, as close as telemetry can observe it (this module is
# imported by every instrumented layer's first import) — the build
# stamp's "when did this process come up"
_PROCESS_START = time.time()

# Default histogram buckets, tuned for wall-clock seconds spanning a
# sub-millisecond metric op to a minutes-long restore (the upper +inf
# bucket is implicit).
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                   120.0, 300.0)

# Peak dense matmul FLOP/s per chip by TPU generation (public bf16 MXU
# figures) — the MFU denominator. The CANONICAL table: bench.py's
# _peak_flops delegates here (keeping its env overrides), and the
# trainer's train_mfu gauge reads it directly. Order matters: first
# substring match wins, so the more specific tags come first.
PEAK_FLOPS_BY_KIND = [
    ("v6", 918e12), ("v5p", 459e12), ("v5e", 197e12), ("v5 lite", 197e12),
    ("v5lite", 197e12), ("v5", 459e12), ("v4", 275e12), ("v3", 123e12),
    ("v2", 45e12),
]


def device_peak_flops(device_kind):
    """Peak FLOP/s for a device kind string (``jax_device.device_kind``),
    or None when the generation is unknown (CPU, emulators)."""
    kind = (device_kind or "").lower()
    for tag, peak in PEAK_FLOPS_BY_KIND:
        if tag in kind:
            return peak
    return None


# resolved once per process (subprocess git call), then cached
_BUILD_STAMP = None


def build_stamp():
    """The build/deploy identity stamped into every snapshot (and so
    into /metrics.json, heartbeat summaries, and blackbox dumps):
    ``{"git": <commit or None>, "start_ts": <process start, epoch s>,
    "pid": ..., "host": ...}`` — what lets a fleet dashboard correlate
    a perf shift with a deploy instead of guessing. ``git`` honors a
    ``SINGA_TPU_BUILD_GIT`` env override (containers deployed without
    a .git directory stamp their image tag there); otherwise one
    cached ``git rev-parse`` of the installed package's tree, None
    when neither exists."""
    global _BUILD_STAMP
    if _BUILD_STAMP is None:
        import socket
        git = os.environ.get("SINGA_TPU_BUILD_GIT") or None
        if git is None:
            try:
                import subprocess
                here = os.path.abspath(__file__)
                pkg_dir = os.path.dirname(here)
                # the repo git walks up to must actually TRACK this
                # package: a venv's site-packages nested inside some
                # unrelated application repo would otherwise stamp
                # that app's HEAD as the library build — worse than
                # the honest None
                tracked = subprocess.run(
                    ["git", "ls-files", "--error-unmatch",
                     os.path.basename(here)],
                    capture_output=True, text=True, timeout=5,
                    cwd=pkg_dir)
                if tracked.returncode == 0:
                    proc = subprocess.run(
                        ["git", "rev-parse", "--short", "HEAD"],
                        capture_output=True, text=True, timeout=5,
                        cwd=pkg_dir)
                    if proc.returncode == 0:
                        git = proc.stdout.strip() or None
            except Exception:   # noqa: BLE001 — stamp is best-effort
                git = None
        try:
            host = socket.gethostname()
        except Exception:       # noqa: BLE001
            host = None
        _BUILD_STAMP = {"git": git, "start_ts": _PROCESS_START,
                        "pid": os.getpid(), "host": host}
    return dict(_BUILD_STAMP)


def _label_key(label_names, labels):
    if set(labels) != set(label_names):
        raise ValueError(
            f"metric labels {sorted(labels)} do not match the declared "
            f"label names {sorted(label_names)}")
    return tuple(str(labels[n]) for n in label_names)


class _Metric:
    """One named metric: a family of series keyed by label values."""

    kind = "untyped"

    def __init__(self, name, help="", label_names=(), lock=None):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._series = {}
        # the registry's lock is shared: one lock bounds the whole
        # snapshot, so a snapshot is internally consistent
        self._lock = lock if lock is not None else threading.Lock()

    def _slot(self, labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            slot = self._series.get(key)
            if slot is None:
                slot = self._new_slot()
                self._series[key] = slot
            return slot

    def _new_slot(self):
        raise NotImplementedError

    def _series_doc(self, key, slot):
        raise NotImplementedError

    def to_doc(self):
        with self._lock:
            series = [dict(self._series_doc(k, s),
                           labels=dict(zip(self.label_names, k)))
                      for k, s in sorted(self._series.items())]
        return {"name": self.name, "kind": self.kind, "help": self.help,
                "labels": list(self.label_names), "series": series}


class Counter(_Metric):
    """Monotonically increasing count (resets only with the process)."""

    kind = "counter"

    def _new_slot(self):
        return [0.0]

    def _series_doc(self, key, slot):
        return {"value": slot[0]}

    def inc(self, amount=1, **labels):
        if amount < 0:
            raise ValueError("counters only go up")
        slot = self._slot(labels)
        with self._lock:
            slot[0] += amount

    def value(self, **labels):
        slot = self._slot(labels)
        with self._lock:
            return slot[0]

    def total(self):
        """Sum over every label combination (the heartbeat summaries
        want one number per rank, not a breakdown)."""
        with self._lock:
            return sum(s[0] for s in self._series.values())


class Gauge(_Metric):
    """A value that goes up and down (loss scale, straggler count)."""

    kind = "gauge"

    def _new_slot(self):
        return [0.0]

    def _series_doc(self, key, slot):
        return {"value": slot[0]}

    def set(self, value, **labels):
        slot = self._slot(labels)
        with self._lock:
            slot[0] = float(value)

    def inc(self, amount=1, **labels):
        slot = self._slot(labels)
        with self._lock:
            slot[0] += amount

    def dec(self, amount=1, **labels):
        self.inc(-amount, **labels)

    def value(self, **labels):
        slot = self._slot(labels)
        with self._lock:
            return slot[0]


class Histogram(_Metric):
    """Cumulative-bucket histogram with exact min/max/sum/count riding
    along (the heartbeat summaries and the fleet aggregation need real
    extrema, not bucket approximations)."""

    kind = "histogram"

    def __init__(self, name, help="", label_names=(), lock=None,
                 buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, label_names, lock)
        self.buckets = tuple(sorted(float(b) for b in buckets))

    def _new_slot(self):
        return {"counts": [0] * (len(self.buckets) + 1), "sum": 0.0,
                "count": 0, "min": math.inf, "max": -math.inf}

    def _series_doc(self, key, slot):
        # lazy import: export renders snapshots (imports this module);
        # the quantile math lives beside the other exposition helpers
        from .export import series_quantiles
        cum, acc = [], 0
        for le, c in zip(self.buckets, slot["counts"]):
            acc += c
            cum.append([le, acc])
        cum.append(["+Inf", slot["count"]])
        doc = {"count": slot["count"], "sum": slot["sum"],
               "min": None if slot["count"] == 0 else slot["min"],
               "max": None if slot["count"] == 0 else slot["max"],
               "buckets": cum}
        doc["quantiles"] = series_quantiles(doc)
        return doc

    def observe(self, value, **labels):
        value = float(value)
        slot = self._slot(labels)
        # linear scan beats bisect at these bucket counts and keeps the
        # hot path allocation-free
        idx = len(self.buckets)
        for i, b in enumerate(self.buckets):
            if value <= b:
                idx = i
                break
        with self._lock:
            slot["counts"][idx] += 1
            slot["sum"] += value
            slot["count"] += 1
            if value < slot["min"]:
                slot["min"] = value
            if value > slot["max"]:
                slot["max"] = value

    def summary(self, **labels):
        """{count, sum, min, max, mean} for one series (all None-safe:
        an empty histogram summarizes to count 0 and None extrema)."""
        slot = self._slot(labels)
        with self._lock:
            n = slot["count"]
            return {"count": n, "sum": slot["sum"],
                    "min": None if n == 0 else slot["min"],
                    "max": None if n == 0 else slot["max"],
                    "mean": None if n == 0 else slot["sum"] / n}


class MetricsRegistry:
    """Named metrics with get-or-create semantics (see module doc)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    def _get_or_create(self, cls, name, help, labels, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, labels, lock=self._lock, **kw)
                self._metrics[name] = m
                return m
        if not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as {m.kind}, "
                f"requested {cls.kind}")
        if tuple(labels) != m.label_names:
            raise ValueError(
                f"metric {name!r} already registered with labels "
                f"{m.label_names}, requested {tuple(labels)}")
        return m

    def counter(self, name, help="", labels=()):
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name, help="", labels=()):
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name, help="", labels=(),
                  buckets=DEFAULT_BUCKETS):
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def reset(self):
        """Drop every metric — tests only; live code never resets (a
        counter that restarts mid-scrape reads as a rollback)."""
        with self._lock:
            self._metrics = {}

    def snapshot(self):
        """The canonical JSON-able serialized form (schema
        ``singa-tpu-metrics/1``) every exporter consumes."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {"schema": SNAPSHOT_SCHEMA, "ts": time.time(),
                "build": build_stamp(),
                "metrics": [m.to_doc() for m in metrics]}

    def to_prometheus(self):
        from .export import render_prometheus
        return render_prometheus(self.snapshot())


# The process-wide default registry every instrumented layer reports
# through. Module-level singleton, like logging.root: one fleet-wide
# view needs one process-wide spine.
REGISTRY = MetricsRegistry()


def default_registry():
    return REGISTRY


def heartbeat_summary(registry=None):
    """The compact per-rank summary that rides cluster heartbeats:
    step-time stats from ``train_step_seconds``, this rank's dropped
    corrupt-frame count, the build stamp (git commit + process start —
    so the fleet view can correlate a perf shift with a deploy), and —
    once the sampling profiler has run — the newest step-timeline
    decomposition (``timeline``: bucket fractions + exposed-comm
    seconds) plus the rank's compile share of step wall-time, the two
    inputs of the coordinator's straggler cause labels. A few hundred
    bytes — cheap enough to attach to every beat; None/absent fields
    mean "no data yet"."""
    reg = registry if registry is not None else REGISTRY
    hist = reg.get("train_step_seconds")
    step = hist.summary() if isinstance(hist, Histogram) else None
    if step is not None and step["count"] == 0:
        step = None
    wires = reg.get("cluster_wire_errors_total")
    out = {"step_time": step,
           "wire_errors": int(wires.total())
           if isinstance(wires, Counter) else 0}
    from . import timeline as _timeline   # lazy: timeline imports us
    tl = _timeline.timeline_summary(reg, site="train")
    if tl is not None:
        out["timeline"] = tl
    compile_hist = reg.get("compile_seconds")
    if isinstance(compile_hist, Histogram) and step is not None \
            and step["sum"]:
        compile_sum = sum(float(s.get("sum") or 0.0) for s in
                          compile_hist.to_doc()["series"])
        if compile_sum:
            out["compile_share"] = min(
                1.0, compile_sum / float(step["sum"]))
    # serving KV pool pressure (paged engines only): the fleet view's
    # early-warning that a replica is running out of blocks — queue
    # depth rises AFTER the pool saturates, this shows it before
    kv_total = reg.get("kv_blocks_total")
    mesh_model = reg.get("serve_mesh_model")
    if isinstance(kv_total, Gauge) or isinstance(mesh_model, Gauge):
        kv = {}
        if isinstance(kv_total, Gauge):
            kv["blocks_total"] = kv_total.value()
        in_use = reg.get("kv_blocks_in_use")
        if isinstance(in_use, Gauge):
            kv["blocks_in_use"] = in_use.value()
        cached = reg.get("kv_blocks_cached")
        if isinstance(cached, Gauge):
            kv["blocks_cached"] = cached.value()
        hits = reg.get("prefix_cache_hits_total")
        if isinstance(hits, Counter):
            kv["prefix_cache_hits"] = int(hits.total())
        ratio = reg.get("speculative_accepted_ratio")
        if isinstance(ratio, Gauge):
            kv["speculative_accepted_ratio"] = ratio.value()
        # host-RAM spill tier (evicted cached prefixes parked in host
        # memory): restore-vs-spill movement shows whether the tier is
        # saving prefills or just churning
        for key, name in (("spills", "serve_kv_spill_total"),
                          ("restores", "serve_kv_restore_total")):
            c = reg.get(name)
            if isinstance(c, Counter):
                kv[key] = int(c.total())
        spill_b = reg.get("serve_kv_spill_bytes")
        if isinstance(spill_b, Gauge):
            kv["spill_bytes"] = spill_b.value()
        # sharded engines: the mesh shape + what ONE chip holds — the
        # fleet view's pool-pressure numbers must be per-device, not
        # the global logical pool (a paged pool is replicated across
        # 'batch' with a heads/model slice per chip; a ring shards its
        # slots over 'batch' too)
        if isinstance(mesh_model, Gauge):
            mesh_batch = reg.get("serve_mesh_batch")
            kv["mesh"] = {
                "batch": mesh_batch.value()
                if isinstance(mesh_batch, Gauge) else None,
                "model": mesh_model.value()}
            per_dev = reg.get("serve_kv_per_device_bytes")
            if isinstance(per_dev, Gauge):
                kv["per_device_bytes"] = per_dev.value()
        out["serving_kv"] = kv
    # live-KV handoff (preemption-deadline drains): migrated-out/-in,
    # typed refusals, recompute fallbacks, checkpoint cadence — the
    # fleet-view evidence a preempted replica's work moved instead of
    # being recomputed
    ho_keys = (("out", "serve_handoff_out_total"),
               ("in", "serve_handoff_in_total"),
               ("refused", "serve_handoff_refused_total"),
               ("fallback", "serve_handoff_fallback_total"),
               ("kv_checkpoints", "serve_kv_checkpoint_total"),
               ("prefill_tokens", "serve_prefill_tokens_total"))
    if any(isinstance(reg.get(n), Counter)
           for _k, n in ho_keys[:4]):
        ho = {}
        for key, name in ho_keys:
            c = reg.get(name)
            if isinstance(c, Counter):
                ho[key] = int(c.total())
        out["serving_handoff"] = ho
    # fleet resilience (processes running a FleetRouter): breaker /
    # re-dispatch / shed movement — the coordinator-view evidence that
    # a replica died and the fleet absorbed it
    fleet_sub = reg.get("serve_fleet_submitted_total")
    if isinstance(fleet_sub, Counter):
        fl = {"submitted": int(fleet_sub.total())}
        for key, name in (("failovers", "serve_fleet_failover_total"),
                          ("redispatches",
                           "serve_fleet_redispatch_total"),
                          ("sheds", "serve_fleet_shed_total"),
                          ("rejected", "serve_fleet_rejected_total"),
                          ("breaker_opens",
                           "serve_fleet_breaker_open_total"),
                          ("handoffs", "serve_fleet_handoff_total"),
                          ("resumes", "serve_fleet_resume_total")):
            c = reg.get(name)
            if isinstance(c, Counter):
                fl[key] = int(c.total())
        breaker = reg.get("serve_fleet_breaker_state")
        if isinstance(breaker, Gauge):
            series = breaker.to_doc().get("series", [])
            fl["breakers_open"] = sum(
                1 for s in series if s.get("value") == 2)
            fl["breakers_half_open"] = sum(
                1 for s in series if s.get("value") == 1)
        stranded = reg.get("serve_stranded_requests_total")
        if isinstance(stranded, Counter):
            fl["stranded"] = int(stranded.total())
        out["serving_fleet"] = fl
    # disaggregated prefill/decode pools: this replica's role tag
    # (engine-published gauge) plus, on router processes, per-pool
    # depth, transfer movement, and the affinity hit ratio — the
    # fleet-view evidence that prefix routing is actually keeping
    # decode-side caches warm
    role_g = reg.get("serve_pool_role")
    if isinstance(role_g, Gauge):
        out["pool_role"] = {1: "prefill", 2: "decode"}.get(
            int(role_g.value() or 0), "colocated")
    pool_xfer = reg.get("serve_pool_transfer_total")
    if isinstance(pool_xfer, Counter):
        pl = {"transferred": int(pool_xfer.total())}
        for key, name in (("retries", "serve_pool_transfer_retry_total"),
                          ("colocate_fallback",
                           "serve_pool_colocate_fallback_total"),
                          ("dup_discarded",
                           "serve_pool_dup_discarded_total"),
                          ("brownouts", "serve_pool_brownout_total"),
                          ("saturated", "serve_pool_saturated_total")):
            c = reg.get(name)
            if isinstance(c, Counter):
                pl[key] = int(c.total())
        hits_c = reg.get("serve_pool_affinity_hit_total")
        miss_c = reg.get("serve_pool_affinity_miss_total")
        h = int(hits_c.total()) if isinstance(hits_c, Counter) else 0
        ms = int(miss_c.total()) if isinstance(miss_c, Counter) else 0
        pl["affinity"] = {"hits": h, "misses": ms,
                          "hit_ratio": (h / (h + ms)) if h + ms
                          else 0.0}
        depth = reg.get("serve_pool_depth")
        if isinstance(depth, Gauge):
            pl["depth"] = {s["labels"].get("pool"): s.get("value")
                           for s in depth.to_doc().get("series", [])}
        out["serving_pools"] = pl
    # autoscaler decisions (processes running serving.autoscaler):
    # population movement + the flap-damping evidence — a fleet view
    # where replace_total climbs while quarantine stays 0 is a crash
    # loop the damping never caught
    pop = reg.get("autoscale_population")
    if isinstance(pop, Gauge):
        asc = {"population": pop.value()}
        for key, name in (("up", "autoscale_up_total"),
                          ("down", "autoscale_down_total"),
                          ("replace", "autoscale_replace_total"),
                          ("quarantine", "autoscale_quarantine_total"),
                          ("warm_refused",
                           "autoscale_warm_refused_total"),
                          ("spawn_failed",
                           "autoscale_spawn_failed_total")):
            c = reg.get(name)
            if isinstance(c, Counter):
                asc[key] = int(c.total())
        for key, name in (("pending_spawns",
                           "autoscale_pending_spawns"),
                          ("rung", "autoscale_rung"),
                          ("quarantined", "autoscale_quarantined")):
            g = reg.get(name)
            if isinstance(g, Gauge):
                asc[key] = g.value()
        spawn = reg.get("autoscale_spawn_seconds")
        if isinstance(spawn, Histogram):
            series = spawn.to_doc().get("series") or []
            if series and series[0]["count"]:
                q = series[0].get("quantiles") or {}
                asc["spawn_p50_s"] = q.get("p50")
                asc["spawn_p99_s"] = q.get("p99")
        out["autoscale"] = asc
    stamp = build_stamp()
    out["build"] = {"git": stamp["git"], "start_ts": stamp["start_ts"]}
    return out


# a rank whose mean step time exceeds this multiple of the fleet's
# count-weighted mean is named a straggler in the aggregated view
STRAGGLER_FACTOR = 1.5


def aggregate_summaries(summaries, ages=None, stale_after=None):
    """Fold per-rank heartbeat summaries into ONE fleet view — what the
    coordinator publishes in its health report: min/max of the ranks'
    step-time extrema, a count-weighted mean, total steps and wire
    errors, how many ranks have reported anything at all, and — when
    more than one rank reports step times — cross-rank straggler
    attribution: the ranks whose own mean step time sits more than
    :data:`STRAGGLER_FACTOR`× above the fleet mean, so "which host is
    slow" is answerable straight off the heartbeat-carried summaries.

    Each named straggler additionally gets a CAUSE label in
    ``straggler_causes`` (``{rank: comm_bound | data_bound |
    compute_bound | compile_bound | unknown}``), judged from the
    timeline fractions and compile share its own heartbeat carried
    (``observability.timeline.classify_cause``) — "rank 2 is slow"
    becomes "rank 2 is slow because its collectives are exposed".

    ``ages`` (``{rank: seconds since last heartbeat}``) with
    ``stale_after`` marks ranks whose last beat is older than the
    threshold as STALE: their last-known gauges are dead data, not
    current load, so they are EXCLUDED from every aggregate above and
    surfaced separately as ``stale`` (``{rank: age}``) — an
    autoscaler reading this view must never scale on a silent
    replica's frozen numbers."""
    summaries = dict(summaries or {})
    stale = {}
    if ages and stale_after:
        for r in list(summaries):
            age = ages.get(str(r), ages.get(r))
            if age is not None and float(age) > float(stale_after):
                stale[str(r)] = round(float(age), 3)
                summaries.pop(r)
    vals = [s for s in summaries.values() if isinstance(s, dict)]
    agg = {"ranks_reporting": len(vals),
           "wire_errors": sum(int(s.get("wire_errors") or 0)
                              for s in vals)}
    if stale:
        agg["stale"] = stale
    per_rank = {r: s["step_time"] for r, s in summaries.items()
                if isinstance(s, dict)
                and isinstance(s.get("step_time"), dict)
                and s["step_time"].get("count")}
    steps = list(per_rank.values())
    if steps:
        total = sum(int(s["count"]) for s in steps)
        agg["steps"] = total
        agg["step_time_min"] = min(float(s["min"]) for s in steps)
        agg["step_time_max"] = max(float(s["max"]) for s in steps)
        agg["step_time_mean"] = sum(
            float(s["mean"]) * int(s["count"]) for s in steps) / total
        fleet = agg["step_time_mean"]
        agg["step_time_stragglers"] = sorted(
            (r for r, s in per_rank.items()
             if float(s["mean"]) > STRAGGLER_FACTOR * fleet),
            key=str) if len(per_rank) > 1 and fleet > 0 else []
        if agg["step_time_stragglers"]:
            from . import timeline as _timeline   # lazy (imports us)
            causes = {}
            for r in agg["step_time_stragglers"]:
                s = summaries.get(r) or {}
                tl = s.get("timeline") or {}
                cause = _timeline.classify_cause(
                    tl.get("fractions"), s.get("compile_share"))
                causes[str(r)] = cause or "unknown"
            agg["straggler_causes"] = causes
    return agg


__all__ = ["SNAPSHOT_SCHEMA", "DEFAULT_BUCKETS", "PEAK_FLOPS_BY_KIND",
           "STRAGGLER_FACTOR", "device_peak_flops", "build_stamp",
           "Counter", "Gauge", "Histogram", "MetricsRegistry",
           "REGISTRY", "default_registry", "heartbeat_summary",
           "aggregate_summaries"]
