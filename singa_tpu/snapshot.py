"""Versioned parameter checkpoints, wire-compatible with the reference.

Capability parity with the reference Snapshot (src/io/snapshot.cc:33-80 and
python/singa/snapshot.py:42-66). Two on-disk formats:

- ``format="singa"`` — the reference's exact bytes: ``<prefix>.bin`` is a
  BinFile ('s','g' magic-word KV records, src/io/binfile_writer.cc) whose
  values are serialized ``TensorProto`` messages (src/proto/core.proto:70
  — shape/data_type/stride/float_data...), and ``<prefix>.desc`` is the
  text sidecar whose first line carries ``SINGA VERSION: 4000``
  (snapshot.cc:46 — major*1000+minor*100+patch) followed by one
  ``parameter name: ...`` line per tensor (snapshot.cc:97-103). A real
  SINGA 4.0.0 checkpoint loads here, and a snapshot written here loads in
  real SINGA (float32/double/int payloads — the dtypes the reference's
  ``to_proto`` implements, tensor.cc:364-418).
- ``format="native"`` — this framework's record-file runtime
  (``SGTPREC0`` magic) with a compact self-describing array header;
  supports every dtype (incl. bf16) and streams through the threaded
  native reader.

Reads auto-detect the format from the magic bytes.
"""

from __future__ import annotations

import os
import struct

import numpy as np

from .integrity import (IntegrityError, read_digest_sidecar,
                        tensor_digest, write_digest_sidecar)
from .native import RecordReader, RecordWriter
from .tensor import Tensor

VERSION = 1
# reference version tag written to .desc (CMakeLists.txt:41 for 4.0.0)
SINGA_VERSION = 4000

# reference core.proto DataType values (core.proto:26-34)
_K_FLOAT32, _K_FLOAT16, _K_INT, _K_CHAR, _K_DOUBLE, _K_UCHAR = range(6)


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf, off):
    n = shift = 0
    while True:
        b = buf[off]
        off += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, off
        shift += 7


def _pack_tensorproto(arr: np.ndarray) -> bytes:
    """Serialize the reference TensorProto wire format
    (core.proto:70-78; payload field per dtype as tensor.cc to_proto)."""
    out = bytearray()
    for s in arr.shape:                       # field 1: repeated uint32
        out += b"\x08" + _varint(int(s))
    if arr.dtype == np.float32:
        dt, field, payload = _K_FLOAT32, 4, arr.astype("<f4").tobytes()
    elif arr.dtype == np.float64:
        dt, field, payload = _K_DOUBLE, 5, arr.astype("<f8").tobytes()
    elif arr.dtype in (np.int32, np.int64):
        # the reference's kInt payload is int32 (core.proto:29): int64
        # input is accepted only when every value fits — a silent
        # wraparound on reload would corrupt step counters
        if arr.dtype == np.int64 and (
                arr.min(initial=0) < -2**31 or
                arr.max(initial=0) >= 2**31):
            raise ValueError(
                "int64 values exceed the reference kInt (int32) range — "
                "use format='native' for full-width integers")
        dt, field = _K_INT, 6
        payload = b"".join(_varint(int(v) & 0xFFFFFFFFFFFFFFFF)
                           for v in arr.astype(np.int64).ravel())
    else:
        raise ValueError(
            f"dtype {arr.dtype} has no reference TensorProto payload "
            f"(to_proto implements float32/double/int, tensor.cc:364) — "
            f"use format='native' for {arr.dtype}")
    out += b"\x10" + _varint(dt)              # field 2: data_type
    # field 3 (stride) is omitted: FromProto recomputes a dense layout
    out += _varint(field << 3 | 2) + _varint(len(payload)) + payload
    return bytes(out)


def _unpack_tensorproto(raw: bytes) -> np.ndarray:
    shape, dtype = [], _K_FLOAT32
    floats = bytearray()
    doubles = bytearray()
    ints = []
    off = 0
    while off < len(raw):
        tag, off = _read_varint(raw, off)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            v, off = _read_varint(raw, off)
            if field == 1:
                shape.append(v)
            elif field == 2:
                dtype = v
            elif field == 6:
                ints.append(v)
            # field 3 (stride) ignored: dense layout is recomputed
        elif wire == 2:
            ln, off = _read_varint(raw, off)
            chunk = raw[off:off + ln]
            off += ln
            if field == 4:
                floats += chunk
            elif field == 5:
                doubles += chunk
            elif field == 6:
                o2 = 0
                while o2 < len(chunk):
                    v, o2 = _read_varint(chunk, o2)
                    ints.append(v)
        elif wire == 5:                       # unpacked float
            if field == 4:
                floats += raw[off:off + 4]
            off += 4
        elif wire == 1:                       # unpacked double
            if field == 5:
                doubles += raw[off:off + 8]
            off += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
    if dtype == _K_DOUBLE:
        arr = np.frombuffer(bytes(doubles), "<f8")
    elif dtype == _K_INT:
        arr = np.asarray([v - (1 << 64) if v >= (1 << 63) else v
                          for v in ints], np.int64).astype(np.int32)
    elif dtype == _K_FLOAT32:
        arr = np.frombuffer(bytes(floats), "<f4")
    else:
        # mirror the clear error _pack_tensorproto gives on write: a
        # kFloat16/kChar/kUChar payload (field 7 bytes_data) is never
        # parsed above, so decoding would hand back an empty/garbled
        # buffer and fail later at reshape with a confusing message
        names = {_K_FLOAT16: "kFloat16", _K_CHAR: "kChar",
                 _K_UCHAR: "kUChar"}
        raise ValueError(
            f"TensorProto data_type {names.get(dtype, dtype)} is not "
            "supported by this reader (only kFloat32/kDouble/kInt "
            "payloads, matching the reference to_proto, "
            "tensor.cc:364-418)")
    return arr.reshape(shape).copy()


def _binfile_write(f, key: str, value: bytes) -> None:
    """One reference BinFile record (src/io/binfile_writer.cc:60-80):
    magic 's','g',has_key,0 then size_t-framed key and value."""
    kb = key.encode("utf-8")
    if kb:
        f.write(b"sg\x01\x00" + struct.pack("<Q", len(kb)) + kb
                + struct.pack("<Q", len(value)) + value)
    else:
        f.write(b"sg\x00\x00" + struct.pack("<Q", len(value)) + value)


def _binfile_read(path):
    """Yield (key, value) from a reference BinFile."""
    with open(path, "rb") as f:
        data = f.read()
    off = 0
    while off < len(data):
        if data[off:off + 2] != b"sg":
            raise ValueError(f"bad BinFile magic at offset {off}")
        has_key = data[off + 2]
        off += 4
        key = ""
        if has_key:
            (klen,) = struct.unpack_from("<Q", data, off)
            off += 8
            key = data[off:off + klen].decode("utf-8")
            off += klen
        (vlen,) = struct.unpack_from("<Q", data, off)
        off += 8
        yield key, data[off:off + vlen]
        off += vlen


def _encode_array(arr: np.ndarray) -> bytes:
    """dtype-str-len u8 | dtype str | ndim u8 | dims u32* | raw bytes

    Extended dtypes (bfloat16, fp8 — registered by ml_dtypes) need
    their registered NAME stored: most have a void ``dtype.str``
    ('<V2'), which would round-trip as raw bytes with the real type
    lost, and float8_e5m2's is '<f1', which ``np.dtype`` cannot parse
    back at all. The robust rule is to store ``dtype.str`` only when it
    provably reconstructs the same dtype, the name otherwise."""
    try:
        str_ok = np.dtype(arr.dtype.str) == arr.dtype
    except TypeError:
        str_ok = False
    dt = (arr.dtype.str if str_ok else arr.dtype.name).encode("ascii")
    out = bytearray()
    out += len(dt).to_bytes(1, "little")
    out += dt
    out += arr.ndim.to_bytes(1, "little")
    for d in arr.shape:
        out += int(d).to_bytes(4, "little")
    out += np.ascontiguousarray(arr).tobytes()
    return bytes(out)


def _decode_array(raw: bytes) -> np.ndarray:
    n = raw[0]
    tok = raw[1:1 + n].decode("ascii")
    if tok and tok[0] not in "<>|=":
        # name-encoded extended dtype: numpy only knows it once
        # ml_dtypes (shipped with jax) has registered it
        try:
            import ml_dtypes  # noqa: F401
        except ImportError:
            pass
    dt = np.dtype(tok)
    off = 1 + n
    ndim = raw[off]
    off += 1
    shape = []
    for _ in range(ndim):
        shape.append(int.from_bytes(raw[off:off + 4], "little"))
        off += 4
    return np.frombuffer(raw, dtype=dt, offset=off).reshape(shape).copy()


_K_BY_DTYPE = {np.dtype(np.float32): _K_FLOAT32,
               np.dtype(np.float64): _K_DOUBLE,
               np.dtype(np.int32): _K_INT,
               np.dtype(np.int64): _K_INT}


def _singa_serializable(arr: np.ndarray) -> bool:
    """Whether the reference TensorProto wire format can carry ``arr``
    losslessly (the dtypes _pack_tensorproto accepts, incl. the int64
    in-int32-range rule)."""
    if arr.dtype in (np.float32, np.float64, np.int32):
        return True
    if arr.dtype == np.int64:
        return bool(arr.min(initial=0) >= -2**31
                    and arr.max(initial=0) < 2**31)
    return False


class Snapshot:
    """Write or read a parameter checkpoint (reference
    python/singa/snapshot.py:42; kWrite/kRead modes).

    ``format`` applies to writes: "auto" (default), "singa" (reference
    4.0.0 wire compatibility) or "native". With "auto" the records are
    buffered in memory and the files land on ``done()``/context exit:
    the reference wire format is used when every tensor fits it, else
    the whole snapshot auto-falls-back to the native record format —
    with a warning — so bfloat16 / out-of-int32-range int64 state that
    saved fine before the singa format existed keeps saving fine (the
    explicit ``format="singa"`` contract still raises on such dtypes).
    Reads auto-detect from the magic bytes, so both kinds (and real
    SINGA checkpoints) load through the same constructor; like the
    reference reader (snapshot.cc:60-64), a ``<prefix>.model`` BinFile
    from SINGA 1.0.0 is accepted when no ``.bin`` exists."""

    kRead = False
    kWrite = True

    def __init__(self, prefix: str, mode: bool, buffer_size: int = 10,
                 format: str = "auto"):
        self.prefix = prefix
        self.mode = mode
        if format not in ("auto", "singa", "native"):
            raise ValueError(f"format must be 'auto', 'singa' or "
                             f"'native', got {format!r}")
        self.format = format
        if mode == self.kWrite:
            self._names = set()
            self._digests = {}      # param name -> content digest
            self._pending = [] if format == "auto" else None
            if format != "auto":
                self._open_write(format)
        else:
            path = prefix + ".bin"
            if not os.path.exists(path):
                # SINGA 1.0.0 wrote <prefix>.model (snapshot.cc:62)
                if os.path.exists(prefix + ".model"):
                    path = prefix + ".model"
                else:
                    raise FileNotFoundError(prefix + ".bin")
            with open(path, "rb") as f:
                head = f.read(8)
            self._read_path = path
            self._read_native = head == RecordWriter.MAGIC \
                if hasattr(RecordWriter, "MAGIC") else \
                head == b"SGTPREC0"
            if self._read_native:
                self._reader = RecordReader(path)
            else:
                if head[:2] != b"sg":
                    raise ValueError(
                        f"{path}: neither a native record file nor a "
                        f"SINGA BinFile (magic {head[:2]!r})")
                self._reader = None

    def _open_write(self, format: str) -> None:
        if format == "native":
            self._writer = RecordWriter(self.prefix + ".bin")
        else:
            self._writer = open(self.prefix + ".bin", "wb")
        self._desc = open(self.prefix + ".desc", "w")
        if format == "singa":
            # snapshot.cc:46 — version header line
            self._desc.write(f"SINGA VERSION: {SINGA_VERSION}\n")
        else:
            self._desc.write(f"version: {VERSION}\n")

    def _write_record(self, format: str, param_name: str,
                      arr: np.ndarray) -> None:
        # the digest covers the DECODED array (dtype+shape+bytes), not
        # the wire encoding, so both formats verify through one rule —
        # and a record that decodes to the wrong values fails even if
        # its framing is intact. The reference kInt payload reloads as
        # int32 (core.proto:29), so in-range int64 input is digested in
        # its canonical round-trip form.
        canon = arr.astype(np.int32) \
            if format == "singa" and arr.dtype == np.int64 else arr
        self._digests[param_name] = tensor_digest(canon)
        if format == "singa":
            _binfile_write(self._writer, param_name,
                           _pack_tensorproto(arr))
            # snapshot.cc:97-103 desc line, byte for byte
            dt = _K_BY_DTYPE.get(arr.dtype)
            shape_str = "".join(f" {s}" for s in arr.shape)
            self._desc.write(
                f"parameter name: {param_name}\tdata type: {dt}"
                f"\tdim: {arr.ndim}\tshape:{shape_str}\n")
        else:
            self._writer.write(param_name, _encode_array(arr))
            self._desc.write(
                f"name: {param_name} shape: {list(arr.shape)} "
                f"dtype: {arr.dtype.name}\n")

    def write(self, param_name: str, param_val) -> None:
        assert self.mode == self.kWrite, "snapshot opened for read"
        # reference Snapshot::Write CHECKs key uniqueness (snapshot.cc:88)
        if param_name in self._names:
            raise ValueError(f"duplicate snapshot key {param_name!r}")
        self._names.add(param_name)
        arr = np.asarray(param_val.numpy()
                         if isinstance(param_val, Tensor) else param_val)
        if self._pending is not None:       # auto: decide format on done()
            self._pending.append((param_name, arr))
        else:
            self._write_record(self.format, param_name, arr)

    def read(self, verify=True):
        """All params as an OrderedDict name -> Tensor (reference
        Snapshot.Read). With ``verify`` (default) every decoded array
        is checked against the ``<prefix>.digest`` sidecar when one
        exists — a flipped bit in the .bin raises
        :class:`~singa_tpu.integrity.IntegrityError` naming the record
        instead of silently handing back corrupt parameters. Snapshots
        without a sidecar (real SINGA files, pre-integrity saves) load
        unverified, as before."""
        assert self.mode == self.kRead, "snapshot opened for write"
        from collections import OrderedDict
        arrays = OrderedDict()
        if self._read_native:
            self._reader.seek_to_first()
            for key, val in self._reader:
                arrays[key.decode("utf-8")] = _decode_array(val)
        else:
            for key, val in _binfile_read(self._read_path):
                if key in arrays:   # reference CHECK(count == 0)
                    raise ValueError(f"duplicate snapshot key {key!r}")
                arrays[key] = _unpack_tensorproto(val)
        if verify:
            sidecar = read_digest_sidecar(self.prefix + ".digest")
            if sidecar is not None:
                for name, want in sidecar["records"].items():
                    if name not in arrays:
                        raise IntegrityError(
                            f"snapshot {self.prefix!r}: digested record "
                            f"{name!r} is missing from the file")
                    got = tensor_digest(arrays[name])
                    if got != want:
                        raise IntegrityError(
                            f"snapshot {self.prefix!r}: record {name!r} "
                            f"failed its content digest ({got} != "
                            f"recorded {want}) — corrupt .bin")
        out = OrderedDict()
        for key, arr in arrays.items():
            out[key] = Tensor(data=arr, requires_grad=False)
        return out

    def done(self) -> None:
        if self.mode == self.kWrite:
            if self._pending is not None:
                pending, self._pending = self._pending, None
                bad = [(n, a.dtype) for n, a in pending
                       if not _singa_serializable(a)]
                fmt = "native" if bad else "singa"
                if bad:
                    import warnings
                    warnings.warn(
                        f"snapshot {self.prefix!r}: {bad[0][0]!r} "
                        f"(dtype {bad[0][1]}) has no reference "
                        "TensorProto payload; writing the whole snapshot "
                        "in the native record format instead (pass "
                        "format='singa' to force the reference wire "
                        "format, which raises on such dtypes)",
                        stacklevel=2)
                self._open_write(fmt)
                self.format = fmt
                for name, arr in pending:
                    self._write_record(fmt, name, arr)
            self._writer.close()
            self._desc.close()
            # the digest sidecar lands LAST (atomic tmp+rename): its
            # presence vouches for a complete .bin, so a write torn
            # before this point simply loads unverified-or-failing,
            # never verified-and-wrong
            write_digest_sidecar(self.prefix + ".digest", self._digests,
                                 format=self.format)
        elif self._reader is not None:
            self._reader.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.done()


def save_states(prefix: str, states: dict, format: str = "auto") -> None:
    """Convenience: dict of name->Tensor/ndarray to a snapshot.
    ``format`` passes through to :class:`Snapshot` ("auto" default:
    reference wire format when every dtype fits, native otherwise).

    With the whole dict in hand, "auto" is resolved HERE by inspecting
    dtypes up front, so the records stream straight to disk instead of
    riding Snapshot's record-at-a-time buffering (which would hold a
    host copy of the entire checkpoint until done())."""
    if format == "auto":
        format = "singa"
        for k, v in states.items():
            dt = np.dtype(getattr(v, "dtype", None) or np.asarray(v).dtype)
            if dt == np.int64:
                # range decides: only the values say whether the
                # reference kInt (int32) payload can carry them
                arr = np.asarray(v.numpy()
                                 if isinstance(v, Tensor) else v)
                if _singa_serializable(arr):
                    continue
            elif dt in (np.float32, np.float64, np.int32):
                continue
            import warnings
            warnings.warn(
                f"save_states {prefix!r}: {k!r} (dtype {dt}) has no "
                "reference TensorProto payload; writing the snapshot "
                "in the native record format instead", stacklevel=2)
            format = "native"
            break
    with Snapshot(prefix, Snapshot.kWrite, format=format) as s:
        for k, v in states.items():
            s.write(k, v)


def load_states(prefix: str) -> dict:
    with Snapshot(prefix, Snapshot.kRead) as s:
        return s.read()
