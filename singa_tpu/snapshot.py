"""Versioned parameter checkpoints.

Capability parity with the reference Snapshot (src/io/snapshot.cc:33-80 and
python/singa/snapshot.py:42-66): ``<prefix>.bin`` holds named tensors as
key/value records through the native record-file runtime, and
``<prefix>.desc`` is a human-readable description (name, shape, dtype) —
the reference's TensorProto payload is replaced by a compact self-describing
binary header, and the version tag is carried in the desc file.
"""

from __future__ import annotations

import os

import numpy as np

from .native import RecordReader, RecordWriter
from .tensor import Tensor

VERSION = 1


def _encode_array(arr: np.ndarray) -> bytes:
    """dtype-str-len u8 | dtype str | ndim u8 | dims u32* | raw bytes"""
    dt = arr.dtype.str.encode("ascii")
    out = bytearray()
    out += len(dt).to_bytes(1, "little")
    out += dt
    out += arr.ndim.to_bytes(1, "little")
    for d in arr.shape:
        out += int(d).to_bytes(4, "little")
    out += np.ascontiguousarray(arr).tobytes()
    return bytes(out)


def _decode_array(raw: bytes) -> np.ndarray:
    n = raw[0]
    dt = np.dtype(raw[1:1 + n].decode("ascii"))
    off = 1 + n
    ndim = raw[off]
    off += 1
    shape = []
    for _ in range(ndim):
        shape.append(int.from_bytes(raw[off:off + 4], "little"))
        off += 4
    return np.frombuffer(raw, dtype=dt, offset=off).reshape(shape).copy()


class Snapshot:
    """Write or read a parameter checkpoint (reference
    python/singa/snapshot.py:42; kWrite/kRead modes)."""

    kRead = False
    kWrite = True

    def __init__(self, prefix: str, mode: bool, buffer_size: int = 10):
        self.prefix = prefix
        self.mode = mode
        if mode == self.kWrite:
            self._writer = RecordWriter(prefix + ".bin")
            self._desc = open(prefix + ".desc", "w")
            self._desc.write(f"version: {VERSION}\n")
        else:
            if not os.path.exists(prefix + ".bin"):
                raise FileNotFoundError(prefix + ".bin")
            self._reader = RecordReader(prefix + ".bin")

    def write(self, param_name: str, param_val) -> None:
        assert self.mode == self.kWrite, "snapshot opened for read"
        arr = np.asarray(param_val.numpy()
                         if isinstance(param_val, Tensor) else param_val)
        self._writer.write(param_name, _encode_array(arr))
        self._desc.write(
            f"name: {param_name} shape: {list(arr.shape)} "
            f"dtype: {arr.dtype.name}\n")

    def read(self):
        """All params as an OrderedDict name -> Tensor (reference
        Snapshot.Read)."""
        assert self.mode == self.kRead, "snapshot opened for write"
        from collections import OrderedDict
        out = OrderedDict()
        self._reader.seek_to_first()
        for key, val in self._reader:
            out[key.decode("utf-8")] = Tensor(data=_decode_array(val),
                                              requires_grad=False)
        return out

    def done(self) -> None:
        if self.mode == self.kWrite:
            self._writer.close()
            self._desc.close()
        else:
            self._reader.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.done()


def save_states(prefix: str, states: dict) -> None:
    """Convenience: dict of name->Tensor/ndarray to a snapshot."""
    with Snapshot(prefix, Snapshot.kWrite) as s:
        for k, v in states.items():
            s.write(k, v)


def load_states(prefix: str) -> dict:
    with Snapshot(prefix, Snapshot.kRead) as s:
        return s.read()
