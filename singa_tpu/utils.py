"""Misc helpers: progress bar, ONNX-style padding math, tape walking.

Capability parity with the reference utils (python/singa/utils.py): the
``update_progress`` console bar, odd/SAME padding helpers used by
Conv/Pool layers for ONNX ``auto_pad`` semantics, and a post-order tape
traversal. The odd-pad forward/backward pair is unnecessary here — our
conv/pool handles take explicit ((top, bottom), (left, right)) pad pairs
and XLA differentiates through them — so ``handle_odd_pad_fwd`` reduces to
a plain asymmetric pad.
"""

from __future__ import annotations

import sys

import jax.numpy as jnp

from .tensor import Tensor


def force_completion(x) -> float:
    """Completion barrier that holds on proxied/tunneled backends.

    ``block_until_ready`` can resolve when a network proxy ACKs the
    ENQUEUE, not when the device finishes (measured 40x over-speed on a
    tunneled chip — see docs/performance.md). Fetching a scalar derived
    from an output to the host is the only barrier that cannot lie: the
    value does not exist until the program ran. One leaf suffices — a
    single XLA executable's outputs complete together. Accepts an array
    or any pytree of arrays; returns the fetched scalar."""
    import jax
    import numpy as np

    leaves = jax.tree_util.tree_leaves(x)
    for leaf in leaves:
        if hasattr(leaf, "dtype") and getattr(leaf, "size", 0):
            return float(np.asarray(
                jnp.sum(jnp.ravel(leaf)[:1]).astype(jnp.float32)))
    # no sizeable leaf to fetch (empty arrays / scalar-free pytree):
    # fall back to block_until_ready so the caller still gets SOME
    # synchronization instead of a silent no-op (on the axon tunnel
    # this is enqueue-ACK semantics — weaker, but never nothing)
    for leaf in leaves:
        if hasattr(leaf, "block_until_ready"):
            leaf.block_until_ready()
    return 0.0


def update_progress(progress: float, info: str = "") -> None:
    """Render a textual progress bar (reference utils.update_progress:27).

    progress in [0, 1]; 1.0 appends Done.
    """
    length = 20
    progress = max(0.0, min(1.0, float(progress)))
    filled = int(round(length * progress))
    bar = "#" * filled + "-" * (length - filled)
    status = " Done." if progress >= 1.0 else ""
    sys.stdout.write(f"\r[{bar}] {progress * 100:3.1f}% {info}{status}")
    sys.stdout.flush()
    if progress >= 1.0:
        sys.stdout.write("\n")


def get_padding_shape(pad_mode, input_spatial_shape, kernel_spatial_shape,
                      strides_spatial):
    """ONNX auto_pad ('SAME_UPPER'/'SAME_LOWER') -> per-dim (begin, end)
    pads (reference utils.get_padding_shape:159)."""
    pads = []
    for i, (d, k, s) in enumerate(zip(input_spatial_shape,
                                      kernel_spatial_shape,
                                      strides_spatial)):
        out = (d + s - 1) // s
        total = max(0, (out - 1) * s + k - d)
        small, big = total // 2, total - total // 2
        if pad_mode == "SAME_LOWER":
            pads.append((big, small))
        else:  # SAME_UPPER
            pads.append((small, big))
    return pads


def get_output_shape(auto_pad, input_spatial_shape, kernel_spatial_shape,
                     strides_spatial):
    """Spatial output shape under an ONNX auto_pad mode
    (reference utils.get_output_shape:189)."""
    if auto_pad in ("SAME_UPPER", "SAME_LOWER"):
        return [(d + s - 1) // s
                for d, s in zip(input_spatial_shape, strides_spatial)]
    if auto_pad == "VALID":
        return [(d - k) // s + 1
                for d, k, s in zip(input_spatial_shape,
                                   kernel_spatial_shape, strides_spatial)]
    raise ValueError(f"unsupported auto_pad {auto_pad}")


def handle_odd_pad_fwd(x, odd_padding, is_pool=False):
    """Apply an asymmetric (top, bottom, left, right) pad to NCHW data
    (reference utils.handle_odd_pad_fwd:56). Tensor inputs go through the
    taped Pad op so gradients flow; the reference's explicit backward twin
    (handle_odd_pad_bwd) is therefore unnecessary."""
    t, b, l, r = odd_padding
    fill = float("-inf") if is_pool else 0.0
    if isinstance(x, Tensor):
        from . import autograd
        # pads layout: begin per dim, then end per dim (N,C,H,W)
        return autograd.pad(x, "constant", [0, 0, t, l, 0, 0, b, r], fill)
    return jnp.pad(jnp.asarray(x), ((0, 0), (0, 0), (t, b), (l, r)),
                   constant_values=fill)


def same_pad_shape_check(handle, pad_mode, x):
    """Validate that the handle's explicit pads equal the auto_pad-derived
    ones (reference utils.same_pad_shape_check:110).

    ConvHandle stores ((t, b), (l, r)) pairs in ``padding``; PoolingHandle
    exposes the same as ``pad_pairs``.
    """
    spatial = x.shape[2:]
    expect = get_padding_shape(pad_mode, spatial, handle.kernel_size,
                               handle.stride)
    got = getattr(handle, "pad_pairs", None)
    if got is None:
        got = handle.padding  # ConvHandle: already pair-of-pairs
    return tuple(map(tuple, got)) == tuple(map(tuple, expect))


def force_unicode(s):
    """bytes -> str passthrough (reference utils.force_unicode:219)."""
    if isinstance(s, bytes):
        return s.decode("utf-8", errors="replace")
    return str(s)


def post_order_recursive(root, visit):
    """Post-order walk over a tape from a root op, calling ``visit(op)``
    per op (reference utils.post_order_recursive:234). Iterative, so deep
    tapes don't hit the recursion limit."""
    seen = set()
    stack = [(root, False)]
    while stack:
        op, expanded = stack.pop()
        if op is None:
            continue
        if expanded:
            visit(op)
            continue
        if id(op) in seen:
            continue
        seen.add(id(op))
        stack.append((op, True))
        for (src_op, _x, _t, _r) in getattr(op, "src", []):
            if src_op is not None and id(src_op) not in seen:
                stack.append((src_op, False))
