"""SLO-driven warm autoscaler: replica lifecycle supervision over a
:class:`~singa_tpu.serving.fleet.FleetRouter`.

PR 16 made the router survive a replica crash and PR 17 made a drain
migrate its in-flight KV — but nothing *managed the population*: a
load spike ended in sheds and a dead replica stayed dead until an
operator noticed. The :class:`Autoscaler` closes that loop. It reads
the per-replica gauges that already exist (windowed p99 TTFT from
``serve_ttft_seconds``, queue depth, paged-KV pool pressure, breaker
states) and drives three lifecycle verbs against SLO targets:

- **scale-up** — spawn a replica pre-warmed from ``tools/aot_cache.py
  prebuild`` artifacts and admit it only after the warm-admission
  gate: ready health, a served first token, and **zero**
  ``compile_seconds{source="fresh"}`` entries. A cold-compiling
  replica admitted into the rotation is itself a fault — it eats its
  first requests' latency budget tracing programs — so the gate
  refuses it typed (:class:`WarmAdmissionRefused`).
- **scale-down** — pick the least-loaded victim and retire it through
  the PR-17 path: ``drain(deadline=)`` with live-KV handoff armed, so
  every in-flight request either finishes or migrates. Zero dropped
  responses is the contract, not an aspiration.
- **replacement** — a replica whose breaker stays open, whose
  heartbeats go stale, or whose engine crashed is removed and
  respawned into the same *seat*.

Robustness is the point, not elasticity alone:

- **hysteresis** — a breach (or calm) must be *sustained* for a
  window before any decision fires; one slow request never burns a
  spawn, one idle tick never drains a replica.
- **per-direction cooldowns** — after a scale-up (scale-down) the
  same direction is locked out for its own cooldown, so the
  population cannot oscillate at the tick rate.
- **flap damping** — a seat whose replicas cycle ready↔dead
  ``flap_threshold`` times inside ``flap_window_s`` is
  **quarantined**: the supervisor stops respawning it (a crash loop
  respawned forever is a money fire, not fault tolerance). The
  population floor shrinks by the quarantined seats — quarantine
  beats the min bound by design.
- **degradation ladder** — brownout → shed → scale-up. The effective
  scale-up window never undercuts the PR-16
  :class:`~singa_tpu.serving.fleet.ShedPolicy` window, so a transient
  spike is absorbed by brownout/shed *before* it burns a replica
  spawn; the current rung rides the ``autoscale_rung`` gauge.

Decisions are observable: ``autoscale_{up,down,replace,quarantine}_
total`` counters, ``autoscale_population`` / ``autoscale_pending_
spawns`` / ``autoscale_rung`` gauges, and an ``autoscale_spawn_
seconds`` histogram of spawn-to-ready durations whose rolling median
feeds :meth:`Autoscaler.retry_after_hint` — the gateway's 503
``Retry-After`` during a scale-up tells clients when capacity
actually lands instead of a constant.

The supervisor is a pure state machine over an injected clock:
``tick(now)`` makes every decision, ``start()`` merely runs ticks on
a daemon thread. Tier-1 tests drive ``tick`` directly with fake
replicas, ``sync=True`` (spawns/retires run inline) and a hand-rolled
``now`` — no sleeps, no threads, no flakes. Chaos
(``tools/chaos_smoke.py --only serve-autoscale``) drives the same
class over real gateway subprocesses.
"""

from __future__ import annotations

import math
import threading
import warnings
from collections import deque
from dataclasses import asdict, dataclass

from ..observability import metrics as _metrics
from ..observability import spans as _spans
from ..resilience.faults import NULL_PLAN, SimulatedCrash
from .fleet import BREAKER_OPEN, EXIT_DRAINED
from .scheduler import ServingError


class SpawnFailed(ServingError):
    """A replica spawn did not produce an admissible replica."""


class WarmAdmissionRefused(SpawnFailed):
    """The warm-admission gate refused a replica that compiled fresh
    (``compile_seconds{source="fresh"}`` > 0) — it would eat its first
    requests' latency budget tracing programs. Prebuild the AOT
    artifacts (``tools/aot_cache.py prebuild``) and spawn with the
    store attached."""


# degradation ladder rungs (the autoscale_rung gauge)
RUNG_HEALTHY = 0        # SLOs met
RUNG_SHED = 1           # breach: brownout/shed (PR-16) absorbing it
RUNG_SPAWN = 2          # breach sustained: capacity is coming


@dataclass
class AutoscaleTargets:
    """SLO targets + robustness knobs. Defaults suit tests and the
    CPU chaos drill; production wants windows/cooldowns in the tens
    of seconds."""

    ttft_p99_s: float = 1.0      # windowed p99 TTFT ceiling
    tpot_p99_s: float = 1.0      # windowed p99 per-token latency
    #                              ceiling (disaggregated pools only:
    #                              TPOT is the decode pool's SLO the
    #                              way TTFT is the prefill pool's)
    queue_high: float = 4.0      # mean queue depth per ready replica
    queue_low: float = 0.5       # ... below which the fleet is calm
    pool_high: float = 0.9       # paged-KV blocks in_use/total ceiling
    pool_low: float = 0.5
    min_replicas: int = 1
    max_replicas: int = 4
    up_window_s: float = 2.0     # breach must be sustained this long
    down_window_s: float = 10.0  # calm must be sustained this long
    up_cooldown_s: float = 5.0   # per-direction lockouts
    down_cooldown_s: float = 15.0
    stale_after_s: float = 3.0   # heartbeat age beyond which gauges
    #                              are dead data, not load signal
    replace_after_s: float = 1.0  # breaker-open / stale persistence
    #                               before a replica is declared dead
    flap_threshold: int = 3      # ready↔dead cycles → quarantine
    flap_window_s: float = 60.0
    recover_fraction: float = 0.5  # calm needs p99 ≤ target × this
    drain_deadline_s: float = 30.0  # scale-down drain budget
    spawn_timeout_s: float = 120.0  # spawn-to-ready ceiling


def fresh_compile_count(replica_or_registry):
    """``compile_seconds{source="fresh"}`` total observations for a
    replica's own registry (``replica.engine._reg``) or a registry
    passed directly. None when unmeasurable (no registry / no
    histogram yet) — the gate can only assert what it can see."""
    reg = replica_or_registry
    if not isinstance(reg, _metrics.MetricsRegistry):
        reg = getattr(getattr(replica_or_registry, "engine", None),
                      "_reg", None)
    if reg is None:
        return None
    hist = reg.get("compile_seconds")
    if hist is None:
        return None
    return sum(int(s.get("count") or 0)
               for s in hist.to_doc().get("series", [])
               if (s.get("labels") or {}).get("source") == "fresh")


class _Spawn:
    """One in-flight spawn: worker thread fills, tick reaps."""

    __slots__ = ("seq", "purpose", "seat", "started", "duration",
                 "replica", "error", "flap", "done", "thread", "pool")

    def __init__(self, seq, purpose, seat, started, pool=None):
        self.seq = seq
        self.purpose = purpose      # "up" | "replace"
        self.seat = seat
        self.started = started
        self.pool = pool            # target pool role (or None)
        self.duration = None
        self.replica = None
        self.error = None
        self.flap = False
        self.done = False
        self.thread = None


class _Retire:
    """One in-flight retirement (drain + handoff on a worker)."""

    __slots__ = ("idx", "name", "started", "error", "done", "thread",
                 "code")

    def __init__(self, idx, name, started):
        self.idx = idx
        self.name = name
        self.started = started
        self.error = None
        self.code = None
        self.done = False
        self.thread = None


class Autoscaler:
    """Supervisor for the replica population behind ``router``.

    ``spawn`` is a zero-arg callable returning a READY-ish replica
    (an object with ``submit``/``health``/``queue_depth`` — a
    :class:`~singa_tpu.serving.fleet.ServingReplica`, or any
    duck-typed stand-in); it may block for the full spin-up (the
    supervisor runs it on a worker thread unless ``sync=True``). The
    warm-admission gate then probes one token and asserts zero fresh
    compiles before :meth:`FleetRouter.add_replica`.

    Injectables (all optional) keep tier-1 tests deterministic:
    ``clock`` (monotonic seconds), ``observe(now) -> {name: obs}``
    replacing the built-in gauge reader, ``retire(idx, replica,
    deadline)`` replacing drain+handoff retirement, ``destroy
    (replica)`` for corpse disposal, ``fresh_compiles(replica)`` for
    the warm gate, and ``faults`` (a
    :class:`~singa_tpu.resilience.faults.FaultPlan` — ``slow_spawn``,
    ``flapping_replica`` and ``stale_heartbeat`` inject here)."""

    def __init__(self, router, spawn, *, targets=None, registry=None,
                 clock=None, interval=1.0, observe=None, retire=None,
                 destroy=None, fresh_compiles=None, require_warm=True,
                 probe_prompt=(1, 2, 3), probe_timeout=60.0,
                 faults=None, sync=False):
        import time as _time
        self.router = router
        self.targets = targets if targets is not None \
            else AutoscaleTargets()
        self.interval = float(interval)
        self.require_warm = bool(require_warm)
        self.probe_prompt = list(probe_prompt)
        self.probe_timeout = float(probe_timeout)
        self.sync = bool(sync)
        self._spawn_fn = spawn
        self._observe_fn = observe
        self._retire_fn = retire
        self._destroy_fn = destroy
        self._fresh_fn = fresh_compiles if fresh_compiles is not None \
            else fresh_compile_count
        self._faults = faults if faults is not None else NULL_PLAN
        self._clock = clock if clock is not None else _time.monotonic
        self._tick_lock = threading.Lock()
        self._lock = threading.Lock()   # pending/duration bookkeeping
        self._pending = []              # [_Spawn]
        self._retiring = []             # [_Retire]
        self._spawn_seq = 0
        self._obs_seq = 0
        self._seats = {}                # seat id -> {deaths, quarantined}
        self._seat_by_name = {}         # replica name -> seat id
        self._next_seat = 0
        self._suspect_since = {}        # name -> first suspect time
        self._breach_since = None
        self._calm_since = None
        self._last_up = -math.inf
        self._last_down = -math.inf
        self._spawn_durations = deque(maxlen=16)
        self._ttft_prev = {}            # name -> last histogram series
        self._tpot_prev = {}            # ... for serve_token_seconds
        self.last_load = None           # newest _load verdict (status)
        self._running = False
        self._stop_evt = threading.Event()
        self._thread = None

        reg = registry if registry is not None else router._reg
        self._reg = reg
        self._c_up = reg.counter(
            "autoscale_up_total",
            "scale-up decisions (spawn initiated after a sustained "
            "SLO breach)")
        self._c_down = reg.counter(
            "autoscale_down_total",
            "scale-down decisions (drain+handoff retirement of the "
            "least-loaded replica)")
        self._c_replace = reg.counter(
            "autoscale_replace_total",
            "replacement decisions (dead/stale/breaker-open replica "
            "respawned into its seat)")
        self._c_quarantine = reg.counter(
            "autoscale_quarantine_total",
            "seats quarantined by flap damping (ready<->dead cycled "
            "past the threshold; NOT respawned)")
        self._c_warm_refused = reg.counter(
            "autoscale_warm_refused_total",
            "spawned replicas the warm-admission gate refused "
            "(compiled fresh instead of loading AOT artifacts)")
        self._c_spawn_failed = reg.counter(
            "autoscale_spawn_failed_total",
            "spawns that errored or timed out before admission")
        self._g_pop = reg.gauge(
            "autoscale_population", "live replicas behind the router")
        self._g_pending = reg.gauge(
            "autoscale_pending_spawns", "spawns in flight")
        self._g_rung = reg.gauge(
            "autoscale_rung",
            "degradation ladder rung: 0=healthy 1=shed/brownout "
            "absorbing a breach 2=scale-up in flight")
        self._g_quarantined = reg.gauge(
            "autoscale_quarantined", "seats parked by flap damping")
        self._h_spawn = reg.histogram(
            "autoscale_spawn_seconds",
            "spawn-to-warm-admission durations (the Retry-After "
            "median's source)")
        self._g_pop.set(router.population())
        self._g_pending.set(0)
        self._g_rung.set(RUNG_HEALTHY)
        self._g_quarantined.set(0)

    # -- supervisor loop ---------------------------------------------------
    def start(self):
        """Run :meth:`tick` every ``interval`` s on a daemon thread."""
        self._running = True
        self._stop_evt.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="autoscaler", daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while self._running:
            try:
                self.tick()
            except Exception as e:   # noqa: BLE001 — supervisor must
                warnings.warn(       # outlive a bad tick
                    f"autoscaler tick failed: {type(e).__name__}: {e}",
                    stacklevel=2)
            self._stop_evt.wait(self.interval)

    def stop(self):
        self._running = False
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- the decision tick -------------------------------------------------
    def tick(self, now=None):
        """One supervision pass. Returns a decision summary dict
        (``population``, ``pending``, ``rung``, ``breach``, ``calm``,
        ``actions`` — a list of human-readable decision strings)."""
        with self._tick_lock:
            now = self._clock() if now is None else float(now)
            actions = []
            self._reap_spawns(now, actions)
            self._reap_retires(actions)
            self._obs_seq += 1
            obs = self._observations(now)
            self.observations = obs
            self._scan_replacements(now, obs, actions)
            load = self._load(obs, now)
            self.last_load = load
            self._update_windows(now, load)
            self._maybe_scale_up(now, load, actions)
            self._maybe_scale_down(now, obs, load, actions)
            self._enforce_floor(now, actions)
            pop = self.router.population()
            pending = sum(1 for s in self._pending if not s.done)
            rung = (RUNG_SPAWN if pending else
                    RUNG_SHED if load["breach"] else RUNG_HEALTHY)
            self._g_pop.set(pop)
            self._g_pending.set(pending)
            self._g_rung.set(rung)
            self._g_quarantined.set(self.quarantined_count())
            return {"now": now, "population": pop, "pending": pending,
                    "rung": rung, "breach": load["breach"],
                    "calm": load["calm"],
                    "grow_pool": load.get("grow_pool"),
                    "actions": actions}

    # -- observations ------------------------------------------------------
    def _observations(self, now):
        if self._observe_fn is not None:
            obs = dict(self._observe_fn(now) or {})
        else:
            obs = self._fleet_observations()
        t = self.targets
        for name, o in obs.items():
            age = o.get("age_s")
            if age is not None and age > t.stale_after_s:
                o["stale"] = True
            if self._faults.on_observe(self._obs_seq, name):
                o["stale"] = True
                if o.get("age_s") is None:
                    o["age_s"] = math.inf
            o.setdefault("stale", False)
        return obs

    def _fleet_observations(self):
        """Per-replica load/health snapshot straight off the gauges
        that already exist: health doc, router queue depth, breaker
        state, windowed TTFT p99 (delta of ``serve_ttft_seconds``
        between ticks — a lifetime histogram never forgets a breach),
        paged-KV pool pressure."""
        breakers = self.router.breaker_states()
        obs = {}
        for idx, r in self.router.live_replicas():
            name = self.router._name(idx)
            try:
                doc = r.health() if hasattr(r, "health") else {}
                status = doc.get("status", "serving")
            except Exception:   # noqa: BLE001 — unreachable = dead
                status = "crashed"
            reg = getattr(getattr(r, "engine", None), "_reg", None)
            depth = self.router._depth(r)
            role_fn = getattr(self.router, "_role", None)
            obs[name] = {
                "idx": idx,
                "status": status,
                "ready": status == "serving",
                "queue_depth": None if depth == math.inf else depth,
                "breaker": breakers.get(name),
                "ttft_p99_s": self._windowed_ttft_p99(name, reg),
                "tpot_p99_s": self._windowed_tpot_p99(name, reg),
                "pool_pressure": self._pool_pressure(reg),
                "pool_role": role_fn(idx) if role_fn is not None
                else "colocated",
                "age_s": None,
            }
        return obs

    def _windowed_ttft_p99(self, name, reg):
        return self._windowed_p99(name, reg, "serve_ttft_seconds",
                                  self._ttft_prev)

    def _windowed_tpot_p99(self, name, reg):
        return self._windowed_p99(name, reg, "serve_token_seconds",
                                  self._tpot_prev)

    def _windowed_p99(self, name, reg, metric, prev_map):
        hist = reg.get(metric) if reg is not None else None
        if not isinstance(hist, _metrics.Histogram):
            return None
        series = hist.to_doc().get("series") or []
        if not series:
            return None
        s = series[0]
        prev = prev_map.get(name)
        prev_map[name] = s
        if not s["count"]:
            return None
        if prev is not None:
            if s["count"] == prev["count"]:
                return None     # no traffic this window: no signal
            if s["count"] > prev["count"]:
                from ..observability.export import series_quantiles
                delta = {
                    "count": s["count"] - prev["count"],
                    "min": None, "max": s.get("max"),
                    "buckets": [[le, c - pc] for (le, c), (_, pc)
                                in zip(s["buckets"],
                                       prev["buckets"])],
                }
                return series_quantiles(delta)["p99"]
        return (s.get("quantiles") or {}).get("p99")

    @staticmethod
    def _pool_pressure(reg):
        if reg is None:
            return None
        total = reg.get("kv_blocks_total")
        in_use = reg.get("kv_blocks_in_use")
        if not isinstance(total, _metrics.Gauge) \
                or not isinstance(in_use, _metrics.Gauge):
            return None
        cap = total.value()
        return None if not cap else float(in_use.value()) / float(cap)

    # -- load evaluation ---------------------------------------------------
    def _load(self, obs, now=None):
        """Fleet-level breach/calm verdicts over READY, NON-STALE
        replicas only — the staleness satellite's contract: never
        scale on dead data. With role-tagged replicas (disaggregated
        prefill/decode pools) the breach also learns a per-pool
        verdict (``grow_pool``): a TTFT breach means prefill is the
        bottleneck (prompts queueing for their first token), a
        TPOT breach or sustained decode-pool transfer pressure means
        decode is — the spawn that answers the breach lands in the
        pool that is actually short."""
        t = self.targets
        live = [o for o in obs.values()
                if o.get("ready") and not o.get("stale")]
        ttfts = [o["ttft_p99_s"] for o in live
                 if o.get("ttft_p99_s") is not None]
        depths = [o["queue_depth"] for o in live
                  if o.get("queue_depth") is not None]
        pools = [o["pool_pressure"] for o in live
                 if o.get("pool_pressure") is not None]
        ttft = max(ttfts) if ttfts else None
        depth = (sum(depths) / len(depths)) if depths else None
        pool = max(pools) if pools else None
        roles = {o.get("pool_role") for o in live}
        pooled = bool(roles & {"prefill", "decode"})
        tpot = None
        xfer_pressed = False
        if pooled:
            tpots = [o["tpot_p99_s"] for o in live
                     if o.get("tpot_p99_s") is not None]
            tpot = max(tpots) if tpots else None
            pp = getattr(self.router, "_pool_pressure", None)
            if pp is not None and now is not None:
                xfer_pressed = pp.sustained(now)
        breach = bool(live) and (
            (ttft is not None and ttft > t.ttft_p99_s)
            or (depth is not None and depth > t.queue_high)
            or (pool is not None and pool > t.pool_high)
            or (tpot is not None and tpot > t.tpot_p99_s)
            or xfer_pressed)
        calm = bool(live) and not breach and (
            (ttft is None or ttft <= t.ttft_p99_s * t.recover_fraction)
            and (depth is None or depth <= t.queue_low)
            and (pool is None or pool <= t.pool_low)
            and (tpot is None
                 or tpot <= t.tpot_p99_s * t.recover_fraction))
        grow = None
        if pooled and breach:
            if (tpot is not None and tpot > t.tpot_p99_s) \
                    or (pool is not None and pool > t.pool_high) \
                    or xfer_pressed:
                # decode-side evidence wins: slow tokens, a pressed
                # KV pool, or transfers bouncing off the decode pool
                grow = "decode"
            elif ttft is not None and ttft > t.ttft_p99_s:
                grow = "prefill"
            else:
                # queue breach only: blame the pool whose replicas
                # actually hold the depth
                by_role = {}
                for o in live:
                    d = o.get("queue_depth")
                    if d is not None:
                        by_role.setdefault(o.get("pool_role"),
                                           []).append(d)
                means = {r: sum(v) / len(v)
                         for r, v in by_role.items()
                         if r in ("prefill", "decode")}
                grow = max(means, key=means.get) if means \
                    else "decode"
        return {"ttft_p99_s": ttft, "tpot_p99_s": tpot,
                "queue_depth_mean": depth,
                "pool_pressure": pool, "breach": breach, "calm": calm,
                "grow_pool": grow, "ready": len(live)}

    def _update_windows(self, now, load):
        if load["breach"]:
            if self._breach_since is None:
                self._breach_since = now
            self._calm_since = None
        elif load["calm"]:
            if self._calm_since is None:
                self._calm_since = now
            self._breach_since = None
        else:
            self._breach_since = None
            self._calm_since = None

    def _effective_up_window(self):
        """The ladder: scale-up never fires before the ShedPolicy has
        had its full window to absorb the spike — brownout → shed →
        spawn, in that order."""
        w = self.targets.up_window_s
        shed = getattr(self.router, "shed_policy", None)
        if shed is not None:
            w = max(w, float(getattr(shed, "window_s", 0.0)))
        return w

    # -- lifecycle: spawn --------------------------------------------------
    def _initiate_spawn(self, now, purpose, seat, actions, reason,
                        pool=None):
        self._spawn_seq += 1
        rec = _Spawn(self._spawn_seq, purpose, seat, now, pool=pool)
        self._pending.append(rec)
        tag = f"[{purpose}:{pool}]" if pool else f"[{purpose}]"
        actions.append(f"spawn{tag} #{rec.seq}: {reason}")
        _spans.event("autoscale.spawn", purpose=purpose, seq=rec.seq,
                     pool=pool, reason=reason)
        if self.sync:
            self._spawn_worker(rec)
            self._reap_spawns(now, actions)     # admit this tick
        else:
            rec.thread = threading.Thread(
                target=self._spawn_worker, args=(rec,),
                name=f"autoscale-spawn-{rec.seq}", daemon=True)
            rec.thread.start()

    @staticmethod
    def _spawn_accepts_pool(fn):
        import inspect
        try:
            params = inspect.signature(fn).parameters
        except (TypeError, ValueError):
            return False
        return "pool_role" in params or any(
            p.kind == p.VAR_KEYWORD for p in params.values())

    def _spawn_worker(self, rec):
        t0 = self._clock()
        try:
            rec.flap = bool(self._faults.on_spawn(rec.seq))
            # per-pool verdict rides into the spawn when the factory
            # can honor it (pool-agnostic factories stay untouched)
            if rec.pool and self._spawn_accepts_pool(self._spawn_fn):
                replica = self._spawn_fn(pool_role=rec.pool)
            else:
                replica = self._spawn_fn()
            self._await_ready(replica)
            self._warm_admission(replica)
            rec.duration = self._clock() - t0
            rec.replica = replica
        except BaseException as e:      # noqa: BLE001 — reaped typed
            rec.error = e
        rec.done = True

    def _await_ready(self, replica):
        """Poll ``health()`` until the replica reports ``serving``
        (bounded by ``spawn_timeout_s``). In sync mode one check —
        in-process replicas are ready the moment ``spawn`` returns."""
        import time as _time
        if not hasattr(replica, "health"):
            return
        deadline = _time.monotonic() + self.targets.spawn_timeout_s
        while True:
            try:
                status = replica.health().get("status")
            except Exception as e:      # noqa: BLE001
                status = f"unreachable: {e}"
            if status == "serving":
                return
            if self.sync or _time.monotonic() >= deadline:
                raise SpawnFailed(
                    f"spawned replica never became ready "
                    f"(last status: {status})")
            _time.sleep(0.05)

    def _warm_admission(self, replica):
        """The gate: one probe token end to end, then assert zero
        fresh compiles. Admission order matters — the probe forces
        prefill+decode through the compile path, so the count AFTER
        it is the honest one."""
        fut = replica.submit(list(self.probe_prompt),
                             max_new_tokens=1, temperature=0.0,
                             timeout=self.probe_timeout)
        fut.result(timeout=self.probe_timeout)
        fresh = self._fresh_fn(replica)
        if self.require_warm and fresh:
            raise WarmAdmissionRefused(
                f"replica compiled {fresh} program(s) fresh during "
                f"warm admission; prebuild AOT artifacts "
                f"(tools/aot_cache.py prebuild) so spawns land warm")

    def _reap_spawns(self, now, actions):
        for rec in [r for r in self._pending if r.done]:
            self._pending.remove(rec)
            if rec.error is not None:
                self._c_spawn_failed.inc()
                if isinstance(rec.error, WarmAdmissionRefused):
                    self._c_warm_refused.inc()
                actions.append(
                    f"spawn #{rec.seq} failed: "
                    f"{type(rec.error).__name__}: {rec.error}")
                _spans.event("autoscale.spawn_failed", seq=rec.seq,
                             error=type(rec.error).__name__)
                continue
            idx = self.router.add_replica(rec.replica)
            name = self.router._name(idx)
            seat = rec.seat if rec.seat is not None \
                else self._new_seat()
            self._seat_by_name[name] = seat
            dur = rec.duration if rec.duration is not None \
                else now - rec.started
            with self._lock:
                self._spawn_durations.append(dur)
            self._h_spawn.observe(dur)
            actions.append(f"admitted {name} (slot {idx}, "
                           f"{dur:.3f}s spawn-to-ready)")
            _spans.event("autoscale.admitted", replica=name,
                         slot=idx, purpose=rec.purpose,
                         spawn_s=round(dur, 4))
            if rec.flap:    # flapping_replica fault: the fresh
                self._doom(rec.replica)   # replica dies right away

    def _new_seat(self):
        seat = self._next_seat
        self._next_seat += 1
        self._seats[seat] = {"deaths": deque(), "quarantined": False}
        return seat

    def _doom(self, replica):
        eng = getattr(replica, "engine", replica)
        crash = getattr(eng, "_crash", None)
        if crash is None:
            crash = getattr(replica, "kill", None)
        if crash is None:
            return
        try:
            crash(SimulatedCrash(
                "flapping_replica: injected post-admission crash"))
        except TypeError:
            try:
                crash()
            except Exception:   # noqa: BLE001 — best-effort corpse
                pass
        except Exception:       # noqa: BLE001
            pass

    # -- lifecycle: replacement + flap damping -----------------------------
    def _scan_replacements(self, now, obs, actions):
        t = self.targets
        for name, o in list(obs.items()):
            idx = o.get("idx")
            if idx is None or self.router.replicas[idx] is None:
                continue
            if any(rt.idx == idx and not rt.done
                   for rt in self._retiring):
                continue        # scale-down owns this one
            crashed = o.get("status") == "crashed"
            suspect = crashed or o.get("stale") \
                or o.get("breaker") == BREAKER_OPEN
            if not suspect:
                self._suspect_since.pop(name, None)
                continue
            since = self._suspect_since.setdefault(name, now)
            if not crashed and now - since < t.replace_after_s:
                continue        # hysteresis: one stale beat ≠ dead
            self._suspect_since.pop(name, None)
            self._replace_dead(now, idx, name, o, actions)

    def _replace_dead(self, now, idx, name, o, actions):
        corpse = self.router.remove_replica(idx)
        self._destroy(corpse)
        self._ttft_prev.pop(name, None)
        self._tpot_prev.pop(name, None)
        seat_id = self._seat_by_name.pop(name, None)
        if seat_id is None:
            seat_id = self._new_seat()
        seat = self._seats[seat_id]
        deaths = seat["deaths"]
        deaths.append(now)
        while deaths and now - deaths[0] > self.targets.flap_window_s:
            deaths.popleft()
        cause = ("crashed" if o.get("status") == "crashed"
                 else "stale_heartbeat" if o.get("stale")
                 else "breaker_open")
        if len(deaths) >= self.targets.flap_threshold \
                and not seat["quarantined"]:
            seat["quarantined"] = True
            self._c_quarantine.inc()
            actions.append(
                f"quarantined seat {seat_id} ({name}): "
                f"{len(deaths)} ready<->dead cycles inside "
                f"{self.targets.flap_window_s:.0f}s")
            _spans.event("autoscale.quarantine", replica=name,
                         seat=seat_id, cycles=len(deaths),
                         cause=cause)
            return
        if seat["quarantined"]:
            return              # already parked: never respawn
        if self.router.population() + len(self._pending) \
                >= self.targets.max_replicas:
            actions.append(f"replace {name} deferred: at max "
                           f"population")
            return
        self._c_replace.inc()
        _spans.event("autoscale.replace", replica=name,
                     seat=seat_id, cause=cause)
        # a dead pool replica respawns into the SAME pool: replacing
        # a decode replica with a colocated one would silently shrink
        # the pool the fleet is already short on
        role = o.get("pool_role")
        self._initiate_spawn(now, "replace", seat_id, actions,
                             f"{name} {cause}",
                             pool=role if role in ("prefill",
                                                   "decode")
                             else None)

    def _destroy(self, replica):
        if replica is None:
            return
        if self._destroy_fn is not None:
            try:
                self._destroy_fn(replica)
            except Exception:   # noqa: BLE001 — corpse disposal
                pass
            return
        eng = getattr(replica, "engine", replica)
        try:
            eng.stop()
        except Exception:       # noqa: BLE001
            pass

    # -- lifecycle: scale up/down ------------------------------------------
    def _maybe_scale_up(self, now, load, actions):
        t = self.targets
        if self._breach_since is None:
            return
        if now - self._breach_since < self._effective_up_window():
            return              # the shed rung is still absorbing it
        if now - self._last_up < t.up_cooldown_s:
            return
        if self._pending or self.router.population() \
                + len(self._pending) >= t.max_replicas:
            return
        self._last_up = now
        self._c_up.inc()
        self._initiate_spawn(
            now, "up", None, actions,
            f"breach sustained {now - self._breach_since:.1f}s "
            f"(ttft_p99={load['ttft_p99_s']}, "
            f"tpot_p99={load.get('tpot_p99_s')}, "
            f"queue={load['queue_depth_mean']}, "
            f"pool={load['pool_pressure']})",
            pool=load.get("grow_pool"))

    def _maybe_scale_down(self, now, obs, load, actions):
        t = self.targets
        if self._calm_since is None \
                or now - self._calm_since < t.down_window_s:
            return
        if now - self._last_down < t.down_cooldown_s:
            return
        if self._pending or any(not r.done for r in self._retiring):
            return              # one lifecycle mutation at a time
        if self.router.population() <= t.min_replicas:
            return
        victim = None           # least-loaded ready replica
        for name, o in obs.items():
            if not o.get("ready") or o.get("stale"):
                continue
            idx = o.get("idx")
            if idx is None or self.router.replicas[idx] is None:
                continue
            depth = o.get("queue_depth")
            depth = math.inf if depth is None else depth
            if victim is None or depth < victim[0]:
                victim = (depth, idx, name)
        if victim is None:
            return
        _depth, idx, name = victim
        self._last_down = now
        self._c_down.inc()
        actions.append(f"retire {name} (slot {idx}): calm "
                       f"{now - self._calm_since:.1f}s")
        _spans.event("autoscale.retire", replica=name, slot=idx)
        rec = _Retire(idx, name, now)
        self._retiring.append(rec)
        if self.sync:
            self._retire_worker(rec)
        else:
            rec.thread = threading.Thread(
                target=self._retire_worker, args=(rec,),
                name=f"autoscale-retire-{name}", daemon=True)
            rec.thread.start()

    def _retire_worker(self, rec):
        try:
            if self._retire_fn is not None:
                rec.code = self._retire_fn(
                    rec.idx, self.router.replicas[rec.idx],
                    self.targets.drain_deadline_s)
            else:
                # PR-17 path: deadline drain with live-KV handoff to
                # the survivors — zero dropped in-flight responses
                rec.code = self.router.drain_replica(
                    rec.idx, timeout=self.targets.drain_deadline_s,
                    handoff=True)
        except BaseException as e:      # noqa: BLE001 — reaped typed
            rec.error = e
        self.router.remove_replica(rec.idx)
        rec.done = True

    def _reap_retires(self, actions):
        for rec in [r for r in self._retiring if r.done]:
            self._retiring.remove(rec)
            self._seat_by_name.pop(rec.name, None)
            self._ttft_prev.pop(rec.name, None)
            self._tpot_prev.pop(rec.name, None)
            if rec.error is not None:
                actions.append(
                    f"retire {rec.name} errored: "
                    f"{type(rec.error).__name__}: {rec.error}")
            else:
                clean = rec.code in (EXIT_DRAINED, None, True)
                actions.append(f"retired {rec.name} "
                               f"({'clean' if clean else 'dirty'} "
                               f"drain)")

    def _enforce_floor(self, now, actions):
        """Population floor = min_replicas minus quarantined seats:
        quarantine beats the min bound (that IS flap damping), but a
        fleet that merely started small or lost spawns is topped up."""
        floor = max(0, self.targets.min_replicas
                    - self.quarantined_count())
        missing = floor - self.router.population() \
            - len(self._pending)
        for _ in range(missing):
            self._initiate_spawn(now, "up", None, actions,
                                 "below population floor")

    # -- introspection -----------------------------------------------------
    def quarantined_count(self):
        return sum(1 for s in self._seats.values()
                   if s["quarantined"])

    def retry_after_hint(self):
        """Seconds until capacity plausibly lands: the rolling median
        of recent spawn-to-ready durations minus the oldest pending
        spawn's elapsed time (floor 1s). None when no spawn is in
        flight or no history exists — the gateway then falls back to
        its constant. This is the satellite contract: a 503 during a
        scale-up carries an *observed* Retry-After."""
        with self._lock:
            durs = sorted(self._spawn_durations)
        pending = [s for s in self._pending if not s.done]
        if not pending or not durs:
            return None
        median = durs[len(durs) // 2]
        elapsed = self._clock() - min(s.started for s in pending)
        return max(1.0, median - elapsed)

    def spawn_stats(self):
        """{count, p50_s, p99_s} over the recorded spawn-to-ready
        durations (the chaos drill banks these)."""
        doc = self._h_spawn.to_doc().get("series") or []
        if not doc:
            return {"count": 0, "p50_s": None, "p99_s": None}
        q = doc[0].get("quantiles") or {}
        return {"count": doc[0]["count"], "p50_s": q.get("p50"),
                "p99_s": q.get("p99")}

    def status(self):
        """One introspection doc (the example's AUTOSCALE log line
        and chaos assertions read this)."""
        load = self.last_load or {}
        return {
            "population": self.router.population(),
            "pending_spawns": sum(1 for s in self._pending
                                  if not s.done),
            "retiring": sum(1 for r in self._retiring if not r.done),
            "quarantined_seats": self.quarantined_count(),
            "rung": int(self._g_rung.value()),
            "grow_pool": load.get("grow_pool"),
            "tpot_p99_s": load.get("tpot_p99_s"),
            "spawn": self.spawn_stats(),
            "targets": asdict(self.targets),
        }


__all__ = ["Autoscaler", "AutoscaleTargets", "SpawnFailed",
           "WarmAdmissionRefused", "fresh_compile_count",
           "RUNG_HEALTHY", "RUNG_SHED", "RUNG_SPAWN"]
