"""Continuous-batching inference engines: prefill/decode split, slot
array, exactly-once delivery.

Two engines share one control plane (:class:`_EngineBase`: submit /
background loop / drain / fault handling / SLO metrics / crash
blackbox):

- :class:`ServingEngine` — autoregressive models (transformer LM,
  char-rnn). TWO fixed-shape compiled programs per model:

  * **prefill**: ``(P, cache, tokens (B_p, S_pad), lengths, slots,
    valid) -> (cache, logits (B_p, V))`` — a fixed-width batch of
    padded prompts writes the DONATED ring KV cache rows of its
    assigned slots and returns last-token logits. ``valid`` masks
    padding rows, so admitting 1 or B_p requests runs the same
    executable.
  * **decode**: ``(P, cache, tokens (W,), positions (W,), active (W,))
    -> (cache, logits (W, V))`` — ONE token for every slot in O(1):
    write the new k/v at ``pos % max_len``, attend over the ring,
    return logits. The slot array has fixed width ``W``; finished
    sequences free their slot mid-batch and new requests refill it via
    the ``active`` validity mask (the ``pad_last`` mask idiom from
    data.py), so the program NEVER retraces —
    ``compiled_step_info()["n_traces"]`` is pinned at 1 by CI exactly
    like the train step's retrace guard.

  Sampling happens host-side per slot through the shared
  :mod:`singa_tpu.models.decode` helper, which is what lets
  per-request temperature/top_k/seed vary without touching the
  compiled program.

  ``mesh=`` / ``model_shards=N`` runs BOTH programs GSPMD-sharded
  over a named (batch × model) mesh (``parallel/gspmd.py``): params
  and KV state are annotated with NamedSharding (heads/MLP hidden/
  vocab over 'model', slots over 'batch'), the SAME pure bodies are
  jitted once, and XLA inserts every collective — no hand-written
  psum anywhere on the serve path. The sharded programs compute the
  greedy argmax IN GRAPH over the vocab-sharded logits (the full
  (rows, V) array never exists on any device or the host), so
  sampled requests are a typed submit-time rejection. Every engine
  invariant survives sharding: one trace per program, whole-state
  donation, typed declines for configs the mesh cannot honor.

  ``kv_layout="paged"`` swaps the ring for the paged BLOCK POOL
  (:mod:`.kv_cache`): memory scales with live tokens, identical
  prompt prefixes share refcounted blocks (a prefix-cache hit skips
  prefill for the shared span), and pool exhaustion is a typed
  admission refusal — never an eviction of a live sequence.
  ``speculative_k=K`` (paged only) turns the decode program into a
  K-token VERIFY program: a host-side n-gram proposer drafts K-1
  tokens, one tick scores all of them, and the greedy accept/reject
  walk emits up to K tokens with token-for-token identity to
  sequential greedy decoding (CI-pinned). Both are still the same
  two-fixed-shape-program contract; ineligible configurations decline
  LOUDLY (warning + ring/plain decode), never silently.

- :class:`BatchServingEngine` — stateless models (the CNN/MLP zoo and
  ONNX imports through ``sonnx.SONNXModel``): each tick gathers up to
  ``W`` queued requests, pads the batch to the fixed width, runs ONE
  jitted forward (state threaded functionally, policy scope entered
  inside the trace), and delivers per-row results. Same queue, same
  exactly-once futures, same drain.

Fault handling reuses :class:`~singa_tpu.resilience.faults.FaultPlan`:
``faults.on_step(tick)`` fires BEFORE any tick mutates engine state, so
an injected transient fault is retried with nothing lost and nothing
doubled (chaos-tested). Retries beyond ``max_retries`` crash the loop:
a flight-recorder blackbox (``telemetry/blackbox-serve.jsonl``) is
dumped and every pending future is failed — once each.
"""

from __future__ import annotations

import os
import threading
import time
import warnings
from collections import deque

import numpy as np

from .. import integrity as _integrity
from ..observability import metrics as _metrics
from ..observability import perf as _perf
from ..observability import spans as _spans
from ..resilience.faults import NULL_PLAN, FaultInjected
from ..models import decode as _decode
from .scheduler import (BlockPoolExhausted, EngineDraining,
                        HandoffRefused, QueueFull, ReplicaCrashed,
                        Request, RequestQueue, RequestTimeout,
                        ServingError, budget_remaining, deadline_in)

# donation is a TPU/accelerator optimisation; on CPU jax warns that the
# donated buffers were unused — expected for OUR two programs, not
# actionable. The suppression is scoped to our own dispatches (warnings
# filters are process-global; a module-level ignore would hide genuine
# donation regressions in the embedding application's unrelated jits).
# The lock keeps concurrent engines from clobbering each other's
# catch_warnings save/restore; dispatch returns before execution, so
# the hold time is microseconds.
_WARN_LOCK = threading.Lock()


def _quiet_donation(fn, *args):
    with _WARN_LOCK, warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        return fn(*args)


# KV level arrays in their ONE canonical serialization order: every
# snapshot/spill frame packs present keys in this order, so the bytes
# on both sides of a handoff agree by construction.
_LEVEL_KEYS = ("k", "v", "k_scale", "v_scale")


def _pack_arrays(arrays):
    """``(specs, payload)`` for a list of host arrays: per-array
    dtype/shape specs (frame metadata) plus one concatenated byte
    blob (frame payload). The inverse of :func:`_unpack_arrays`."""
    specs, chunks = [], []
    for arr in arrays:
        arr = np.ascontiguousarray(arr)
        specs.append({"dtype": str(arr.dtype),
                      "shape": [int(d) for d in arr.shape]})
        chunks.append(arr.tobytes())
    return specs, b"".join(chunks)


def _unpack_arrays(specs, payload):
    """Rebuild the packed arrays from a CRC-verified frame. Length
    mismatches raise IntegrityError: the CRC vouched for the bytes,
    so a mismatch against the specs is a protocol bug — still typed,
    still never written into a pool. ``jnp.dtype`` resolves extended
    dtypes (bfloat16, fp8) that plain numpy refuses by name."""
    import jax.numpy as jnp
    payload = bytes(payload)
    out, off = [], 0
    for spec in specs:
        dt = jnp.dtype(str(spec["dtype"]))
        shape = tuple(int(d) for d in spec["shape"])
        n = int(dt.itemsize) * int(np.prod(shape, dtype=np.int64))
        chunk = payload[off:off + n]
        if len(chunk) != n:
            raise _integrity.IntegrityError(
                f"frame payload truncated: array {spec} needs {n}B, "
                f"{len(chunk)}B left")
        out.append(np.frombuffer(chunk, dtype=dt).reshape(shape))
        off += n
    if off != len(payload):
        raise _integrity.IntegrityError(
            f"frame payload has {len(payload) - off} trailing bytes")
    return out


def _cache_counts():
    """Persistent-compile-cache counter snapshot before a dispatch
    that may trace (labels the compile source cache-vs-fresh)."""
    from ..aot import cache as _aot_cache
    return _aot_cache.snapshot()


def _attribute_trace(rec, registry, program, arrays, names, t0,
                     cache_counts0=None):
    """Compile/retrace attribution for ONE serve-program dispatch that
    traced (caller checks the ``n_traces`` delta): wall-clock into
    ``compile_seconds{program, source}``, signature (diffed against
    this program's previous trace) into a compile/retrace event — a
    decode retrace is the broken no-retrace contract, and the event
    names what changed. ``cache_counts0`` (a persistent-compile-cache
    counter snapshot taken before the dispatch) labels the source
    cache-vs-fresh."""
    from ..aot import cache as _aot_cache
    sig = _perf.step_signature(arrays, names=names)
    source = _aot_cache.classify(cache_counts0) \
        if cache_counts0 is not None else "fresh"
    _perf.record_compile(program, time.perf_counter() - t0, sig,
                         prev_signature=rec.get("sig"),
                         source=source, registry=registry)
    rec["sig"] = sig


class _EngineBase:
    """Shared control plane: queue, loop thread, drain, faults, SLOs."""

    def __init__(self, *, queue_capacity=64, faults=None, registry=None,
                 telemetry_dir="telemetry", max_retries=3,
                 trace_requests=True, profile_every=0):
        self._reg = registry if registry is not None \
            else _metrics.default_registry()
        self.queue = RequestQueue(queue_capacity, registry=self._reg)
        self.faults = faults if faults is not None else NULL_PLAN
        self.telemetry_dir = telemetry_dir
        self.max_retries = int(max_retries)
        # per-request flight-recorder events (request.queued →
        # request.prefill → request.decode_tick... → request.delivered,
        # all carrying the request's trace id) — what the Perfetto
        # exporter reconstructs into one timeline lane per request.
        # Each event is a µs-scale dict append; trace_requests=False
        # turns them off for latency-critical deployments.
        self._trace_requests = bool(trace_requests)
        self._hbm_dev = None        # set by subclasses (HBM sampling)
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._idle_evt = threading.Event()
        self._thread = None
        self._running = False
        self._draining = False
        self._stopped = False
        self._crashed = None
        self._tick_count = 0
        # every-Nth-tick profiled decode tick (the trainer's
        # profile_every, serving-side): the tick runs under a profiler
        # trace through the ALREADY-compiled programs (n_traces pin
        # untouched), refreshing this registry's profile_fusion_* and
        # timeline_* gauges with site=serve. 0 disables; non-profiled
        # ticks pay one integer check.
        self._profile_every = int(profile_every or 0)
        self._profiling_now = False
        self._last_timeline = None
        self._retries = self._reg.counter(
            "serve_retries_total",
            "serve-loop ticks retried after an injected/transient fault")
        # submit sequence number: the key the fleet-level wire-error
        # fault fires on (send numbers, like the control plane's)
        self._submit_seq = 0
        # deadline drain: the handoff callable (set per-drain), the
        # absolute budget clock, and an EWMA of tick cost the handoff
        # pass uses to predict whether a request fits the budget
        self._handoff = None
        self._drain_deadline = None
        self._tick_ewma = 0.0
        self._handoff_seq = 0
        self._stranded = self._reg.counter(
            "serve_stranded_requests_total",
            "requests a serve-loop crash failed while admitted "
            "(queued or slotted) — each one is re-dispatchable by a "
            "fleet router with its remaining deadline budget")
        self._ttft = self._reg.histogram(
            "serve_ttft_seconds",
            "request submit to first generated token (queue wait "
            "included — this is what the caller feels)")
        self._tok_lat = self._reg.histogram(
            "serve_token_seconds",
            "per-token decode latency (one continuous-batching tick)")

    # -- admission ---------------------------------------------------------
    def _admit(self, req):
        # fleet fault point: the submit RPC dies on the wire before the
        # engine sees it (raises ConnectionError — what a router's
        # breaker must classify as a replica failure, not a request one)
        self._submit_seq += 1
        self.faults.on_submit(self._submit_seq)
        if self._crashed is not None:
            self.queue.finish("rejected")
            raise ReplicaCrashed(
                f"engine crashed ({self._crashed}); not accepting "
                "requests — see the blackbox dump")
        if self._draining or self._stopped:
            self.queue.finish("rejected")
            raise EngineDraining(
                "engine is draining/stopped; not accepting new requests")
        # the queued event lands BEFORE the put: the loop thread can
        # pop-and-prefill the instant the request is visible, and the
        # per-request timeline must stay causal (queued < prefill)
        if self._trace_requests:
            _spans.event("request.queued", request=req.trace_id,
                         queue_depth=len(self.queue))
        try:
            self.queue.put(req)
        except QueueFull:
            if self._trace_requests:
                _spans.event("request.rejected", request=req.trace_id,
                             reason="queue_full")
            raise
        self._wake.set()
        # fleet fault point: the replica dies the instant after it
        # admitted this request — the stranded-request shape a router's
        # exactly-once re-dispatch exists for (the future comes back
        # already failed with ReplicaCrashed)
        if self.faults.on_admit(req.id):
            self._crash(RuntimeError("injected crash after admit"))
        return req.future

    # -- background loop ---------------------------------------------------
    def start(self):
        """Run the serve loop on a daemon thread. Idempotent."""
        with self._lock:
            if self._thread is not None:
                return self
            self._running = True
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="serve-loop")
            self._thread.start()
        return self

    def _busy(self):
        raise NotImplementedError

    def _tick(self):
        raise NotImplementedError

    def _fail_inflight(self, error):
        raise NotImplementedError

    def _run_tick(self):
        """One scheduler tick, every Nth one profiled: the profiled
        tick runs THROUGH the compiled dispatch under a jax.profiler
        trace (``measure_step_fusions`` — no retrace, one trace dump)
        and refreshes ``profile_fusion_*`` plus the step-timeline
        decomposition (``timeline_*{site=serve}`` gauges, a
        ``timeline.sample`` event). The profiled tick's inflated
        per-token latency stays OUT of the SLO series (PR 9's
        trainer invariant, serving-side): its true cost lands in
        ``serve_profile_capture_seconds``."""
        if not (self._profile_every and self._tick_count > 0
                and self._tick_count % self._profile_every == 0):
            self._tick()
            return
        from .. import profiling as _profiling
        self._profiling_now = True
        t0 = time.perf_counter()
        events = []
        try:
            # a failure of the TICK itself propagates untouched (the
            # loop's crash path owns it, exactly like an unprofiled
            # tick); measure_step_fusions already degrades profiler
            # breakage to an empty table
            _, table = _profiling.measure_step_fusions(
                self._tick, events_out=events)
        finally:
            self._profiling_now = False
        capture_s = time.perf_counter() - t0
        try:
            self._record_profiled_tick(table, events, capture_s)
        except Exception as e:      # noqa: BLE001 — never a blocker
            # telemetry must not take the serve loop down (a metric
            # name/kind collision in a caller's registry would
            # otherwise crash the engine and fail every inflight
            # request over bookkeeping)
            warnings.warn(
                f"profiled-tick telemetry failed "
                f"({type(e).__name__}: {e})", stacklevel=2)

    def _record_profiled_tick(self, table, events, capture_s):
        from .. import profiling as _profiling
        from ..observability import timeline as _timeline
        self._reg.counter(
            "serve_profile_samples_total",
            "profiled serving ticks (every profile_every-th)").inc()
        self._reg.histogram(
            "serve_profile_capture_seconds",
            "wall-clock of one profiled serving tick (trace dump + "
            "parse included — the sampling overhead bound)").observe(
                capture_s)
        if table:
            _profiling.record_fusion_metrics(table, registry=self._reg)
        tl = _timeline.analyze(events)
        if tl is not None:
            _timeline.record_timeline(tl, registry=self._reg,
                                      site="serve")
            self._last_timeline = tl
            _spans.event("timeline.sample", site="serve",
                         tick=self._tick_count, lanes=tl["lanes"],
                         **_timeline.compact(tl))

    @property
    def last_timeline(self):
        """The newest profiled tick's step-timeline decomposition
        (None before the first sample) — what the gateway serves at
        ``GET /timeline.json``."""
        return self._last_timeline

    def _fail_batch(self, batch, exc):
        """Fail requests that were popped from the queue but died
        before reaching the slot table / delivery (exactly once).
        Typed ReplicaCrashed: a tick exception takes the whole loop
        down right after this, so these requests are stranded by a
        dying replica — re-dispatchable, not malformed."""
        err = ReplicaCrashed(f"serve tick failed: {exc}")
        err.__cause__ = exc
        for req in batch:
            if not req.future.done():
                req.future.set_error(err)
                self.queue.finish("failed")

    def _loop(self):
        consecutive = 0
        while self._running:
            if not self._busy():
                self._idle_evt.set()
                self._wake.wait(0.02)
                self._wake.clear()
                continue
            self._idle_evt.clear()
            try:
                # the fault hook fires BEFORE any state mutates, so a
                # retry replays the tick cleanly: nothing delivered
                # twice, nothing dropped
                self.faults.on_step(self._tick_count)
                self._run_tick()
                self._tick_count += 1
                consecutive = 0
            except FaultInjected as e:
                consecutive += 1
                self._retries.inc()
                if consecutive > self.max_retries:
                    self._crash(e)
                    return
            except Exception as e:          # noqa: BLE001 — crash path
                self._crash(e)
                return
        self._idle_evt.set()

    def _crash(self, exc):
        """Serve-loop death: blackbox dump, then fail every pending
        future exactly once."""
        self._crashed = exc
        self._running = False
        # no loop will ever process the queue again: refuse at the
        # door from this instant (exactly-once forbids futures that
        # never resolve)
        self._stopped = True
        try:
            path = os.path.join(self.telemetry_dir,
                                "blackbox-serve.jsonl")
            extra = {"tick": self._tick_count,
                     "error": f"{type(exc).__name__}: {exc}",
                     "queue_depth": len(self.queue)}
            # serve-side OOM post-mortem: where the HBM went
            hbm = _perf.hbm_stats(self._hbm_dev)
            if hbm:
                extra["hbm"] = hbm
            live = _perf.live_array_report()
            if live:
                extra["live_arrays"] = live
            _spans.recorder().dump(
                path, reason="serve_loop_crash", extra=extra,
                registry=self._reg)
            print(f"[serving] loop crashed ({type(exc).__name__}: "
                  f"{exc}); blackbox at {path}")
        except Exception:   # losing the blackbox must not mask the crash
            pass
        err = ReplicaCrashed(f"serve loop crashed: {exc}")
        err.__cause__ = exc
        # stranded-request capture: everything admitted (queued or
        # slotted) dies HERE with a re-dispatchable typed error — the
        # count is the fleet router's recovery workload
        stranded = self.queue.drain_pending(err)
        stranded += self._count_inflight()
        self._fail_inflight(err)
        if stranded:
            self._stranded.inc(stranded)
        self._idle_evt.set()

    def _count_inflight(self):
        """Requests currently holding a slot (subclass-specific)."""
        return 0

    def _sample_hbm(self):
        """HBM gauges on the serving tick cadence (every 16th tick —
        decode ticks can be sub-ms; a CPU run costs one probe ever)."""
        if self._tick_count % 16 == 0:
            _perf.record_hbm(self._hbm_dev, self._reg, site="serve")

    # -- AOT export (cold-start elimination) -------------------------------
    def export_aot(self, store=None):
        """Serialize this engine's compiled executables into an AOT
        store (the engine's own ``aot_store`` when none is given) so
        the next replica spin-up deserializes instead of tracing.
        Returns {program: manifest}."""
        from ..aot import export as _aot_export
        if getattr(self, "sharded", False):
            d = self._part.describe()
            raise ValueError(
                f"export_aot is not supported for sharded serving: "
                f"the compiled programs are bound to this mesh "
                f"(batch={d['batch']} × model={d['model']} over "
                f"{d['devices']} devices) and a deserialized "
                "NamedSharding executable cannot be verified against "
                "another host's topology — the persistent compile "
                "cache is the sharded warm-start path")
        if store is None:
            store = getattr(self, "_aot_store", None)
        if store is None:
            raise ValueError(
                "export_aot needs a store: pass one, or build the "
                "engine with aot_store=")
        if not isinstance(store, _aot_export.AotStore):
            store = _aot_export.AotStore(store, registry=self._reg)
        docs = _aot_export.export_serving(self, store)
        # keep the warm-restart audit truthful: a cold spin-up that
        # just exported must not keep reporting refused:missing on
        # /healthz and /aot.json (a program that WAS deserialized
        # stays "loaded" — exporting beside it changes nothing)
        if getattr(self, "_aot_store", None) is None:
            self._aot_store = store
        src = dict(getattr(self, "_aot_source", None) or {})
        for program in docs:
            if src.get(program) != "loaded":
                src[program] = "exported"
        self._aot_source = src
        return docs

    # -- synchronous stepping (tests, simple callers) ----------------------
    def step(self):
        """Run ONE scheduler tick inline (only valid without the
        background thread). Returns True when there was work."""
        if self._thread is not None:
            raise RuntimeError("step() is for synchronous use; the "
                               "background loop is running")
        if not self._busy():
            return False
        self.faults.on_step(self._tick_count)
        self._run_tick()
        self._tick_count += 1
        return True

    def run_until_idle(self, max_ticks=10_000):
        """Synchronously tick until no work remains (tests). Transient
        injected faults are retried like the background loop would."""
        ticks = 0
        consecutive = 0
        while self._busy():
            try:
                self.step()
                consecutive = 0
            except FaultInjected:
                consecutive += 1
                self._retries.inc()
                if consecutive > self.max_retries:
                    raise
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError("engine did not go idle "
                                   f"within {max_ticks} ticks")
        return ticks

    # -- drain / stop ------------------------------------------------------
    @property
    def draining(self):
        return self._draining

    def ttft_stats(self):
        """Caller-felt TTFT quantiles, ``{"count", "p50_s", "p99_s"}``.

        Reads the ``serve_ttft_seconds`` histogram (queue wait
        included — the number the SLO is written against); quantiles
        are None until at least one request has produced a first
        token. This is the supervisor-facing accessor: an autoscaler
        or dashboard should call this instead of digging through the
        registry snapshot."""
        h = self._ttft
        doc = h._series_doc(None, h._slot({}))
        q = doc.get("quantiles") or {}
        return {"count": int(doc.get("count", 0) or 0),
                "p50_s": q.get("p50"), "p99_s": q.get("p99")}

    def drain(self, timeout=60.0, handoff=None):
        """Graceful drain: refuse new requests, FINISH everything
        in flight and queued, return True once idle. The drainable-
        replica contract: a drained engine dropped nothing.

        ``handoff`` turns ``timeout`` from a wait into a BUDGET
        (preemption-deadline drain): each tick the engine migrates
        queued requests and any in-flight request that cannot finish
        inside the remaining budget through
        ``handoff(request, snapshot_or_None, budget_s) -> bool`` —
        True means a survivor took ownership of delivering the
        response; anything else fails the request typed
        (:class:`EngineDraining`, the fleet's recompute re-dispatch
        rung). Either way drain returns by the deadline with nothing
        unresolved left behind."""
        self._handoff = handoff
        self._drain_deadline = time.monotonic() + float(timeout)
        self._draining = True
        self._wake.set()
        if self._thread is None:
            # synchronous engines drain inline
            self.run_until_idle()
            return True
        deadline = self._drain_deadline
        while True:
            if self._crashed is not None:
                return False
            if not self._busy() and self._idle_evt.wait(0.05):
                if not self._busy():
                    return True
            now = time.monotonic()
            if now >= deadline:
                if handoff is None:
                    return not self._busy()
                # deadline drain: the handoff pass runs at tick
                # boundaries, and a tick already in flight (the first
                # decode compile, say) cannot be interrupted — so past
                # the deadline the budget is simply negative (the next
                # pass migrates EVERYTHING) and we give the loop a
                # bounded grace to reach that boundary rather than
                # abandoning work a survivor could continue
                if now >= deadline + getattr(self, "_drain_grace", 5.0):
                    return not self._busy()
            time.sleep(0.01)

    def stop(self):
        """Hard stop: end the loop; queued/in-flight requests are
        failed (use :meth:`drain` first for a graceful exit)."""
        self._stopped = True
        self._running = False
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self._crashed is None:
            err = EngineDraining("engine stopped")
            n = self.queue.drain_pending(err)
            self._fail_inflight(err)
            return n
        return 0


class ServingEngine(_EngineBase):
    """Continuous-batching autoregressive engine (module docstring)."""

    def __init__(self, adapter, *, slots=4, max_len=64, prefill_len=16,
                 prefill_batch=2, policy=None, aot_store=None,
                 kv_layout="ring", kv_block_size=16, kv_blocks=None,
                 speculative_k=0, mesh=None, model_shards=None,
                 spill_bytes=0, snapshot_every=0,
                 pool_role="colocated", **kw):
        super().__init__(**kw)
        import jax

        self.adapter = adapter
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.prefill_len = int(prefill_len)
        self.prefill_batch = max(1, min(int(prefill_batch), self.slots))
        if self.prefill_len > self.max_len:
            raise ValueError(
                f"prefill_len {self.prefill_len} exceeds the ring "
                f"length max_len {self.max_len}: prompt rows must fit "
                "the cache without wrapping over themselves")
        validate = getattr(adapter, "validate", None)
        if validate is not None:
            # model-side limits (e.g. the positional-embedding table)
            # fail HERE, typed, instead of crashing the first compiled
            # prefill with a shape error
            validate(prefill_len=self.prefill_len, max_len=self.max_len)
        self.policy = policy
        self._P = adapter.params()
        self._slots = [None] * self.slots        # host-side slot table
        # live-KV handoff state: validated snapshot injects waiting
        # for a free slot (+ paged blocks), cadence checkpoints a
        # crashed replica's router resumes from, and the drain pass's
        # wall-clock reserve for the final snapshot/transfer
        self._injects = deque()
        self.snapshot_every = int(snapshot_every or 0)
        self._kv_checkpoints = {}       # trace_id -> {"meta","frame"}
        self._drain_reserve = 0.25
        self._drain_grace = 5.0
        # disaggregated prefill/decode pools: the role tag is ROUTING
        # metadata (the fleet router reads it for pool placement and
        # arms a prefill engine's transfer callable); the engine stays
        # fully capable either way — a decode replica can recompute a
        # prompt from scratch and a prefill replica can decode to the
        # end (the colocate-fallback rung of the degradation ladder)
        pool_role = str(pool_role)
        if pool_role not in ("colocated", "prefill", "decode"):
            raise ValueError(
                f"pool_role must be 'colocated', 'prefill' or "
                f"'decode', got {pool_role!r}")
        self.pool_role = pool_role
        if pool_role != "colocated":
            # published so heartbeat_summary (registry-only view) can
            # report the replica's role: 1=prefill 2=decode
            self._reg.gauge(
                "serve_pool_role",
                "this replica's disaggregated-pool role: "
                "1=prefill 2=decode (absent/0 = colocated)").set(
                1 if pool_role == "prefill" else 2)
        self._transfer = None           # armed by FleetRouter
        self._transfer_seq = 0
        self._transfer_out = None
        self._colocated = None

        # -- GSPMD sharded serving (mesh=/model_shards=) ------------------
        # One NamedSharding partitioner over a named (batch × model)
        # mesh (parallel/gspmd.py): params/KV annotated, the SAME pure
        # programs jitted once, XLA inserts every collective. Configs
        # the mesh cannot honor are typed declines at build — never a
        # silently replicated "sharded" serve.
        self._part = None
        if mesh is not None or model_shards:
            from ..parallel import gspmd
            if not getattr(adapter, "supports_sharded", False):
                raise gspmd.ShardingDecline(
                    f"{type(adapter).__name__} has no sharded (GSPMD) "
                    "serve programs: its decode state cannot be "
                    "partitioned over a (batch × model) mesh — serve "
                    "this model single-device")
            part = gspmd.serving_partitioner(
                mesh=mesh, model_shards=model_shards,
                max_batch=self.slots)
            # the slot array (and the ring cache's W axis) shards over
            # 'batch': the decode program's rows must tile the axis
            # (auto-built meshes already fit it; an explicit mesh is
            # the caller's pin and refuses typed here)
            part.require_divisible("slots", self.slots,
                                   part.batch_axis)
            self._part = part
        self.sharded = self._part is not None

        # -- KV layout resolution (decline loudly, never silently) -------
        kv_layout = str(kv_layout)
        if kv_layout not in ("ring", "paged"):
            raise ValueError(
                f"kv_layout must be 'ring' or 'paged', got "
                f"{kv_layout!r}")
        self._kv_declined = None
        if kv_layout == "paged" and \
                not getattr(adapter, "supports_paged", False):
            warnings.warn(
                f"kv_layout='paged' declined: "
                f"{type(adapter).__name__} has no paged block-pool "
                "programs (its decode state is not per-position KV "
                "rows); serving on the ring layout instead",
                stacklevel=3)
            self._kv_declined = "adapter_unsupported"
            kv_layout = "ring"
        self.kv_layout = kv_layout
        # speculative_k = verify-program width: up to speculative_k
        # tokens emitted per tick (speculative_k - 1 of them drafted).
        # It needs the paged mask's position-exactness — a wrapped
        # ring re-attributes a rejected draft's stale row INTO the
        # sliding window (pos+1 wraps to pos-L+1), so the ring path
        # declines rather than risking silent corruption.
        spec = int(speculative_k or 0)
        self._spec_declined = None
        if spec > 1 and self.kv_layout != "paged":
            warnings.warn(
                "speculative_k declined: speculative decoding needs "
                "kv_layout='paged' (the ring's wraparound would "
                "re-attribute rejected-draft rows into the attention "
                "window); decoding one token per tick",
                stacklevel=3)
            self._spec_declined = "requires_paged_layout"
            spec = 0
        self._spec_width = max(1, spec)
        self.speculative_k = self._spec_width \
            if self._spec_width > 1 else 0
        # brownout knob: while set, no drafts are proposed (each tick
        # emits one token through the SAME compiled verify program —
        # rows padded to width 1, no retrace, greedy identity intact).
        # A fleet shed policy flips this before refusing outright.
        self._spec_throttled = False

        self._prefill_rec = {"n_traces": 0}
        self._decode_rec = {"n_traces": 0}
        prefill_rec, decode_rec = self._prefill_rec, self._decode_rec

        if self.kv_layout == "paged":
            from . import kv_cache as _kvc
            self.kv_block_size = int(kv_block_size)
            if self.kv_block_size < 1:
                raise ValueError(
                    f"kv_block_size must be >= 1, got {kv_block_size}")
            self._max_blocks = -(-self.max_len // self.kv_block_size)
            # default pool covers slots × max_len (no saving, full
            # safety); a smaller kv_blocks is where paged memory
            # elasticity lives — admission backpressure keeps it safe
            self.kv_blocks = int(kv_blocks) if kv_blocks \
                else self.slots * self._max_blocks
            if self.kv_blocks < 1:
                raise ValueError(
                    f"kv_blocks must be >= 1, got {kv_blocks}")
            self._mgr = _kvc.BlockManager(self.kv_blocks,
                                          self.kv_block_size)
            self._cache = adapter.init_pool(self.kv_blocks,
                                            self.kv_block_size)
            if self.sharded:
                # sharded programs return argmax TOKENS computed over
                # the vocab-sharded logits in graph — the full (R, V)
                # logits array is never gathered or output
                prefill_raw = adapter.greedy_paged_prefill_fn()
                decode_raw = adapter.greedy_paged_decode_fn()
            else:
                prefill_raw = adapter.paged_prefill_fn()
                decode_raw = adapter.paged_decode_fn()

            def prefill_body(P, pool, tables, tokens, starts, lengths,
                             valid):
                prefill_rec["n_traces"] += 1
                return prefill_raw(P, pool, tables, tokens, starts,
                                   lengths, valid)

            def decode_body(P, pool, tables, tokens, positions,
                            counts):
                # host-side trace counter, same contract as
                # Model._build_step: 1 forever (CI-pinned) — block
                # tables/draft rows vary per tick but their SHAPES are
                # fixed, so prefix hits and speculative ticks reuse
                # the one executable
                decode_rec["n_traces"] += 1
                return decode_raw(P, pool, tables, tokens, positions,
                                  counts)
        else:
            self._mgr = None
            self.kv_block_size = None
            self.kv_blocks = None
            self._cache = adapter.init_cache(self.slots, self.max_len)
            if self.sharded:
                prefill_raw = adapter.greedy_prefill_fn()
                decode_raw = adapter.greedy_decode_fn()
            else:
                prefill_raw = adapter.prefill_fn()
                decode_raw = adapter.decode_fn()

            def prefill_body(P, cache, tokens, lengths, slot_ids,
                             valid):
                prefill_rec["n_traces"] += 1
                return prefill_raw(P, cache, tokens, lengths, slot_ids,
                                   valid)

            def decode_body(P, cache, tokens, positions, active):
                # host-side trace counter, same contract as
                # Model._build_step: the serve path must keep this at 1
                decode_rec["n_traces"] += 1
                return decode_raw(P, cache, tokens, positions, active)

        jit_kw_prefill = {}
        jit_kw_decode = {}
        if self._part is not None:
            # annotate the named state + KV layout once, jit the same
            # pure bodies: XLA's SPMD partitioner inserts the
            # collectives (heads/MLP/vocab over 'model', slots over
            # 'batch'). Explicit out_shardings keep the donated cache's
            # layout identical in and out, so whole-state donation
            # survives sharding.
            pspecs, cspecs = adapter.sharding_specs(
                self._part, self._P, self._cache, self.kv_layout)
            self._P = self._part.shard(self._P, pspecs)
            self._cache = self._part.shard(self._cache, cspecs)
            from ..parallel import gspmd as _gspmd
            io = _gspmd.serving_arg_specs(self._part, self.kv_layout)
            p_sh = self._part.sharding_tree(pspecs)
            c_sh = self._part.sharding_tree(cspecs)
            tok_sh = self._part.sharding(io["tokens_out"])
            arg = self._part.sharding
            jit_kw_prefill = dict(
                in_shardings=(p_sh, c_sh,
                              *(arg(s) for s in io["prefill"])),
                out_shardings=(c_sh, tok_sh))
            jit_kw_decode = dict(
                in_shardings=(p_sh, c_sh,
                              *(arg(s) for s in io["decode"])),
                out_shardings=(c_sh, tok_sh))
        self._hbm_dev = _perf.first_jax_device(self._cache)
        # the KV state (ring cache or block pool) is DONATED: the one
        # large serving buffer is updated in place by XLA instead of
        # doubling per tick
        self._prefill = jax.jit(prefill_body, donate_argnums=(1,),
                                **jit_kw_prefill)
        self._decode = jax.jit(decode_body, donate_argnums=(1,),
                               **jit_kw_decode)
        # warm restart: deserialize previously exported prefill/decode
        # executables (honored-or-refused per artifact — a refused one
        # compiles fresh, loudly). The trace that produced a loaded
        # program happened in the EXPORTING process, so its n_traces
        # counter reads 1 and the no-retrace pin still holds.
        self._aot_store = None
        self._aot_source = None
        if aot_store is not None:
            if self.sharded:
                # a NamedSharding executable is topology-bound: the
                # manifest contract cannot vouch for it across hosts.
                # Refuse typed, naming the mesh — the persistent
                # compile cache is the sharded warm-start path.
                d = self._part.describe()
                warnings.warn(
                    f"aot_store declined: sharded serving programs "
                    f"(mesh batch={d['batch']} × model={d['model']}) "
                    "are not AOT-exportable; compiling fresh (the "
                    "persistent compile cache still warms them)",
                    stacklevel=3)
                reason = (f"refused:sharded_mesh_{d['batch']}x"
                          f"{d['model']}")
                self._aot_source = {"serve_prefill": reason,
                                    "serve_decode": reason}
            else:
                # ring AND paged manifests carry the layout geometry
                # (kv_block_size/kv_blocks/speculative_k), so both
                # round-trip; a layout mismatch refuses typed
                self._load_aot(aot_store)

        self._occupancy = self._reg.gauge(
            "serve_slot_occupancy", "active sequences in the slot array")
        self._reg.gauge("serve_slots",
                        "slot array width (max in-flight sequences)"
                        ).set(self.slots)
        self._tokens_total = self._reg.counter(
            "serve_tokens_total", "tokens generated")
        self._decode_steps = self._reg.counter(
            "serve_decode_steps_total", "continuous-batching decode "
            "ticks executed")
        self._prefills = self._reg.counter(
            "serve_prefill_total", "prompts prefilled into a slot")
        self._prefill_tok = self._reg.counter(
            "serve_prefill_tokens_total",
            "prompt tokens run through the prefill program (suffix "
            "only under paged prefix hits) — the recompute cost a KV "
            "handoff or spill restore avoids")
        self._handoff_out = self._reg.counter(
            "serve_handoff_out_total",
            "requests a deadline drain migrated to a survivor "
            "(snapshot or recompute handoff, accepted by the receiver)")
        self._handoff_in = self._reg.counter(
            "serve_handoff_in_total",
            "live KV snapshots this engine accepted for injection")
        self._handoff_refused = self._reg.counter(
            "serve_handoff_refused_total",
            "snapshot injects refused typed (CRC failure or geometry/"
            "policy mismatch) — corrupt KV is never written")
        self._handoff_fallback = self._reg.counter(
            "serve_handoff_fallback_total",
            "drain handoffs that fell back to recompute re-dispatch")
        self._ckpt_count = self._reg.counter(
            "serve_kv_checkpoint_total",
            "in-flight KV snapshots checkpointed on the "
            "snapshot_every cadence (crash re-dispatch resumes from "
            "the newest one instead of token zero)")
        if self.kv_layout == "paged":
            # pool-pressure gauges: what /metrics.json and the
            # heartbeat fleet view read to see a replica running out
            # of KV blocks before requests start backing up
            self._reg.gauge(
                "kv_blocks_total",
                "paged KV pool size in blocks").set(self.kv_blocks)
            self._blocks_in_use = self._reg.gauge(
                "kv_blocks_in_use",
                "pool blocks referenced by live sequences (never "
                "evicted)")
            self._blocks_cached = self._reg.gauge(
                "kv_blocks_cached",
                "unreferenced blocks held by the prefix cache "
                "(reclaimable, LRU)")
            self._prefix_hits = self._reg.counter(
                "prefix_cache_hits_total",
                "admitted prompts whose prefix matched cached blocks "
                "(prefill skipped for the shared span)")
            self._prefix_tokens = self._reg.counter(
                "prefix_cache_tokens_total",
                "prompt tokens served from cached prefix blocks "
                "instead of prefill compute")
            self._spec_proposed = self._reg.counter(
                "speculative_proposed_total",
                "draft tokens proposed to the verify program")
            self._spec_accepted = self._reg.counter(
                "speculative_accepted_total",
                "draft tokens accepted by the greedy verify rule")
            self._spec_ratio = self._reg.gauge(
                "speculative_accepted_ratio",
                "cumulative accepted/proposed draft-token ratio (the "
                "speculative speedup is roughly 1 + ratio × (k-1))")
        if self.sharded:
            # fleet-view honesty: the mesh shape plus what ONE chip
            # actually holds — heartbeat_summary's serving_kv block and
            # /healthz read these so pool-pressure numbers stay
            # per-device, not global, under sharding
            d = self._part.describe()
            self._reg.gauge(
                "serve_mesh_batch",
                "serving mesh 'batch' axis degree (slots shard over "
                "it)").set(d["batch"])
            self._reg.gauge(
                "serve_mesh_model",
                "serving mesh 'model' axis degree (heads/MLP/vocab "
                "shard over it)").set(d["model"])
            self._reg.gauge(
                "serve_kv_per_device_bytes",
                "KV state bytes ONE device holds (ring: slots/batch × "
                "heads/model slice; paged: whole pool × heads/model "
                "slice)").set(self._part.per_device_bytes(self._cache))
            self._reg.gauge(
                "serve_kv_global_bytes",
                "logical (unsharded) KV state bytes across the mesh"
            ).set(self._part.global_bytes(self._cache))

        # -- host-RAM spill tier (paged, single-device) -------------------
        self.spill_bytes = int(spill_bytes or 0)
        self._spill_tier = None
        self._spill_declined = None
        if self.spill_bytes > 0:
            if self.kv_layout != "paged":
                warnings.warn(
                    "spill_bytes declined: the host-RAM spill tier "
                    "parks evicted cached-prefix BLOCKS, which only "
                    "the paged layout has", stacklevel=3)
                self._spill_declined = "requires_paged_layout"
            elif self.sharded:
                warnings.warn(
                    "spill_bytes declined: a sharded pool's blocks "
                    "are sliced over the mesh ('model' axis) — a "
                    "host spill/restore would need per-device "
                    "gathers; serve single-device to spill",
                    stacklevel=3)
                self._spill_declined = "sharded"
            else:
                from . import kv_cache as _kvc_spill
                tier = _kvc_spill.HostSpillTier(self.spill_bytes)
                self._spill_tier = tier
                spill_c = self._reg.counter(
                    "serve_kv_spill_total",
                    "cached-prefix blocks spilled to the host-RAM "
                    "tier on pool eviction")
                restore_c = self._reg.counter(
                    "serve_kv_restore_total",
                    "prefix blocks restored from the host-RAM tier "
                    "instead of being re-prefilled")
                spill_g = self._reg.gauge(
                    "serve_kv_spill_bytes",
                    "bytes the host-RAM spill tier currently holds "
                    f"(budget {self.spill_bytes})")

                def _on_spill():
                    spill_c.inc()
                    spill_g.set(tier.bytes_used)

                def _on_restore():
                    restore_c.inc()
                    spill_g.set(tier.bytes_used)

                self._mgr.attach_spill(
                    tier, self._spill_block_read,
                    self._spill_block_write,
                    on_spill=_on_spill, on_restore=_on_restore)

    # -- AOT export / warm restart -----------------------------------------
    def _load_aot(self, store):
        from ..aot import export as _aot_export
        from ..observability import perf as _perf2
        if not isinstance(store, _aot_export.AotStore):
            # the engine's own registry: aot_loads_total and the
            # quarantine counter must land beside the engine's
            # compile_seconds, not in the default registry
            store = _aot_export.AotStore(store, registry=self._reg)
        self._aot_store = store
        prefill_avals, decode_avals = \
            _aot_export.serving_program_avals(self)
        geometry = _aot_export.serving_geometry(self)
        self._aot_source = {}
        for program, avals, rec, attr in (
                (_aot_export.SERVE_PREFILL, prefill_avals,
                 self._prefill_rec, "_prefill"),
                (_aot_export.SERVE_DECODE, decode_avals,
                 self._decode_rec, "_decode")):
            t0 = time.perf_counter()
            fn, _doc = store.try_load_program(
                program, avals=avals, donate_argnums=(1,),
                policy=self.policy, jax_device=self._hbm_dev,
                expect_extra=geometry)
            if fn is None:
                self._aot_source[program] = store.outcomes.get(
                    program, "fresh")
                continue
            setattr(self, attr, fn)
            rec["n_traces"] = 1
            sig = _perf2.step_signature(avals[2:])
            _perf2.record_compile(program,
                                  time.perf_counter() - t0, sig,
                                  source="aot", registry=self._reg)
            rec["sig"] = sig
            self._aot_source[program] = "loaded"

    # -- public API --------------------------------------------------------
    def submit(self, prompt, max_new_tokens=16, temperature=0.0,
               top_k=None, eos_id=None, seed=0, timeout=None,
               trace_id=None):
        """Queue one generation request; returns its
        :class:`~singa_tpu.serving.scheduler.ServeFuture` (``.result()``
        is ``{"tokens": [...], "prompt_len": n, "ttft_s": ...}``).
        Prompts longer than ``prefill_len`` are rejected here, typed
        and synchronous. ``trace_id`` names the request in the
        per-request flight-recorder trace (the gateway mints one per
        HTTP request); defaults to ``req-<n>``."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if int(max_new_tokens) < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1 (got {max_new_tokens}): "
                "the first token is sampled from the prefill logits, "
                "so every accepted request generates at least one")
        if prompt.size > self.prefill_len:
            self.queue.finish("rejected")
            raise ServingError(
                f"prompt of {prompt.size} tokens exceeds this engine's "
                f"prefill_len {self.prefill_len}")
        if self.sharded and (temperature != 0 or top_k):
            # the sharded programs argmax IN GRAPH over vocab-sharded
            # logits (nothing ever gathers the (rows, V) array), so
            # there are no host logits to sample from. Typed and
            # synchronous — never a silent fall-back to greedy.
            self.queue.finish("rejected")
            raise ServingError(
                f"sharded serving is greedy-only: temperature="
                f"{temperature}, top_k={top_k} would need the full "
                "vocab logits on the host, which the sharded decode "
                "program never materialises — submit with "
                "temperature=0, or serve this model unsharded")
        if self.kv_layout == "paged":
            total = int(prompt.size) + int(max_new_tokens)
            if total > self.max_len:
                self.queue.finish("rejected")
                raise ServingError(
                    f"prompt ({prompt.size}) + max_new_tokens "
                    f"({int(max_new_tokens)}) = {total} exceeds "
                    f"max_len {self.max_len}: the paged layout is "
                    "exact full attention within max_len (no logical "
                    "slot exists past it) — raise max_len, or use the "
                    "ring layout for sliding-window generation")
            if self._mgr.n_for(total) > self._mgr.n_blocks:
                self.queue.finish("rejected")
                raise BlockPoolExhausted(
                    f"request needs {self._mgr.n_for(total)} KV blocks "
                    f"but the whole pool is {self._mgr.n_blocks} "
                    f"(× {self.kv_block_size} tokens): it can NEVER "
                    "be admitted — raise kv_blocks or lower "
                    "max_new_tokens")
        req = Request(prompt, max_new_tokens=max_new_tokens,
                      temperature=temperature, top_k=top_k,
                      eos_id=eos_id, seed=seed, timeout=timeout,
                      trace_id=trace_id)
        return self._admit(req)

    def compiled_step_info(self):
        """Serve-path retrace audit (the train-step audit's sibling):
        the decode program's ``n_traces`` must be 1 across ANY refill
        pattern — that is the continuous-batching invariant CI pins."""
        info = {"n_traces": self._decode_rec["n_traces"],
                "prefill_n_traces": self._prefill_rec["n_traces"],
                "slots": self.slots, "max_len": self.max_len,
                "prefill_len": self.prefill_len,
                "prefill_batch": self.prefill_batch,
                "kv_layout": self.kv_layout,
                "speculative_k": self.speculative_k,
                "policy": self.policy.describe()
                if self.policy is not None else None,
                # warm-restart audit: per-program executable source
                # ("loaded" = deserialized AOT artifact, otherwise the
                # store's refusal outcome / "fresh"); None without a
                # store. The chaos warm-restart gate reads this off
                # /healthz.
                "aot": self._aot_source}
        if self._kv_declined:
            info["kv_layout_declined"] = self._kv_declined
        if self._spec_declined:
            info["speculative_declined"] = self._spec_declined
        if self.sharded:
            # /healthz honesty under sharding: the mesh shape and what
            # ONE device holds (not the global logical pool)
            info["mesh"] = self._part.describe()
            info["model_shards"] = self._part.model_shards
            info["kv_per_device_bytes"] = \
                self._part.per_device_bytes(self._cache)
            info["kv_global_bytes"] = \
                self._part.global_bytes(self._cache)
            if self.kv_layout == "ring":
                info["slots_per_device"] = \
                    self.slots // self._part.batch_shards
        if self.kv_layout == "paged":
            info.update(
                kv_block_size=self.kv_block_size,
                kv_blocks=self.kv_blocks,
                kv_blocks_in_use=self._mgr.blocks_live(),
                kv_blocks_cached=self._mgr.blocks_cached(),
                prefix_cache_entries=len(self._mgr._cache))
            if self._spill_tier is not None:
                info["spill"] = {
                    "budget_bytes": self._spill_tier.budget_bytes,
                    "bytes_used": self._spill_tier.bytes_used,
                    "entries": len(self._spill_tier),
                    "spilled_total": self._mgr.spilled_total,
                    "restored_total": self._mgr.restored_total}
        if self._spill_declined:
            info["spill_declined"] = self._spill_declined
        if self.snapshot_every:
            info["snapshot_every"] = self.snapshot_every
        return info

    def active_slots(self):
        return sum(1 for s in self._slots if s is not None)

    def throttle_speculation(self, on=True):
        """Brownout: suspend draft proposal (one token per tick through
        the unchanged compiled verify program) while ``on`` — less
        wasted verify compute under pressure, same greedy tokens.
        Idempotent; a fleet ``ShedPolicy`` brownout hook is the
        intended caller. Returns ``self``."""
        self._spec_throttled = bool(on)
        return self

    # -- disaggregated pools (prefill→decode transfer) ---------------------
    def set_transfer(self, cb):
        """Arm the prefill→decode transfer callable (a
        :class:`~singa_tpu.serving.fleet.FleetRouter` wiring its
        pools). ``cb(request, snapshot, resnap) -> bool``: True means a
        decode replica took ownership of delivering the response (the
        slot frees WITHOUT fulfilling the future — the router's relay
        owns it now); False/raise keeps the request here end-to-end
        (colocate fallback). ``resnap()`` re-extracts a FRESH sealed
        snapshot of the same slot — the retry-on-next-peer rung calls
        it so a frame corrupted at extraction is not re-delivered
        verbatim. ``None`` disarms. Returns ``self``."""
        self._transfer = cb
        if cb is not None:
            self._transfer_out = self._reg.counter(
                "serve_pool_transfer_out_total",
                "slots this prefill-role engine migrated to a decode "
                "replica right after prefill (KV transfer accepted)")
            self._colocated = self._reg.counter(
                "serve_pool_colocate_total",
                "requests this prefill-role engine kept end-to-end "
                "because no decode replica could take the transfer "
                "(the colocate-fallback rung)")
        return self

    def _transfer_pass(self):
        """Offer every active slot whose transfer has not been decided
        yet to the armed transfer callable (runs between prefill and
        decode in :meth:`_tick`, so an accepted slot never pays a
        local decode tick). A decline is sticky per request — the
        colocate fallback decodes it here to the end rather than
        re-negotiating every tick."""
        for i, slot in enumerate(list(self._slots)):
            if slot is None:
                continue
            req = slot["req"]
            if req.future.done() or getattr(req, "_xfer_declined",
                                            False):
                continue
            try:
                snap = self.snapshot_slot(i)
            except Exception:   # noqa: BLE001 — sharded/typed decline
                snap = None
            moved = False
            if snap is not None:
                def _resnap(idx=i):
                    return self.snapshot_slot(idx)
                try:
                    moved = bool(self._transfer(req, snap, _resnap))
                except Exception:   # noqa: BLE001 — colocate fallback
                    moved = False
            if moved:
                # mirror the drain pass's migrate-out: the slot frees
                # WITHOUT fulfilling the future (the router's relay
                # delivers the decode replica's response into it)
                self._slots[i] = None
                self._release_blocks(slot)
                self._kv_checkpoints.pop(req.trace_id, None)
                self._transfer_out.inc()
                self.queue.finish("migrated")
                if self._trace_requests:
                    _spans.event("request.transfer_out",
                                 request=req.trace_id,
                                 tokens=len(req.tokens))
            else:
                req._xfer_declined = True
                self._colocated.inc()
                if self._trace_requests:
                    _spans.event("request.colocate_fallback",
                                 request=req.trace_id)
        self._occupancy.set(self.active_slots())

    def transfer_deliveries(self, frame):
        """The transfer-path fault point: the list of frames ONE
        delivery attempt actually lands at the decode peer —
        ``[frame]`` clean, ``[]`` dropped in flight, ``[frame, frame]``
        duplicated (``faults.slow_transfer`` / ``drop_transfer`` /
        ``dup_transfer``). Sequence numbers count deliveries from 1
        per engine, like handoff extraction numbers."""
        self._transfer_seq += 1
        return self.faults.on_transfer_send(self._transfer_seq, frame)

    # -- live KV handoff (extract / inject / checkpoint) -------------------
    def _handoff_geometry(self):
        """What must match EXACTLY between two engines for a KV
        snapshot (or spilled block) to be bit-meaningful in the
        receiver's pool: layout, layer count, cache dtype +
        quantization, head geometry, position space, and the
        quantization policy. Rides every frame's CRC-covered meta."""
        level = self._cache[0]
        shape = tuple(int(d) for d in level["k"].shape)
        g = {"layout": self.kv_layout,
             "n_layers": len(self._cache),
             "dtype": str(level["k"].dtype),
             "quantized": "k_scale" in level,
             "heads": shape[1], "head_dim": shape[3],
             "max_len": int(self.max_len),
             "policy": self.policy.describe()
             if self.policy is not None else None}
        if self.kv_layout == "paged":
            g["block_size"] = int(self.kv_block_size)
        return g

    @staticmethod
    def _geometry_mismatch(got, want):
        """Canonical-JSON comparison (tuples/lists, key order, and
        int/float JSON round-trips must not create false mismatches)."""
        try:
            return _integrity.frame_meta({"g": got}) != \
                _integrity.frame_meta({"g": want})
        except (TypeError, ValueError):
            return True

    def _snapshot_slot(self, i):
        """Seal slot ``i``'s live state: generated tokens + sampling
        config in the frame meta, the slot's KV rows (ring) or blocks
        (paged block-table walk) as the payload. Pure read — the slot
        keeps running."""
        slot = self._slots[i]
        req = slot["req"]
        arrays = []
        if self.kv_layout == "paged":
            bids = np.asarray(slot["alloc"].blocks, np.int32)
            for level in self._cache:
                for name in _LEVEL_KEYS:
                    if name in level:
                        arrays.append(np.asarray(level[name][bids]))
        else:
            for level in self._cache:
                for name in _LEVEL_KEYS:
                    if name in level:
                        arrays.append(np.asarray(level[name][i]))
        specs, payload = _pack_arrays(arrays)
        doc = {"v": 1, "kind": "kv_snapshot",
               "geometry": self._handoff_geometry(),
               "prompt": [int(t) for t in req.prompt],
               "tokens": [int(t) for t in req.tokens],
               "pos": int(slot["pos"]), "tok": int(slot["tok"]),
               "max_new_tokens": int(req.max_new_tokens),
               "temperature": req.temperature, "top_k": req.top_k,
               "eos_id": req.eos_id, "trace_id": req.trace_id,
               # the request's OWN remaining deadline budget (None =
               # unlimited) — the survivor re-arms this clock, so a
               # migration never resets nor shortens a request's life
               "timeout_s": budget_remaining(req.deadline),
               "arrays": specs}
        meta = _integrity.frame_meta(doc)
        return {"meta": meta,
                "frame": _integrity.seal_frame(meta, payload)}

    def snapshot_slot(self, i):
        """Public extract: :meth:`_snapshot_slot` plus the fleet fault
        point (``corrupt_handoff`` / ``slow_handoff`` /
        ``kill_mid_handoff`` fire on the sealed frame here, exactly
        like wire sends). Sharded engines refuse typed — each device
        holds only a KV slice, so recompute re-dispatch is their
        failover path."""
        if self.sharded:
            raise HandoffRefused(
                "sharded engines cannot snapshot a slot: each device "
                "holds only its slice of the KV state — re-dispatch "
                "(recompute) is the sharded failover path")
        if self._slots[i] is None:
            raise ValueError(f"slot {i} is empty")
        snap = self._snapshot_slot(i)
        self._handoff_seq += 1
        frame = self.faults.on_handoff_send(self._handoff_seq,
                                            snap["frame"])
        return {"meta": snap["meta"], "frame": frame}

    def inject_snapshot(self, meta, frame, timeout=None):
        """Validate a sealed KV snapshot and queue it for injection;
        returns the continuation's ServeFuture (same result shape as
        :meth:`submit`). Validation is synchronous and REFUSES typed
        (:class:`HandoffRefused`, counted) on a CRC failure or any
        geometry/policy mismatch — corrupt or wrong-shape KV is never
        written into the pool. A validated snapshot waits for a free
        slot (and, paged, its block reservation) exactly like an
        admitted request; continuation after placement is bitwise
        identical to an uninterrupted greedy run."""
        if self._crashed is not None:
            raise ReplicaCrashed(
                f"engine crashed ({self._crashed}); not accepting "
                "snapshots")
        if self._draining or self._stopped:
            raise EngineDraining(
                "engine is draining/stopped; not accepting snapshots")
        if self.sharded:
            self._handoff_refused.inc()
            raise HandoffRefused(
                "sharded engines do not accept KV snapshots: the pool "
                "is sliced over the mesh")
        try:
            payload = _integrity.open_frame(meta, frame)
            doc = _integrity.parse_frame_meta(meta)
        except _integrity.IntegrityError as e:
            self._handoff_refused.inc()
            raise HandoffRefused(f"snapshot frame refused: {e}")
        if doc.get("kind") != "kv_snapshot":
            self._handoff_refused.inc()
            raise HandoffRefused(
                f"frame kind {doc.get('kind')!r} is not a KV snapshot")
        want = self._handoff_geometry()
        if self._geometry_mismatch(doc.get("geometry"), want):
            self._handoff_refused.inc()
            raise HandoffRefused(
                f"snapshot geometry {doc.get('geometry')} does not "
                f"match this engine's {want}")
        try:
            arrays = _unpack_arrays(doc["arrays"], payload)
            prompt = np.asarray(doc["prompt"], np.int32).reshape(-1)
            pos, tok = int(doc["pos"]), int(doc["tok"])
            max_new = int(doc["max_new_tokens"])
        except (_integrity.IntegrityError, KeyError, TypeError,
                ValueError) as e:
            self._handoff_refused.inc()
            raise HandoffRefused(f"snapshot refused: {e}")
        if self.kv_layout == "paged":
            total = int(prompt.size) + max_new
            if total > self.max_len or \
                    self._mgr.n_for(total) > self._mgr.n_blocks:
                self._handoff_refused.inc()
                raise HandoffRefused(
                    f"snapshot needs {total} token positions "
                    f"({self._mgr.n_for(total)} blocks) but this "
                    f"engine caps at max_len {self.max_len} / "
                    f"{self._mgr.n_blocks} blocks")
        # the request keeps ITS deadline (snapshot-carried remainder);
        # `timeout` bounds only how long the snapshot may wait for a
        # slot — a handoff budget must not shorten the request's life
        req = Request(prompt, max_new_tokens=max_new,
                      temperature=doc.get("temperature", 0.0),
                      top_k=doc.get("top_k"),
                      eos_id=doc.get("eos_id"),
                      timeout=doc.get("timeout_s"),
                      trace_id=doc.get("trace_id"))
        req.tokens = [int(t) for t in doc.get("tokens", [])]
        self._handoff_in.inc()
        done = (len(req.tokens) >= req.max_new_tokens or
                (req.eos_id is not None and req.tokens and
                 req.tokens[-1] == req.eos_id))
        if done:
            # the dying replica finished it between snapshot and send
            req.future.set_result({"tokens": list(req.tokens),
                                   "prompt_len": int(prompt.size),
                                   "ttft_s": None})
            self.queue.finish("completed")
            return req.future
        self._injects.append((req, {"pos": pos, "tok": tok}, arrays,
                              deadline_in(timeout)))
        self._wake.set()
        return req.future

    def _place_injects(self, now):
        """Move validated snapshots into free slots (paged: once their
        block reservation fits — BlockPoolExhausted is backpressure,
        the snapshot stays pending). The write path is host-side
        ``.at[].set`` on the cache arrays OUTSIDE the two compiled
        serve programs: no retrace, and the fresh buffers are donated
        on the next tick exactly like any other."""
        while self._injects:
            free = [i for i, s in enumerate(self._slots) if s is None]
            if not free:
                return
            req, state, arrays, place_by = self._injects[0]
            if req.expired(now) or \
                    (place_by is not None and now > place_by):
                self._injects.popleft()
                if not req.future.done():
                    req.future.set_error(RequestTimeout(
                        "deadline passed before the snapshot could "
                        "be placed"))
                    self.queue.finish("timed_out")
                continue
            alloc = None
            if self.kv_layout == "paged":
                try:
                    alloc = self._mgr.admit(
                        req.prompt,
                        int(req.prompt.size) + req.max_new_tokens)
                except BlockPoolExhausted:
                    return          # backpressure: retry next tick
            self._injects.popleft()
            try:
                self._write_snapshot(arrays, free[0], alloc)
            except Exception as e:  # noqa: BLE001 — typed refusal below
                if alloc is not None:
                    from . import kv_cache as _kvc_r
                    # never cache the partially-written blocks: a
                    # zero-prompt_blocks release frees them uncached
                    self._mgr.release(
                        _kvc_r.SlotAlloc(alloc.blocks,
                                         alloc.shared_tokens, 0),
                        req.prompt)
                    self._update_pool_gauges()
                self._handoff_refused.inc()
                if not req.future.done():
                    req.future.set_error(HandoffRefused(
                        f"snapshot write failed: {e}"))
                    self.queue.finish("failed")
                continue
            self._slots[free[0]] = {"req": req, "pos": state["pos"],
                                    "tok": state["tok"],
                                    "alloc": alloc}
            if self._trace_requests:
                _spans.event("request.injected",
                             request=req.trace_id, slot=free[0],
                             tokens=len(req.tokens))
            self._update_pool_gauges()

    def _write_snapshot(self, arrays, slot_idx, alloc):
        """Write a validated snapshot's rows into the pool. Paged
        allocations skip their already-correct leading blocks (prefix
        cache hits / spill restores cover the same positions with
        bitwise-identical content under greedy determinism)."""
        import jax.numpy as jnp
        if self.kv_layout == "paged":
            skip = alloc.shared_tokens // self.kv_block_size
            bids = jnp.asarray(alloc.blocks[skip:], jnp.int32)
        it = iter(arrays)
        new_cache = []
        for level in self._cache:
            upd = dict(level)
            for name in _LEVEL_KEYS:
                if name not in level:
                    continue
                arr = next(it)
                if self.kv_layout == "paged":
                    if arr.shape[0] != len(alloc.blocks) or \
                            tuple(arr.shape[1:]) != \
                            tuple(level[name].shape[1:]):
                        raise HandoffRefused(
                            f"snapshot array {name} shape "
                            f"{arr.shape} does not cover this "
                            f"allocation ({len(alloc.blocks)} blocks "
                            f"of {tuple(level[name].shape[1:])})")
                    sub = arr[skip:]
                    if len(sub):
                        upd[name] = level[name].at[bids].set(
                            jnp.asarray(sub))
                else:
                    if tuple(arr.shape) != \
                            tuple(level[name].shape[1:]):
                        raise HandoffRefused(
                            f"snapshot array {name} shape "
                            f"{arr.shape} does not match this ring's "
                            f"slot rows {level[name].shape[1:]}")
                    upd[name] = level[name].at[slot_idx].set(
                        jnp.asarray(arr))
            new_cache.append(upd)
        self._cache = new_cache

    def _checkpoint_inflight(self):
        """Cadence crash armor: snapshot every active slot to host
        memory, keyed by trace id. Best-effort — a checkpoint failure
        must never take the serve loop down."""
        if self.sharded:
            return
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            try:
                snap = self._snapshot_slot(i)
            except Exception:       # noqa: BLE001 — best-effort
                continue
            self._kv_checkpoints[slot["req"].trace_id] = snap
            self._ckpt_count.inc()

    def take_kv_checkpoint(self, trace_id):
        """Newest cadence checkpoint for ``trace_id`` (None when none
        exists). Host memory, so it survives a serve-loop crash — the
        fleet router's re-dispatch injects it into a survivor and
        resumes mid-stream instead of from token zero."""
        return self._kv_checkpoints.get(str(trace_id))

    # -- host-RAM spill tier plumbing (BlockManager's device access) -------
    def _spill_block_read(self, bid):
        """Pull ONE pool block's rows (every layer, payloads and
        scales) to host for the spill tier."""
        arrays = []
        for level in self._cache:
            for name in _LEVEL_KEYS:
                if name in level:
                    arrays.append(np.asarray(level[name][int(bid)]))
        specs, payload = _pack_arrays(arrays)
        doc = {"v": 1, "kind": "kv_block",
               "geometry": self._handoff_geometry(), "arrays": specs}
        return _integrity.frame_meta(doc), payload

    def _spill_block_write(self, bid, meta, payload):
        """Restore one spilled block's rows into pool block ``bid``.
        Raises on any mismatch — the BlockManager catches and degrades
        to re-prefilling the span, never writes a wrong block."""
        import jax.numpy as jnp
        doc = _integrity.parse_frame_meta(meta)
        if doc.get("kind") != "kv_block" or self._geometry_mismatch(
                doc.get("geometry"), self._handoff_geometry()):
            raise HandoffRefused(
                "spilled block does not match this engine's pool "
                "geometry")
        arrays = _unpack_arrays(doc.get("arrays", ()), payload)
        it = iter(arrays)
        new_cache = []
        for level in self._cache:
            upd = dict(level)
            for name in _LEVEL_KEYS:
                if name in level:
                    arr = next(it)
                    if tuple(arr.shape) != \
                            tuple(level[name].shape[1:]):
                        raise HandoffRefused(
                            f"spilled block array {name} shape "
                            f"{arr.shape} != {level[name].shape[1:]}")
                    upd[name] = level[name].at[int(bid)].set(
                        jnp.asarray(arr))
            new_cache.append(upd)
        self._cache = new_cache

    # -- deadline drain (handoff pass) -------------------------------------
    def _drain_handoff_pass(self, now):
        """Migrate what cannot finish inside the drain budget: queued
        requests outright (they would cost a full prefill + decode),
        and any active slot whose remaining tokens — at the EWMA tick
        cost, plus a snapshot/transfer reserve — overrun the budget.
        Requests that fit keep decoding here and finish normally."""
        budget = budget_remaining(self._drain_deadline, now)
        for req in self.queue.pop_batch(len(self.queue), now):
            self._handoff_request(req, None, budget)
        per_tick = max(self._tick_ewma, 1e-4)
        for i, slot in enumerate(list(self._slots)):
            if slot is None:
                continue
            budget = budget_remaining(self._drain_deadline)
            req = slot["req"]
            remaining = req.max_new_tokens - len(req.tokens)
            if budget is None or remaining * per_tick \
                    + self._drain_reserve <= budget:
                continue            # it fits: let it finish here
            snap = None
            try:
                snap = self.snapshot_slot(i)
            except Exception:       # noqa: BLE001 — recompute handoff
                snap = None
            self._slots[i] = None
            self._release_blocks(slot)
            self._handoff_request(req, snap, budget)
        self._occupancy.set(self.active_slots())

    def _handoff_request(self, req, snapshot, budget):
        """One rung of the fallback ladder: offer the request (with
        its snapshot when one exists) to the drain's handoff callable;
        a decline or error falls back to failing it typed with
        :class:`EngineDraining` — the fleet router's recompute
        re-dispatch picks it up with the remaining deadline budget."""
        ok = False
        try:
            ok = bool(self._handoff(req, snapshot, budget))
        except Exception:           # noqa: BLE001 — fallback below
            ok = False
        if ok:
            self._handoff_out.inc()
            self.queue.finish("migrated")
            if self._trace_requests:
                _spans.event("request.migrated",
                             request=req.trace_id,
                             snapshot=snapshot is not None,
                             tokens=len(req.tokens))
            return
        self._handoff_fallback.inc()
        if not req.future.done():
            req.future.set_error(EngineDraining(
                "drain deadline: request was not migrated in time — "
                "re-dispatch with the remaining budget"))
            self.queue.finish("failed")

    # -- loop internals ----------------------------------------------------
    def _busy(self):
        return len(self.queue) > 0 or len(self._injects) > 0 or any(
            s is not None for s in self._slots)

    def _release_blocks(self, slot):
        """Return a finished/failed paged sequence's block references
        to the manager (its full prompt blocks enter the prefix
        cache); no-op for ring slots."""
        alloc = slot.get("alloc")
        if alloc is not None and self._mgr is not None:
            self._mgr.release(alloc, slot["req"].prompt)
            self._update_pool_gauges()

    def _update_pool_gauges(self):
        if self._mgr is not None:
            self._blocks_in_use.set(self._mgr.blocks_live())
            self._blocks_cached.set(self._mgr.blocks_cached())

    def _count_inflight(self):
        return self.active_slots()

    def _fail_inflight(self, error):
        for i, slot in enumerate(self._slots):
            if slot is not None:
                self._slots[i] = None
                self._release_blocks(slot)
                if not slot["req"].future.done():
                    slot["req"].future.set_error(error)
                    self.queue.finish("failed")
        # validated-but-unplaced snapshot injects die here too —
        # exactly-once forbids futures that never resolve
        while self._injects:
            req, _state, _arrays, _by = self._injects.popleft()
            if not req.future.done():
                req.future.set_error(error)
                self.queue.finish("failed")
        self._occupancy.set(0)

    def _fail_batch(self, batch, exc):
        # popped-but-never-slotted paged requests carry their block
        # reservation on the request: give it back before failing them
        for req in batch:
            alloc = getattr(req, "_alloc", None)
            if alloc is not None and self._mgr is not None:
                self._mgr.release(alloc, req.prompt)
                req._alloc = None
        if self._mgr is not None:
            self._update_pool_gauges()
        super()._fail_batch(batch, exc)

    def _finish_slot(self, i, status="completed"):
        slot = self._slots[i]
        self._slots[i] = None
        self._release_blocks(slot)
        req = slot["req"]
        # a finished request's cadence checkpoint is dead weight
        self._kv_checkpoints.pop(req.trace_id, None)
        if self._trace_requests:
            _spans.event("request.delivered", request=req.trace_id,
                         status=status, tokens=len(req.tokens))
        if status == "completed":
            req.future.set_result({
                "tokens": list(req.tokens),
                "prompt_len": int(req.prompt.size),
                "ttft_s": (req.first_token_at - req.submitted_at
                           if req.first_token_at else None)})
        elif status == "timed_out":
            # same type a queued expiry raises: callers catch ONE
            # timeout error regardless of where the deadline hit
            req.future.set_error(RequestTimeout(
                f"deadline passed mid-generation after "
                f"{len(req.tokens)} tokens"))
        else:
            req.future.set_error(ServingError(status))
        self.queue.finish(status)

    def _sample_and_place(self, req, out_row, slot_idx, pos,
                          alloc=None):
        """Shared first-token/next-token bookkeeping: resolve the
        program output row into a token, record, finish or keep the
        slot hot. ``out_row`` is a logits vector on the single-device
        engines and an in-graph-argmax'd token id on the sharded ones
        — the ONE place that split is decided. ``alloc`` is the paged
        block reservation riding the slot."""
        tok = int(out_row) if self.sharded else _decode.sample_logits(
            out_row, temperature=req.temperature, top_k=req.top_k,
            rng=req.rng)
        req.tokens.append(tok)
        self._tokens_total.inc()
        done = (len(req.tokens) >= req.max_new_tokens or
                (req.eos_id is not None and tok == req.eos_id))
        self._slots[slot_idx] = {"req": req, "pos": pos, "tok": tok,
                                 "alloc": alloc}
        if done:
            self._finish_slot(slot_idx)

    def _tick(self):
        now = time.monotonic()
        tick_t0 = now
        # 0) deadline drain: migrate what the budget cannot cover;
        #    then place validated snapshot injects into free slots
        if self._draining and self._handoff is not None:
            self._drain_handoff_pass(now)
        if self._injects:
            self._place_injects(now)
        # 1) reap deadline-expired in-flight requests (their slot frees
        #    mid-batch — that is the continuous part of the batching)
        for i, slot in enumerate(self._slots):
            if slot is not None and slot["req"].expired(now):
                self._finish_slot(i, status="timed_out")

        # 2) admit: fill free slots, a fixed-width prefill batch per tick.
        #    A paged engine additionally gates each pop on the block
        #    pool: the admit predicate RESERVES the request's blocks
        #    (prefix-shared ones re-referenced) so a batch can never
        #    over-commit the pool; a request that doesn't fit right now
        #    stays at the head of the queue (backpressure, FIFO-fair —
        #    live sequences are never evicted to make room).
        free = [i for i, s in enumerate(self._slots) if s is None]
        if free and len(self.queue) > 0:
            admit = None
            if self.kv_layout == "paged":
                def admit(req):
                    try:
                        req._alloc = self._mgr.admit(
                            req.prompt,
                            int(req.prompt.size) + req.max_new_tokens)
                        return True
                    except BlockPoolExhausted:
                        return False
            batch = self.queue.pop_batch(
                min(len(free), self.prefill_batch), now, admit=admit)
            if batch:
                try:
                    with _spans.span("serve.prefill", n=len(batch)):
                        self._run_prefill(batch, free)
                except Exception as e:
                    # popped-but-not-yet-slotted requests are in
                    # neither the queue nor the slot table: the crash
                    # path can't see them, so fail them HERE or they
                    # hang forever (exactly-once applies to errors too)
                    self._fail_batch(batch, e)
                    raise

        # 2b) disaggregated pools: offer freshly-prefilled slots to the
        #     decode pool BEFORE paying a local decode tick (an
        #     accepted transfer frees the slot; a declined one decodes
        #     here — the colocate fallback)
        if self._transfer is not None:
            self._transfer_pass()

        # 3) decode: one token for EVERY active slot, one fixed program
        if any(s is not None for s in self._slots):
            t0 = time.perf_counter()
            with _spans.span("serve.decode"):
                self._run_decode()
            # a PROFILED tick's dispatch runs under an active trace:
            # its inflated latency must not read as an SLO regression
            # (the sampling cost is serve_profile_capture_seconds)
            if not self._profiling_now:
                self._tok_lat.observe(time.perf_counter() - t0)
            self._decode_steps.inc()
        self._occupancy.set(self.active_slots())
        self._sample_hbm()
        # 4) cadence crash armor + the drain pass's tick-cost EWMA
        if self.snapshot_every and \
                self._tick_count % self.snapshot_every == 0:
            self._checkpoint_inflight()
        dt = time.monotonic() - tick_t0
        self._tick_ewma = dt if not self._tick_ewma \
            else 0.8 * self._tick_ewma + 0.2 * dt

    def _run_prefill(self, batch, free):
        if self.kv_layout == "paged":
            return self._run_prefill_paged(batch, free)
        return self._run_prefill_ring(batch, free)

    def _run_prefill_ring(self, batch, free):
        B, S = self.prefill_batch, self.prefill_len
        tokens = np.zeros((B, S), np.int32)
        lengths = np.zeros((B,), np.int32)
        slot_ids = np.zeros((B,), np.int32)
        valid = np.zeros((B,), bool)
        placed = []
        for b, req in enumerate(batch):
            n = req.prompt.size
            tokens[b, :n] = req.prompt
            lengths[b] = n
            slot_ids[b] = free[b]
            valid[b] = True
            placed.append((req, free[b]))
            self._prefill_tok.inc(int(n))
        n0 = self._prefill_rec["n_traces"]
        t0c = time.perf_counter()
        cc0 = _cache_counts()
        self._cache, out = _quiet_donation(
            self._prefill, self._P, self._cache, tokens, lengths,
            slot_ids, valid)
        if self._prefill_rec["n_traces"] > n0:
            _attribute_trace(self._prefill_rec, self._reg,
                             "serve_prefill",
                             [tokens, lengths, slot_ids, valid],
                             ("tokens", "lengths", "slot_ids",
                              "valid"), t0c, cc0)
        # (B, V) logits single-device; (B,) in-graph argmax tokens when
        # sharded (the full-vocab array never reaches the host)
        out = np.asarray(out)
        for b, (req, slot_idx) in enumerate(placed):
            req.first_token_at = time.monotonic()
            self._ttft.observe(req.first_token_at - req.submitted_at)
            self._prefills.inc()
            if self._trace_requests:
                _spans.event("request.prefill", request=req.trace_id,
                             slot=slot_idx,
                             prompt_len=int(req.prompt.size))
            # the first generated token sits at position prompt_len;
            # its k/v are written by the NEXT decode tick
            self._sample_and_place(req, out[b], slot_idx,
                                   pos=int(req.prompt.size))

    def _run_prefill_paged(self, batch, free):
        """Paged admission: each popped request arrives with its block
        reservation already taken (the pop predicate); a prefix-cache
        hit enters the compiled program with ``start > 0`` and only
        its SUFFIX tokens — the shared span's prefill is skipped
        entirely, its K/V served from the refcounted cached blocks."""
        B, S = self.prefill_batch, self.prefill_len
        tokens = np.zeros((B, S), np.int32)
        starts = np.zeros((B,), np.int32)
        lengths = np.zeros((B,), np.int32)
        tables = np.zeros((B, self._max_blocks), np.int32)
        valid = np.zeros((B,), bool)
        placed = []
        for b, req in enumerate(batch):
            alloc = req._alloc
            suffix = req.prompt[alloc.shared_tokens:]
            tokens[b, :suffix.size] = suffix
            starts[b] = alloc.shared_tokens
            lengths[b] = suffix.size
            tables[b, :len(alloc.blocks)] = alloc.blocks
            valid[b] = True
            placed.append((req, free[b], alloc))
            self._prefill_tok.inc(int(suffix.size))
            if alloc.shared_tokens:
                self._prefix_hits.inc()
                self._prefix_tokens.inc(alloc.shared_tokens)
        n0 = self._prefill_rec["n_traces"]
        t0c = time.perf_counter()
        cc0 = _cache_counts()
        self._cache, out = _quiet_donation(
            self._prefill, self._P, self._cache, tables, tokens,
            starts, lengths, valid)
        if self._prefill_rec["n_traces"] > n0:
            _attribute_trace(self._prefill_rec, self._reg,
                             "serve_prefill",
                             [tables, tokens, starts, lengths, valid],
                             ("tables", "tokens", "starts", "lengths",
                              "valid"), t0c, cc0)
        out = np.asarray(out)      # (B, V) logits, or (B,) sharded toks
        self._update_pool_gauges()
        for b, (req, slot_idx, alloc) in enumerate(placed):
            req._alloc = None      # the slot owns the reservation now
            req.first_token_at = time.monotonic()
            self._ttft.observe(req.first_token_at - req.submitted_at)
            self._prefills.inc()
            if self._trace_requests:
                _spans.event("request.prefill", request=req.trace_id,
                             slot=slot_idx,
                             prompt_len=int(req.prompt.size),
                             prefix_hit_tokens=int(alloc.shared_tokens))
            # the first generated token sits at position prompt_len;
            # its k/v are written by the NEXT decode tick
            self._sample_and_place(req, out[b], slot_idx,
                                   pos=int(req.prompt.size),
                                   alloc=alloc)

    def _run_decode(self):
        if self.kv_layout == "paged":
            return self._run_decode_paged()
        return self._run_decode_ring()

    def _run_decode_paged(self):
        """One verify tick: every active slot's row is its pending
        token plus up to ``speculative_k - 1`` n-gram drafts; the ONE
        compiled program writes all rows' k/v and scores every
        position, and the host accept/reject walk emits the longest
        prefix of drafts matching greedy — each emitted token is
        EXACTLY what sequential greedy would have produced (the CI
        parity invariant). Rejected drafts leave stale rows at
        positions past the new ``pos``; the position-exact paged mask
        keeps them unreachable until overwritten."""
        W, K = self.slots, self._spec_width
        tokens = np.zeros((W, K), np.int32)
        positions = np.zeros((W,), np.int32)
        counts = np.zeros((W,), np.int32)
        tables = np.zeros((W, self._max_blocks), np.int32)
        rows = {}
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            req = slot["req"]
            n = 1
            if K > 1 and req.temperature == 0 \
                    and not self._spec_throttled:
                # greedy-only: the accept rule below is exact for
                # argmax; a sampled request decodes one token per tick
                # (its per-request rng draw order must not change)
                remaining = req.max_new_tokens - len(req.tokens)
                room = self.max_len - slot["pos"]
                n = max(1, min(K, remaining, room))
            row = [slot["tok"]]
            if n > 1:
                row += _decode.ngram_propose(
                    list(req.prompt) + req.tokens, n - 1)
                self._spec_proposed.inc(n - 1)
            tokens[i, :len(row)] = row
            positions[i] = slot["pos"]
            counts[i] = len(row)
            tables[i, :len(slot["alloc"].blocks)] = \
                slot["alloc"].blocks
            rows[i] = row
        n0 = self._decode_rec["n_traces"]
        t0c = time.perf_counter()
        cc0 = _cache_counts()
        self._cache, out = _quiet_donation(
            self._decode, self._P, self._cache, tables, tokens,
            positions, counts)
        if self._decode_rec["n_traces"] > n0:
            _attribute_trace(self._decode_rec, self._reg,
                             "serve_decode",
                             [tables, tokens, positions, counts],
                             ("tables", "tokens", "positions",
                              "counts"), t0c, cc0)
        # (W, K, V) logits single-device; (W, K) in-graph argmax tokens
        # when sharded — the accept walk below only ever needs argmax
        out = np.asarray(out)
        for i, slot in enumerate(list(self._slots)):
            if slot is None:
                continue
            req, row, cnt = slot["req"], rows[i], int(counts[i])
            emitted = 0
            done = False
            for j in range(cnt):
                tok = int(out[i, j]) if self.sharded else \
                    _decode.sample_logits(
                        out[i, j], temperature=req.temperature,
                        top_k=req.top_k, rng=req.rng)
                req.tokens.append(tok)
                self._tokens_total.inc()
                emitted += 1
                done = (len(req.tokens) >= req.max_new_tokens or
                        (req.eos_id is not None and tok == req.eos_id))
                if done:
                    break
                if j + 1 < cnt and row[j + 1] == tok:
                    continue        # draft accepted: its k/v row is
                break               # already correct; score the next
            if cnt > 1:
                self._spec_accepted.inc(emitted - 1)
                proposed = self._spec_proposed.total()
                if proposed:
                    self._spec_ratio.set(
                        self._spec_accepted.total() / proposed)
            n_tok = len(req.tokens)
            if self._trace_requests and \
                    (n_tok < 16 or n_tok % 16 < emitted):
                _spans.event("request.decode_tick",
                             request=req.trace_id, slot=i,
                             pos=slot["pos"] + emitted,
                             emitted=emitted)
            self._slots[i] = {"req": req, "pos": slot["pos"] + emitted,
                              "tok": req.tokens[-1],
                              "alloc": slot["alloc"]}
            if done:
                self._finish_slot(i)

    def _run_decode_ring(self):
        W = self.slots
        tokens = np.zeros((W,), np.int32)
        positions = np.zeros((W,), np.int32)
        active = np.zeros((W,), bool)
        for i, slot in enumerate(self._slots):
            if slot is not None:
                tokens[i] = slot["tok"]
                positions[i] = slot["pos"]
                active[i] = True
        n0 = self._decode_rec["n_traces"]
        t0c = time.perf_counter()
        cc0 = _cache_counts()
        self._cache, out = _quiet_donation(
            self._decode, self._P, self._cache, tokens, positions,
            active)
        if self._decode_rec["n_traces"] > n0:
            _attribute_trace(self._decode_rec, self._reg,
                             "serve_decode",
                             [tokens, positions, active],
                             ("tokens", "positions", "active"), t0c,
                             cc0)
        out = np.asarray(out)      # (W, V) logits, or (W,) sharded toks
        for i, slot in enumerate(list(self._slots)):
            if slot is None:
                continue
            # decimated past the first 16 tokens: a 4-slot engine
            # generating hundreds of tokens per request would otherwise
            # evict the whole flight-recorder ring (capacity 1024) with
            # ticks, beheading every request lane and crash blackbox
            n_tok = len(slot["req"].tokens)
            if self._trace_requests and \
                    (n_tok < 16 or n_tok % 16 == 0):
                _spans.event("request.decode_tick",
                             request=slot["req"].trace_id, slot=i,
                             pos=slot["pos"] + 1)
            self._sample_and_place(slot["req"], out[i], i,
                                   pos=slot["pos"] + 1)


class BatchServingEngine(_EngineBase):
    """Stateless (non-autoregressive) serving: classifier zoo models
    and ONNX imports. One jitted fixed-width forward per tick over a
    padded batch of queued requests (module docstring)."""

    def __init__(self, model, *, input_shape, batch=8,
                 input_dtype=np.float32, policy=None, aot_store=None,
                 **kw):
        super().__init__(**kw)
        import jax
        from ..autograd_base import CTX
        from ..tensor import Tensor
        from .. import mixed_precision as mp
        from ..device import get_default_device

        self.model = model
        self.batch = int(batch)
        self.input_shape = tuple(int(d) for d in input_shape)
        self.input_dtype = np.dtype(input_dtype)
        self.policy = policy if policy is not None \
            else getattr(model, "_policy", None)
        dev = getattr(model, "dev", None) or get_default_device()

        # materialise lazily-initialised params with ONE eager eval
        # forward (ONNX imports already hold theirs; zoo models may not)
        x0 = Tensor(
            data=np.zeros((self.batch,) + self.input_shape,
                          self.input_dtype),
            device=dev, requires_grad=False)
        from ..quant.core import dequant_params_scope
        prev = CTX.training
        CTX.training = False
        try:
            with mp.policy_scope(self.policy), \
                    dequant_params_scope(model):
                model.forward(x0)
        finally:
            CTX.training = prev
        state_list = model._state_tensors()
        self._state_arrays = [t.data for t in state_list]
        rec = {"n_traces": 0}
        self._rec = rec

        def fwd(state_arrays, x):
            rec["n_traces"] += 1
            backup = [t.data for t in state_list]
            for t, a in zip(state_list, state_arrays):
                t.data = a
            prev = CTX.training
            CTX.training = False
            try:
                # a weight-quantized model (quant.quantize_params)
                # dequantizes IN GRAPH here too: the scope rebinds int8
                # payloads to payload x scale for the traced body only
                with mp.policy_scope(self.policy), \
                        dequant_params_scope(model):
                    out = model.forward(Tensor(data=x, device=dev,
                                               requires_grad=False))
            finally:
                CTX.training = prev
                for t, a in zip(state_list, backup):
                    t.data = a
            outs = out if isinstance(out, (list, tuple)) else (out,)
            leaves = [o.data if isinstance(o, Tensor) else o
                      for o in outs]
            if self.policy is not None:
                leaves = [self.policy.cast_output(x) for x in leaves]
            return leaves

        self._fwd = jax.jit(fwd)
        self._hbm_dev = _perf.first_jax_device(self._state_arrays)
        # warm spin-up: deserialize a previously exported batch
        # forward (honored-or-refused; a refusal compiles fresh).
        # n_traces reads 1: the trace happened in the exporting
        # process, and the no-retrace audit still holds.
        self._aot_store = None
        self._aot_source = None
        if aot_store is not None:
            from ..aot import export as _aot_export
            from ..observability import perf as _perf2
            if not isinstance(aot_store, _aot_export.AotStore):
                aot_store = _aot_export.AotStore(aot_store,
                                                 registry=self._reg)
            self._aot_store = aot_store
            t0 = time.perf_counter()
            avals = _aot_export.batch_program_avals(self)
            fn, _doc = aot_store.try_load_program(
                _aot_export.SERVE_BATCH, avals=avals,
                donate_argnums=(), policy=self.policy,
                jax_device=self._hbm_dev,
                expect_extra=_aot_export.batch_geometry(self))
            if fn is not None:
                self._fwd = fn
                rec["n_traces"] = 1
                sig = _perf2.step_signature([avals[1]])
                _perf2.record_compile(
                    _aot_export.SERVE_BATCH,
                    time.perf_counter() - t0, sig, source="aot",
                    registry=self._reg)
                rec["sig"] = sig
            self._aot_source = {_aot_export.SERVE_BATCH:
                                aot_store.outcomes.get(
                                    _aot_export.SERVE_BATCH, "fresh")}
        self._occupancy = self._reg.gauge(
            "serve_slot_occupancy", "active sequences in the slot array")
        self._reg.gauge("serve_slots",
                        "slot array width (max in-flight sequences)"
                        ).set(self.batch)

    def submit(self, x, timeout=None, trace_id=None):
        """Queue one input array of ``input_shape``; the future's
        result is the model's per-row output (array, or tuple for
        multi-output models)."""
        x = np.asarray(x, self.input_dtype)
        if x.shape != self.input_shape:
            self.queue.finish("rejected")
            raise ServingError(
                f"input shape {x.shape} != engine input_shape "
                f"{self.input_shape}")
        req = Request(None, payload=x, timeout=timeout,
                      trace_id=trace_id)
        return self._admit(req)

    def compiled_step_info(self):
        return {"n_traces": self._rec["n_traces"],
                "slots": self.batch,
                "input_shape": self.input_shape,
                "policy": self.policy.describe()
                if self.policy is not None else None,
                "aot": self._aot_source}

    def _busy(self):
        return len(self.queue) > 0

    def _fail_inflight(self, error):
        pass            # stateless: nothing lives between ticks

    def _tick(self):
        batch = self.queue.pop_batch(self.batch)
        if not batch:
            return
        self._occupancy.set(len(batch))
        x = np.zeros((self.batch,) + self.input_shape, self.input_dtype)
        for i, req in enumerate(batch):
            x[i] = req.payload
        t0 = time.perf_counter()
        n0 = self._rec["n_traces"]
        cc0 = _cache_counts()
        try:
            with _spans.span("serve.batch_forward", n=len(batch)):
                leaves = self._fwd(self._state_arrays, x)
        except Exception as e:
            # popped requests are invisible to the crash path's queue
            # drain — fail them here, exactly once
            self._fail_batch(batch, e)
            raise
        if self._rec["n_traces"] > n0:
            _attribute_trace(self._rec, self._reg, "serve_batch",
                             [x], ("input",), t0, cc0)
        # same rule as the autoregressive decode: a PROFILED tick's
        # trace-inflated latency stays out of the SLO series
        if not self._profiling_now:
            self._tok_lat.observe(time.perf_counter() - t0)
        leaves = [np.asarray(leaf) for leaf in leaves]
        for i, req in enumerate(batch):
            now = time.monotonic()
            req.first_token_at = now
            self._ttft.observe(now - req.submitted_at)
            row = tuple(leaf[i] for leaf in leaves)
            if self._trace_requests:
                _spans.event("request.delivered",
                             request=req.trace_id, status="completed")
            req.future.set_result(row[0] if len(row) == 1 else row)
            self.queue.finish("completed")
        self._occupancy.set(0)
        self._sample_hbm()


def _check_quant_policy(policy, target, *, weights_ok, cache_ok, hint):
    """A quantized policy the target cannot honor must FAIL at build —
    serving full fp32 while the caller believes they deployed int8 is
    the silent no-op this guard exists to prevent. ``hint`` names the
    working route for THIS target."""
    wq = getattr(policy, "weight_quant", None)
    cq = getattr(policy, "cache_quant", None)
    if wq is not None and not weights_ok:
        raise ValueError(
            f"policy {policy.name!r} requests {wq} weight quantization "
            f"but {target} cannot honor it; it would serve full-"
            f"precision weights silently. {hint}")
    if cq is not None and not cache_ok:
        raise ValueError(
            f"policy {policy.name!r} requests an {cq} KV cache but "
            f"{target} has no ring cache to quantize")


def build_engine(model, **kw):
    """The ``Model.compile_serving`` backend: autoregressive models
    (anything exposing ``decode_adapter``) get a :class:`ServingEngine`
    over their ring-cache adapter; everything else — the classifier
    zoo, ONNX imports — serves statelessly through a
    :class:`BatchServingEngine` (pass ``input_shape=``).

    Quantized policies are honored-or-refused: an adapter that does not
    declare ``supports_weight_quant`` / ``supports_cache_quant`` (the
    transformer adapter does, the char-rnn's (h,c) slot state cannot)
    rejects them typed at build, and a stateless engine accepts a
    weight-quant policy only over an already ``quantize_params``'d
    model (the cache axis is inert there — it has no KV cache)."""
    if hasattr(model, "decode_adapter"):
        adapter_kw = {}
        if "policy" in kw:
            adapter_kw["policy"] = kw.get("policy")
        adapter = model.decode_adapter(**adapter_kw)
        if kw.get("policy") is not None:
            _check_quant_policy(
                kw["policy"], f"{type(model).__name__}'s decode adapter",
                weights_ok=getattr(adapter, "supports_weight_quant",
                                   False),
                cache_ok=getattr(adapter, "supports_cache_quant",
                                 False),
                hint="Serve under a non-quantized policy (an in-place-"
                "quantized model's weights are dequantized at engine "
                "build either way)")
        ar_keys = ("slots", "max_len", "prefill_len", "prefill_batch",
                   "policy", "queue_capacity", "faults", "registry",
                   "telemetry_dir", "max_retries", "trace_requests",
                   "aot_store", "profile_every", "kv_layout",
                   "kv_block_size", "kv_blocks", "speculative_k",
                   "mesh", "model_shards", "spill_bytes",
                   "snapshot_every", "pool_role")
        unknown = sorted(set(kw) - set(ar_keys))
        if unknown:
            raise TypeError(
                f"unknown serving option(s) {unknown} for "
                f"autoregressive {type(model).__name__} "
                f"(accepted: {sorted(ar_keys)})")
        return ServingEngine(adapter, **kw)
    if "input_shape" not in kw:
        raise TypeError(
            "stateless serving needs input_shape=(per-sample shape); "
            f"{type(model).__name__} has no decode_adapter")
    bt_keys = ("input_shape", "batch", "input_dtype", "policy",
               "queue_capacity", "faults", "registry", "telemetry_dir",
               "max_retries", "trace_requests", "aot_store",
               "profile_every")
    unknown = sorted(set(kw) - set(bt_keys))
    if unknown:
        raise TypeError(
            f"unknown serving option(s) {unknown} for stateless "
            f"{type(model).__name__} (accepted: {sorted(bt_keys)})")
    if kw.get("policy") is not None:
        _check_quant_policy(
            kw["policy"], f"stateless {type(model).__name__} serving",
            weights_ok=bool(getattr(model, "_quant_pairs", None)),
            cache_ok=True,   # inert: a batch engine has no KV cache
            hint="Run quant.quantize_params(model) first, or use a "
            "non-quantized policy")
    return BatchServingEngine(model, **kw)


__all__ = ["ServingEngine", "BatchServingEngine", "build_engine"]
