"""Stdlib HTTP gateway over a serving engine.

Deliberately dependency-free (``http.server``): the gateway is the thin
edge of the engine, not a web framework. Endpoints:

- ``POST /v1/generate`` — autoregressive engines. JSON body
  ``{"prompt": [ids...], "max_new_tokens": n, "temperature": t,
  "top_k": k, "eos_id": id, "seed": s, "timeout": secs}`` (everything
  but ``prompt`` optional); 200 with the completed
  ``{"tokens": [...], "prompt_len": n, "ttft_s": ...}``.
- ``POST /v1/predict`` — stateless engines. ``{"input": nested list}``;
  200 with ``{"output": nested list}`` (or ``"outputs"`` for
  multi-output models).
- ``GET /healthz`` — replica health JSON. **503 while draining or
  crashed**, 200 otherwise — this is the load-balancer contract: a
  draining replica stops receiving traffic because it says so here and
  on every refused submit, not because anyone remembered to deregister
  it.
- ``GET /metrics`` / ``GET /metrics.json`` — Prometheus text / snapshot
  JSON of the engine's registry (quantile summaries included).
- ``GET /trace.json`` — the process flight-recorder ring (in-flight
  spans included) rendered as a Chrome-trace document that opens in
  ui.perfetto.dev — per-request timeline lanes keyed by the request
  ids this gateway minted.
- ``GET /timeline.json`` — the newest profiled tick's step-timeline
  decomposition (compute/collective/memcpy/host/idle fractions +
  exposed-communication seconds; engines built with
  ``profile_every=N`` refresh it continuously).
- ``POST /drain`` — begin a graceful drain; 202 immediately (the drain
  finishes in the background; watch ``/healthz``). ``?deadline=2.5``
  (or ``{"deadline": 2.5}``) arms a preemption budget: finish what
  fits, hand off / fail-typed the rest by the deadline.
- ``POST /v1/inject`` — live-KV handoff receive: ``{"meta": b64,
  "frame": b64, "timeout": secs}`` (a sealed snapshot from a draining
  peer); 200 with the continuation's response, **409 on a typed
  refusal** (corrupt frame, geometry mismatch) — the sender falls back
  to recompute re-dispatch, corrupt KV is never injected.

Request tracing: every ``/v1/generate`` / ``/v1/predict`` call gets a
request id (``request_id`` in the body to supply your own, else a
fresh hex id), passed to the engine as its trace id and echoed in the
response — the handle that finds this request's lane in
``/trace.json``.

Refusal mapping: draining/full queue/exhausted block pool → 503 (fail
over), shed under sustained backpressure → 503 with a ``Retry-After``
header (back off, don't hammer), request deadline → 504, malformed
request → 400, oversized/undeclared body → 413 (refused before a byte
is read), serve-loop crash → 500. Every generate/predict request
lives on ONE deadline: the engine-side timeout and the handler's wait
derive from the same clock, so a fleet retry inherits the true
remaining budget. Handler threads are non-daemon and joined at
``server_close()``, so a drained process never exits with a response
half-written.
"""

from __future__ import annotations

import base64
import json
import math
import threading
import uuid
from urllib.parse import parse_qs, urlsplit

from .scheduler import (BlockPoolExhausted, EngineDraining,
                        HandoffRefused, QueueFull, ReplicaCrashed,
                        RequestShed, RequestTimeout, ServingError,
                        budget_remaining, deadline_in)


def _result_doc(res):
    import numpy as np
    if isinstance(res, dict):
        return res
    if isinstance(res, tuple):
        return {"outputs": [np.asarray(r).tolist() for r in res]}
    return {"output": np.asarray(res).tolist()}


def serve_gateway(engine, host="127.0.0.1", port=0, replica=None,
                  default_timeout=120.0, max_body_bytes=8 << 20,
                  retry_after=None):
    """Start the gateway on a daemon thread. Returns ``(server, port)``;
    ``server.shutdown(); server.server_close()`` stops it (close joins
    in-flight handler threads). ``replica`` (a
    :class:`~singa_tpu.serving.fleet.ServingReplica`) upgrades
    ``/healthz`` to the full replica view and routes ``/drain`` through
    the replica's drain contract. ``engine`` may also be a
    :class:`~singa_tpu.serving.fleet.FleetRouter` — a fleet-front
    gateway: ``/healthz`` lists every replica (200 while at least one
    serves), ``/drain`` drains them all, and requests ride the
    router's breaker/re-dispatch/shed machinery. POST bodies larger
    than ``max_body_bytes`` (or with a missing/garbage
    ``Content-Length``) are refused 413 before a byte is read — the
    gateway never buffers unbounded input. Binds localhost by
    default — put a real LB/mesh in front for anything public.

    ``retry_after`` (seconds, or a zero-arg callable returning
    seconds-or-None) sets the ``Retry-After`` on backpressure 503s.
    Wire it to :meth:`Autoscaler.retry_after_hint
    <singa_tpu.serving.autoscaler.Autoscaler.retry_after_hint>` and a
    503 emitted while the fleet is scaling up tells clients when
    capacity actually lands — the rolling median of observed
    spawn-to-ready durations — instead of a constant; None (or no
    hint) falls back to the constant 1s."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from ..observability.export import render_prometheus

    is_fleet = hasattr(engine, "replicas")

    def retry_after_header():
        v = retry_after() if callable(retry_after) else retry_after
        try:
            v = None if v is None else float(v)
        except (TypeError, ValueError):
            v = None
        if v is None or v <= 0:
            return "1"
        return str(max(1, int(math.ceil(v))))

    def health_doc():
        if replica is not None:
            return replica.health()
        if is_fleet:
            docs = engine.health()
            n_ok = sum(1 for d in docs if isinstance(d, dict)
                       and d.get("status") == "serving")
            doc = {"status": "serving" if n_ok else "unavailable",
                   "replicas": docs,
                   "breakers": engine.breaker_states()}
            # disaggregated prefill/decode view: per-pool depth +
            # transfer/affinity counters (absent when pools are off)
            pools = getattr(engine, "pools_summary", lambda: None)()
            if pools is not None:
                doc["pools"] = pools
            return doc
        return {"status": ("crashed" if engine._crashed is not None
                           else "draining" if engine.draining
                           else "serving"),
                "queue_depth": len(engine.queue),
                "compiled": engine.compiled_step_info()}

    def begin_drain(deadline=None):
        if replica is not None:
            replica.request_drain(deadline=deadline)
            # run_until_drained (the replica's main thread) finishes it;
            # a replica-less engine drains on a helper thread instead
            return
        kw = {} if deadline is None else {"timeout": float(deadline)}
        threading.Thread(target=engine.drain, kwargs=kw, daemon=True,
                         name="gateway-drain").start()

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _reply(self, code, doc, headers=()):
            body = json.dumps(doc).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            for k, v in headers:
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(body)))
            # one request per connection: keep-alive would park handler
            # threads in a blocking read, and server_close() JOINS
            # handler threads (that join is the drain guarantee — it
            # must never wait on an idle keep-alive socket)
            self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(body)
            self.close_connection = True

        def do_GET(self):       # noqa: N802 — stdlib API
            try:
                if self.path.startswith("/healthz"):
                    doc = health_doc()
                    self._reply(200 if doc.get("status") == "serving"
                                else 503, doc)
                elif self.path.startswith("/metrics.json"):
                    self._reply(200, engine._reg.snapshot())
                elif self.path.startswith("/aot.json"):
                    # warm-restart audit: which executables were
                    # deserialized vs compiled fresh, plus the store's
                    # on-disk manifests (None/{} without an AOT store)
                    store = getattr(engine, "_aot_store", None)
                    self._reply(200, {
                        "source": getattr(engine, "_aot_source", None),
                        "manifests": store.inspect()
                        if store is not None else {}})
                elif self.path.startswith("/timeline.json"):
                    # the newest profiled tick's step-timeline
                    # decomposition (engines built with profile_every=N
                    # refresh it continuously); the interval lanes are
                    # dropped from the reply — the fractions and the
                    # exposed-comm number are the dashboard payload,
                    # /trace.json renders the lanes
                    tl = getattr(engine, "last_timeline", None)
                    self._reply(200, {
                        "site": "serve",
                        "timeline": ({k: v for k, v in tl.items()
                                      if k != "lanes"}
                                     if tl else None)})
                elif self.path.startswith("/trace.json"):
                    from ..observability import trace_export as _texp
                    # _reply's own dumps is the single serialization
                    # AND the serializability check (failure → 500)
                    self._reply(200, _texp.validate_chrome_trace(
                        _texp.to_chrome_trace(_texp.live_records(
                            registry=engine._reg)),
                        check_serializable=False))
                elif self.path.startswith("/metrics"):
                    body = render_prometheus(
                        engine._reg.snapshot()).encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.send_header("Connection", "close")
                    self.end_headers()
                    self.wfile.write(body)
                    self.close_connection = True
                else:
                    self._reply(404, {"error": "unknown path"})
            except Exception as e:   # noqa: BLE001 — a probe must not kill us
                try:
                    self._reply(500,
                                {"error": f"{type(e).__name__}: {e}"})
                except Exception:
                    pass

        def do_POST(self):      # noqa: N802 — stdlib API
            # body cap BEFORE any read: a missing or garbage
            # Content-Length means "read until the peer hangs up" —
            # unbounded — and an honest oversized one is refused by
            # the declared size alone (never buffered then rejected)
            raw_len = self.headers.get("Content-Length")
            try:
                n = int(raw_len)
                if n < 0:
                    raise ValueError
            except (TypeError, ValueError):
                self._reply(413, {
                    "error": f"missing or unparseable Content-Length "
                             f"{raw_len!r}: the gateway reads exactly "
                             "the declared bytes"})
                return
            if n > max_body_bytes:
                self._reply(413, {
                    "error": f"request body of {n} bytes exceeds the "
                             f"gateway limit of {max_body_bytes}"})
                return
            try:
                raw = self.rfile.read(n) if n else b"{}"
                body = json.loads(raw.decode() or "{}")
            except Exception:
                self._reply(400, {"error": "body is not JSON"})
                return
            self._rid = None
            try:
                if self.path.startswith("/drain"):
                    # ?deadline=2.5 arms a preemption budget: the
                    # drain finishes what fits and migrates/fails the
                    # rest by then instead of waiting out the default
                    q = parse_qs(urlsplit(self.path).query)
                    deadline = body.get("deadline")
                    if deadline is None and q.get("deadline"):
                        deadline = q["deadline"][0]
                    begin_drain(deadline=None if deadline is None
                                else float(deadline))
                    doc = {"status": "draining"}
                    if deadline is not None:
                        doc["deadline_s"] = float(deadline)
                    self._reply(202, doc)
                elif self.path.startswith("/v1/generate"):
                    self._generate(body)
                elif self.path.startswith("/v1/inject"):
                    self._inject(body)
                elif self.path.startswith("/v1/predict"):
                    self._predict(body)
                else:
                    self._reply(404, {"error": "unknown path"})
            except RequestShed as e:
                # typed fast-fail shed: Retry-After is the contract —
                # the client backs off instead of hammering an
                # overloaded fleet into timeouts
                self._reply(503, self._err(
                    e, retryable=True, retry_after=e.retry_after),
                    headers=(("Retry-After",
                              str(max(1, int(e.retry_after)))),))
            except HandoffRefused as e:
                # typed inject refusal (corrupt frame, geometry
                # mismatch): 409 — recompute-redispatch territory, NOT
                # a fail-over-and-retry-the-same-bytes 503
                self._reply(409, self._err(e, retryable=False))
            except (EngineDraining, QueueFull,
                    BlockPoolExhausted) as e:
                # Retry-After rides every backpressure refusal: a
                # draining replica tells the client when to re-probe
                # the fleet instead of hammering this instance; the
                # hint (when wired) is spawn-to-ready derived, so the
                # back-off tracks real warm-up time
                self._reply(503, self._err(e, retryable=True),
                            headers=(("Retry-After",
                                      retry_after_header()),))
            except RequestTimeout as e:
                self._reply(504, self._err(e))
            except ReplicaCrashed as e:
                # serve-loop crash → 500 (the docstring's refusal map);
                # still retryable — a fleet LB fails over on it
                self._reply(500, self._err(e, retryable=True))
            except (ServingError, ValueError, TypeError) as e:
                self._reply(400, self._err(e))
            except Exception as e:   # noqa: BLE001 — crash → 500, once
                self._reply(500, self._err(e, named=True))

        def _err(self, e, named=False, **extra):
            # error replies keep the minted request id — a FAILED
            # request's trace lane is the main /trace.json debugging
            # target, and without the echo a server-minted id is
            # unfindable
            doc = {"error": f"{type(e).__name__}: {e}" if named
                   else str(e), **extra}
            if getattr(self, "_rid", None):
                doc["request_id"] = self._rid
            return doc

        @staticmethod
        def _mint_rid(body):
            # the request id minted here rides every engine span/event
            # for this request — the /trace.json timeline handle
            rid = body.get("request_id")
            return str(rid) if rid else uuid.uuid4().hex[:12]

        def _generate(self, body):
            prompt = body.get("prompt")
            if not isinstance(prompt, list) or not prompt:
                raise ValueError(
                    "generate needs a non-empty integer list 'prompt'")
            kw = {k: body[k] for k in ("max_new_tokens", "temperature",
                                       "top_k", "eos_id", "seed",
                                       "timeout") if k in body}
            # ONE deadline: the engine-side timeout and this handler's
            # wait are the same clock (started here), so a fleet
            # retry inherits the true remainder and the 504 fires in
            # lockstep with the request's own expiry
            wait = float(kw["timeout"]) \
                if kw.get("timeout") is not None else default_timeout
            deadline = deadline_in(wait)
            kw["timeout"] = wait
            rid = self._rid = self._mint_rid(body)
            fut = engine.submit(prompt, trace_id=rid, **kw)
            doc = fut.result(timeout=budget_remaining(deadline))
            if isinstance(doc, dict):
                doc = dict(doc, request_id=rid)
            self._reply(200, doc)

        def _inject(self, body):
            # live-KV handoff receive: a draining/dying peer POSTs a
            # sealed snapshot here; the engine validates (CRC +
            # geometry) before ANY bytes touch the pool — a refusal is
            # 409 and the sender falls back to recompute re-dispatch
            try:
                meta = base64.b64decode(body["meta"])
                frame = base64.b64decode(body["frame"])
            except (KeyError, TypeError, ValueError):
                raise ValueError(
                    "inject needs base64 'meta' and 'frame'")
            eng = replica.engine if replica is not None else \
                engine.engine if hasattr(engine, "engine") else engine
            inject = getattr(eng, "inject_snapshot", None)
            if inject is None:
                raise ValueError(
                    "this endpoint's engine does not accept KV "
                    "snapshots")
            wait = float(body["timeout"]) \
                if body.get("timeout") is not None else default_timeout
            deadline = deadline_in(wait)
            fut = inject(meta, frame, timeout=wait)
            doc = fut.result(timeout=budget_remaining(deadline))
            self._reply(200, doc if isinstance(doc, dict)
                        else {"tokens": doc})

        def _predict(self, body):
            if "input" not in body:
                raise ValueError("predict needs 'input'")
            wait = float(body["timeout"]) \
                if body.get("timeout") is not None else default_timeout
            deadline = deadline_in(wait)
            rid = self._rid = self._mint_rid(body)
            fut = engine.submit(body["input"], timeout=wait,
                                trace_id=rid)
            doc = _result_doc(fut.result(
                timeout=budget_remaining(deadline)))
            self._reply(200, dict(doc, request_id=rid))

        def log_message(self, *a):   # silence per-request stderr spam
            pass

    class Server(ThreadingHTTPServer):
        # joined at server_close(): a drain never abandons a response
        daemon_threads = False
        block_on_close = True

    server = Server((host, int(port)), Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="serve-gateway")
    t.start()
    return server, server.server_address[1]


__all__ = ["serve_gateway"]
