"""TPU-native inference: continuous batching over AOT-compiled
fixed-shape programs.

The training runtime's hard-won invariants, applied to serving:

- **one trace, forever** — the decode program's
  ``compiled_step_info()["n_traces"]`` must stay 1 across ANY pattern of
  requests finishing mid-batch and new ones refilling their slots (the
  ``pad_last`` validity-mask idiom from the data pipeline, CI-pinned
  like the train step's retrace guard);
- **O(1) per token** — each slot owns a ring of KV rows, or (under
  ``kv_layout="paged"``) a block table into a fixed refcounted block
  pool with prefix sharing and speculative multi-token verify ticks
  (:mod:`.kv_cache`); work and memory per emitted token are constant;
- **exactly-once delivery** — every submitted request resolves its
  future exactly once (completed, failed, timed out, or rejected —
  never two of those, never zero), chaos-tested under injected faults;
- **drainable** — a replica told to drain finishes everything in
  flight, refuses new work loudly (so a router fails over), and exits
  ``EXIT_DRAINED`` (0);
- **observable** — TTFT, per-token latency, queue depth, slot
  occupancy, and terminal request outcomes flow through the
  observability registry, and a serve-loop crash dumps the flight
  recorder to ``telemetry/blackbox-serve.jsonl``.

Layout: :mod:`.engine` (the continuous-batching engines +
``build_engine``, which ``Model.compile_serving`` fronts),
:mod:`.kv_cache` (ring-cache math), :mod:`.scheduler` (request queue /
futures / SLO bookkeeping), :mod:`.fleet` (drainable replicas +
client-side routing), :mod:`.gateway` (stdlib HTTP front).
"""

from .autoscaler import (Autoscaler, AutoscaleTargets,    # noqa: F401
                         SpawnFailed, WarmAdmissionRefused)
from .engine import (BatchServingEngine, ServingEngine,   # noqa: F401
                     build_engine)
from .fleet import (EXIT_DRAINED, CircuitBreaker,         # noqa: F401
                    FleetFuture, FleetRouter, ServingReplica,
                    ShedPolicy, brownout_shrink_generation)
from .gateway import serve_gateway                        # noqa: F401
from .kv_cache import (HostSpillTier, affinity_hash,      # noqa: F401
                       prefix_chain_key)
from .scheduler import (BlockPoolExhausted,               # noqa: F401
                        EngineDraining, HandoffRefused, PoolSaturated,
                        QueueFull, ReplicaCrashed, Request,
                        RequestQueue, RequestShed, RequestTimeout,
                        ServeFuture, ServingError, budget_remaining,
                        deadline_in)

__all__ = [
    "ServingEngine", "BatchServingEngine", "build_engine",
    "Autoscaler", "AutoscaleTargets", "SpawnFailed",
    "WarmAdmissionRefused",
    "ServingReplica", "FleetRouter", "FleetFuture", "CircuitBreaker",
    "ShedPolicy", "brownout_shrink_generation", "EXIT_DRAINED",
    "serve_gateway", "ServingError", "QueueFull", "EngineDraining",
    "RequestTimeout", "ReplicaCrashed", "RequestShed",
    "PoolSaturated", "BlockPoolExhausted", "HandoffRefused",
    "HostSpillTier", "affinity_hash", "prefix_chain_key",
    "ServeFuture", "Request", "RequestQueue",
    "deadline_in", "budget_remaining",
]
