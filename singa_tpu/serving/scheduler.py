"""Request queue, per-request futures, and admission bookkeeping.

The serving engine's control plane is deliberately boring host-side
python: a bounded deque of :class:`Request` records and a
:class:`ServeFuture` per request that is fulfilled EXACTLY ONCE — the
delivery guard is a real invariant (chaos-tested with injected faults),
not a convention. Rejection is synchronous and loud: a full queue or a
draining engine refuses at ``submit`` time with a typed error, so a
load balancer can fail over instead of letting requests rot.

SLO metrics recorded here (all through the PR-6 observability
registry):

- ``serve_queue_depth`` (gauge) — requests waiting for a slot;
- ``serve_requests_total{status=...}`` (counter) — terminal outcome of
  every request: ``completed`` | ``rejected`` | ``timed_out`` |
  ``failed`` | ``cancelled``;
- admission wait rides the engine's TTFT histogram (queue time is part
  of time-to-first-token, which is what the user feels).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque

import numpy as np

from ..observability import metrics as _metrics


class ServingError(RuntimeError):
    """Base class for serve-path failures."""


class QueueFull(ServingError):
    """Admission refused: the bounded request queue is at capacity."""


class EngineDraining(ServingError):
    """Admission refused: the engine is draining (finishing in-flight
    work, accepting nothing new) or already stopped."""


class RequestTimeout(ServingError):
    """The request's deadline passed before a response completed."""


class ReplicaCrashed(ServingError):
    """The replica that held this request died (serve-loop crash, wire
    failure, or a crashed engine refusing at the door). Unlike
    backpressure refusals this is a REPLICA failure, not a request
    failure: the request itself is pure submit args + a fresh id, so a
    fleet router may re-dispatch it to a survivor exactly once —
    deterministic greedy decode makes the retried response
    token-identical to the one the dead replica would have produced."""


class RequestShed(ServingError):
    """The fleet refused this request on purpose: sustained
    backpressure (QueueFull / BlockPoolExhausted across every admitted
    replica) tripped the shed policy. Fast-fail, typed, with a
    ``retry_after`` hint the gateway turns into a ``Retry-After``
    header — degrading loudly beats queueing into a timeout."""

    def __init__(self, message, retry_after=1.0):
        super().__init__(message)
        self.retry_after = float(retry_after)


class PoolSaturated(RequestShed):
    """The disaggregated decode pool refused this request AFTER the
    degradation ladder ran dry: brownout stepped generation down,
    colocate fallback (prefill replicas serving decode end-to-end)
    absorbed what it could, and the fleet still cannot place the
    request. A :class:`RequestShed` subclass, so the gateway's 503 +
    ``Retry-After`` contract applies unchanged — but typed, so tests
    and dashboards can tell pool saturation from generic overload."""


def deadline_in(timeout, now=None):
    """Monotonic deadline for a timeout budget; ``None`` timeout means
    no deadline. The single clock a request lives on: the gateway and
    the fleet router both derive engine-side timeouts AND client-side
    waits from one of these, so a retry inherits the true remainder."""
    if timeout is None:
        return None
    return (now if now is not None else time.monotonic()) \
        + float(timeout)


def budget_remaining(deadline, now=None):
    """Seconds left until ``deadline``, floored at 0.0 (``None``
    deadline → ``None``: unlimited)."""
    if deadline is None:
        return None
    return max(0.0, deadline - (now if now is not None
                                else time.monotonic()))


class HandoffRefused(ServingError):
    """A live-KV snapshot inject was refused, typed: the sealed frame
    failed :func:`integrity.open_frame` (corruption in flight), or its
    geometry/policy metadata does not match this engine's compiled
    programs (layout, dtype, head/block shape, cache quantization).
    Corrupt or wrong-shape KV state is NEVER written into a survivor's
    pool — the caller falls back to plain recompute re-dispatch with
    whatever deadline budget remains."""


class BlockPoolExhausted(ServingError):
    """Admission refused: the paged KV block pool cannot cover the
    request's ``prompt + max_new_tokens`` reservation without evicting
    a LIVE sequence's blocks (which never happens — only unreferenced
    cached prefixes are reclaimable). Raised synchronously at
    ``submit`` when the request could NEVER fit the pool; a request
    that merely has to wait for in-flight sequences to finish stays
    queued instead (backpressure, not failure)."""


class ServeFuture:
    """One request's response slot: fulfilled exactly once.

    ``result(timeout)`` blocks for the response and re-raises the
    request's error. ``deliveries`` counts fulfillment attempts — the
    exactly-once chaos test asserts it is 1 for every request, and a
    second delivery attempt raises instead of silently overwriting."""

    def __init__(self):
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._result = None
        self._error = None
        self.deliveries = 0

    def _fulfill(self, result=None, error=None):
        with self._lock:
            self.deliveries += 1
            if self._event.is_set():
                raise RuntimeError(
                    "double delivery: this request already has a "
                    "response (exactly-once violation)")
            self._result = result
            self._error = error
            self._event.set()

    def set_result(self, result):
        self._fulfill(result=result)

    def set_error(self, error):
        self._fulfill(error=error)

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise RequestTimeout(
                f"no response within {timeout}s (request still "
                "in flight)")
        if self._error is not None:
            raise self._error
        return self._result


class Request:
    """One generation request: prompt token ids + sampling config.

    ``rng`` is per-request (seeded) so a retried/re-ordered schedule
    cannot change what any single request samples. ``trace_id`` names
    the request in the per-request flight-recorder trace (minted at
    the gateway for HTTP traffic; defaults to ``req-<n>``) — every
    span/event the engine records for this request carries it."""

    _ids = itertools.count(1)

    def __init__(self, prompt, max_new_tokens=16, temperature=0.0,
                 top_k=None, eos_id=None, seed=0, timeout=None,
                 payload=None, trace_id=None):
        self.id = next(Request._ids)
        self.trace_id = str(trace_id) if trace_id else f"req-{self.id}"
        self.prompt = np.asarray(prompt, np.int32).reshape(-1) \
            if prompt is not None else None
        self.payload = payload          # stateless-mode input array
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = top_k
        self.eos_id = eos_id
        self.rng = np.random.RandomState(int(seed) + self.id)
        self.submitted_at = time.monotonic()
        # `is not None`, not truthiness: timeout=0 means "already due"
        # (a fail-fast probe), the opposite of no deadline
        self.deadline = (self.submitted_at + float(timeout)
                         if timeout is not None else None)
        self.first_token_at = None      # set by the engine at prefill
        self.future = ServeFuture()
        self.tokens: list = []          # generated ids (engine-owned)

    def expired(self, now=None):
        return self.deadline is not None and \
            (now if now is not None else time.monotonic()) > self.deadline


class RequestQueue:
    """Bounded FIFO admission queue with deadline sweeping."""

    def __init__(self, capacity=64, registry=None):
        self.capacity = int(capacity)
        self._q = deque()
        self._lock = threading.Lock()
        self._reg = registry if registry is not None \
            else _metrics.default_registry()
        self._depth = self._reg.gauge(
            "serve_queue_depth", "requests admitted but not yet slotted")
        self._outcomes = self._reg.counter(
            "serve_requests_total",
            "terminal request outcomes", labels=("status",))

    def finish(self, status):
        """Record a request's terminal outcome (engine calls this at
        the single point each future is fulfilled)."""
        self._outcomes.inc(status=status)

    def put(self, req):
        """Admit or raise :class:`QueueFull` (counted as rejected)."""
        with self._lock:
            if len(self._q) >= self.capacity:
                full = True
            else:
                self._q.append(req)
                full = False
            depth = len(self._q)
        self._depth.set(depth)
        if full:
            self.finish("rejected")
            raise QueueFull(
                f"request queue at capacity ({self.capacity}); "
                "retry against another replica")

    def pop_batch(self, n, now=None, admit=None):
        """Up to ``n`` non-expired requests, FIFO. Expired requests are
        fulfilled with :class:`RequestTimeout` here (counted
        ``timed_out``) — they never consume a slot. ``admit`` (an
        optional predicate) gates each pop: the first refused request
        STOPS the batch and stays at the head of the queue — the paged
        engine's block-pool backpressure, FIFO-fair by construction
        (nothing behind an unplaceable request jumps it)."""
        taken, expired = [], []
        with self._lock:
            while self._q and len(taken) < n:
                req = self._q[0]
                if req.expired(now):
                    expired.append(self._q.popleft())
                    continue
                if admit is not None and not admit(req):
                    # the blocked head stays — but the deadline sweep
                    # must still reach everything queued BEHIND it, or
                    # a timed-out request would sit unresolved for as
                    # long as the head waits for blocks
                    keep = deque()
                    while self._q:
                        r = self._q.popleft()
                        (expired if r.expired(now)
                         else keep).append(r)
                    self._q.extend(keep)
                    break
                taken.append(self._q.popleft())
            depth = len(self._q)
        self._depth.set(depth)
        for req in expired:
            req.future.set_error(RequestTimeout(
                "deadline passed while queued"))
            self.finish("timed_out")
        return taken

    def drain_pending(self, error):
        """Fulfill every queued request with ``error`` (hard-stop
        path; graceful drain empties the queue by serving it)."""
        with self._lock:
            pending = list(self._q)
            self._q.clear()
        self._depth.set(0)
        for req in pending:
            if not req.future.done():
                req.future.set_error(error)
                self.finish("failed")
        return len(pending)

    def __len__(self):
        with self._lock:
            return len(self._q)


__all__ = ["ServingError", "QueueFull", "EngineDraining",
           "RequestTimeout", "ReplicaCrashed", "RequestShed",
           "PoolSaturated", "BlockPoolExhausted", "HandoffRefused",
           "ServeFuture", "Request", "RequestQueue", "deadline_in",
           "budget_remaining"]
