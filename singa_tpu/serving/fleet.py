"""Drainable replicas and client-side fleet routing.

One serving process = one :class:`ServingReplica`: an engine plus
(optionally) a membership seat in a ``resilience.cluster`` pod — the
same coordinator/worker control plane training uses for health,
heartbeat-carried metric summaries, and dead-peer detection, so a
serving fleet's coordinator health report looks exactly like a training
pod's.

The drain contract (the serving sibling of the exit-75/76 supervisor
table):

1. something asks the replica to drain (SIGTERM, the gateway's
   ``POST /drain``, or :meth:`ServingReplica.drain` directly);
2. the engine refuses new requests **loudly** — ``submit`` raises
   :class:`~singa_tpu.serving.scheduler.EngineDraining`, the gateway
   returns 503 — so a router fails the traffic over instead of letting
   it rot;
3. every request already admitted (queued or mid-decode) runs to a
   normal response: a drained replica drops NOTHING (chaos-proved by
   the ``serve-drain`` scenario in ``tools/chaos_smoke.py``);
4. the replica leaves its cluster seat and exits
   :data:`EXIT_DRAINED` (0) — "done, on purpose": a supervisor must NOT
   relaunch it (75 means relaunch, 76 means cordon, 0 means the drain
   you asked for completed).

:class:`FleetRouter` is the client half for in-process fleets (tests,
chaos drivers, single-host multi-engine setups): least-depth dispatch
with failover on refusal. Across hosts the same logic belongs to any
load balancer that honors the gateway's 503 — the router documents the
semantics, it does not replace your LB.
"""

from __future__ import annotations

import signal
import threading

from ..observability import metrics as _metrics
from ..observability import spans as _spans
from .scheduler import EngineDraining, QueueFull, ServingError

# the drain exit code: intentional, successful, do-not-relaunch — the
# 0 row of the README's supervisor exit-code contract table
EXIT_DRAINED = 0


class ServingReplica:
    """One engine + one (optional) cluster seat + the drain contract."""

    def __init__(self, engine, *, cluster=None, name="replica",
                 registry=None):
        self.engine = engine
        self.cluster = cluster
        self.name = str(name)
        self._reg = registry if registry is not None \
            else _metrics.default_registry()
        self._drain_evt = threading.Event()
        self._drain_gauge = self._reg.gauge(
            "serve_replica_draining",
            "1 while this replica is draining (refusing new requests)")
        self._drain_gauge.set(0)

    # -- serving -----------------------------------------------------------
    def start(self):
        self.engine.start()
        return self

    def submit(self, *args, **kwargs):
        return self.engine.submit(*args, **kwargs)

    def export_aot(self, store=None):
        """Serialize the engine's compiled programs into an AOT store
        (``singa_tpu.aot``) so the replica that replaces this one —
        rolling restart, failover respawn — deserializes instead of
        retracing. Delegates to ``engine.export_aot``."""
        return self.engine.export_aot(store)

    @property
    def draining(self):
        return self.engine.draining

    def queue_depth(self):
        return len(self.engine.queue)

    def health(self):
        """Engine + membership view (what the gateway's ``/healthz``
        serves)."""
        eng = self.engine
        doc = {
            "name": self.name,
            "status": ("crashed" if eng._crashed is not None
                       else "draining" if eng.draining
                       else "serving"),
            "queue_depth": len(eng.queue),
            "active_slots": getattr(eng, "active_slots",
                                    lambda: None)(),
            "compiled": eng.compiled_step_info(),
        }
        if self.cluster is not None:
            try:
                doc["cluster"] = self.cluster.health()
            except Exception as e:      # noqa: BLE001 — health is advisory
                doc["cluster"] = {"error": f"{type(e).__name__}: {e}"}
        return doc

    # -- drain -------------------------------------------------------------
    def request_drain(self):
        """Mark the replica draining and wake whoever is blocked in
        :meth:`run_until_drained`. Idempotent, signal-safe (this is the
        SIGTERM handler's body: no joins, no blocking)."""
        self._drain_gauge.set(1)
        self.engine._draining = True    # refuse from this instant
        self.engine._wake.set()
        self._drain_evt.set()

    def drain(self, timeout=60.0):
        """Execute the full drain: finish everything in flight, close
        the cluster seat, stop the loop. Returns the process exit code —
        :data:`EXIT_DRAINED` (0) on a clean drain, 1 when work had to be
        abandoned (timeout or a crashed serve loop)."""
        self.request_drain()
        with _spans.span("serve.drain", replica=self.name):
            ok = self.engine.drain(timeout=timeout)
        if self.cluster is not None:
            try:
                self.cluster.close()
            except Exception:   # a dead coordinator must not dirty a
                pass            # clean drain
        self.engine.stop()
        return EXIT_DRAINED if ok else 1

    def install_signal_handlers(self, signals=(signal.SIGTERM,
                                               signal.SIGINT)):
        """SIGTERM/SIGINT → :meth:`request_drain` (the handler only
        flips flags; the blocking drain runs in
        :meth:`run_until_drained` on the main thread)."""
        for s in signals:
            signal.signal(s, lambda _s, _f: self.request_drain())
        return self

    def run_until_drained(self, poll=0.25, timeout=60.0):
        """Block the main thread until a drain is requested (signal,
        gateway, or :meth:`request_drain`), then drain and return the
        exit code. A serve-loop crash also unblocks — with exit code 1
        (the blackbox is already on disk by then)."""
        while not self._drain_evt.wait(poll):
            if self.engine._crashed is not None:
                return 1
        return self.drain(timeout=timeout)


class FleetRouter:
    """Least-depth dispatch over in-process replicas with failover on
    refusal (draining replica / full queue). Raises
    :class:`~singa_tpu.serving.scheduler.ServingError` only when EVERY
    replica refused — one live replica absorbs the whole queue."""

    def __init__(self, replicas, registry=None):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.replicas = list(replicas)
        reg = registry if registry is not None \
            else _metrics.default_registry()
        self._submitted = reg.counter(
            "serve_fleet_submitted_total",
            "requests the router placed on some replica")
        self._failovers = reg.counter(
            "serve_fleet_failover_total",
            "submissions that had to skip a refusing replica")
        self._rejected = reg.counter(
            "serve_fleet_rejected_total",
            "submissions every replica refused")

    @staticmethod
    def _depth(r):
        try:
            return r.queue_depth() if hasattr(r, "queue_depth") \
                else len(r.engine.queue) if hasattr(r, "engine") \
                else len(r.queue)
        except Exception:       # noqa: BLE001 — routing hint only
            return 0

    def submit(self, *args, **kwargs):
        order = sorted(self.replicas,
                       key=lambda r: (bool(r.draining), self._depth(r)))
        last_exc = None
        for r in order:
            try:
                fut = r.submit(*args, **kwargs)
            except (EngineDraining, QueueFull) as e:
                last_exc = e
                self._failovers.inc()
                # the failover joins the request's timeline: a traced
                # request shows WHICH replica refused it and why
                ev = {"replica": getattr(r, "name", None),
                      "reason": type(e).__name__}
                if kwargs.get("trace_id"):
                    ev["request"] = kwargs["trace_id"]
                _spans.event("request.failover", **ev)
                continue
            self._submitted.inc()
            return fut
        self._rejected.inc()
        raise ServingError(
            f"all {len(self.replicas)} replicas refused the request "
            f"(last: {last_exc})")

    def drain_replica(self, idx, timeout=60.0):
        """Drain ONE replica (rolling-restart building block); the
        router's failover routes everything new to the survivors."""
        return self.replicas[idx].drain(timeout=timeout)

    def health(self):
        return [r.health() if hasattr(r, "health") else None
                for r in self.replicas]


__all__ = ["ServingReplica", "FleetRouter", "EXIT_DRAINED"]
