"""Drainable replicas and fault-tolerant client-side fleet routing.

One serving process = one :class:`ServingReplica`: an engine plus
(optionally) a membership seat in a ``resilience.cluster`` pod — the
same coordinator/worker control plane training uses for health,
heartbeat-carried metric summaries, and dead-peer detection, so a
serving fleet's coordinator health report looks exactly like a training
pod's.

The drain contract (the serving sibling of the exit-75/76 supervisor
table):

1. something asks the replica to drain (SIGTERM, the gateway's
   ``POST /drain``, or :meth:`ServingReplica.drain` directly);
2. the engine refuses new requests **loudly** — ``submit`` raises
   :class:`~singa_tpu.serving.scheduler.EngineDraining`, the gateway
   returns 503 — so a router fails the traffic over instead of letting
   it rot;
3. every request already admitted (queued or mid-decode) runs to a
   normal response: a drained replica drops NOTHING (chaos-proved by
   the ``serve-drain`` scenario in ``tools/chaos_smoke.py``);
4. the replica leaves its cluster seat and exits
   :data:`EXIT_DRAINED` (0) — "done, on purpose": a supervisor must NOT
   relaunch it (75 means relaunch, 76 means cordon, 0 means the drain
   you asked for completed).

:class:`FleetRouter` is the fault-tolerant client half (tests, chaos
drivers, single-host multi-engine setups; across hosts the same logic
belongs to any LB that honors the gateway's refusal codes — the router
documents the semantics, it does not replace your LB). Three layers:

- **Health-gated dispatch** — every submit/settle outcome is
  classified per replica through a :class:`CircuitBreaker`:
  ``threshold`` consecutive replica failures (crashed engine, wire
  error, per-try timeout) eject it into ``open`` with capped
  exponential backoff; after the backoff ONE half-open probe re-admits
  it (success closes, failure re-opens with a doubled delay). Open
  replicas are skipped in the dispatch order — never probed more often
  than the backoff allows — and a replica whose depth can't even be
  read sorts *last*.
- **Exactly-once re-dispatch** — ``submit`` returns a
  :class:`FleetFuture` that owns delivery. When the holding replica
  crashes (or a ``per_try_timeout`` fires) the request — pure submit
  args, idempotent by construction, token-identical on any replica
  under greedy decode — is resubmitted to a survivor with its
  **remaining deadline budget**, never a reset clock. The future
  fulfills exactly once (a late original is simply never consumed; a
  second fulfillment attempt raises, mirroring ``ServeFuture``'s
  tested double-delivery guard), so a budget-exhausted request fails
  typed (:class:`~singa_tpu.serving.scheduler.RequestTimeout` → 504)
  exactly once instead of hanging silently.
- **Graceful degradation** — a :class:`ShedPolicy` turns sustained
  ``QueueFull``/``BlockPoolExhausted`` backpressure into typed
  fast-fail :class:`~singa_tpu.serving.scheduler.RequestShed` errors
  carrying ``retry_after`` (the gateway's ``Retry-After`` header), and
  an optional brownout hook steps request cost down
  (``max_new_tokens``, speculative drafting) before refusing outright.

When replicas carry pool roles (``build_engine(pool_role=...)``) the
router also runs **disaggregated prefill/decode**: fresh prompts land
on the prefill pool; each finished prefill's sealed KV snapshot
transfers to a decode replica chosen by **prefix affinity**
(rendezvous hash of the prompt's block-aligned prefix chain — the
same keys the paged prefix cache uses, so repeated prefixes keep
hitting the replica whose cache is already warm). Every transfer edge
is defended: CRC refusal or a dropped frame retries ONCE on the
next-best peer with a freshly re-sealed snapshot; duplicate
deliveries are discarded by the exactly-once guard; a decode replica
dying mid-request re-dispatches through the FleetFuture budget,
resuming from its newest KV checkpoint; and a saturated decode pool
degrades down a ladder — brownout (shrink ``max_new_tokens``) →
colocate fallback (the prefill replica decodes end-to-end) → typed
:class:`~singa_tpu.serving.scheduler.PoolSaturated` shed.
"""

from __future__ import annotations

import signal
import threading
import time

from ..observability import metrics as _metrics
from ..observability import spans as _spans
from .kv_cache import affinity_hash, prefix_chain_key
from .scheduler import (BlockPoolExhausted, EngineDraining,
                        HandoffRefused, PoolSaturated, QueueFull,
                        ReplicaCrashed, RequestShed, RequestTimeout,
                        ServingError, budget_remaining, deadline_in)

# the drain exit code: intentional, successful, do-not-relaunch — the
# 0 row of the README's supervisor exit-code contract table
EXIT_DRAINED = 0

# submit-time refusals that mean "try a healthier replica, this one is
# ALIVE but won't take the request" — failover fodder, not breaker fodder
_BACKPRESSURE = (EngineDraining, QueueFull, BlockPoolExhausted)
# submit-time failures that mean "this REPLICA is broken" — breaker
# fodder (ConnectionError ⊂ OSError covers real wire deaths and the
# injected fail_submit fault)
_REPLICA_FAILURES = (ReplicaCrashed, OSError)

BREAKER_CLOSED = "closed"
BREAKER_HALF_OPEN = "half_open"
BREAKER_OPEN = "open"
_BREAKER_GAUGE = {BREAKER_CLOSED: 0, BREAKER_HALF_OPEN: 1,
                  BREAKER_OPEN: 2}


class ServingReplica:
    """One engine + one (optional) cluster seat + the drain contract."""

    def __init__(self, engine, *, cluster=None, name="replica",
                 registry=None):
        self.engine = engine
        self.cluster = cluster
        self.name = str(name)
        self._reg = registry if registry is not None \
            else _metrics.default_registry()
        self._drain_evt = threading.Event()
        # preemption budget: request_drain(deadline=) pins the drain's
        # absolute deadline here (monotonic), so the blocking drain —
        # wherever it runs — honors the budget the preemption gave us
        self._drain_deadline_at = None
        self._drain_gauge = self._reg.gauge(
            "serve_replica_draining",
            "1 while this replica is draining (refusing new requests)")
        self._drain_gauge.set(0)

    # -- serving -----------------------------------------------------------
    def start(self):
        self.engine.start()
        return self

    def submit(self, *args, **kwargs):
        return self.engine.submit(*args, **kwargs)

    def export_aot(self, store=None):
        """Serialize the engine's compiled programs into an AOT store
        (``singa_tpu.aot``) so the replica that replaces this one —
        rolling restart, failover respawn — deserializes instead of
        retracing. Delegates to ``engine.export_aot``."""
        return self.engine.export_aot(store)

    @property
    def draining(self):
        return self.engine.draining

    @property
    def pool_role(self):
        """This replica's disaggregated-pool role (``prefill`` |
        ``decode`` | ``colocated`` — the engine's ``pool_role``
        build option; engines that predate pools read colocated)."""
        return getattr(self.engine, "pool_role", None) or "colocated"

    def queue_depth(self):
        return len(self.engine.queue)

    def health(self):
        """Engine + membership view (what the gateway's ``/healthz``
        serves)."""
        eng = self.engine
        doc = {
            "name": self.name,
            "status": ("crashed" if eng._crashed is not None
                       else "draining" if eng.draining
                       else "serving"),
            "pool_role": self.pool_role,
            "queue_depth": len(eng.queue),
            "active_slots": getattr(eng, "active_slots",
                                    lambda: None)(),
            "compiled": eng.compiled_step_info(),
        }
        if eng.draining and self._drain_deadline_at is not None:
            # preemption honesty: how much budget the drain has left
            doc["drain_deadline_s"] = round(
                budget_remaining(self._drain_deadline_at), 4)
        if self.cluster is not None:
            try:
                doc["cluster"] = self.cluster.health()
            except Exception as e:      # noqa: BLE001 — health is advisory
                doc["cluster"] = {"error": f"{type(e).__name__}: {e}"}
        return doc

    # -- drain -------------------------------------------------------------
    def request_drain(self, deadline=None):
        """Mark the replica draining and wake whoever is blocked in
        :meth:`run_until_drained`. Idempotent, signal-safe (this is the
        SIGTERM handler's body: no joins, no blocking). ``deadline``
        (seconds) arms a preemption budget: the blocking drain uses it
        instead of its default timeout, migrating what cannot finish
        when a handoff callable is armed."""
        if deadline is not None:
            self._drain_deadline_at = \
                time.monotonic() + float(deadline)
        self._drain_gauge.set(1)
        self.engine._draining = True    # refuse from this instant
        self.engine._wake.set()
        self._drain_evt.set()

    def drain(self, timeout=60.0, handoff=None):
        """Execute the full drain: finish everything in flight (or,
        with ``handoff`` and a deadline budget, migrate what does not
        fit — see ``engine.drain``), close the cluster seat, stop the
        loop. Returns the process exit code — :data:`EXIT_DRAINED` (0)
        on a clean drain, 1 when work had to be abandoned (timeout or
        a crashed serve loop). A stop after a deadline drain fails any
        leftovers typed (:class:`EngineDraining`) so a fleet router
        re-dispatches them — nothing is ever left unresolved."""
        self.request_drain()
        if self._drain_deadline_at is not None:
            # the preemption's budget, not the caller's default
            timeout = budget_remaining(self._drain_deadline_at)
        with _spans.span("serve.drain", replica=self.name):
            ok = self.engine.drain(timeout=timeout, handoff=handoff)
        if self.cluster is not None:
            try:
                self.cluster.close()
            except Exception:   # a dead coordinator must not dirty a
                pass            # clean drain
        self.engine.stop()
        return EXIT_DRAINED if ok else 1

    def install_signal_handlers(self, signals=(signal.SIGTERM,
                                               signal.SIGINT),
                                deadline=None):
        """SIGTERM/SIGINT → :meth:`request_drain` (the handler only
        flips flags; the blocking drain runs in
        :meth:`run_until_drained` on the main thread). ``deadline``
        arms the preemption budget the signal carries — a TPU
        maintenance SIGTERM gives seconds, not minutes."""
        for s in signals:
            signal.signal(
                s, lambda _s, _f: self.request_drain(deadline=deadline))
        return self

    def run_until_drained(self, poll=0.25, timeout=60.0, handoff=None):
        """Block the main thread until a drain is requested (signal,
        gateway, or :meth:`request_drain`), then drain and return the
        exit code. A serve-loop crash also unblocks — with exit code 1
        (the blackbox is already on disk by then). ``handoff`` is the
        deadline drain's migration callable (``engine.drain``)."""
        while not self._drain_evt.wait(poll):
            if self.engine._crashed is not None:
                return 1
        return self.drain(timeout=timeout, handoff=handoff)


class CircuitBreaker:
    """Per-replica health gate: ``closed`` → (``threshold`` consecutive
    failures) → ``open`` for ``backoff × 2^(opens-1)`` seconds (capped)
    → ONE ``half_open`` probe → ``closed`` on success, back to ``open``
    with a doubled delay on failure. Any success resets both the
    failure streak and the backoff ladder.

    Pure state machine over an injected clock — the router owns
    locking and metrics; tier-1 tests drive transitions with a fake
    ``now``."""

    def __init__(self, threshold=3, backoff=0.25, backoff_cap=30.0):
        self.threshold = max(1, int(threshold))
        self.backoff = float(backoff)
        self.backoff_cap = float(backoff_cap)
        self.state = BREAKER_CLOSED
        self.consecutive_failures = 0
        self.opens = 0              # backoff ladder position
        self.open_until = 0.0
        self.probe_inflight = False

    def admits(self, now):
        """May the router dispatch to this replica right now? True
        while closed; an open breaker admits exactly ONE probe once
        its backoff has elapsed (``begin_probe`` must claim it)."""
        if self.state == BREAKER_CLOSED:
            return True
        if self.probe_inflight:
            return False
        return self.state == BREAKER_HALF_OPEN or now >= self.open_until

    def begin_probe(self, now):
        """Claim the single half-open probe slot before dispatching to
        a non-closed breaker's replica."""
        self.state = BREAKER_HALF_OPEN
        self.probe_inflight = True

    def record_success(self, now):
        self.state = BREAKER_CLOSED
        self.consecutive_failures = 0
        self.opens = 0
        self.probe_inflight = False

    def record_failure(self, now):
        """One replica failure (submit OR settle). Returns True when
        this failure tripped the breaker open."""
        self.probe_inflight = False
        self.consecutive_failures += 1
        if self.state == BREAKER_HALF_OPEN \
                or self.consecutive_failures >= self.threshold:
            self.opens += 1
            delay = min(self.backoff_cap,
                        self.backoff * (2 ** (self.opens - 1)))
            self.open_until = now + delay
            self.state = BREAKER_OPEN
            return True
        return False


def brownout_shrink_generation(kwargs):
    """Default brownout hook: halve ``max_new_tokens`` (floor 1).
    Returns the stepped-down submit kwargs, or ``None`` when there is
    nothing left to shrink (→ the shed policy refuses instead)."""
    mnt = int(kwargs.get("max_new_tokens", 16))
    if mnt <= 1:
        return None
    return dict(kwargs, max_new_tokens=max(1, mnt // 2))


class ShedPolicy:
    """Sustained-backpressure detector + typed fast-fail shed.

    Every all-replicas-backpressured submit records one event; once
    ``threshold`` events land within ``window_s`` seconds the fleet is
    *sustainedly* overloaded and the router stops queueing into
    timeouts: the optional ``brownout`` hook (``kwargs → kwargs|None``,
    e.g. :func:`brownout_shrink_generation`) gets one chance to step
    the request's cost down; if there is no hook (or it declines) the
    request fails fast with :class:`RequestShed` carrying
    ``retry_after`` — the gateway's ``Retry-After`` contract."""

    def __init__(self, window_s=5.0, threshold=8, retry_after=1.0,
                 brownout=None):
        self.window_s = float(window_s)
        self.threshold = max(1, int(threshold))
        self.retry_after = float(retry_after)
        self.brownout = brownout
        self._events = []

    def _trim(self, now):
        cutoff = now - self.window_s
        self._events = [t for t in self._events if t >= cutoff]

    def record_backpressure(self, now):
        self._events.append(now)
        self._trim(now)

    def sustained(self, now):
        self._trim(now)
        return len(self._events) >= self.threshold

    def apply_brownout(self, kwargs):
        """Stepped-down kwargs, or None (no hook / hook declined)."""
        if self.brownout is None:
            return None
        return self.brownout(dict(kwargs))


class FleetFuture:
    """A fleet-level response slot that OWNS delivery across replica
    failures. Wraps the current attempt's ``ServeFuture``; crashes,
    delivered backpressure, and per-try timeouts re-dispatch the
    request (pure submit args) to a survivor with the **remaining
    deadline budget** — never a reset clock. Fulfills exactly once: a
    late result from a superseded attempt is never consumed, and a
    second fulfillment attempt raises (the ``ServeFuture`` guard,
    fleet-level).

    ``result(timeout)`` is the drive loop (same surface as
    ``ServeFuture.result``); ``deliveries`` / ``attempts`` /
    ``redispatches`` are the chaos-test counters. Like stdlib futures,
    completion happens inside ``result`` — poll ``done()`` only after
    some caller has driven it."""

    def __init__(self, router, args, kwargs):
        self._router = router
        self._args = tuple(args)
        self._kwargs = dict(kwargs)
        # the ONE clock this request lives on: every re-dispatch's
        # engine-side timeout is derived from this deadline's remainder
        self._deadline = deadline_in(self._kwargs.get("timeout"),
                                     now=router._clock())
        self._flock = threading.Lock()
        self._drive = threading.Lock()
        self._event = threading.Event()
        self._result = None
        self._error = None
        self.deliveries = 0
        self.attempts = 0
        self.redispatches = 0
        self._idx = None            # current attempt's replica index
        self._fut = None            # current attempt's ServeFuture

    # -- exactly-once fulfillment (mirrors ServeFuture) --------------------
    def _fulfill(self, result=None, error=None):
        with self._flock:
            self.deliveries += 1
            if self._event.is_set():
                raise RuntimeError(
                    "double delivery: this request already has a "
                    "response (exactly-once violation)")
            self._result = result
            self._error = error
            self._event.set()
        # terminal: release any decode-holder record this request
        # pinned (pool transfers track the replica holding the KV)
        self._router._forget_trace(self._kwargs.get("trace_id"))

    def done(self):
        return self._event.is_set()

    def _finish(self):
        if self._error is not None:
            raise self._error
        return self._result

    # -- dispatch ----------------------------------------------------------
    def _first_dispatch(self):
        self._idx, self._fut = self._router._place(self._args,
                                                   self._kwargs)
        self.attempts = 1

    def _redispatch(self, reason, cause):
        """Place the request on a survivor with the remaining budget,
        or fulfill a terminal typed error exactly once and raise it."""
        rt = self._router
        budget = budget_remaining(self._deadline, rt._clock())
        if budget is not None and budget <= 0.0:
            err = RequestTimeout(
                f"deadline budget exhausted after {self.attempts} "
                f"attempt(s) (last replica failure: {reason})")
            err.__cause__ = cause
            self._fulfill(error=err)
            raise err
        if self.redispatches >= rt.max_redispatch:
            err = ServingError(
                f"request failed on {self.attempts} replica(s) "
                f"(re-dispatch limit {rt.max_redispatch} reached; "
                f"last: {cause})")
            err.__cause__ = cause
            self._fulfill(error=err)
            raise err
        kwargs = dict(self._kwargs)
        if budget is not None:
            # the remainder, NEVER a fresh full timeout: attempt N+1's
            # engine-side deadline coincides with the original one
            kwargs["timeout"] = budget
        # checkpoint rung first: a banked KV snapshot on the failed
        # replica resumes decode where it left off instead of
        # recomputing the prompt + every generated token from zero
        try:
            resumed = rt._resume_from_checkpoint(self, budget)
        except Exception:   # noqa: BLE001 — recovery rung, best-effort
            resumed = None
        if resumed is not None:
            idx, fut = resumed
        else:
            try:
                idx, fut = rt._place(self._args, kwargs,
                                     exclude=(self._idx,))
            except ServingError as e:
                # no survivor could take it — terminal, exactly once
                self._fulfill(error=e)
                raise
        rt._redispatches.inc()
        ev = {"from_replica": rt._name(self._idx),
              "to_replica": rt._name(idx), "reason": reason,
              "attempt": self.attempts + 1}
        if budget is not None:
            ev["budget_s"] = round(budget, 4)
        if self._kwargs.get("trace_id"):
            ev["request"] = self._kwargs["trace_id"]
        _spans.event("request.redispatch", **ev)
        self._idx, self._fut = idx, fut
        self.attempts += 1
        self.redispatches += 1

    # -- the drive loop ----------------------------------------------------
    def result(self, timeout=None):
        """Block for the response, re-dispatching across replica
        failures; re-raises the request's (typed) error. ``timeout``
        bounds THIS caller's wait — the request's own deadline budget
        (from its ``timeout`` submit kwarg) bounds the retries."""
        if self._event.is_set():
            return self._finish()
        rt = self._router
        wall = deadline_in(timeout, now=rt._clock())
        with self._drive:
            if self._event.is_set():
                return self._finish()
            while True:
                now = rt._clock()
                budget = budget_remaining(self._deadline, now)
                caller = budget_remaining(wall, now)
                wait, why = None, None
                for w, k in ((budget, "budget"), (caller, "caller"),
                             (rt.per_try_timeout, "per_try")):
                    if w is not None and (wait is None or w < wait):
                        wait, why = w, k
                try:
                    res = self._fut.result(timeout=wait)
                except RequestTimeout as e:
                    if self._fut.done():
                        # the ENGINE delivered the timeout: the
                        # request's own deadline expired server-side —
                        # the budget is spent, terminal
                        self._fulfill(error=e)
                        raise
                    if why == "caller":
                        # this caller's patience ran out, not the
                        # request's budget: still in flight — mirror
                        # ServeFuture (no fulfillment, call again)
                        raise
                    if why == "budget":
                        err = RequestTimeout(
                            f"deadline budget exhausted after "
                            f"{self.attempts} attempt(s) (request "
                            "still in flight on the last replica)")
                        self._fulfill(error=err)
                        raise err
                    # per-try timeout: the replica is straggling —
                    # breaker failure + re-dispatch with the remainder
                    rt._record_failure(self._idx, "per_try_timeout")
                    self._redispatch("per_try_timeout", e)
                except _BACKPRESSURE as e:
                    # DELIVERED backpressure (hard-stopped engine, 503
                    # from a wire replica): it never served the
                    # request, so re-dispatch is trivially exactly-once
                    self._redispatch(type(e).__name__, e)
                except _REPLICA_FAILURES as e:
                    # the holding replica died with the request
                    # admitted (the stranded shape); for a transferred
                    # request the DECODE replica holding the KV gets
                    # the breaker blame, not just the placement slot
                    rt._record_failure(self._idx, type(e).__name__)
                    rt._fail_holder(self._kwargs.get("trace_id"),
                                    type(e).__name__)
                    self._redispatch(type(e).__name__, e)
                except ServingError as e:
                    # request-shaped failure: it would fail the same
                    # way on every replica — terminal, exactly once
                    self._fulfill(error=e)
                    raise
                else:
                    rt._record_success(self._idx)
                    self._fulfill(result=res)
                    return res


class FleetRouter:
    """Health-gated least-depth dispatch over in-process replicas with
    circuit breakers, exactly-once re-dispatch, and load shedding (see
    module docstring). ``submit`` returns a :class:`FleetFuture`;
    it raises only when NO admitted replica accepted the request —
    typed :class:`RequestShed` under a sustained-backpressure shed,
    plain ``ServingError`` otherwise.

    ``per_try_timeout`` (seconds, default None=off) bounds ONE
    replica's attempt; a request whose deadline budget still has
    remainder when it fires is re-dispatched to a survivor with that
    remainder. ``max_redispatch`` caps re-dispatches per request.

    Membership is dynamic: :meth:`add_replica` admits a new replica
    into dispatch, :meth:`remove_replica` retires a slot. Removal
    TOMBSTONES the slot (``replicas[idx] is None``) instead of
    shifting the list — in-flight :class:`FleetFuture`\\ s hold their
    origin index for crash re-dispatch exclusion, so indices must stay
    stable for the router's lifetime."""

    def __init__(self, replicas, registry=None, *,
                 breaker_threshold=3, breaker_backoff=0.25,
                 breaker_backoff_cap=30.0, per_try_timeout=None,
                 max_redispatch=2, shed_policy=None, clock=None,
                 affinity_block_size=None, pool_shed=None,
                 affinity_routing=True):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.replicas = list(replicas)
        self.per_try_timeout = per_try_timeout if per_try_timeout \
            is None else float(per_try_timeout)
        self.max_redispatch = int(max_redispatch)
        self.shed_policy = shed_policy
        self._clock = clock if clock is not None else time.monotonic
        self._blk = threading.Lock()
        self._breaker_params = (breaker_threshold, breaker_backoff,
                                breaker_backoff_cap)
        self._breakers = [CircuitBreaker(breaker_threshold,
                                         breaker_backoff,
                                         breaker_backoff_cap)
                          for _ in self.replicas]
        # last-known names of tombstoned slots (health/trace labels
        # must keep naming a slot after its replica object is gone)
        self._slot_names = {}
        reg = registry if registry is not None \
            else _metrics.default_registry()
        self._reg = reg
        self._submitted = reg.counter(
            "serve_fleet_submitted_total",
            "requests the router placed on some replica")
        self._failovers = reg.counter(
            "serve_fleet_failover_total",
            "submissions that had to skip a refusing replica")
        self._rejected = reg.counter(
            "serve_fleet_rejected_total",
            "submissions every admitted replica refused")
        self._redispatches = reg.counter(
            "serve_fleet_redispatch_total",
            "requests re-dispatched to a survivor after a replica "
            "crash / delivered backpressure / per-try timeout")
        self._sheds = reg.counter(
            "serve_fleet_shed_total",
            "requests fast-failed by the shed policy under sustained "
            "backpressure (typed RequestShed, Retry-After at the "
            "gateway)")
        self._brownouts = reg.counter(
            "serve_fleet_brownout_total",
            "requests stepped down by the shed policy's brownout hook "
            "instead of being refused")
        self._handoffs = reg.counter(
            "serve_fleet_handoff_total",
            "drain-deadline requests migrated to a survivor (live-KV "
            "inject or mid-flight recompute) instead of being dropped")
        self._resumes = reg.counter(
            "serve_fleet_resume_total",
            "crash re-dispatches that resumed from a KV checkpoint "
            "instead of recomputing from token zero")
        self._breaker_opens = reg.counter(
            "serve_fleet_breaker_open_total",
            "circuit-breaker trips (replica ejected from dispatch)",
            labels=("replica",))
        self._probes = reg.counter(
            "serve_fleet_probe_total",
            "half-open breaker probes dispatched",
            labels=("replica",))
        self._breaker_state = reg.gauge(
            "serve_fleet_breaker_state",
            "per-replica breaker state: 0=closed 1=half_open 2=open",
            labels=("replica",))
        for i in range(len(self.replicas)):
            self._breaker_state.set(0, replica=self._name(i))
        # -- disaggregated prefill/decode pool state -----------------
        # trace_id → decode slot index while a transferred request is
        # decoding away from the replica its FleetFuture points at —
        # crash recovery and breaker blame must follow the KV, not the
        # prefill replica that long since forgot the request
        self._decode_holder = {}
        # prefix chain key → decode replica name that last served it
        # (affinity hit/miss accounting; rendezvous hashing does the
        # actual placement so this is observation, not state)
        self._prefix_owner = {}
        self._aff_bs = (int(affinity_block_size)
                        if affinity_block_size is not None else None)
        # affinity_routing=False is the A/B baseline knob: decode
        # placement round-robins instead of rendezvous-hashing (hit /
        # miss accounting unchanged, so the two legs compare directly)
        self._affinity = bool(affinity_routing)
        self._rr = 0
        # decode-pool pressure window: feeds the ladder (brownout →
        # colocate → typed PoolSaturated); separate from shed_policy
        # so generic overload and pool saturation stay distinguishable
        self._pool_pressure = pool_shed if pool_shed is not None \
            else ShedPolicy(window_s=5.0, threshold=4, retry_after=1.0)
        self._pool_metrics_ready = False
        self._arm_transfers()

    def _name(self, idx):
        r = self.replicas[idx]
        if r is None:
            return self._slot_names.get(idx, str(idx))
        return getattr(r, "name", None) or str(idx)

    # -- membership --------------------------------------------------------
    def add_replica(self, replica):
        """Admit a replica into dispatch (fresh closed breaker).
        Returns its slot index. The caller owns readiness: admit only
        replicas that already answer ``/healthz``-level probes — the
        autoscaler's warm-admission gate lives above this."""
        if replica is None:
            raise ValueError("cannot add a None replica")
        with self._blk:
            self.replicas.append(replica)
            self._breakers.append(CircuitBreaker(*self._breaker_params))
            idx = len(self.replicas) - 1
            self._set_state_gauge(idx)
        # membership changed: re-arm transfer hooks (a new prefill
        # replica starts transferring; a new decode replica enters
        # every prefill replica's rendezvous candidate set)
        self._arm_transfers()
        _spans.event("fleet.replica_added",
                     replica=self._name(idx), slot=idx)
        return idx

    def remove_replica(self, idx):
        """Tombstone slot ``idx`` and return its replica (None if the
        slot was already empty). The slot never dispatches again; its
        index is never reused. Call AFTER the replica is drained or
        declared dead — removal does not stop the engine."""
        with self._blk:
            r = self.replicas[idx]
            if r is not None:
                self._slot_names[idx] = \
                    getattr(r, "name", None) or str(idx)
            self.replicas[idx] = None
        if r is not None:
            _spans.event("fleet.replica_removed",
                         replica=self._slot_names[idx], slot=idx)
        return r

    def live_replicas(self):
        """``[(idx, replica)]`` for the non-tombstoned slots."""
        with self._blk:
            return [(i, r) for i, r in enumerate(self.replicas)
                    if r is not None]

    def population(self):
        """Live (non-tombstoned) replica count."""
        with self._blk:
            return sum(1 for r in self.replicas if r is not None)

    @staticmethod
    def _depth(r):
        try:
            return r.queue_depth() if hasattr(r, "queue_depth") \
                else len(r.engine.queue) if hasattr(r, "engine") \
                else len(r.queue)
        except Exception:       # noqa: BLE001 — routing hint only
            # unreadable depth = suspect replica: sort it LAST (0 would
            # make the sickest replica the most attractive target)
            return float("inf")

    # -- breaker bookkeeping (all under _blk) ------------------------------
    def _set_state_gauge(self, idx):
        self._breaker_state.set(
            _BREAKER_GAUGE[self._breakers[idx].state],
            replica=self._name(idx))

    def _record_success(self, idx):
        with self._blk:
            self._breakers[idx].record_success(self._clock())
            self._set_state_gauge(idx)

    def _record_failure(self, idx, reason):
        with self._blk:
            br = self._breakers[idx]
            opened = br.record_failure(self._clock())
            self._set_state_gauge(idx)
        if opened:
            self._breaker_opens.inc(replica=self._name(idx))
            _spans.event("replica.breaker_open",
                         replica=self._name(idx), reason=reason,
                         consecutive=br.consecutive_failures,
                         backoff_s=round(br.open_until
                                         - self._clock(), 4))

    def breaker_states(self):
        """{replica name: breaker state} — /healthz fodder
        (tombstoned slots omitted)."""
        with self._blk:
            return {self._name(i): br.state
                    for i, br in enumerate(self._breakers)
                    if self.replicas[i] is not None}

    # -- disaggregated prefill/decode pools --------------------------------
    def _role(self, idx):
        """Pool role of slot ``idx`` ('prefill' | 'decode' |
        'colocated'). Reads the replica's ``pool_role`` first (wire
        replicas carry a plain attribute), then the engine's; anything
        unset is colocated. Lock-free (attribute reads only), safe
        under ``_blk``."""
        r = self.replicas[idx]
        if r is None:
            return "colocated"
        role = getattr(r, "pool_role", None)
        if not role:
            role = getattr(getattr(r, "engine", None), "pool_role",
                           None)
        return role or "colocated"

    def pools_enabled(self):
        """True when at least one live replica is decode-role — the
        switch that turns on role-aware placement, KV transfer, and
        the decode-pool degradation ladder."""
        with self._blk:
            return any(r is not None and self._role(i) == "decode"
                       for i, r in enumerate(self.replicas))

    def _arm_transfers(self):
        """Arm every live prefill-role engine's transfer hook (the
        engine calls it after each prefill pass with the finished
        slot's sealed snapshot). Idempotent; re-run whenever
        membership changes so a scaled-up prefill replica starts
        transferring immediately."""
        if not self.pools_enabled():
            return
        for i, r in self.live_replicas():
            if self._role(i) != "prefill":
                continue
            eng = getattr(r, "engine", r)
            set_transfer = getattr(eng, "set_transfer", None)
            if set_transfer is not None:
                set_transfer(self._make_transfer(i))

    def _ensure_pool_metrics(self):
        if self._pool_metrics_ready:
            return
        reg = self._reg
        self._pool_transfers = reg.counter(
            "serve_pool_transfer_total",
            "prefill→decode KV transfers that a decode replica "
            "accepted (slot freed on the prefill side without "
            "fulfilling the future)")
        self._pool_retries = reg.counter(
            "serve_pool_transfer_retry_total",
            "transfer attempts retried on the next-best decode peer "
            "(CRC refusal with a fresh re-snapshot, or a dropped "
            "frame)")
        self._pool_colocates = reg.counter(
            "serve_pool_colocate_fallback_total",
            "transfers that fell back to colocated decode on the "
            "prefill replica (decode pool refused / saturated)")
        self._pool_dups = reg.counter(
            "serve_pool_dup_discarded_total",
            "duplicate transfer deliveries discarded by the "
            "exactly-once guard (second copy never injected)")
        self._pool_aff_hits = reg.counter(
            "serve_pool_affinity_hit_total",
            "transfers routed to the decode replica that last served "
            "the same block-aligned prefix chain")
        self._pool_aff_misses = reg.counter(
            "serve_pool_affinity_miss_total",
            "transfers whose prefix chain was cold or owned by "
            "another decode replica")
        self._pool_brownouts = reg.counter(
            "serve_pool_brownout_total",
            "requests stepped down (max_new halved) under sustained "
            "decode-pool pressure — ladder rung one")
        self._pool_saturated = reg.counter(
            "serve_pool_saturated_total",
            "requests refused typed PoolSaturated after the "
            "degradation ladder ran dry")
        self._pool_depth = reg.gauge(
            "serve_pool_depth",
            "summed queue depth per pool role", labels=("pool",))
        self._pool_metrics_ready = True

    def _affinity_block(self):
        """Block size the affinity hash chunks prompts by. Must match
        the decode pool's paged ``kv_block_size`` so the chain key IS
        the BlockManager's prefix-cache key; falls back to the
        constructor override, then 16 (ring engines have no block
        size but still benefit from stable prefix→replica pinning)."""
        if self._aff_bs is not None:
            return self._aff_bs
        for i, r in self.live_replicas():
            if self._role(i) != "decode":
                continue
            bs = getattr(getattr(r, "engine", None), "kv_block_size",
                         None)
            if bs:
                self._aff_bs = int(bs)
                return self._aff_bs
        return 16

    def _decode_order(self, key, now, exclude=()):
        """Decode-pool candidate indices for a prefix chain ``key``.

        Warm prefix (key not None): rendezvous/HRW order — each
        candidate scores ``affinity_hash(key, salt=name)`` and the
        list sorts highest-score first. Stable across router restarts
        (sha1 of the chain key, not per-process ``hash()``), and when
        membership changes only the keys whose top scorer joined or
        left move — every other prefix keeps its replica, which is
        exactly what keeps the decode-side prefix caches warm. The
        sorted tail doubles as the natural "next-best peer" retry
        order. Cold prefix (key None): least-loaded first. With
        ``affinity_routing=False`` (the measurement baseline) the key
        is ignored and candidates round-robin."""
        with self._blk:
            cands = [i for i, r in enumerate(self.replicas)
                     if r is not None and i not in exclude
                     and self._role(i) == "decode"
                     and self._breakers[i].admits(now)]
            if not self._affinity and cands:
                k = self._rr % len(cands)
                self._rr += 1
                return cands[k:] + cands[:k]
            if key is None:
                return sorted(cands,
                              key=lambda i: (self._depth(
                                  self.replicas[i]), i))
            return sorted(
                cands,
                key=lambda i: affinity_hash(key, salt=self._name(i)),
                reverse=True)

    def _make_transfer(self, pidx):
        """Build prefill replica ``pidx``'s transfer callable:
        ``cb(req, snapshot, resnap) -> bool`` (True = some decode
        replica owns the request now; False = colocate fallback, the
        prefill engine decodes it end-to-end). Every edge is
        defended:

        - CRC refusal / dropped frame → retry ONCE on the next-best
          rendezvous peer with a FRESH re-snapshot (``resnap`` —
          corruption happens at sealing, so resending the same bytes
          would refuse everywhere);
        - duplicate delivery (``dup_transfer`` fault) → the second
          copy is discarded by the exactly-once guard, never
          injected;
        - decode backpressure / no decode pool → pressure evidence
          for the ladder + colocate fallback;
        - decode replica death at inject → breaker failure, next
          peer."""

        def _transfer(req, snapshot, resnap):
            self._ensure_pool_metrics()
            trace = req.trace_id
            with self._blk:
                already = trace in self._decode_holder
            if already:
                # a duplicated EARLIER transfer already owns this
                # request downstream — discard, never double-inject
                self._pool_dups.inc()
                return True
            src = getattr(self.replicas[pidx], "engine",
                          self.replicas[pidx])
            now = self._clock()
            key = prefix_chain_key(req.prompt, self._affinity_block())
            order = self._decode_order(key, now)
            snap = snapshot
            saw_pressure = False
            hard_fails = 0
            for didx in order:
                if hard_fails > 1:
                    break       # retry once on next-best, then ladder
                r = self.replicas[didx]
                if r is None:
                    continue
                eng = getattr(r, "engine", r)
                inject = getattr(eng, "inject_snapshot", None)
                if inject is None:
                    continue
                # the transfer wire: faults may delay, drop, or
                # duplicate the sealed frame here
                frames = src.transfer_deliveries(snap["frame"]) \
                    if hasattr(src, "transfer_deliveries") \
                    else [snap["frame"]]
                if not frames:      # dropped in flight
                    self._pool_retries.inc()
                    hard_fails += 1
                    continue
                fut = None
                refused = False
                for frame in frames:
                    if fut is not None:
                        # duplicated delivery: first copy was
                        # accepted — discard the second
                        self._pool_dups.inc()
                        continue
                    try:
                        fut = inject(snap["meta"], frame,
                                     timeout=budget_remaining(
                                         req.deadline))
                    except HandoffRefused:
                        # CRC/geometry refusal: re-seal FRESH (a new
                        # handoff seq — a times=1 corruption fault
                        # will not re-fire) and try the next peer
                        refused = True
                        break
                    except _BACKPRESSURE:
                        saw_pressure = True
                        break
                    except _REPLICA_FAILURES as e:
                        self._record_failure(didx, type(e).__name__)
                        break
                if refused:
                    self._pool_retries.inc()
                    hard_fails += 1
                    try:
                        snap = resnap()
                    except Exception:   # noqa: BLE001 — slot gone
                        return False
                    if snap is None:
                        return False
                    continue
                if fut is None:
                    if saw_pressure:
                        break
                    continue
                self._record_success(didx)
                with self._blk:
                    self._decode_holder[trace] = didx
                    owner = self._prefix_owner.get(key) \
                        if key is not None else None
                    if key is not None:
                        self._prefix_owner[key] = self._name(didx)
                if key is not None and owner == self._name(didx):
                    self._pool_aff_hits.inc()
                else:
                    self._pool_aff_misses.inc()
                self._pool_transfers.inc()
                _spans.event("request.transfer",
                             from_replica=self._name(pidx),
                             to_replica=self._name(didx),
                             request=trace,
                             affinity=key is not None)
                self._relay_transfer(fut, req.future, trace)
                return True
            if saw_pressure or not order:
                # decode pool refused or does not exist: ladder
                # evidence — sustained pressure escalates submit-time
                # brownout and, past that, typed PoolSaturated
                self._pool_pressure.record_backpressure(now)
            self._pool_colocates.inc()
            return False

        return _transfer

    def _relay_transfer(self, src, dst, trace_id):
        """Pipe the decode replica's future into the original
        request's future, releasing the decode-holder record once the
        response lands (successfully or not — a failed relay leaves
        re-dispatch to the FleetFuture drive loop, which consults the
        holder first)."""

        def _pipe():
            try:
                res = src.result(timeout=None)
            except BaseException as e:      # noqa: BLE001 — relayed
                if not dst.done():
                    dst.set_error(e)
            else:
                self._forget_trace(trace_id)
                if not dst.done():
                    dst.set_result(res)

        threading.Thread(target=_pipe, name="kv-transfer-relay",
                         daemon=True).start()

    def _forget_trace(self, trace_id):
        if not trace_id:
            return
        with self._blk:
            self._decode_holder.pop(trace_id, None)

    def _fail_holder(self, trace_id, reason):
        """Blame the decode replica actually holding a transferred
        request (the FleetFuture's ``_idx`` still points at the
        prefill replica that placed it)."""
        if not trace_id:
            return
        with self._blk:
            idx = self._decode_holder.get(trace_id)
        if idx is not None:
            self._record_failure(idx, reason)

    def pools_summary(self):
        """Per-pool depth + transfer/affinity counters (the
        gateway's ``/healthz`` ``pools`` block and the heartbeat's
        ``serving_pools`` summary). None when pools are disabled."""
        if not self.pools_enabled():
            return None
        self._ensure_pool_metrics()
        pools = {}
        for i, r in self.live_replicas():
            role = self._role(i)
            p = pools.setdefault(role,
                                 {"replicas": 0, "queue_depth": 0})
            p["replicas"] += 1
            d = self._depth(r)
            p["queue_depth"] += int(d) if d != float("inf") else 0
        for role, p in pools.items():
            self._pool_depth.set(p["queue_depth"], pool=role)
        hits = self._pool_aff_hits.total()
        misses = self._pool_aff_misses.total()
        routed = hits + misses
        return {
            "pools": pools,
            "transfers": {
                "transferred": self._pool_transfers.total(),
                "retries": self._pool_retries.total(),
                "colocate_fallback": self._pool_colocates.total(),
                "dup_discarded": self._pool_dups.total(),
            },
            "affinity": {
                "hits": hits, "misses": misses,
                "hit_ratio": (hits / routed) if routed else 0.0,
            },
        }

    def decode_placement(self, prompt):
        """Decode replica names in the order the affinity hash would
        try them for ``prompt`` — introspection for tests and
        capacity planning (stable-hash, minimal-movement, and
        cold-prefix assertions read this instead of poking
        internals)."""
        key = prefix_chain_key(prompt, self._affinity_block())
        return [self._name(i)
                for i in self._decode_order(key, self._clock())]

    # -- placement ---------------------------------------------------------
    def _order(self, now, exclude=(), roles=None):
        """Breaker-admitted replicas, least-depth first, draining
        last; open-but-probe-due replicas carry probing=True.
        ``roles`` (optional set of pool roles) filters candidates."""
        out = []
        with self._blk:
            for i, r in enumerate(self.replicas):
                if i in exclude or r is None:
                    continue
                if roles is not None and self._role(i) not in roles:
                    continue
                br = self._breakers[i]
                if not br.admits(now):
                    continue
                out.append((bool(r.draining), self._depth(r), i,
                            br.state != BREAKER_CLOSED))
        out.sort(key=lambda t: t[:3])
        return [(i, probing) for _d, _q, i, probing in out]

    def _place(self, args, kwargs, exclude=()):
        """One placement pass: try each admitted replica in order.
        Returns ``(idx, serve_future)``; raises typed when nobody took
        the request (RequestShed under a sustained-backpressure shed)."""
        now = self._clock()
        last_exc = None
        saw_replica_failure = False
        if self.pools_enabled():
            # fresh prompts land on the prefill pool (decode peers
            # receive work by KV transfer, not admission) — but a
            # starved prefill pool may still spill onto decode
            # replicas as a last resort before refusing outright
            order = self._order(now, exclude,
                                roles=("prefill", "colocated"))
            seen = {i for i, _p in order}
            order += [(i, p) for i, p
                      in self._order(now, exclude, roles=("decode",))
                      if i not in seen]
        else:
            order = self._order(now, exclude)
        for idx, probing in order:
            r = self.replicas[idx]
            if probing:
                with self._blk:
                    self._breakers[idx].begin_probe(now)
                    self._set_state_gauge(idx)
                self._probes.inc(replica=self._name(idx))
            try:
                fut = r.submit(*args, **kwargs)
            except _BACKPRESSURE as e:
                # alive but refusing: failover fodder (and a probe
                # SUCCESS — the replica answered), plus shed evidence
                last_exc = e
                self._failovers.inc()
                if probing:
                    self._record_success(idx)
                if self.shed_policy is not None and \
                        not isinstance(e, EngineDraining):
                    self.shed_policy.record_backpressure(now)
                self._failover_event(r, e, kwargs)
                continue
            except _REPLICA_FAILURES as e:
                # crashed engine / wire death: breaker fodder — one
                # dead replica must never kill routing while survivors
                # exist
                last_exc = e
                saw_replica_failure = True
                self._failovers.inc()
                self._record_failure(idx, type(e).__name__)
                self._failover_event(r, e, kwargs)
                continue
            except BaseException:
                # request-shaped refusal (bad params, prompt too long):
                # the REPLICA answered — release a claimed probe slot
                # before the error propagates to the caller
                if probing:
                    self._record_success(idx)
                raise
            self._submitted.inc()
            if probing:
                self._record_success(idx)
            return idx, fut
        if not order:
            last_exc = last_exc or ServingError(
                "every replica is ejected (breaker open) or excluded")
        if not saw_replica_failure and self.pools_enabled() \
                and self._pool_pressure.sustained(now):
            # ladder's last rung: brownout stepped down, colocate
            # absorbed what it could, and placement STILL failed —
            # refuse typed so dashboards and callers can tell pool
            # saturation from generic overload
            self._ensure_pool_metrics()
            self._pool_saturated.inc()
            raise PoolSaturated(
                f"decode pool saturated: degradation ladder "
                f"exhausted (brownout + colocate fallback) and no "
                f"replica can place the request (last: {last_exc}); "
                f"retry after {self._pool_pressure.retry_after}s",
                retry_after=self._pool_pressure.retry_after)
        if not saw_replica_failure and self.shed_policy is not None \
                and self.shed_policy.sustained(now):
            self._sheds.inc()
            raise RequestShed(
                f"fleet shedding load: sustained backpressure across "
                f"all {self.population()} replicas (last: "
                f"{last_exc}); retry after "
                f"{self.shed_policy.retry_after}s",
                retry_after=self.shed_policy.retry_after)
        self._rejected.inc()
        raise ServingError(
            f"all {self.population()} replicas refused the request "
            f"(last: {last_exc})")

    @staticmethod
    def _failover_event(r, e, kwargs):
        # the failover joins the request's timeline: a traced request
        # shows WHICH replica refused it and why
        ev = {"replica": getattr(r, "name", None),
              "reason": type(e).__name__}
        if kwargs.get("trace_id"):
            ev["request"] = kwargs["trace_id"]
        _spans.event("request.failover", **ev)

    # -- public surface ----------------------------------------------------
    def submit(self, *args, **kwargs):
        """Place one request; returns a :class:`FleetFuture` (same
        ``result(timeout)`` / ``deliveries`` surface as
        ``ServeFuture``). Under a sustained shed the brownout hook gets
        one chance to step the request down before a typed
        :class:`RequestShed` refusal."""
        if self.pools_enabled() \
                and self._pool_pressure.sustained(self._clock()):
            # decode-pool ladder rung one: shrink generation before
            # anything is refused — shorter decodes drain the pool
            stepped = brownout_shrink_generation(kwargs)
            if stepped is not None:
                self._ensure_pool_metrics()
                self._pool_brownouts.inc()
                kwargs = stepped
        if self.shed_policy is not None \
                and self.shed_policy.sustained(self._clock()):
            stepped = self.shed_policy.apply_brownout(kwargs)
            if stepped is None:
                self._sheds.inc()
                raise RequestShed(
                    "fleet shedding load: sustained backpressure "
                    f"(window {self.shed_policy.window_s}s); retry "
                    f"after {self.shed_policy.retry_after}s",
                    retry_after=self.shed_policy.retry_after)
            if stepped != kwargs:
                self._brownouts.inc()
            kwargs = stepped
        fut = FleetFuture(self, args, kwargs)
        fut._first_dispatch()
        return fut

    def drain_replica(self, idx, timeout=60.0, handoff=False):
        """Drain ONE replica (rolling-restart building block); the
        router's failover routes everything new to the survivors.
        ``handoff=True`` arms live-KV migration: work that cannot
        finish inside the budget moves to a survivor mid-flight
        (snapshot inject, recompute fallback) instead of failing."""
        r = self.replicas[idx]
        if r is None:
            raise ValueError(f"slot {idx} is tombstoned (removed)")
        cb = self._handoff_to_survivors(idx) if handoff else None
        return r.drain(timeout=timeout, handoff=cb)

    # -- live-KV handoff (drain-deadline migration) ------------------------
    def _handoff_to_survivors(self, idx):
        """The draining engine's ``handoff(req, snapshot, budget)``
        callable: the migration ladder. For each survivor in dispatch
        order — (1) inject the sealed KV snapshot (continuation is
        bitwise-identical, zero recomputed prefill); (2) on a typed
        :class:`HandoffRefused` (corrupt frame, geometry mismatch) fall
        back to recompute on the SAME survivor — corrupt KV is never
        injected anywhere; (3) backpressure → next survivor. Returns
        True once some survivor owns the request (a relay thread wires
        its response into the original future), False when nobody could
        take it (the engine then fails it typed → PR-16 re-dispatch)."""

        def _handoff(req, snapshot, budget):
            now = self._clock()
            for sidx, _probing in self._order(now, exclude=(idx,)):
                r = self.replicas[sidx]
                fut = None
                if snapshot is not None:
                    eng = getattr(r, "engine", r)
                    inject = getattr(eng, "inject_snapshot", None)
                    if inject is not None:
                        try:
                            fut = inject(snapshot["meta"],
                                         snapshot["frame"],
                                         timeout=budget)
                        except HandoffRefused:
                            fut = None      # recompute, same survivor
                        except _BACKPRESSURE:
                            continue
                        except _REPLICA_FAILURES as e:
                            self._record_failure(sidx,
                                                 type(e).__name__)
                            continue
                if fut is None:
                    try:
                        # the request's OWN remaining clock, not the
                        # drain budget (that only bounds the handoff)
                        fut = r.submit(
                            list(req.prompt),
                            max_new_tokens=req.max_new_tokens,
                            temperature=req.temperature,
                            top_k=req.top_k, eos_id=req.eos_id,
                            timeout=budget_remaining(req.deadline),
                            trace_id=req.trace_id)
                    except _BACKPRESSURE:
                        continue
                    except _REPLICA_FAILURES as e:
                        self._record_failure(sidx, type(e).__name__)
                        continue
                self._handoffs.inc()
                _spans.event("request.handoff",
                             from_replica=self._name(idx),
                             to_replica=self._name(sidx),
                             request=req.trace_id,
                             migrated=snapshot is not None)
                self._relay(fut, req.future)
                return True
            return False

        return _handoff

    @staticmethod
    def _relay(src, dst):
        """Pipe a survivor's future into the original request's future
        from a daemon thread (the draining engine cannot block on its
        peer's decode loop)."""

        def _pipe():
            try:
                res = src.result(timeout=None)
            except BaseException as e:      # noqa: BLE001 — relayed
                if not dst.done():
                    dst.set_error(e)
            else:
                if not dst.done():
                    dst.set_result(res)

        threading.Thread(target=_pipe, name="kv-handoff-relay",
                         daemon=True).start()

    def _resume_from_checkpoint(self, ffut, budget):
        """Crash-recovery rung above recompute: if the dead replica's
        engine banked a KV checkpoint for this request (snapshot_every
        cadence), inject it into a survivor so decode resumes from the
        last checkpoint instead of token zero. Returns ``(idx, fut)``
        or None (no checkpoint / no engine access / survivor refused
        typed → caller falls through to plain recompute)."""
        trace_id = ffut._kwargs.get("trace_id")
        if not trace_id or ffut._idx is None:
            return None
        # a transferred request's newest checkpoints live on the
        # DECODE replica that held it, not the prefill replica the
        # FleetFuture placed it on — follow the KV
        with self._blk:
            src_idx = self._decode_holder.get(trace_id, ffut._idx)
        dead = self.replicas[src_idx]
        if dead is None:        # tombstoned slot: no checkpoint access
            return None
        eng = getattr(dead, "engine", dead)
        take = getattr(eng, "take_kv_checkpoint", None)
        if take is None:
            return None
        try:
            snap = take(trace_id)
        except Exception:   # noqa: BLE001 — dead engine, best-effort
            snap = None
        if snap is None:
            return None
        now = self._clock()
        if self.pools_enabled():
            # resume onto the decode pool in affinity order (next-best
            # rendezvous peer keeps the prefix pinned), then anyone
            cands = [(i, False) for i in self._decode_order(
                prefix_chain_key(ffut._args[0]
                                 if ffut._args else (),
                                 self._affinity_block()),
                now, exclude=(src_idx,))]
            seen = {i for i, _p in cands}
            cands += [(i, p) for i, p in self._order(
                now, exclude=(ffut._idx, src_idx))
                if i not in seen]
        else:
            cands = self._order(now, exclude=(ffut._idx, src_idx))
        for sidx, _probing in cands:
            seng = getattr(self.replicas[sidx], "engine",
                           self.replicas[sidx])
            inject = getattr(seng, "inject_snapshot", None)
            if inject is None:
                continue
            try:
                fut = inject(snap["meta"], snap["frame"],
                             timeout=budget)
            except HandoffRefused:
                # typed refusal: corrupt/mismatched checkpoint — it
                # would be refused everywhere; recompute instead
                return None
            except _BACKPRESSURE:
                continue
            except _REPLICA_FAILURES as e:
                self._record_failure(sidx, type(e).__name__)
                continue
            self._resumes.inc()
            self._submitted.inc()
            with self._blk:
                if trace_id in self._decode_holder:
                    self._decode_holder[trace_id] = sidx
            _spans.event("request.resume_from_checkpoint",
                         from_replica=self._name(src_idx),
                         to_replica=self._name(sidx),
                         request=trace_id)
            return sidx, fut
        return None

    def drain(self, timeout=60.0):
        """Drain every live replica (the fleet-front gateway's POST
        /drain body). Returns True when all drains were clean."""
        return all(r.drain(timeout=timeout) == EXIT_DRAINED
                   for _i, r in self.live_replicas())

    @property
    def draining(self):
        return all(bool(getattr(r, "draining", False))
                   for _i, r in self.live_replicas())

    def health(self):
        docs = [None if r is None
                else r.health() if hasattr(r, "health") else None
                for r in list(self.replicas)]
        states = self.breaker_states()
        for i, doc in enumerate(docs):
            if isinstance(doc, dict):
                doc["breaker"] = states.get(self._name(i))
        return docs


__all__ = ["ServingReplica", "FleetRouter", "FleetFuture",
           "CircuitBreaker", "ShedPolicy", "PoolSaturated",
           "brownout_shrink_generation", "EXIT_DRAINED",
           "BREAKER_CLOSED", "BREAKER_HALF_OPEN", "BREAKER_OPEN"]
