"""Drainable replicas and fault-tolerant client-side fleet routing.

One serving process = one :class:`ServingReplica`: an engine plus
(optionally) a membership seat in a ``resilience.cluster`` pod — the
same coordinator/worker control plane training uses for health,
heartbeat-carried metric summaries, and dead-peer detection, so a
serving fleet's coordinator health report looks exactly like a training
pod's.

The drain contract (the serving sibling of the exit-75/76 supervisor
table):

1. something asks the replica to drain (SIGTERM, the gateway's
   ``POST /drain``, or :meth:`ServingReplica.drain` directly);
2. the engine refuses new requests **loudly** — ``submit`` raises
   :class:`~singa_tpu.serving.scheduler.EngineDraining`, the gateway
   returns 503 — so a router fails the traffic over instead of letting
   it rot;
3. every request already admitted (queued or mid-decode) runs to a
   normal response: a drained replica drops NOTHING (chaos-proved by
   the ``serve-drain`` scenario in ``tools/chaos_smoke.py``);
4. the replica leaves its cluster seat and exits
   :data:`EXIT_DRAINED` (0) — "done, on purpose": a supervisor must NOT
   relaunch it (75 means relaunch, 76 means cordon, 0 means the drain
   you asked for completed).

:class:`FleetRouter` is the fault-tolerant client half (tests, chaos
drivers, single-host multi-engine setups; across hosts the same logic
belongs to any LB that honors the gateway's refusal codes — the router
documents the semantics, it does not replace your LB). Three layers:

- **Health-gated dispatch** — every submit/settle outcome is
  classified per replica through a :class:`CircuitBreaker`:
  ``threshold`` consecutive replica failures (crashed engine, wire
  error, per-try timeout) eject it into ``open`` with capped
  exponential backoff; after the backoff ONE half-open probe re-admits
  it (success closes, failure re-opens with a doubled delay). Open
  replicas are skipped in the dispatch order — never probed more often
  than the backoff allows — and a replica whose depth can't even be
  read sorts *last*.
- **Exactly-once re-dispatch** — ``submit`` returns a
  :class:`FleetFuture` that owns delivery. When the holding replica
  crashes (or a ``per_try_timeout`` fires) the request — pure submit
  args, idempotent by construction, token-identical on any replica
  under greedy decode — is resubmitted to a survivor with its
  **remaining deadline budget**, never a reset clock. The future
  fulfills exactly once (a late original is simply never consumed; a
  second fulfillment attempt raises, mirroring ``ServeFuture``'s
  tested double-delivery guard), so a budget-exhausted request fails
  typed (:class:`~singa_tpu.serving.scheduler.RequestTimeout` → 504)
  exactly once instead of hanging silently.
- **Graceful degradation** — a :class:`ShedPolicy` turns sustained
  ``QueueFull``/``BlockPoolExhausted`` backpressure into typed
  fast-fail :class:`~singa_tpu.serving.scheduler.RequestShed` errors
  carrying ``retry_after`` (the gateway's ``Retry-After`` header), and
  an optional brownout hook steps request cost down
  (``max_new_tokens``, speculative drafting) before refusing outright.
"""

from __future__ import annotations

import signal
import threading
import time

from ..observability import metrics as _metrics
from ..observability import spans as _spans
from .scheduler import (BlockPoolExhausted, EngineDraining,
                        HandoffRefused, QueueFull, ReplicaCrashed,
                        RequestShed, RequestTimeout, ServingError,
                        budget_remaining, deadline_in)

# the drain exit code: intentional, successful, do-not-relaunch — the
# 0 row of the README's supervisor exit-code contract table
EXIT_DRAINED = 0

# submit-time refusals that mean "try a healthier replica, this one is
# ALIVE but won't take the request" — failover fodder, not breaker fodder
_BACKPRESSURE = (EngineDraining, QueueFull, BlockPoolExhausted)
# submit-time failures that mean "this REPLICA is broken" — breaker
# fodder (ConnectionError ⊂ OSError covers real wire deaths and the
# injected fail_submit fault)
_REPLICA_FAILURES = (ReplicaCrashed, OSError)

BREAKER_CLOSED = "closed"
BREAKER_HALF_OPEN = "half_open"
BREAKER_OPEN = "open"
_BREAKER_GAUGE = {BREAKER_CLOSED: 0, BREAKER_HALF_OPEN: 1,
                  BREAKER_OPEN: 2}


class ServingReplica:
    """One engine + one (optional) cluster seat + the drain contract."""

    def __init__(self, engine, *, cluster=None, name="replica",
                 registry=None):
        self.engine = engine
        self.cluster = cluster
        self.name = str(name)
        self._reg = registry if registry is not None \
            else _metrics.default_registry()
        self._drain_evt = threading.Event()
        # preemption budget: request_drain(deadline=) pins the drain's
        # absolute deadline here (monotonic), so the blocking drain —
        # wherever it runs — honors the budget the preemption gave us
        self._drain_deadline_at = None
        self._drain_gauge = self._reg.gauge(
            "serve_replica_draining",
            "1 while this replica is draining (refusing new requests)")
        self._drain_gauge.set(0)

    # -- serving -----------------------------------------------------------
    def start(self):
        self.engine.start()
        return self

    def submit(self, *args, **kwargs):
        return self.engine.submit(*args, **kwargs)

    def export_aot(self, store=None):
        """Serialize the engine's compiled programs into an AOT store
        (``singa_tpu.aot``) so the replica that replaces this one —
        rolling restart, failover respawn — deserializes instead of
        retracing. Delegates to ``engine.export_aot``."""
        return self.engine.export_aot(store)

    @property
    def draining(self):
        return self.engine.draining

    def queue_depth(self):
        return len(self.engine.queue)

    def health(self):
        """Engine + membership view (what the gateway's ``/healthz``
        serves)."""
        eng = self.engine
        doc = {
            "name": self.name,
            "status": ("crashed" if eng._crashed is not None
                       else "draining" if eng.draining
                       else "serving"),
            "queue_depth": len(eng.queue),
            "active_slots": getattr(eng, "active_slots",
                                    lambda: None)(),
            "compiled": eng.compiled_step_info(),
        }
        if eng.draining and self._drain_deadline_at is not None:
            # preemption honesty: how much budget the drain has left
            doc["drain_deadline_s"] = round(
                budget_remaining(self._drain_deadline_at), 4)
        if self.cluster is not None:
            try:
                doc["cluster"] = self.cluster.health()
            except Exception as e:      # noqa: BLE001 — health is advisory
                doc["cluster"] = {"error": f"{type(e).__name__}: {e}"}
        return doc

    # -- drain -------------------------------------------------------------
    def request_drain(self, deadline=None):
        """Mark the replica draining and wake whoever is blocked in
        :meth:`run_until_drained`. Idempotent, signal-safe (this is the
        SIGTERM handler's body: no joins, no blocking). ``deadline``
        (seconds) arms a preemption budget: the blocking drain uses it
        instead of its default timeout, migrating what cannot finish
        when a handoff callable is armed."""
        if deadline is not None:
            self._drain_deadline_at = \
                time.monotonic() + float(deadline)
        self._drain_gauge.set(1)
        self.engine._draining = True    # refuse from this instant
        self.engine._wake.set()
        self._drain_evt.set()

    def drain(self, timeout=60.0, handoff=None):
        """Execute the full drain: finish everything in flight (or,
        with ``handoff`` and a deadline budget, migrate what does not
        fit — see ``engine.drain``), close the cluster seat, stop the
        loop. Returns the process exit code — :data:`EXIT_DRAINED` (0)
        on a clean drain, 1 when work had to be abandoned (timeout or
        a crashed serve loop). A stop after a deadline drain fails any
        leftovers typed (:class:`EngineDraining`) so a fleet router
        re-dispatches them — nothing is ever left unresolved."""
        self.request_drain()
        if self._drain_deadline_at is not None:
            # the preemption's budget, not the caller's default
            timeout = budget_remaining(self._drain_deadline_at)
        with _spans.span("serve.drain", replica=self.name):
            ok = self.engine.drain(timeout=timeout, handoff=handoff)
        if self.cluster is not None:
            try:
                self.cluster.close()
            except Exception:   # a dead coordinator must not dirty a
                pass            # clean drain
        self.engine.stop()
        return EXIT_DRAINED if ok else 1

    def install_signal_handlers(self, signals=(signal.SIGTERM,
                                               signal.SIGINT),
                                deadline=None):
        """SIGTERM/SIGINT → :meth:`request_drain` (the handler only
        flips flags; the blocking drain runs in
        :meth:`run_until_drained` on the main thread). ``deadline``
        arms the preemption budget the signal carries — a TPU
        maintenance SIGTERM gives seconds, not minutes."""
        for s in signals:
            signal.signal(
                s, lambda _s, _f: self.request_drain(deadline=deadline))
        return self

    def run_until_drained(self, poll=0.25, timeout=60.0, handoff=None):
        """Block the main thread until a drain is requested (signal,
        gateway, or :meth:`request_drain`), then drain and return the
        exit code. A serve-loop crash also unblocks — with exit code 1
        (the blackbox is already on disk by then). ``handoff`` is the
        deadline drain's migration callable (``engine.drain``)."""
        while not self._drain_evt.wait(poll):
            if self.engine._crashed is not None:
                return 1
        return self.drain(timeout=timeout, handoff=handoff)


class CircuitBreaker:
    """Per-replica health gate: ``closed`` → (``threshold`` consecutive
    failures) → ``open`` for ``backoff × 2^(opens-1)`` seconds (capped)
    → ONE ``half_open`` probe → ``closed`` on success, back to ``open``
    with a doubled delay on failure. Any success resets both the
    failure streak and the backoff ladder.

    Pure state machine over an injected clock — the router owns
    locking and metrics; tier-1 tests drive transitions with a fake
    ``now``."""

    def __init__(self, threshold=3, backoff=0.25, backoff_cap=30.0):
        self.threshold = max(1, int(threshold))
        self.backoff = float(backoff)
        self.backoff_cap = float(backoff_cap)
        self.state = BREAKER_CLOSED
        self.consecutive_failures = 0
        self.opens = 0              # backoff ladder position
        self.open_until = 0.0
        self.probe_inflight = False

    def admits(self, now):
        """May the router dispatch to this replica right now? True
        while closed; an open breaker admits exactly ONE probe once
        its backoff has elapsed (``begin_probe`` must claim it)."""
        if self.state == BREAKER_CLOSED:
            return True
        if self.probe_inflight:
            return False
        return self.state == BREAKER_HALF_OPEN or now >= self.open_until

    def begin_probe(self, now):
        """Claim the single half-open probe slot before dispatching to
        a non-closed breaker's replica."""
        self.state = BREAKER_HALF_OPEN
        self.probe_inflight = True

    def record_success(self, now):
        self.state = BREAKER_CLOSED
        self.consecutive_failures = 0
        self.opens = 0
        self.probe_inflight = False

    def record_failure(self, now):
        """One replica failure (submit OR settle). Returns True when
        this failure tripped the breaker open."""
        self.probe_inflight = False
        self.consecutive_failures += 1
        if self.state == BREAKER_HALF_OPEN \
                or self.consecutive_failures >= self.threshold:
            self.opens += 1
            delay = min(self.backoff_cap,
                        self.backoff * (2 ** (self.opens - 1)))
            self.open_until = now + delay
            self.state = BREAKER_OPEN
            return True
        return False


def brownout_shrink_generation(kwargs):
    """Default brownout hook: halve ``max_new_tokens`` (floor 1).
    Returns the stepped-down submit kwargs, or ``None`` when there is
    nothing left to shrink (→ the shed policy refuses instead)."""
    mnt = int(kwargs.get("max_new_tokens", 16))
    if mnt <= 1:
        return None
    return dict(kwargs, max_new_tokens=max(1, mnt // 2))


class ShedPolicy:
    """Sustained-backpressure detector + typed fast-fail shed.

    Every all-replicas-backpressured submit records one event; once
    ``threshold`` events land within ``window_s`` seconds the fleet is
    *sustainedly* overloaded and the router stops queueing into
    timeouts: the optional ``brownout`` hook (``kwargs → kwargs|None``,
    e.g. :func:`brownout_shrink_generation`) gets one chance to step
    the request's cost down; if there is no hook (or it declines) the
    request fails fast with :class:`RequestShed` carrying
    ``retry_after`` — the gateway's ``Retry-After`` contract."""

    def __init__(self, window_s=5.0, threshold=8, retry_after=1.0,
                 brownout=None):
        self.window_s = float(window_s)
        self.threshold = max(1, int(threshold))
        self.retry_after = float(retry_after)
        self.brownout = brownout
        self._events = []

    def _trim(self, now):
        cutoff = now - self.window_s
        self._events = [t for t in self._events if t >= cutoff]

    def record_backpressure(self, now):
        self._events.append(now)
        self._trim(now)

    def sustained(self, now):
        self._trim(now)
        return len(self._events) >= self.threshold

    def apply_brownout(self, kwargs):
        """Stepped-down kwargs, or None (no hook / hook declined)."""
        if self.brownout is None:
            return None
        return self.brownout(dict(kwargs))


class FleetFuture:
    """A fleet-level response slot that OWNS delivery across replica
    failures. Wraps the current attempt's ``ServeFuture``; crashes,
    delivered backpressure, and per-try timeouts re-dispatch the
    request (pure submit args) to a survivor with the **remaining
    deadline budget** — never a reset clock. Fulfills exactly once: a
    late result from a superseded attempt is never consumed, and a
    second fulfillment attempt raises (the ``ServeFuture`` guard,
    fleet-level).

    ``result(timeout)`` is the drive loop (same surface as
    ``ServeFuture.result``); ``deliveries`` / ``attempts`` /
    ``redispatches`` are the chaos-test counters. Like stdlib futures,
    completion happens inside ``result`` — poll ``done()`` only after
    some caller has driven it."""

    def __init__(self, router, args, kwargs):
        self._router = router
        self._args = tuple(args)
        self._kwargs = dict(kwargs)
        # the ONE clock this request lives on: every re-dispatch's
        # engine-side timeout is derived from this deadline's remainder
        self._deadline = deadline_in(self._kwargs.get("timeout"),
                                     now=router._clock())
        self._flock = threading.Lock()
        self._drive = threading.Lock()
        self._event = threading.Event()
        self._result = None
        self._error = None
        self.deliveries = 0
        self.attempts = 0
        self.redispatches = 0
        self._idx = None            # current attempt's replica index
        self._fut = None            # current attempt's ServeFuture

    # -- exactly-once fulfillment (mirrors ServeFuture) --------------------
    def _fulfill(self, result=None, error=None):
        with self._flock:
            self.deliveries += 1
            if self._event.is_set():
                raise RuntimeError(
                    "double delivery: this request already has a "
                    "response (exactly-once violation)")
            self._result = result
            self._error = error
            self._event.set()

    def done(self):
        return self._event.is_set()

    def _finish(self):
        if self._error is not None:
            raise self._error
        return self._result

    # -- dispatch ----------------------------------------------------------
    def _first_dispatch(self):
        self._idx, self._fut = self._router._place(self._args,
                                                   self._kwargs)
        self.attempts = 1

    def _redispatch(self, reason, cause):
        """Place the request on a survivor with the remaining budget,
        or fulfill a terminal typed error exactly once and raise it."""
        rt = self._router
        budget = budget_remaining(self._deadline, rt._clock())
        if budget is not None and budget <= 0.0:
            err = RequestTimeout(
                f"deadline budget exhausted after {self.attempts} "
                f"attempt(s) (last replica failure: {reason})")
            err.__cause__ = cause
            self._fulfill(error=err)
            raise err
        if self.redispatches >= rt.max_redispatch:
            err = ServingError(
                f"request failed on {self.attempts} replica(s) "
                f"(re-dispatch limit {rt.max_redispatch} reached; "
                f"last: {cause})")
            err.__cause__ = cause
            self._fulfill(error=err)
            raise err
        kwargs = dict(self._kwargs)
        if budget is not None:
            # the remainder, NEVER a fresh full timeout: attempt N+1's
            # engine-side deadline coincides with the original one
            kwargs["timeout"] = budget
        # checkpoint rung first: a banked KV snapshot on the failed
        # replica resumes decode where it left off instead of
        # recomputing the prompt + every generated token from zero
        try:
            resumed = rt._resume_from_checkpoint(self, budget)
        except Exception:   # noqa: BLE001 — recovery rung, best-effort
            resumed = None
        if resumed is not None:
            idx, fut = resumed
        else:
            try:
                idx, fut = rt._place(self._args, kwargs,
                                     exclude=(self._idx,))
            except ServingError as e:
                # no survivor could take it — terminal, exactly once
                self._fulfill(error=e)
                raise
        rt._redispatches.inc()
        ev = {"from_replica": rt._name(self._idx),
              "to_replica": rt._name(idx), "reason": reason,
              "attempt": self.attempts + 1}
        if budget is not None:
            ev["budget_s"] = round(budget, 4)
        if self._kwargs.get("trace_id"):
            ev["request"] = self._kwargs["trace_id"]
        _spans.event("request.redispatch", **ev)
        self._idx, self._fut = idx, fut
        self.attempts += 1
        self.redispatches += 1

    # -- the drive loop ----------------------------------------------------
    def result(self, timeout=None):
        """Block for the response, re-dispatching across replica
        failures; re-raises the request's (typed) error. ``timeout``
        bounds THIS caller's wait — the request's own deadline budget
        (from its ``timeout`` submit kwarg) bounds the retries."""
        if self._event.is_set():
            return self._finish()
        rt = self._router
        wall = deadline_in(timeout, now=rt._clock())
        with self._drive:
            if self._event.is_set():
                return self._finish()
            while True:
                now = rt._clock()
                budget = budget_remaining(self._deadline, now)
                caller = budget_remaining(wall, now)
                wait, why = None, None
                for w, k in ((budget, "budget"), (caller, "caller"),
                             (rt.per_try_timeout, "per_try")):
                    if w is not None and (wait is None or w < wait):
                        wait, why = w, k
                try:
                    res = self._fut.result(timeout=wait)
                except RequestTimeout as e:
                    if self._fut.done():
                        # the ENGINE delivered the timeout: the
                        # request's own deadline expired server-side —
                        # the budget is spent, terminal
                        self._fulfill(error=e)
                        raise
                    if why == "caller":
                        # this caller's patience ran out, not the
                        # request's budget: still in flight — mirror
                        # ServeFuture (no fulfillment, call again)
                        raise
                    if why == "budget":
                        err = RequestTimeout(
                            f"deadline budget exhausted after "
                            f"{self.attempts} attempt(s) (request "
                            "still in flight on the last replica)")
                        self._fulfill(error=err)
                        raise err
                    # per-try timeout: the replica is straggling —
                    # breaker failure + re-dispatch with the remainder
                    rt._record_failure(self._idx, "per_try_timeout")
                    self._redispatch("per_try_timeout", e)
                except _BACKPRESSURE as e:
                    # DELIVERED backpressure (hard-stopped engine, 503
                    # from a wire replica): it never served the
                    # request, so re-dispatch is trivially exactly-once
                    self._redispatch(type(e).__name__, e)
                except _REPLICA_FAILURES as e:
                    # the holding replica died with the request
                    # admitted (the stranded shape)
                    rt._record_failure(self._idx, type(e).__name__)
                    self._redispatch(type(e).__name__, e)
                except ServingError as e:
                    # request-shaped failure: it would fail the same
                    # way on every replica — terminal, exactly once
                    self._fulfill(error=e)
                    raise
                else:
                    rt._record_success(self._idx)
                    self._fulfill(result=res)
                    return res


class FleetRouter:
    """Health-gated least-depth dispatch over in-process replicas with
    circuit breakers, exactly-once re-dispatch, and load shedding (see
    module docstring). ``submit`` returns a :class:`FleetFuture`;
    it raises only when NO admitted replica accepted the request —
    typed :class:`RequestShed` under a sustained-backpressure shed,
    plain ``ServingError`` otherwise.

    ``per_try_timeout`` (seconds, default None=off) bounds ONE
    replica's attempt; a request whose deadline budget still has
    remainder when it fires is re-dispatched to a survivor with that
    remainder. ``max_redispatch`` caps re-dispatches per request.

    Membership is dynamic: :meth:`add_replica` admits a new replica
    into dispatch, :meth:`remove_replica` retires a slot. Removal
    TOMBSTONES the slot (``replicas[idx] is None``) instead of
    shifting the list — in-flight :class:`FleetFuture`\\ s hold their
    origin index for crash re-dispatch exclusion, so indices must stay
    stable for the router's lifetime."""

    def __init__(self, replicas, registry=None, *,
                 breaker_threshold=3, breaker_backoff=0.25,
                 breaker_backoff_cap=30.0, per_try_timeout=None,
                 max_redispatch=2, shed_policy=None, clock=None):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.replicas = list(replicas)
        self.per_try_timeout = per_try_timeout if per_try_timeout \
            is None else float(per_try_timeout)
        self.max_redispatch = int(max_redispatch)
        self.shed_policy = shed_policy
        self._clock = clock if clock is not None else time.monotonic
        self._blk = threading.Lock()
        self._breaker_params = (breaker_threshold, breaker_backoff,
                                breaker_backoff_cap)
        self._breakers = [CircuitBreaker(breaker_threshold,
                                         breaker_backoff,
                                         breaker_backoff_cap)
                          for _ in self.replicas]
        # last-known names of tombstoned slots (health/trace labels
        # must keep naming a slot after its replica object is gone)
        self._slot_names = {}
        reg = registry if registry is not None \
            else _metrics.default_registry()
        self._reg = reg
        self._submitted = reg.counter(
            "serve_fleet_submitted_total",
            "requests the router placed on some replica")
        self._failovers = reg.counter(
            "serve_fleet_failover_total",
            "submissions that had to skip a refusing replica")
        self._rejected = reg.counter(
            "serve_fleet_rejected_total",
            "submissions every admitted replica refused")
        self._redispatches = reg.counter(
            "serve_fleet_redispatch_total",
            "requests re-dispatched to a survivor after a replica "
            "crash / delivered backpressure / per-try timeout")
        self._sheds = reg.counter(
            "serve_fleet_shed_total",
            "requests fast-failed by the shed policy under sustained "
            "backpressure (typed RequestShed, Retry-After at the "
            "gateway)")
        self._brownouts = reg.counter(
            "serve_fleet_brownout_total",
            "requests stepped down by the shed policy's brownout hook "
            "instead of being refused")
        self._handoffs = reg.counter(
            "serve_fleet_handoff_total",
            "drain-deadline requests migrated to a survivor (live-KV "
            "inject or mid-flight recompute) instead of being dropped")
        self._resumes = reg.counter(
            "serve_fleet_resume_total",
            "crash re-dispatches that resumed from a KV checkpoint "
            "instead of recomputing from token zero")
        self._breaker_opens = reg.counter(
            "serve_fleet_breaker_open_total",
            "circuit-breaker trips (replica ejected from dispatch)",
            labels=("replica",))
        self._probes = reg.counter(
            "serve_fleet_probe_total",
            "half-open breaker probes dispatched",
            labels=("replica",))
        self._breaker_state = reg.gauge(
            "serve_fleet_breaker_state",
            "per-replica breaker state: 0=closed 1=half_open 2=open",
            labels=("replica",))
        for i in range(len(self.replicas)):
            self._breaker_state.set(0, replica=self._name(i))

    def _name(self, idx):
        r = self.replicas[idx]
        if r is None:
            return self._slot_names.get(idx, str(idx))
        return getattr(r, "name", None) or str(idx)

    # -- membership --------------------------------------------------------
    def add_replica(self, replica):
        """Admit a replica into dispatch (fresh closed breaker).
        Returns its slot index. The caller owns readiness: admit only
        replicas that already answer ``/healthz``-level probes — the
        autoscaler's warm-admission gate lives above this."""
        if replica is None:
            raise ValueError("cannot add a None replica")
        with self._blk:
            self.replicas.append(replica)
            self._breakers.append(CircuitBreaker(*self._breaker_params))
            idx = len(self.replicas) - 1
            self._set_state_gauge(idx)
        _spans.event("fleet.replica_added",
                     replica=self._name(idx), slot=idx)
        return idx

    def remove_replica(self, idx):
        """Tombstone slot ``idx`` and return its replica (None if the
        slot was already empty). The slot never dispatches again; its
        index is never reused. Call AFTER the replica is drained or
        declared dead — removal does not stop the engine."""
        with self._blk:
            r = self.replicas[idx]
            if r is not None:
                self._slot_names[idx] = \
                    getattr(r, "name", None) or str(idx)
            self.replicas[idx] = None
        if r is not None:
            _spans.event("fleet.replica_removed",
                         replica=self._slot_names[idx], slot=idx)
        return r

    def live_replicas(self):
        """``[(idx, replica)]`` for the non-tombstoned slots."""
        with self._blk:
            return [(i, r) for i, r in enumerate(self.replicas)
                    if r is not None]

    def population(self):
        """Live (non-tombstoned) replica count."""
        with self._blk:
            return sum(1 for r in self.replicas if r is not None)

    @staticmethod
    def _depth(r):
        try:
            return r.queue_depth() if hasattr(r, "queue_depth") \
                else len(r.engine.queue) if hasattr(r, "engine") \
                else len(r.queue)
        except Exception:       # noqa: BLE001 — routing hint only
            # unreadable depth = suspect replica: sort it LAST (0 would
            # make the sickest replica the most attractive target)
            return float("inf")

    # -- breaker bookkeeping (all under _blk) ------------------------------
    def _set_state_gauge(self, idx):
        self._breaker_state.set(
            _BREAKER_GAUGE[self._breakers[idx].state],
            replica=self._name(idx))

    def _record_success(self, idx):
        with self._blk:
            self._breakers[idx].record_success(self._clock())
            self._set_state_gauge(idx)

    def _record_failure(self, idx, reason):
        with self._blk:
            br = self._breakers[idx]
            opened = br.record_failure(self._clock())
            self._set_state_gauge(idx)
        if opened:
            self._breaker_opens.inc(replica=self._name(idx))
            _spans.event("replica.breaker_open",
                         replica=self._name(idx), reason=reason,
                         consecutive=br.consecutive_failures,
                         backoff_s=round(br.open_until
                                         - self._clock(), 4))

    def breaker_states(self):
        """{replica name: breaker state} — /healthz fodder
        (tombstoned slots omitted)."""
        with self._blk:
            return {self._name(i): br.state
                    for i, br in enumerate(self._breakers)
                    if self.replicas[i] is not None}

    # -- placement ---------------------------------------------------------
    def _order(self, now, exclude=()):
        """Breaker-admitted replicas, least-depth first, draining
        last; open-but-probe-due replicas carry probing=True."""
        out = []
        with self._blk:
            for i, r in enumerate(self.replicas):
                if i in exclude or r is None:
                    continue
                br = self._breakers[i]
                if not br.admits(now):
                    continue
                out.append((bool(r.draining), self._depth(r), i,
                            br.state != BREAKER_CLOSED))
        out.sort(key=lambda t: t[:3])
        return [(i, probing) for _d, _q, i, probing in out]

    def _place(self, args, kwargs, exclude=()):
        """One placement pass: try each admitted replica in order.
        Returns ``(idx, serve_future)``; raises typed when nobody took
        the request (RequestShed under a sustained-backpressure shed)."""
        now = self._clock()
        last_exc = None
        saw_replica_failure = False
        order = self._order(now, exclude)
        for idx, probing in order:
            r = self.replicas[idx]
            if probing:
                with self._blk:
                    self._breakers[idx].begin_probe(now)
                    self._set_state_gauge(idx)
                self._probes.inc(replica=self._name(idx))
            try:
                fut = r.submit(*args, **kwargs)
            except _BACKPRESSURE as e:
                # alive but refusing: failover fodder (and a probe
                # SUCCESS — the replica answered), plus shed evidence
                last_exc = e
                self._failovers.inc()
                if probing:
                    self._record_success(idx)
                if self.shed_policy is not None and \
                        not isinstance(e, EngineDraining):
                    self.shed_policy.record_backpressure(now)
                self._failover_event(r, e, kwargs)
                continue
            except _REPLICA_FAILURES as e:
                # crashed engine / wire death: breaker fodder — one
                # dead replica must never kill routing while survivors
                # exist
                last_exc = e
                saw_replica_failure = True
                self._failovers.inc()
                self._record_failure(idx, type(e).__name__)
                self._failover_event(r, e, kwargs)
                continue
            except BaseException:
                # request-shaped refusal (bad params, prompt too long):
                # the REPLICA answered — release a claimed probe slot
                # before the error propagates to the caller
                if probing:
                    self._record_success(idx)
                raise
            self._submitted.inc()
            if probing:
                self._record_success(idx)
            return idx, fut
        if not order:
            last_exc = last_exc or ServingError(
                "every replica is ejected (breaker open) or excluded")
        if not saw_replica_failure and self.shed_policy is not None \
                and self.shed_policy.sustained(now):
            self._sheds.inc()
            raise RequestShed(
                f"fleet shedding load: sustained backpressure across "
                f"all {self.population()} replicas (last: "
                f"{last_exc}); retry after "
                f"{self.shed_policy.retry_after}s",
                retry_after=self.shed_policy.retry_after)
        self._rejected.inc()
        raise ServingError(
            f"all {self.population()} replicas refused the request "
            f"(last: {last_exc})")

    @staticmethod
    def _failover_event(r, e, kwargs):
        # the failover joins the request's timeline: a traced request
        # shows WHICH replica refused it and why
        ev = {"replica": getattr(r, "name", None),
              "reason": type(e).__name__}
        if kwargs.get("trace_id"):
            ev["request"] = kwargs["trace_id"]
        _spans.event("request.failover", **ev)

    # -- public surface ----------------------------------------------------
    def submit(self, *args, **kwargs):
        """Place one request; returns a :class:`FleetFuture` (same
        ``result(timeout)`` / ``deliveries`` surface as
        ``ServeFuture``). Under a sustained shed the brownout hook gets
        one chance to step the request down before a typed
        :class:`RequestShed` refusal."""
        if self.shed_policy is not None \
                and self.shed_policy.sustained(self._clock()):
            stepped = self.shed_policy.apply_brownout(kwargs)
            if stepped is None:
                self._sheds.inc()
                raise RequestShed(
                    "fleet shedding load: sustained backpressure "
                    f"(window {self.shed_policy.window_s}s); retry "
                    f"after {self.shed_policy.retry_after}s",
                    retry_after=self.shed_policy.retry_after)
            if stepped != kwargs:
                self._brownouts.inc()
            kwargs = stepped
        fut = FleetFuture(self, args, kwargs)
        fut._first_dispatch()
        return fut

    def drain_replica(self, idx, timeout=60.0, handoff=False):
        """Drain ONE replica (rolling-restart building block); the
        router's failover routes everything new to the survivors.
        ``handoff=True`` arms live-KV migration: work that cannot
        finish inside the budget moves to a survivor mid-flight
        (snapshot inject, recompute fallback) instead of failing."""
        r = self.replicas[idx]
        if r is None:
            raise ValueError(f"slot {idx} is tombstoned (removed)")
        cb = self._handoff_to_survivors(idx) if handoff else None
        return r.drain(timeout=timeout, handoff=cb)

    # -- live-KV handoff (drain-deadline migration) ------------------------
    def _handoff_to_survivors(self, idx):
        """The draining engine's ``handoff(req, snapshot, budget)``
        callable: the migration ladder. For each survivor in dispatch
        order — (1) inject the sealed KV snapshot (continuation is
        bitwise-identical, zero recomputed prefill); (2) on a typed
        :class:`HandoffRefused` (corrupt frame, geometry mismatch) fall
        back to recompute on the SAME survivor — corrupt KV is never
        injected anywhere; (3) backpressure → next survivor. Returns
        True once some survivor owns the request (a relay thread wires
        its response into the original future), False when nobody could
        take it (the engine then fails it typed → PR-16 re-dispatch)."""

        def _handoff(req, snapshot, budget):
            now = self._clock()
            for sidx, _probing in self._order(now, exclude=(idx,)):
                r = self.replicas[sidx]
                fut = None
                if snapshot is not None:
                    eng = getattr(r, "engine", r)
                    inject = getattr(eng, "inject_snapshot", None)
                    if inject is not None:
                        try:
                            fut = inject(snapshot["meta"],
                                         snapshot["frame"],
                                         timeout=budget)
                        except HandoffRefused:
                            fut = None      # recompute, same survivor
                        except _BACKPRESSURE:
                            continue
                        except _REPLICA_FAILURES as e:
                            self._record_failure(sidx,
                                                 type(e).__name__)
                            continue
                if fut is None:
                    try:
                        # the request's OWN remaining clock, not the
                        # drain budget (that only bounds the handoff)
                        fut = r.submit(
                            list(req.prompt),
                            max_new_tokens=req.max_new_tokens,
                            temperature=req.temperature,
                            top_k=req.top_k, eos_id=req.eos_id,
                            timeout=budget_remaining(req.deadline),
                            trace_id=req.trace_id)
                    except _BACKPRESSURE:
                        continue
                    except _REPLICA_FAILURES as e:
                        self._record_failure(sidx, type(e).__name__)
                        continue
                self._handoffs.inc()
                _spans.event("request.handoff",
                             from_replica=self._name(idx),
                             to_replica=self._name(sidx),
                             request=req.trace_id,
                             migrated=snapshot is not None)
                self._relay(fut, req.future)
                return True
            return False

        return _handoff

    @staticmethod
    def _relay(src, dst):
        """Pipe a survivor's future into the original request's future
        from a daemon thread (the draining engine cannot block on its
        peer's decode loop)."""

        def _pipe():
            try:
                res = src.result(timeout=None)
            except BaseException as e:      # noqa: BLE001 — relayed
                if not dst.done():
                    dst.set_error(e)
            else:
                if not dst.done():
                    dst.set_result(res)

        threading.Thread(target=_pipe, name="kv-handoff-relay",
                         daemon=True).start()

    def _resume_from_checkpoint(self, ffut, budget):
        """Crash-recovery rung above recompute: if the dead replica's
        engine banked a KV checkpoint for this request (snapshot_every
        cadence), inject it into a survivor so decode resumes from the
        last checkpoint instead of token zero. Returns ``(idx, fut)``
        or None (no checkpoint / no engine access / survivor refused
        typed → caller falls through to plain recompute)."""
        trace_id = ffut._kwargs.get("trace_id")
        if not trace_id or ffut._idx is None:
            return None
        dead = self.replicas[ffut._idx]
        if dead is None:        # tombstoned slot: no checkpoint access
            return None
        eng = getattr(dead, "engine", dead)
        take = getattr(eng, "take_kv_checkpoint", None)
        if take is None:
            return None
        try:
            snap = take(trace_id)
        except Exception:   # noqa: BLE001 — dead engine, best-effort
            snap = None
        if snap is None:
            return None
        now = self._clock()
        for sidx, _probing in self._order(now, exclude=(ffut._idx,)):
            seng = getattr(self.replicas[sidx], "engine",
                           self.replicas[sidx])
            inject = getattr(seng, "inject_snapshot", None)
            if inject is None:
                continue
            try:
                fut = inject(snap["meta"], snap["frame"],
                             timeout=budget)
            except HandoffRefused:
                # typed refusal: corrupt/mismatched checkpoint — it
                # would be refused everywhere; recompute instead
                return None
            except _BACKPRESSURE:
                continue
            except _REPLICA_FAILURES as e:
                self._record_failure(sidx, type(e).__name__)
                continue
            self._resumes.inc()
            self._submitted.inc()
            _spans.event("request.resume_from_checkpoint",
                         from_replica=self._name(ffut._idx),
                         to_replica=self._name(sidx),
                         request=trace_id)
            return sidx, fut
        return None

    def drain(self, timeout=60.0):
        """Drain every live replica (the fleet-front gateway's POST
        /drain body). Returns True when all drains were clean."""
        return all(r.drain(timeout=timeout) == EXIT_DRAINED
                   for _i, r in self.live_replicas())

    @property
    def draining(self):
        return all(bool(getattr(r, "draining", False))
                   for _i, r in self.live_replicas())

    def health(self):
        docs = [None if r is None
                else r.health() if hasattr(r, "health") else None
                for r in list(self.replicas)]
        states = self.breaker_states()
        for i, doc in enumerate(docs):
            if isinstance(doc, dict):
                doc["breaker"] = states.get(self._name(i))
        return docs


__all__ = ["ServingReplica", "FleetRouter", "FleetFuture",
           "CircuitBreaker", "ShedPolicy",
           "brownout_shrink_generation", "EXIT_DRAINED",
           "BREAKER_CLOSED", "BREAKER_HALF_OPEN", "BREAKER_OPEN"]
