"""Fixed-shape KV caches: ring buffers and the paged block pool.

The serving engine's decode program must have ONE shape forever —
``compiled_step_info()["n_traces"] == 1`` is the serve-path invariant —
so the attention cache cannot grow with the sequence. Two layouts
satisfy that contract:

**Ring** (the original, still the default): each slot owns a RING of
``length`` key/value rows per layer: token ``t`` writes ring index
``t % length``, and the decode attention masks each index by the token
position it currently holds. Work and memory per emitted token are
constant (the compiler-first O(1)-cache design of PAPERS.md arxiv
2603.09555); semantically the ring IS sliding-window attention over the
last ``length`` tokens, and for sequences that fit (``pos < length``)
it is exactly full causal attention — the wraparound-vs-reference test
in ``tests/test_serving.py`` pins both. One ring level is
``(n_slots, n_heads, length, head_dim)`` — a W×L×H×D monolith whether
the slots are long, short, or empty.

**Paged** (``compile_serving(kv_layout="paged")``): one fixed POOL of
``(n_blocks, n_heads, block_size, head_dim)`` KV blocks per layer plus
a host-side per-slot block table mapping logical block index
``position // block_size`` to a pool block id. Memory scales with LIVE
tokens (each admitted request reserves exactly the blocks its
``prompt + max_new_tokens`` span needs) instead of slots × max_len, and
identical prompt prefixes SHARE refcounted blocks: a prefix-cache hit
skips prefill compute for the shared span entirely (the suffix is
prefilled chunked, attending to the cached prefix through the same
block table). Sharing granularity is whole blocks, capped one token
short of the full prompt (the last prompt token is always prefilled so
its logits exist); divergence is handled by construction — the
divergent tail block is never shared, the new request writes its own
copy (copy-on-write without a device copy). The device math is
position-exact: logical block ``b`` offset ``o`` holds position
``b*block_size + o``, attention masks ``position <= query position``,
so stale rows (freed sequences, rejected speculative drafts) are
unreachable until overwritten. The host-side :class:`BlockManager`
owns allocation, refcounts, and the prefix cache; exhaustion is a
typed :class:`~singa_tpu.serving.scheduler.BlockPoolExhausted`
admission refusal — a LIVE sequence's blocks are never evicted, only
unreferenced cached prefixes are reclaimed (LRU).

Everything device-side here is a pure function over arrays,
shape-stable by construction, ready to be closed over by a jitted
prefill/decode body. ``dtype=int8`` rides both layouts: per-row fp32
scales beside the ring, per-(block, offset) scale pools beside the
paged blocks.

Ring position bookkeeping (who holds ring index ``j`` when the newest
written token is at position ``p``)::

    t_j = p - ((p - j) % length)        # newest token position at j
    valid(j) = t_j >= 0                 # j was ever written

which masks exactly the last ``min(p+1, length)`` token positions —
no flags, no per-slot host state, just arithmetic on ``p``.
"""

from __future__ import annotations

import hashlib

import jax
import jax.numpy as jnp
from jax import lax


# ---------------------------------------------------------------------------
# chained prefix content keys (shared by the block manager's prefix
# cache and the fleet router's prefix-affinity placement)
# ---------------------------------------------------------------------------

def chain_keys(prompt, block_size):
    """Chained content keys for each FULL block of ``prompt``: key
    ``b`` covers block ``b``'s tokens AND everything before it, so a
    key match guarantees the whole preceding context matches. The ONE
    key construction — :class:`BlockManager`'s prefix cache and the
    fleet router's prefix-affinity hash both build keys here, so
    "lands on the replica holding the blocks" is true by construction,
    never by parallel reimplementation."""
    bs = int(block_size)
    keys, prev = [], ()
    for b in range(len(prompt) // bs):
        prev = (prev, tuple(int(t) for t in prompt[b*bs:(b+1)*bs]))
        keys.append(prev)
    return keys


def prefix_chain_key(prompt, block_size):
    """The chained content key of ``prompt``'s longest CACHEABLE
    full-block prefix — capped one token short of the whole prompt
    (``match_prefix``'s cap: the last token is always prefilled so its
    logits exist). ``None`` for a prompt too short to share even one
    block (a *cold* prefix — affinity routing falls back to
    least-loaded)."""
    cap = (len(prompt) - 1) // int(block_size)
    if cap <= 0:
        return None
    return chain_keys(prompt, block_size)[cap - 1]


def affinity_hash(key, salt=""):
    """Stable 64-bit digest of a chain key (optionally salted with a
    replica name for rendezvous/HRW scoring). Deliberately NOT python
    ``hash()``: that is randomized per process, and the affinity
    contract is *same prefix → same decode replica across router
    restarts*. sha1 over the key's canonical repr is stable across
    processes, platforms, and time."""
    h = hashlib.sha1(
        (repr(key) + "\x00" + str(salt)).encode()).digest()
    return int.from_bytes(h[:8], "big")


def init_cache(n_slots, n_heads, length, head_dim, dtype=jnp.float32):
    """One layer's ring cache: zeroed ``{"k","v"}`` of shape
    ``(n_slots, n_heads, length, head_dim)``.

    ``dtype=int8`` builds the QUANTIZED ring (the
    ``singa_tpu.quant`` serving presets): int8 payloads plus one fp32
    scale per (slot, ring index) — ``{"k_scale","v_scale"}`` of shape
    ``(n_slots, length)`` — written alongside every token/prompt row
    and folded back in inside :func:`attend`'s f32 softmax. 4x less
    cache HBM per token; scales init to 1 (a zero payload dequantizes
    to zero either way)."""
    shape = (int(n_slots), int(n_heads), int(length), int(head_dim))
    level = {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    if jnp.dtype(dtype) == jnp.dtype(jnp.int8):
        # two DISTINCT buffers: the engine donates the whole cache
        # pytree, and donating one shared array twice is an XLA error
        level["k_scale"] = jnp.ones((int(n_slots), int(length)),
                                    jnp.float32)
        level["v_scale"] = jnp.ones((int(n_slots), int(length)),
                                    jnp.float32)
    return level


def _quant_rows(x, axes):
    """Per-row cache quantization (one scale per written token row) —
    the ONE symmetric-int8 convention, shared with weight quantization
    so the two can never silently diverge."""
    from ..quant.core import quantize_int8_rows
    return quantize_int8_rows(x, axes)


def _dequant_level(level):
    """f32 views of a level's k/v — identity for float caches, payload
    × per-row scale for the quantized ring."""
    k, v = level["k"], level["v"]
    if "k_scale" in level:
        # (W, H, L, D) payload, (W, L) scale -> broadcast over H and D
        k = k.astype(jnp.float32) * level["k_scale"][:, None, :, None]
        v = v.astype(jnp.float32) * level["v_scale"][:, None, :, None]
    return k, v


def ring_positions(pos, length):
    """For newest-written position ``pos`` (vector over slots), the
    token position held at each ring index: ``(W, length)`` int32.
    Negative entries mean "never written"."""
    j = jnp.arange(length, dtype=jnp.int32)
    pos = pos.astype(jnp.int32)[:, None]
    return pos - ((pos - j[None, :]) % length)


def ring_mask(pos, length):
    """``(W, length)`` bool: ring entries holding a real token when the
    newest written position is ``pos`` per slot."""
    return ring_positions(pos, length) >= 0


def write_token(level, k_new, v_new, pos):
    """Write one new token per slot at its ring index.

    ``level``: ``{"k","v"}`` of ``(W, H, L, D)``;
    ``k_new``/``v_new``: ``(W, H, D)``; ``pos``: ``(W,)`` int — the new
    token's position. Returns the updated level. Every slot is written
    (the engine masks dead slots by never attending to them; a freed
    slot's rows are fully overwritten by its next prefill before any
    mask can reach them). A quantized level additionally writes each
    row's fp32 scale into its per-slot scale row."""
    L = level["k"].shape[2]
    pos = pos.astype(jnp.int32)

    def upd(c, row, p):
        return lax.dynamic_update_slice(
            c, row[:, None, :].astype(c.dtype), (0, p % L, 0))

    if "k_scale" not in level:
        return {"k": jax.vmap(upd)(level["k"], k_new, pos),
                "v": jax.vmap(upd)(level["v"], v_new, pos)}
    # quantized ring: one scale per (slot, ring index), amax over (H,D)
    kq, ks = _quant_rows(k_new, (1, 2))           # (W,H,D) -> (W,)
    vq, vs = _quant_rows(v_new, (1, 2))

    def upd_s(srow, sval, p):
        return lax.dynamic_update_slice(srow, sval[None], (p % L,))

    return {"k": jax.vmap(upd)(level["k"], kq, pos),
            "v": jax.vmap(upd)(level["v"], vq, pos),
            "k_scale": jax.vmap(upd_s)(level["k_scale"], ks, pos),
            "v_scale": jax.vmap(upd_s)(level["v_scale"], vs, pos)}


def write_prompt(level, slot, k_rows, v_rows, valid):
    """Write one prompt's rows into one slot, starting at ring index 0.

    ``k_rows``/``v_rows``: ``(H, S, D)`` with ``S <= L`` (the engine's
    ``prefill_len <= max_len`` contract); ``slot`` scalar int;
    ``valid`` scalar bool — False rows (prefill-batch padding) leave
    the cache untouched, which is what lets the prefill program keep a
    FIXED batch width over a variable number of admitted requests. A
    quantized level quantizes per token row (scale amax over heads ×
    head_dim) and writes the prompt's scale rows alongside."""
    if "k_scale" in level:
        # (H, S, D): one scale per prompt position -> (S,)
        k_rows, ks = _quant_rows(k_rows, (0, 2))
        v_rows, vs = _quant_rows(v_rows, (0, 2))
    k_up = lax.dynamic_update_slice(
        level["k"], k_rows[None].astype(level["k"].dtype),
        (slot, 0, 0, 0))
    v_up = lax.dynamic_update_slice(
        level["v"], v_rows[None].astype(level["v"].dtype),
        (slot, 0, 0, 0))
    out = {"k": jnp.where(valid, k_up, level["k"]),
           "v": jnp.where(valid, v_up, level["v"])}
    if "k_scale" in level:
        ks_up = lax.dynamic_update_slice(level["k_scale"], ks[None],
                                         (slot, 0))
        vs_up = lax.dynamic_update_slice(level["v_scale"], vs[None],
                                         (slot, 0))
        out["k_scale"] = jnp.where(valid, ks_up, level["k_scale"])
        out["v_scale"] = jnp.where(valid, vs_up, level["v_scale"])
    return out


def attend(q, level, pos, scale):
    """Ring attention for one decode tick.

    ``q``: ``(W, H, 1, D)`` (the new token's query, already written to
    the ring along with its k/v); ``pos``: ``(W,)`` — the new token's
    position. Softmax in f32 regardless of cache dtype (bf16 AND int8
    serving keep their numerics sane — a quantized ring dequantizes
    its rows here, payload × per-row scale, before the f32 scores),
    result cast back to ``q.dtype``. Returns ``(W, H, 1, D)``."""
    L = level["k"].shape[2]
    kf, vf = _dequant_level(level)
    s = jnp.einsum("whqd,whld->whql", q.astype(jnp.float32),
                   kf.astype(jnp.float32)) * scale
    mask = ring_mask(pos, L)[:, None, None, :]
    s = jnp.where(mask, s, -jnp.inf)
    a = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("whql,whld->whqd", a, vf.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# paged block pool: device math
# ---------------------------------------------------------------------------

def init_pool(n_blocks, n_heads, block_size, head_dim,
              dtype=jnp.float32):
    """One layer's block pool: zeroed ``{"k","v"}`` of shape
    ``(n_blocks, n_heads, block_size, head_dim)``.

    ``dtype=int8`` builds the QUANTIZED pool: int8 payloads plus one
    fp32 scale per (block, offset) row — ``{"k_scale","v_scale"}`` of
    shape ``(n_blocks, block_size)`` — written alongside every row and
    folded back in inside :func:`gather_pages`. Same per-row symmetric
    convention as the int8 ring (``quant.core.quantize_int8_rows``),
    so the two layouts cannot silently diverge numerically."""
    shape = (int(n_blocks), int(n_heads), int(block_size),
             int(head_dim))
    level = {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    if jnp.dtype(dtype) == jnp.dtype(jnp.int8):
        # distinct buffers (whole-pool donation, like the int8 ring)
        level["k_scale"] = jnp.ones((int(n_blocks), int(block_size)),
                                    jnp.float32)
        level["v_scale"] = jnp.ones((int(n_blocks), int(block_size)),
                                    jnp.float32)
    return level


def write_rows(level, tables, k_new, v_new, pos, wmask):
    """Write token rows into their block-table-mapped pool rows.

    ``tables``: ``(R, n_pages)`` int32 pool block ids per row (slot);
    ``k_new``/``v_new``: ``(R, H, Q, D)`` fresh rows; ``pos``:
    ``(R, Q)`` absolute token positions; ``wmask``: ``(R, Q)`` bool —
    False rows (batch padding, inactive slots, draft padding) are
    DROPPED via an out-of-bounds scatter index, never written. One
    scatter per tensor, fixed shape for any R/Q."""
    N = level["k"].shape[0]
    bs = level["k"].shape[2]
    pos = pos.astype(jnp.int32)
    page = jnp.take_along_axis(tables.astype(jnp.int32),
                               pos // bs, axis=1)        # (R, Q)
    off = pos % bs
    # masked rows scatter to block id N: out of bounds, mode="drop"
    page = jnp.where(wmask, page, N)
    R, H, Q, D = k_new.shape
    flat = lambda a: a.transpose(0, 2, 1, 3).reshape(R * Q, H, D)  # noqa: E731
    pf, of = page.reshape(-1), off.reshape(-1)
    if "k_scale" not in level:
        k_rows, v_rows = flat(k_new), flat(v_new)
        return dict(
            level,
            k=level["k"].at[pf, :, of, :].set(
                k_rows.astype(level["k"].dtype), mode="drop"),
            v=level["v"].at[pf, :, of, :].set(
                v_rows.astype(level["v"].dtype), mode="drop"))
    from ..quant.core import quantize_int8_rows
    # one scale per (row, token): amax over heads × head_dim
    kq, ks = quantize_int8_rows(k_new, (1, 3))           # scale (R, Q)
    vq, vs = quantize_int8_rows(v_new, (1, 3))
    return dict(
        level,
        k=level["k"].at[pf, :, of, :].set(flat(kq), mode="drop"),
        v=level["v"].at[pf, :, of, :].set(flat(vq), mode="drop"),
        k_scale=level["k_scale"].at[pf, of].set(
            ks.reshape(-1), mode="drop"),
        v_scale=level["v_scale"].at[pf, of].set(
            vs.reshape(-1), mode="drop"))


def gather_pages(level, tables):
    """Materialise each row's logical KV view from its block table:
    ``(R, n_pages)`` table -> f32 ``k, v`` of
    ``(R, H, n_pages*block_size, D)`` with logical index == token
    position. A quantized pool dequantizes here (payload × per-row
    scale) into the caller's f32 softmax. Unallocated table entries
    gather garbage by design — the caller's position mask never admits
    a position beyond the row's allocated span."""
    t = tables.astype(jnp.int32)
    k = jnp.take(level["k"], t, axis=0)     # (R, P, H, bs, D)
    v = jnp.take(level["v"], t, axis=0)
    if "k_scale" in level:
        ks = jnp.take(level["k_scale"], t, axis=0)       # (R, P, bs)
        vs = jnp.take(level["v_scale"], t, axis=0)
        k = k.astype(jnp.float32) * ks[:, :, None, :, None]
        v = v.astype(jnp.float32) * vs[:, :, None, :, None]
    R, P, H, bs, D = k.shape
    k = k.transpose(0, 2, 1, 3, 4).reshape(R, H, P * bs, D)
    v = v.transpose(0, 2, 1, 3, 4).reshape(R, H, P * bs, D)
    return k, v


def attend_pages(q, level, tables, q_pos, scale):
    """Paged causal attention: each query attends every cached position
    ``<= its own`` through the row's block table.

    ``q``: ``(R, H, Q, D)``; ``q_pos``: ``(R, Q)`` absolute query
    positions (the fresh rows are written BEFORE this runs, so a query
    sees itself and everything earlier — exactly full causal
    attention). Softmax in f32 regardless of pool dtype, result cast
    back to ``q.dtype``. Returns ``(R, H, Q, D)``."""
    kf, vf = gather_pages(level, tables)
    L = kf.shape[2]
    s = jnp.einsum("rhqd,rhld->rhql", q.astype(jnp.float32),
                   kf.astype(jnp.float32)) * scale
    mask = jnp.arange(L, dtype=jnp.int32)[None, None, None, :] \
        <= q_pos.astype(jnp.int32)[:, None, :, None]
    s = jnp.where(mask, s, -jnp.inf)
    a = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("rhql,rhld->rhqd", a, vf.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# host-RAM spill tier for evicted cached-prefix blocks
# ---------------------------------------------------------------------------

class HostSpillTier:
    """Byte-budgeted host-RAM tier for evicted cached-prefix KV blocks.

    When pool pressure evicts an unreferenced cached-prefix block
    (:meth:`BlockManager._evict_lru`), its rows are pulled to host and
    parked here as a CRC-sealed frame (:func:`integrity.seal_frame`)
    keyed by the same chained content key the device prefix cache uses.
    A later prefix hit RESTORES the rows into a fresh pool block instead
    of re-prefilling the span — graceful degradation under pressure, not
    recompute. LIVE blocks never reach this tier by construction:
    eviction only ever selects refcount-0 cached blocks.

    The budget is exact: an insert evicts LRU entries until the new
    entry fits, and an entry larger than the whole budget is refused
    outright. A frame that fails its CRC on the way back out is dropped
    (counted in ``drops``) and the caller re-prefills — corrupt rows are
    never restored into the pool."""

    def __init__(self, budget_bytes):
        from collections import OrderedDict
        self.budget_bytes = int(budget_bytes)
        self._entries = OrderedDict()   # key -> (meta, sealed_frame)
        self.bytes_used = 0
        self.drops = 0                  # CRC-failed frames discarded

    def __len__(self):
        return len(self._entries)

    @staticmethod
    def _size(meta, sealed):
        return len(meta) + len(sealed)

    def put(self, key, meta, payload):
        """Seal and store one evicted block's rows. Returns True when
        stored, False when the entry alone exceeds the byte budget."""
        from .. import integrity as _integrity
        meta = bytes(meta)
        sealed = _integrity.seal_frame(meta, payload)
        size = self._size(meta, sealed)
        if size > self.budget_bytes:
            return False
        old = self._entries.pop(key, None)
        if old is not None:
            self.bytes_used -= self._size(*old)
        while self._entries and self.bytes_used + size > self.budget_bytes:
            _k, (m, s) = self._entries.popitem(last=False)
            self.bytes_used -= self._size(m, s)
        self._entries[key] = (meta, sealed)
        self.bytes_used += size
        return True

    def get(self, key):
        """``(meta, payload)`` for a stored key after CRC verification,
        or None (absent, or corrupt — corrupt entries are dropped)."""
        from .. import integrity as _integrity
        entry = self._entries.get(key)
        if entry is None:
            return None
        meta, sealed = entry
        try:
            payload = _integrity.open_frame(meta, sealed)
        except _integrity.IntegrityError:
            self._entries.pop(key, None)
            self.bytes_used -= self._size(meta, sealed)
            self.drops += 1
            return None
        self._entries.move_to_end(key)          # LRU refresh
        return meta, payload


# ---------------------------------------------------------------------------
# paged block pool: host-side manager (allocation, refcounts, prefix cache)
# ---------------------------------------------------------------------------

class SlotAlloc:
    """One admitted sequence's block reservation: the pool block ids
    covering its full ``prompt + max_new_tokens`` span (shared prefix
    blocks first, then private blocks), plus how many prompt tokens the
    prefix-cache hit covers (``shared_tokens`` — prefill skips them)."""

    __slots__ = ("blocks", "shared_tokens", "prompt_blocks")

    def __init__(self, blocks, shared_tokens, prompt_blocks):
        self.blocks = list(blocks)
        self.shared_tokens = int(shared_tokens)
        # how many leading blocks hold FULL prompt content (cacheable
        # on release); the partial tail / generated blocks never cache
        self.prompt_blocks = int(prompt_blocks)


class BlockManager:
    """Host-side block accounting for one engine's pool (single loop
    thread; no locking needed — submit-path callers only read totals).

    Block states: **free** (on the free list), **live** (refcount > 0 —
    NEVER reclaimed), **cached** (refcount 0 but registered in the
    prefix cache — reclaimable, LRU). The prefix cache maps a CHAINED
    content key (this block's tokens + everything before it) to a block
    id, so a hit guarantees the whole preceding context matches — the
    only condition under which cached K/V rows are reusable."""

    def __init__(self, n_blocks, block_size):
        self.n_blocks = int(n_blocks)
        self.block_size = int(block_size)
        self._ref = [0] * self.n_blocks
        self._key = [None] * self.n_blocks      # prefix-cache key or None
        self._free = list(range(self.n_blocks - 1, -1, -1))
        self._cache = {}                        # chained key -> block id
        self._lru = {}                          # block id -> stamp
        self._tick = 0
        # host-RAM spill tier (attach_spill): evicted cached prefixes
        # park here instead of vanishing
        self._spill = None
        self._spill_read = None
        self._spill_write = None
        self._on_spill = None
        self._on_restore = None
        self.spilled_total = 0
        self.restored_total = 0

    def attach_spill(self, tier, reader, writer,
                     on_spill=None, on_restore=None):
        """Arm the host-RAM spill tier. The manager has no device
        access, so the engine supplies ``reader(bid) -> (meta, bytes)``
        (pull one pool block's rows to host) and
        ``writer(bid, meta, payload)`` (push them back). ``on_spill`` /
        ``on_restore`` are metric hooks called once per block moved."""
        self._spill = tier
        self._spill_read = reader
        self._spill_write = writer
        self._on_spill = on_spill
        self._on_restore = on_restore

    # -- introspection (gauges, tests) -------------------------------------
    def blocks_live(self):
        return sum(1 for r in self._ref if r > 0)

    def blocks_cached(self):
        return sum(1 for i, r in enumerate(self._ref)
                   if r == 0 and self._key[i] is not None)

    def blocks_free(self):
        return len(self._free)

    def n_for(self, n_tokens):
        """Blocks covering ``n_tokens`` positions."""
        return -(-int(n_tokens) // self.block_size)

    # -- prefix cache -------------------------------------------------------
    def _chain_keys(self, prompt):
        """Chained content keys for each FULL block of ``prompt``."""
        return chain_keys(prompt, self.block_size)

    def match_prefix(self, prompt):
        """Longest cached full-block prefix of ``prompt``, capped one
        token short of the whole prompt (the last token must be
        prefilled so its logits exist). Returns
        ``(block_ids, n_tokens)`` WITHOUT taking references —
        :meth:`admit` re-matches and takes them atomically."""
        cap = (len(prompt) - 1) // self.block_size
        ids = []
        for key in self._chain_keys(prompt)[:cap]:
            bid = self._cache.get(key)
            if bid is None:
                break
            ids.append(bid)
        return ids, len(ids) * self.block_size

    # -- allocation ---------------------------------------------------------
    def _reclaimable(self, shared):
        """Free + cached blocks available to a request whose prefix hit
        covers ``shared`` (those are about to become live — they must
        not be counted as evictable fuel for the same admission)."""
        keep = set(shared)
        cached = sum(1 for i, r in enumerate(self._ref)
                     if r == 0 and self._key[i] is not None
                     and i not in keep)
        return len(self._free) + cached

    def can_admit(self, prompt, total_tokens):
        """Whether :meth:`admit` would succeed right now (the queue's
        backpressure gate — a request that cannot be placed THIS tick
        stays queued, it is not failed)."""
        shared, _ = self.match_prefix(prompt)
        need = self.n_for(total_tokens) - len(shared)
        return need <= self._reclaimable(shared)

    def admit(self, prompt, total_tokens):
        """Reserve every block the sequence can ever touch (positions
        ``[0, total_tokens)`` — decode can then never stall or corrupt
        a neighbour mid-flight). Shared prefix blocks are re-referenced
        FIRST (so LRU reclaim can never eat the prefix being shared);
        the rest come from the free list, reclaiming LRU cached blocks
        when it runs dry. Raises
        :class:`~singa_tpu.serving.scheduler.BlockPoolExhausted` when
        the pool cannot cover it without touching a live block."""
        from .scheduler import BlockPoolExhausted
        shared, shared_tokens = self.match_prefix(prompt)
        need = self.n_for(total_tokens) - len(shared)
        if need > self._reclaimable(shared):
            live = self.blocks_live()
            raise BlockPoolExhausted(
                f"block pool exhausted: need {need} free blocks for a "
                f"{total_tokens}-token reservation ({len(shared)} "
                f"shared), have {len(self._free)} free + "
                f"{self.blocks_cached()} reclaimable cached "
                f"({live} live blocks are never evicted; pool is "
                f"{self.n_blocks} × {self.block_size} tokens)")
        self._tick += 1
        for bid in shared:
            self._ref[bid] += 1
            self._lru[bid] = self._tick
        fresh = [self._take_free() for _ in range(need)]
        shared_tokens += self._restore_spilled(prompt, shared, fresh)
        return SlotAlloc(shared + fresh, shared_tokens,
                         len(prompt) // self.block_size)

    def _restore_spilled(self, prompt, shared, fresh):
        """Continue the prefix chain past the device-cache hit against
        the spill tier: each consecutive hit restores its rows into the
        next fresh block (which then re-enters the prefix cache under
        its chained key) and extends the shared span — the tokens it
        covers skip prefill. Returns extra shared tokens. Restored
        blocks come out of the SAME ``fresh`` reservation, so admission
        accounting (``can_admit``/``_reclaimable``) is unchanged."""
        if self._spill is None or self._spill_write is None or not fresh:
            return 0
        keys = self._chain_keys(prompt)
        cap = (len(prompt) - 1) // self.block_size   # match_prefix cap
        restored = 0
        for j in range(len(shared), cap):
            if restored >= len(fresh):
                break
            hit = self._spill.get(keys[j])
            if hit is None:
                break
            meta, payload = hit
            bid = fresh[restored]
            try:
                self._spill_write(bid, meta, payload)
            except Exception:
                break       # degrade to re-prefilling the span
            if keys[j] not in self._cache:
                self._key[bid] = keys[j]
                self._cache[keys[j]] = bid
            self._lru[bid] = self._tick
            restored += 1
            self.restored_total += 1
            if self._on_restore is not None:
                self._on_restore()
        return restored * self.block_size

    def _take_free(self):
        if not self._free:
            self._evict_lru()
        bid = self._free.pop()
        self._ref[bid] = 1
        return bid

    def _evict_lru(self):
        """Reclaim the least-recently-used CACHED block (refcount 0).
        Callers guarantee one exists (can_admit/admit checked). With a
        spill tier attached the victim's rows move to host RAM first —
        only cached-prefix blocks ever reach this point, so a LIVE
        block can never be spilled."""
        victim = min(
            (i for i in range(self.n_blocks)
             if self._ref[i] == 0 and self._key[i] is not None),
            key=lambda i: self._lru.get(i, 0))
        if self._spill is not None and self._spill_read is not None:
            try:
                meta, payload = self._spill_read(victim)
                if self._spill.put(self._key[victim], meta, payload):
                    self.spilled_total += 1
                    if self._on_spill is not None:
                        self._on_spill()
            except Exception:
                pass        # spilling is best-effort; eviction is not
        del self._cache[self._key[victim]]
        self._key[victim] = None
        self._lru.pop(victim, None)
        self._free.append(victim)

    def release(self, alloc, prompt):
        """Drop a finished/failed sequence's references. Its FULL
        prompt blocks enter the prefix cache (refcount 0, reclaimable)
        so the next identical prompt skips their prefill; partial-tail
        and generated-token blocks free immediately."""
        keys = self._chain_keys(prompt)
        self._tick += 1
        for i, bid in enumerate(alloc.blocks):
            self._ref[bid] -= 1
            if i < alloc.prompt_blocks and self._key[bid] is None \
                    and keys[i] not in self._cache:
                self._key[bid] = keys[i]
                self._cache[keys[i]] = bid
                self._lru[bid] = self._tick
            if self._ref[bid] == 0 and self._key[bid] is None:
                self._free.append(bid)


__all__ = ["init_cache", "ring_positions", "ring_mask", "write_token",
           "write_prompt", "attend", "init_pool", "write_rows",
           "gather_pages", "attend_pages", "SlotAlloc", "BlockManager",
           "HostSpillTier", "chain_keys", "prefix_chain_key",
           "affinity_hash"]
