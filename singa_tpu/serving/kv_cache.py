"""Fixed-shape ring KV caches: O(1) autoregressive decode state.

The serving engine's decode program must have ONE shape forever —
``compiled_step_info()["n_traces"] == 1`` is the serve-path invariant —
so the attention cache cannot grow with the sequence. Instead each slot
owns a RING of ``length`` key/value rows per layer: token ``t`` writes
ring index ``t % length``, and the decode attention masks each index by
the token position it currently holds. Work and memory per emitted
token are therefore constant (the compiler-first O(1)-cache design of
PAPERS.md arxiv 2603.09555); semantically the ring IS sliding-window
attention over the last ``length`` tokens, and for sequences that fit
(``pos < length``) it is exactly full causal attention — the
wraparound-vs-reference test in ``tests/test_serving.py`` pins both.

Everything here is a pure function over arrays, shape-stable by
construction, ready to be closed over by a jitted prefill/decode body.
Layout: one cache level is ``(n_slots, n_heads, length, head_dim)``.

Position bookkeeping (who holds ring index ``j`` when the newest
written token is at position ``p``)::

    t_j = p - ((p - j) % length)        # newest token position at j
    valid(j) = t_j >= 0                 # j was ever written

which masks exactly the last ``min(p+1, length)`` token positions —
no flags, no per-slot host state, just arithmetic on ``p``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def init_cache(n_slots, n_heads, length, head_dim, dtype=jnp.float32):
    """One layer's ring cache: zeroed ``{"k","v"}`` of shape
    ``(n_slots, n_heads, length, head_dim)``.

    ``dtype=int8`` builds the QUANTIZED ring (the
    ``singa_tpu.quant`` serving presets): int8 payloads plus one fp32
    scale per (slot, ring index) — ``{"k_scale","v_scale"}`` of shape
    ``(n_slots, length)`` — written alongside every token/prompt row
    and folded back in inside :func:`attend`'s f32 softmax. 4x less
    cache HBM per token; scales init to 1 (a zero payload dequantizes
    to zero either way)."""
    shape = (int(n_slots), int(n_heads), int(length), int(head_dim))
    level = {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    if jnp.dtype(dtype) == jnp.dtype(jnp.int8):
        # two DISTINCT buffers: the engine donates the whole cache
        # pytree, and donating one shared array twice is an XLA error
        level["k_scale"] = jnp.ones((int(n_slots), int(length)),
                                    jnp.float32)
        level["v_scale"] = jnp.ones((int(n_slots), int(length)),
                                    jnp.float32)
    return level


def _quant_rows(x, axes):
    """Per-row cache quantization (one scale per written token row) —
    the ONE symmetric-int8 convention, shared with weight quantization
    so the two can never silently diverge."""
    from ..quant.core import quantize_int8_rows
    return quantize_int8_rows(x, axes)


def _dequant_level(level):
    """f32 views of a level's k/v — identity for float caches, payload
    × per-row scale for the quantized ring."""
    k, v = level["k"], level["v"]
    if "k_scale" in level:
        # (W, H, L, D) payload, (W, L) scale -> broadcast over H and D
        k = k.astype(jnp.float32) * level["k_scale"][:, None, :, None]
        v = v.astype(jnp.float32) * level["v_scale"][:, None, :, None]
    return k, v


def ring_positions(pos, length):
    """For newest-written position ``pos`` (vector over slots), the
    token position held at each ring index: ``(W, length)`` int32.
    Negative entries mean "never written"."""
    j = jnp.arange(length, dtype=jnp.int32)
    pos = pos.astype(jnp.int32)[:, None]
    return pos - ((pos - j[None, :]) % length)


def ring_mask(pos, length):
    """``(W, length)`` bool: ring entries holding a real token when the
    newest written position is ``pos`` per slot."""
    return ring_positions(pos, length) >= 0


def write_token(level, k_new, v_new, pos):
    """Write one new token per slot at its ring index.

    ``level``: ``{"k","v"}`` of ``(W, H, L, D)``;
    ``k_new``/``v_new``: ``(W, H, D)``; ``pos``: ``(W,)`` int — the new
    token's position. Returns the updated level. Every slot is written
    (the engine masks dead slots by never attending to them; a freed
    slot's rows are fully overwritten by its next prefill before any
    mask can reach them). A quantized level additionally writes each
    row's fp32 scale into its per-slot scale row."""
    L = level["k"].shape[2]
    pos = pos.astype(jnp.int32)

    def upd(c, row, p):
        return lax.dynamic_update_slice(
            c, row[:, None, :].astype(c.dtype), (0, p % L, 0))

    if "k_scale" not in level:
        return {"k": jax.vmap(upd)(level["k"], k_new, pos),
                "v": jax.vmap(upd)(level["v"], v_new, pos)}
    # quantized ring: one scale per (slot, ring index), amax over (H,D)
    kq, ks = _quant_rows(k_new, (1, 2))           # (W,H,D) -> (W,)
    vq, vs = _quant_rows(v_new, (1, 2))

    def upd_s(srow, sval, p):
        return lax.dynamic_update_slice(srow, sval[None], (p % L,))

    return {"k": jax.vmap(upd)(level["k"], kq, pos),
            "v": jax.vmap(upd)(level["v"], vq, pos),
            "k_scale": jax.vmap(upd_s)(level["k_scale"], ks, pos),
            "v_scale": jax.vmap(upd_s)(level["v_scale"], vs, pos)}


def write_prompt(level, slot, k_rows, v_rows, valid):
    """Write one prompt's rows into one slot, starting at ring index 0.

    ``k_rows``/``v_rows``: ``(H, S, D)`` with ``S <= L`` (the engine's
    ``prefill_len <= max_len`` contract); ``slot`` scalar int;
    ``valid`` scalar bool — False rows (prefill-batch padding) leave
    the cache untouched, which is what lets the prefill program keep a
    FIXED batch width over a variable number of admitted requests. A
    quantized level quantizes per token row (scale amax over heads ×
    head_dim) and writes the prompt's scale rows alongside."""
    if "k_scale" in level:
        # (H, S, D): one scale per prompt position -> (S,)
        k_rows, ks = _quant_rows(k_rows, (0, 2))
        v_rows, vs = _quant_rows(v_rows, (0, 2))
    k_up = lax.dynamic_update_slice(
        level["k"], k_rows[None].astype(level["k"].dtype),
        (slot, 0, 0, 0))
    v_up = lax.dynamic_update_slice(
        level["v"], v_rows[None].astype(level["v"].dtype),
        (slot, 0, 0, 0))
    out = {"k": jnp.where(valid, k_up, level["k"]),
           "v": jnp.where(valid, v_up, level["v"])}
    if "k_scale" in level:
        ks_up = lax.dynamic_update_slice(level["k_scale"], ks[None],
                                         (slot, 0))
        vs_up = lax.dynamic_update_slice(level["v_scale"], vs[None],
                                         (slot, 0))
        out["k_scale"] = jnp.where(valid, ks_up, level["k_scale"])
        out["v_scale"] = jnp.where(valid, vs_up, level["v_scale"])
    return out


def attend(q, level, pos, scale):
    """Ring attention for one decode tick.

    ``q``: ``(W, H, 1, D)`` (the new token's query, already written to
    the ring along with its k/v); ``pos``: ``(W,)`` — the new token's
    position. Softmax in f32 regardless of cache dtype (bf16 AND int8
    serving keep their numerics sane — a quantized ring dequantizes
    its rows here, payload × per-row scale, before the f32 scores),
    result cast back to ``q.dtype``. Returns ``(W, H, 1, D)``."""
    L = level["k"].shape[2]
    kf, vf = _dequant_level(level)
    s = jnp.einsum("whqd,whld->whql", q.astype(jnp.float32),
                   kf.astype(jnp.float32)) * scale
    mask = ring_mask(pos, L)[:, None, None, :]
    s = jnp.where(mask, s, -jnp.inf)
    a = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("whql,whld->whqd", a, vf.astype(jnp.float32))
    return out.astype(q.dtype)


__all__ = ["init_cache", "ring_positions", "ring_mask", "write_token",
           "write_prompt", "attend"]
