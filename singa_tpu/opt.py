"""Optimizers and the distributed optimizer driver.

Capability parity with reference python/singa/opt.py:
- tensor-resident scheduled hyperparameters (DecayScheduler, opt.py:28-68)
  so the learning rate is a traced value — schedules advance inside the
  compiled step with no recompilation;
- SGD/RMSProp/AdaGrad/Adam with the same update math (opt.py:174-660);
- DistOpt (opt.py:686-1094) whose all-reduce is `jax.lax.psum` over the mesh
  'data' axis instead of NCCL: the reference's fused-buffer trick
  (Communicator::fusedSynch) is unnecessary because XLA fuses and overlaps
  collectives; fp16 comm becomes bf16-cast-before-psum; topK/threshold
  sparsification is reproduced with mask + error-feedback residuals.

Because ``autograd.backward`` yields (param, grad) lazily, each all-reduce is
issued as soon as that gradient is complete — inside one jit trace XLA then
overlaps collectives with remaining backward compute, which is the TPU form
of the reference's stream-overlap design (opt.py:826-865).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd
from .tensor import Tensor


class DecayScheduler:
    """lr(step) as a traced function (reference opt.py:28-45)."""

    def __init__(self, init_value):
        self.init_value = init_value

    def __call__(self, step):
        raise NotImplementedError

    def get_states(self):
        return {"init_value": self.init_value}

    def set_states(self, states):
        if "init_value" in states:
            self.init_value = float(states["init_value"])


class Constant(DecayScheduler):
    def __call__(self, step):
        return jnp.asarray(self.init_value, dtype=jnp.float32)


class ExponentialDecay(DecayScheduler):
    def __init__(self, init_value, decay_steps, decay_rate, staircase=False):
        super().__init__(init_value)
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def __call__(self, step):
        s = step.data if isinstance(step, Tensor) else step
        s = s.astype(jnp.float32)
        e = s / self.decay_steps
        if self.staircase:
            e = jnp.floor(e)
        return self.init_value * jnp.power(self.decay_rate, e)


class Regularizer:
    """Parameter-gradient regularizer (reference
    include/singa/model/optimizer.h:151-244, src/model/optimizer/
    optimizer.cc:92-99: L2 is ``grad += coefficient * value``).

    Functional: ``apply`` returns the new gradient array so it composes
    inside a jit-traced update."""

    def __init__(self, type="l2", coefficient=0.0):
        self.type = type.lower()
        if self.type not in ("l1", "l2", "notset"):
            raise ValueError(f"unknown regularizer type {type!r}")
        self.coefficient = coefficient

    def apply(self, value, grad):
        if self.type == "l2":
            return grad + self.coefficient * value
        if self.type == "l1":
            return grad + self.coefficient * jnp.sign(value)
        return grad


class Constraint:
    """Parameter-gradient constraint (reference optimizer.h:101-144: clip
    the gradient's L2 norm to a threshold; the reference declares the API
    and documents the semantics but stubs the math — here it is real)."""

    def __init__(self, type="l2", threshold=1.0):
        self.type = type.lower()
        if self.type not in ("l2", "value", "notset"):
            raise ValueError(f"unknown constraint type {type!r}")
        self.threshold = threshold

    def apply(self, value, grad):
        if self.type == "l2":
            norm = jnp.sqrt(jnp.sum(grad.astype(jnp.float32) ** 2))
            scale = jnp.minimum(1.0, self.threshold / (norm + 1e-12))
            return grad * scale.astype(grad.dtype)
        if self.type == "value":
            return jnp.clip(grad, -self.threshold, self.threshold)
        return grad


class Optimizer:
    """Base optimizer (reference opt.py:71-173). Aux states are Tensors so
    the whole update is jit-traceable and thread-able as donated state.

    Regularizer/Constraint/lr-multiplier registration mirrors reference
    Optimizer::Register + ApplyRegularizerConstraint (include/singa/model/
    optimizer.h:44-100, src/model/optimizer/optimizer.cc:36-77): per-param
    entries win over the global default."""

    def __init__(self, lr):
        self.lr = lr if isinstance(lr, DecayScheduler) else Constant(lr)
        self.step_counter = Tensor(shape=(), dtype=jnp.float32,
                                   requires_grad=False)
        self.step_counter.name = "step_counter"
        # dynamic-loss-scale state lives WITH the optimizer (not the
        # guard that drives it) so every checkpoint route — zip
        # save_states, Snapshot, the async sharded manager — carries it
        # and a resumed run continues with the backed-off scale instead
        # of re-diverging at the stale one. 1.0 = scaling inactive.
        self.loss_scale = Tensor(shape=(), dtype=jnp.float32,
                                 requires_grad=False)
        self.loss_scale.data = jnp.ones((), jnp.float32)
        self.loss_scale.name = "loss_scale"
        self._aux = {}  # name -> Tensor, created lazily per param
        self.regularizer = None       # global default
        self.constraint = None        # global default
        self._regularizers = {}       # per-param overrides
        self._constraints = {}
        self._lr_multipliers = {}

    def register(self, name, regularizer=None, constraint=None,
                 lr_multiplier=None):
        """Attach a per-param regularizer/constraint/lr multiplier
        (reference Optimizer::Register, optimizer.cc:36-56)."""
        if regularizer is not None:
            self._regularizers[name] = regularizer
        if constraint is not None:
            self._constraints[name] = constraint
        if lr_multiplier is not None:
            self._lr_multipliers[name] = float(lr_multiplier)

    def apply_regularizer_constraint(self, name, value, grad):
        """Regularizer first, then constraint (reference
        Optimizer::ApplyRegularizerConstraint, optimizer.cc:63-77)."""
        reg = self._regularizers.get(name, self.regularizer)
        if reg is not None:
            grad = reg.apply(value, grad)
        con = self._constraints.get(name, self.constraint)
        if con is not None:
            grad = con.apply(value, grad)
        return grad

    def _scaled_lr(self, name):
        mult = self._lr_multipliers.get(name)
        return self.lr_value * mult if mult is not None else self.lr_value

    def _fused_ok(self, name, p):
        """Whether THIS param's update may take the fused Pallas kernel:
        the optimizer was built with ``fused=True``, no regularizer or
        constraint applies to the param (their math is caller-composed
        and stays on the reference path — declining keeps them correct
        rather than silently dropped), the param is floating, and the
        backend-eligibility gate (``ops.fused_optim.available``) says a
        kernel launch pays for itself. Everything else falls through to
        the reference elementwise chain, per-param."""
        if not getattr(self, "fused", False):
            return False
        if self._regularizers.get(name, self.regularizer) is not None:
            return False
        if self._constraints.get(name, self.constraint) is not None:
            return False
        if not jnp.issubdtype(p.dtype, jnp.floating):
            return False
        from .ops import fused_optim
        return fused_optim.available(int(np.prod(p.shape)))

    # -- lr as a traced value --------------------------------------------
    @property
    def lr_value(self):
        return self.lr(self.step_counter)

    def should_apply_weight_decay(self, name):
        return True

    def telemetry_info(self):
        """Static facts for the telemetry layer (recorded once as
        labels/gauges at run start — NEVER per step: the schedule's
        current value lives in the traced ``lr_value``, and reading it
        back would add a device round trip). Wrappers (DistOpt,
        GuardedOptimizer) delegate through ``__getattr__``, so the run
        record names the innermost real optimizer."""
        return {"optimizer": type(self).__name__,
                "lr": float(self.lr.init_value)
                if hasattr(self.lr, "init_value") else None}

    # -- train driving -----------------------------------------------------
    def __call__(self, loss):
        self.backward_and_update(loss)

    def backward_and_update(self, loss):
        for p, g in autograd.backward(loss):
            self.apply(p.name or f"param/{id(p)}", p, g)
        self.step()

    def step(self):
        self.step_counter.data = self.step_counter.data + 1.0

    def apply(self, param_name, param_value, param_grad):
        raise NotImplementedError

    # -- state -------------------------------------------------------------
    def _get_aux(self, key, like):
        t = self._aux.get(key)
        if t is None:
            if getattr(self, "_frozen", False):
                raise RuntimeError(
                    f"optimizer aux state '{key}' created inside a compiled "
                    "step; it would silently reset every iteration. All aux "
                    "state must be materialised by the first (eager) step.")
            t = Tensor(shape=like.shape, device=like.device,
                       dtype=like.dtype, requires_grad=False)
            t.spec = like.spec  # momentum/moments shard like their param
            self._aux[key] = t
        return t

    def state_tensors(self):
        """All mutable optimizer state, for jit state-threading."""
        return [self.step_counter, self.loss_scale] + \
            list(self._aux.values())

    def state_tensor_dict(self):
        """name -> LIVE state Tensor — no gather, no host copy; the
        sharded-checkpointing counterpart of get_states (which pulls
        everything to host for the zip route)."""
        d = {"step_counter": self.step_counter,
             "loss_scale": self.loss_scale}
        d.update(self._aux)
        return d

    def restore_state_tensor(self, name, array, spec=None):
        """Set one live state entry from a restored (possibly sharded)
        array, creating lazily-built aux that does not exist yet (the
        fresh-process resume path). ``spec`` announces the mesh layout
        for a freshly created entry (momentum shards like its param)."""
        if name == "step_counter":
            self.step_counter.data = jnp.asarray(array)
            return
        if name == "loss_scale":
            self.loss_scale.data = jnp.asarray(array)
            return
        t = self._aux.get(name)
        if t is None:
            t = Tensor(data=array, requires_grad=False)
            t.spec = spec
            self._aux[name] = t
        else:
            t.data = array

    def get_states(self):
        from .tensor import to_host_tree
        states = {"step_counter": np.asarray(self.step_counter.data),
                  "loss_scale": np.asarray(self.loss_scale.data)}
        # batched gather: host-sharded aux (e.g. expert momentum) pays
        # one cross-process collective for the whole dict
        states.update(to_host_tree({k: v.data
                                    for k, v in self._aux.items()}))
        return states

    def set_states(self, states):
        if "step_counter" in states:
            self.step_counter.data = jnp.asarray(states["step_counter"])
        if "loss_scale" in states:
            self.loss_scale.data = jnp.asarray(
                states["loss_scale"], dtype=jnp.float32)
        for k, v in states.items():
            if k in ("step_counter", "loss_scale"):
                continue
            if k in self._aux:
                # keep the live buffer's dtype: checkpoints store bf16
                # aux as portable f32, and a dtype flip here would leak
                # f32 into the compiled bf16 update step
                self._aux[k].data = jnp.asarray(
                    v, dtype=self._aux[k].data.dtype)
            else:
                self._aux[k] = Tensor(data=np.asarray(v),
                                      requires_grad=False)

    def announce_aux_specs(self, params_by_name):
        """Re-attach mesh layouts to aux entries restored without one
        (``set_states`` on a fresh optimizer creates bare Tensors): an
        aux named ``<param>:<kind>`` shards like its param. Without this
        a restored momentum for a tensor-parallel weight would enter the
        compiled step replicated at full shape and collide with the
        local-shard gradient."""
        for k, t in self._aux.items():
            if getattr(t, "spec", None) is None:
                src = params_by_name.get(k.rsplit(":", 1)[0])
                if src is not None and getattr(src, "spec", None) is not None:
                    t.spec = src.spec


class SGD(Optimizer):
    """SGD with momentum / nesterov / weight decay (reference opt.py:174-334,
    update composed of the same axpy algebra, now one fused XLA kernel).

    ``fused=True`` routes eligible per-param updates through the
    one-HBM-pass Pallas kernel (``ops.fused_optim.sgd_momentum_update``;
    momentum runs only — a momentum-less SGD has no aux to fuse with).
    Ineligible params (regularizer/constraint attached, too small for a
    kernel launch, non-TPU backend without the interpret test hook)
    keep the reference path per-param. Parity is pinned in
    tests/test_fused_kernels.py; bench selects the mode via the banked
    ``fused_optim_ab`` A/B — never unconditionally."""

    def __init__(self, lr=0.1, momentum=0.0, dampening=0.0,
                 weight_decay=0.0, nesterov=False, fused=False):
        super().__init__(lr)
        self.momentum = momentum
        self.dampening = dampening
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.fused = bool(fused)
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError(
                "Nesterov momentum requires momentum>0 and dampening=0")

    def apply(self, name, p: Tensor, g: Tensor):
        grad = g.data if isinstance(g, Tensor) else g
        grad = grad.astype(p.dtype)
        wd = self.weight_decay \
            if self.weight_decay != 0 and \
            self.should_apply_weight_decay(name) else 0.0
        if self.momentum != 0 and self._fused_ok(name, p):
            from .ops import fused_optim
            buf = self._get_aux(f"{name}:momentum", p)
            p.data, buf.data = fused_optim.sgd_momentum_update(
                p.data, grad, buf.data, self._scaled_lr(name),
                momentum=self.momentum, dampening=self.dampening,
                weight_decay=wd, nesterov=self.nesterov)
            return
        if wd:
            grad = grad + wd * p.data
        grad = self.apply_regularizer_constraint(name, p.data, grad)
        if self.momentum != 0:
            buf = self._get_aux(f"{name}:momentum", p)
            buf.data = (self.momentum * buf.data
                        + (1 - self.dampening) * grad).astype(buf.dtype)
            grad = grad + self.momentum * buf.data if self.nesterov \
                else buf.data
        # update math promotes to f32 for low-precision params (the traced
        # lr is f32); store back in the param's dtype so bf16/fp16 training
        # keeps its precision class instead of silently upcasting
        p.data = (p.data - self._scaled_lr(name) * grad).astype(p.dtype)


class RMSProp(Optimizer):
    """(reference opt.py:336-442)

    ``fused=True`` routes eligible per-param updates through the
    one-HBM-pass Pallas kernel (``ops.fused_optim.rmsprop_update``:
    grad + master + rms read once, master + rms written once, aliased
    in place). Same per-param decline rules as ``SGD(fused=True)`` —
    regularizer/constraint attached, non-floating param, or too small
    for a kernel launch keeps the reference elementwise chain — same
    interpret-mode parity pin in the ``pallas`` tier, same
    ``step_flops`` reference-twin registration (the kernel marks the
    trace collector, so fused and unfused programs report identical
    FLOPs)."""

    def __init__(self, lr=0.1, rho=0.9, epsilon=1e-8, weight_decay=0.0,
                 fused=False):
        super().__init__(lr)
        self.rho = rho
        self.epsilon = epsilon
        self.weight_decay = weight_decay
        self.fused = bool(fused)

    def apply(self, name, p: Tensor, g: Tensor):
        grad = (g.data if isinstance(g, Tensor) else g).astype(p.dtype)
        if self._fused_ok(name, p):
            from .ops import fused_optim
            rms = self._get_aux(f"{name}:rms", p)
            p.data, rms.data = fused_optim.rmsprop_update(
                p.data, grad, rms.data, self._scaled_lr(name),
                rho=self.rho, epsilon=self.epsilon,
                weight_decay=self.weight_decay)
            return
        if self.weight_decay != 0:
            grad = grad + self.weight_decay * p.data
        grad = self.apply_regularizer_constraint(name, p.data, grad)
        rms = self._get_aux(f"{name}:rms", p)
        rms.data = (self.rho * rms.data
                    + (1 - self.rho) * grad * grad).astype(rms.dtype)
        p.data = (p.data - self._scaled_lr(name) * grad
                  / jnp.sqrt(rms.data + self.epsilon)).astype(p.dtype)


class AdaGrad(Optimizer):
    """(reference opt.py:444-534)

    ``fused=True``: eligible params update through the one-HBM-pass
    Pallas kernel (``ops.fused_optim.adagrad_update``). Same
    gating/parity/FLOPs-twin story as ``RMSProp(fused=True)``."""

    def __init__(self, lr=0.1, epsilon=1e-8, weight_decay=0.0,
                 fused=False):
        super().__init__(lr)
        self.epsilon = epsilon
        self.weight_decay = weight_decay
        self.fused = bool(fused)

    def apply(self, name, p: Tensor, g: Tensor):
        grad = (g.data if isinstance(g, Tensor) else g).astype(p.dtype)
        if self._fused_ok(name, p):
            from .ops import fused_optim
            hist = self._get_aux(f"{name}:history", p)
            p.data, hist.data = fused_optim.adagrad_update(
                p.data, grad, hist.data, self._scaled_lr(name),
                epsilon=self.epsilon, weight_decay=self.weight_decay)
            return
        if self.weight_decay != 0:
            grad = grad + self.weight_decay * p.data
        grad = self.apply_regularizer_constraint(name, p.data, grad)
        hist = self._get_aux(f"{name}:history", p)
        hist.data = (hist.data + grad * grad).astype(hist.dtype)
        p.data = (p.data - self._scaled_lr(name) * grad
                  / jnp.sqrt(hist.data + self.epsilon)).astype(p.dtype)


class Adam(Optimizer):
    """(reference opt.py:536-660)

    ``fused=True``: eligible params update through the one-HBM-pass
    Pallas kernel (``ops.fused_optim.adam_update``; amsgrad keeps the
    reference path — its vmax compare-exchange is a fourth state tensor
    the fused contract doesn't cover). Same gating/parity story as
    ``SGD(fused=True)``."""

    def __init__(self, lr=0.001, beta_1=0.9, beta_2=0.999, epsilon=1e-8,
                 weight_decay=0.0, amsgrad=False, fused=False):
        super().__init__(lr)
        self.beta_1 = beta_1
        self.beta_2 = beta_2
        self.epsilon = epsilon
        self.weight_decay = weight_decay
        self.amsgrad = amsgrad
        self.fused = bool(fused)

    def apply(self, name, p: Tensor, g: Tensor):
        grad = (g.data if isinstance(g, Tensor) else g).astype(p.dtype)
        if not self.amsgrad and self._fused_ok(name, p):
            from .ops import fused_optim
            m = self._get_aux(f"{name}:m", p)
            v = self._get_aux(f"{name}:v", p)
            t = self.step_counter.data + 1.0
            p.data, m.data, v.data = fused_optim.adam_update(
                p.data, grad, m.data, v.data, self._scaled_lr(name),
                1 - jnp.power(self.beta_1, t),
                1 - jnp.power(self.beta_2, t),
                beta_1=self.beta_1, beta_2=self.beta_2,
                epsilon=self.epsilon, weight_decay=self.weight_decay)
            return
        if self.weight_decay != 0:
            grad = grad + self.weight_decay * p.data
        grad = self.apply_regularizer_constraint(name, p.data, grad)
        m = self._get_aux(f"{name}:m", p)
        v = self._get_aux(f"{name}:v", p)
        m.data = (self.beta_1 * m.data
                  + (1 - self.beta_1) * grad).astype(m.dtype)
        v.data = (self.beta_2 * v.data
                  + (1 - self.beta_2) * grad * grad).astype(v.dtype)
        t = self.step_counter.data + 1.0
        mhat = m.data / (1 - jnp.power(self.beta_1, t))
        if self.amsgrad:
            vmax = self._get_aux(f"{name}:vmax", p)
            vmax.data = jnp.maximum(vmax.data, v.data)
            vhat = vmax.data / (1 - jnp.power(self.beta_2, t))
        else:
            vhat = v.data / (1 - jnp.power(self.beta_2, t))
        p.data = (p.data - self._scaled_lr(name) * mhat
                  / (jnp.sqrt(vhat) + self.epsilon)).astype(p.dtype)


class DistOpt:
    """Distributed optimizer: data-parallel all-reduce over the mesh 'data'
    axis (reference DistOpt opt.py:686-1094 + Communicator
    src/io/communicator.cc, re-expressed as XLA collectives over ICI).

    Inside the compiled (shard_map'd) step, ``all_reduce`` is a
    ``lax.psum``; outside any mesh context it is the identity (world of 1),
    which keeps single-chip scripts unchanged.
    """

    def __init__(self, opt=None, nccl_id=None, local_rank=None,
                 world_size=None, buffSize=None, axis_name="data",
                 reduce_axes=None, bucket_mb=None, overlap=True,
                 zero=False):
        """``reduce_axes``: mesh axes gradients are summed over (default
        just the data axis; add 'seq' under sequence parallelism where the
        token batch is split over that axis too).

        ``zero=True``: ZeRO/FSDP — optimizer state and fp32 masters
        sharded over the data axis, gathered just-in-time inside the
        compiled step. Implies the GSPMD train path
        (``Model.compile`` picks it up as ``fsdp_axis=axis_name``); the
        specialized drivers (half/partialUpdate/sparse) keep replicated
        state and raise a typed :class:`ShardingDecline` instead of
        running a silently replicated "ZeRO" step.

        ``bucket_mb``: size target (MiB of wire bytes) for gradient-psum
        bucketing. ``None``/``0`` keeps the per-gradient streaming psum;
        a positive value makes :meth:`grad_reduce_stream` concatenate
        gradients — in the reverse-layer order backward produces them —
        into size-targeted buckets and issue ONE collective per bucket
        the moment it fills, so XLA can hide the fewer, larger
        all-reduces under the remaining backward compute (the
        ``timeline_exposed_collective_seconds`` target). A python attr
        read at trace time: changing it after ``compile`` needs a
        recompile, like every other static step config.

        ``overlap=False`` is the measured no-overlap BASELINE: every
        collective is pinned behind the full backward via
        ``lax.optimization_barrier``, so an A/B against it shows what
        the overlap actually buys on the step timeline."""
        from .parallel.communicator import Communicator
        self.opt = opt if opt is not None else SGD()
        self.communicator = Communicator(axis_name=axis_name,
                                         world_size=world_size,
                                         reduce_axes=reduce_axes)
        self.world_size = self.communicator.world_size
        self.local_rank = local_rank if local_rank is not None \
            else self.communicator.local_rank
        self.global_rank = self.communicator.global_rank
        self.axis_name = axis_name
        self.bucket_mb = float(bucket_mb) if bucket_mb else 0.0
        if self.bucket_mb < 0:
            raise ValueError(f"bucket_mb must be >= 0, got {bucket_mb!r}")
        self.overlap = bool(overlap)
        self.zero = bool(zero)
        # sparsification error-feedback residuals (reference sparse modes)
        self._residuals = {}

    # -- mirror underlying optimizer surface ------------------------------
    @property
    def step_counter(self):
        return self.opt.step_counter

    @property
    def loss_scale(self):
        return self.opt.loss_scale

    def state_tensors(self):
        return self.opt.state_tensors() + list(self._residuals.values())

    def state_tensor_dict(self):
        d = self.opt.state_tensor_dict()
        d.update({f"residual/{k}": v
                  for k, v in self._residuals.items()})
        return d

    def restore_state_tensor(self, name, array, spec=None):
        if name.startswith("residual/"):
            nm = name[len("residual/"):]
            t = self._residuals.get(nm)
            if t is None:
                t = Tensor(data=array, requires_grad=False)
                t.spec = spec
                self._residuals[nm] = t
            else:
                t.data = array
        else:
            self.opt.restore_state_tensor(name, array, spec)

    def get_states(self):
        from .tensor import to_host_tree
        states = self.opt.get_states()
        states.update(to_host_tree({f"residual/{k}": v.data
                                    for k, v in self._residuals.items()}))
        return states

    def set_states(self, states):
        self.opt.set_states({k: v for k, v in states.items()
                             if not k.startswith("residual/")})
        for k, v in states.items():
            if k.startswith("residual/"):
                name = k[len("residual/"):]
                if name in self._residuals:
                    self._residuals[name].data = jnp.asarray(v)
                else:
                    self._residuals[name] = Tensor(data=np.asarray(v),
                                                   requires_grad=False)

    def announce_aux_specs(self, params_by_name):
        self.opt.announce_aux_specs(params_by_name)
        # sparsification error-feedback residuals are keyed by the param
        # name itself and must shard like it too
        for k, t in self._residuals.items():
            if getattr(t, "spec", None) is None:
                src = params_by_name.get(k)
                if src is not None and getattr(src, "spec", None) is not None:
                    t.spec = src.spec

    def step(self):
        self.opt.step()

    def __call__(self, loss):
        self.backward_and_update(loss)

    # -- collectives -------------------------------------------------------
    @staticmethod
    def _shard_axes(p):
        """Mesh axes ``p`` is sharded over (its Tensor.spec): per-shard
        gradients on those axes are distinct values, not replicas, so they
        are excluded from the gradient all-reduce — expert weights on
        'expert', tensor-parallel weights on 'model'."""
        spec = getattr(p, "spec", None)
        if spec is None:
            return ()
        axes = set()
        for entry in spec:
            if entry is None:
                continue
            if isinstance(entry, (tuple, list)):
                axes.update(entry)
            else:
                axes.add(entry)
        return tuple(axes)

    def all_reduce(self, arr, exclude=()):
        return self.communicator.all_reduce(arr, exclude=exclude)

    def all_reduce_wire(self, arr, exclude=(), wire=None):
        """All-reduce with the policy's (or an explicit) 16-bit wire
        cast, returning f32 when a cast happened — the ONE place the
        comm-dtype discipline lives, shared by the plain and guarded
        drivers. ``wire=None`` resolves the active policy; no policy
        (or the grad already on the wire dtype) reduces as-is."""
        if wire is None:
            wire = self._policy_wire()
        if wire is not None and arr.dtype != wire:
            return self.all_reduce(arr.astype(wire),
                                   exclude=exclude).astype(jnp.float32)
        return self.all_reduce(arr, exclude=exclude)

    def update(self, p: Tensor, g: Tensor):
        """Average an already-summed gradient and apply
        (reference opt.py:738-746: grad /= world_size).

        The divisor is the FULL batch-shard count over every reduce axis,
        even for shard-excluded params: an expert-sharded weight's gradient
        already accumulates its expert-axis peers' token contributions
        through the all-to-all transpose, so only the psum skips the axis —
        the per-token averaging does not."""
        g.data = g.data / self.communicator.effective_world_size()
        self.opt.apply(p.name or f"param/{id(p)}", p, g)

    @staticmethod
    def _policy_wire():
        """Wire dtype for gradient collectives under the ACTIVE precision
        policy (None = reduce in the gradients' own dtype). The compiled
        step enters the model's policy scope, so a bf16_mixed model's
        psums automatically move 16-bit bytes — the policy-driven form of
        the explicit ``backward_and_update_half`` driver."""
        from .mixed_precision import active_policy
        pol = active_policy()
        return pol.comm_dtype if pol is not None else None

    # -- bucketed gradient reduction ----------------------------------------
    def _wire_cast_back(self, arr, orig_dtype, wire):
        """all_reduce_wire's post-reduce rule, factored for the bucketed
        path: a gradient that was CAST to a 16-bit wire comes back f32;
        one already on the wire dtype (or reduced with no wire policy)
        keeps its dtype."""
        if wire is not None and orig_dtype != wire:
            return arr.astype(jnp.float32)
        return arr

    def _flush_bucket(self, key, items, wire):
        """Reduce one bucket with a SINGLE collective: concatenate the
        members' (wire-cast) flattened gradients, all-reduce the buffer,
        split it back, and re-apply the per-gradient cast-back rule —
        numerically the same elements summed over the same replicas as
        per-gradient psums, just fewer/larger wire messages."""
        excl, eff = key
        casts = [g.data.astype(eff) if g.data.dtype != eff else g.data
                 for _p, g in items]
        if len(items) == 1:
            # a lone member (oversized grad, stream tail) skips the
            # concat/split round trip
            (p, g), red = items[0], self.all_reduce(casts[0], exclude=excl)
            g.data = self._wire_cast_back(red, g.data.dtype, wire)
            return [(p, g)]
        buf = jnp.concatenate([c.ravel() for c in casts])
        red = self.all_reduce(buf, exclude=excl)
        out, off = [], 0
        for (p, g), c in zip(items, casts):
            piece = red[off:off + c.size].reshape(c.shape)
            off += c.size
            g.data = self._wire_cast_back(piece, g.data.dtype, wire)
            out.append((p, g))
        return out

    def grad_reduce_stream(self, pairs, wire=None):
        """Generator transform over backward's ``(param, grad)`` stream:
        yields the same pairs with ``grad.data`` SUMMED over the reduce
        axes (averaging stays with the consumer, :meth:`update`). The
        ONE reduction chokepoint the plain and guarded drivers share, so
        bucketing/overlap config and the 16-bit wire-cast discipline
        (:meth:`all_reduce_wire` semantics, preserved per-gradient) can
        never diverge between them.

        - default (``overlap=True, bucket_mb=0``): per-gradient psum the
          moment backward yields it — the streaming path unchanged;
        - ``bucket_mb>0``: gradients accumulate into size-targeted
          buckets keyed by (shard-exclude axes, wire dtype) — members of
          different keys cannot share a collective — and each bucket
          reduces with ONE concatenated all-reduce as soon as it fills
          (backward yields reverse-layer order, so the bucket's grads
          are the newest ready and the collective overlaps the rest of
          backward);
        - ``overlap=False``: every gradient is first pinned behind the
          COMPLETE backward with ``lax.optimization_barrier`` — the
          honest no-overlap baseline an A/B measures against (without
          the barrier XLA's scheduler would overlap anyway, making the
          "off" leg a lie).
        """
        if wire is None:
            wire = self._policy_wire()
        if self.overlap and not self.bucket_mb:
            for p, g in pairs:
                g.data = self.all_reduce_wire(
                    g.data, exclude=self._shard_axes(p), wire=wire)
                yield p, g
            return
        if not self.overlap:
            # materialise the whole backward, then tie every grad to the
            # full set: no collective can issue before backward finishes
            pairs = list(pairs)
            barriered = jax.lax.optimization_barrier(
                tuple(g.data for _p, g in pairs))
            for (_p, g), arr in zip(pairs, barriered):
                g.data = arr
            pairs = iter(pairs)
        if not self.bucket_mb:
            for p, g in pairs:
                g.data = self.all_reduce_wire(
                    g.data, exclude=self._shard_axes(p), wire=wire)
                yield p, g
            return
        target = int(self.bucket_mb * (1 << 20))
        buckets = {}          # (excl, eff_dtype) -> [items, nbytes]
        order = []            # flush stale buckets in arrival order
        for p, g in pairs:
            excl = self._shard_axes(p)
            eff = np.dtype(wire) if wire is not None \
                else np.dtype(g.data.dtype)
            key = (excl, eff)
            if key not in buckets:
                buckets[key] = [[], 0]
                order.append(key)
            slot = buckets[key]
            slot[0].append((p, g))
            slot[1] += int(np.prod(np.shape(g.data))) * eff.itemsize
            if slot[1] >= target:
                items, _n = buckets.pop(key)
                order.remove(key)
                yield from self._flush_bucket(key, items, wire)
        for key in order:
            yield from self._flush_bucket(key, buckets[key][0], wire)

    def _decline_zero(self, driver):
        """``zero=True`` under a specialized driver is REFUSED, not
        warned: these drivers keep their own per-gradient reduction +
        replicated optimizer state, so a ZeRO request would silently
        train with full-size state on every chip while the run reports
        "ZeRO" — the exact lie the typed-decline discipline exists to
        prevent. Use the plain driver (``model(tx, ty)`` /
        ``backward_and_update``) on the GSPMD path, or drop zero."""
        if not getattr(self, "zero", False):
            return
        from .parallel.gspmd import ShardingDecline
        raise ShardingDecline(
            f"DistOpt(zero=True) cannot run the {driver} driver: it "
            "keeps replicated optimizer state and hand-rolled "
            "per-gradient collectives, so the requested ZeRO sharding "
            "would silently not happen. Use the plain driver "
            "(backward_and_update via the compiled GSPMD step) or "
            "construct the DistOpt without zero=True")

    def _warn_driver_skips_bucketing(self, driver):
        """The specialised drivers (half / partialUpdate / sparse) keep
        their own per-gradient reduction paths: a bucket_mb/overlap
        config would be silently dead there, and a user A/B'ing the
        overlap knobs under them would bank a comparison of two
        identical programs. Say so, once per driver."""
        if not self.bucket_mb and self.overlap:
            return
        warned = getattr(self, "_bucket_warned", None)
        if warned is None:
            warned = self._bucket_warned = set()
        if driver in warned:
            return
        warned.add(driver)
        import warnings
        warnings.warn(
            f"DistOpt(bucket_mb={self.bucket_mb}, overlap="
            f"{self.overlap}) has no effect on {driver}: only the "
            "plain and guarded drivers ride grad_reduce_stream; this "
            "driver streams per-gradient collectives", stacklevel=3)

    # -- training drivers ---------------------------------------------------
    def backward_and_update(self, loss, threshold=2097152):
        """All-reduce each gradient as soon as backward produces it
        (reference opt.py:826-865). ``threshold`` is accepted for parity;
        XLA handles small-tensor fusion so no manual fused buffer exists
        — but ``bucket_mb`` (see ``__init__``) additionally coalesces
        gradients into size-targeted single-collective buckets through
        :meth:`grad_reduce_stream`, the overlap knob the step timeline's
        exposed-communication gauge steers. Under an active 16-bit
        precision policy the reduce moves the policy's comm dtype on the
        wire; the update math that follows is back in the masters'
        precision."""
        wire = self._policy_wire()
        for p, g in self.grad_reduce_stream(autograd.backward(loss),
                                            wire=wire):
            self.update(p, g)
        self.opt.step()

    @classmethod
    def _half_wire_defaults(cls, dtype, clipping):
        """Resolve backward_and_update_half's (dtype, clipping)
        defaults: an explicit dtype keeps the caller's choices; a None
        dtype takes the active policy's comm dtype (else bfloat16), and
        a POLICY-selected fp16 wire forces clipping on — fp16 overflows
        above 65504 and this driver runs unguarded."""
        if dtype is not None:
            return dtype, clipping
        wire_pol = cls._policy_wire()
        if wire_pol == jnp.dtype(jnp.float16):
            return "float16", True
        return wire_pol or "bfloat16", clipping

    def backward_and_update_half(self, loss, threshold=2097152,
                                 clipping=False, clip_value=2.5,
                                 dtype=None):
        """Reduced-precision communication: cast to a 16-bit type before
        the all-reduce (reference synchHalf fp16 comm,
        src/io/communicator.cc:262-299). ``dtype`` selects the wire
        format: "bfloat16" (the TPU-native half type, same exponent
        range as fp32 so no clipping is required) or "float16" (the
        reference's IEEE wire format, e.g. for DCN cross-slice links
        where the fp16 convention is fixed; pair with ``clipping`` since
        fp16 overflows above 65504). Default (None): the active
        precision policy's comm dtype, else bfloat16 — and when the
        POLICY selects the fp16 wire, clipping turns on with it (this
        driver runs unguarded, so an unclipped policy-default fp16 wire
        would let one large gradient sum land inf in the params)."""
        self._decline_zero('backward_and_update_half')
        self._warn_driver_skips_bucketing('backward_and_update_half')
        dtype, clipping = self._half_wire_defaults(dtype, clipping)
        wire = {"bfloat16": jnp.bfloat16, "float16": jnp.float16,
                jnp.bfloat16: jnp.bfloat16,
                jnp.float16: jnp.float16,
                jnp.dtype(jnp.bfloat16): jnp.bfloat16,
                jnp.dtype(jnp.float16): jnp.float16}.get(dtype)
        if wire is None:
            raise ValueError(
                f"dtype must be 'bfloat16' or 'float16', got {dtype!r}")
        for p, g in autograd.backward(loss):
            grad = g.data
            if clipping:
                grad = jnp.clip(grad, -clip_value, clip_value)
            half = grad.astype(wire)
            g.data = self.all_reduce(
                half, exclude=self._shard_axes(p)).astype(jnp.float32)
            self.update(p, g)
        self.opt.step()

    def backward_and_partial_update(self, loss, threshold=2097152,
                                    rotation=None):
        """Partial synchronisation: each step, only a rotating
        1/world_size partition of the parameters takes the globally
        averaged gradient; the rest update locally
        (reference opt.py:922-992).

        ``rotation`` — a STATIC python int (normally ``step %
        world_size``) — selects the partition at TRACE time, so the
        all-reduce is only emitted for the selected parameters: the
        reference's actual communication saving, at the cost of one
        compiled-step specialization per rotation value (the Model's
        static-arg cache holds all n).

        With ``rotation=None`` the selection rides the optimizer's traced
        step counter instead: a single compiled step that keeps rotating,
        but XLA cannot skip a collective on a traced predicate, so every
        gradient is still reduced and only the APPLICATION is masked.
        """
        self._decline_zero('backward_and_partial_update')
        self._warn_driver_skips_bucketing('backward_and_partial_update')
        n = max(1, self.communicator.effective_world_size())
        if rotation is not None:
            rot = int(rotation) % n
            for i, (p, g) in enumerate(autograd.backward(loss)):
                if i % n == rot:
                    g.data = self.all_reduce(
                        g.data, exclude=self._shard_axes(p)) / n
                self.opt.apply(p.name or f"param/{id(p)}", p, g)
            self.opt.step()
            return
        step = self.opt.step_counter.data
        for i, (p, g) in enumerate(autograd.backward(loss)):
            summed = self.all_reduce(g.data,
                                     exclude=self._shard_axes(p))
            sel = jnp.equal(jnp.mod(step + i, n), 0)
            g.data = jnp.where(sel, summed / n, g.data)
            self.opt.apply(p.name or f"param/{id(p)}", p, g)
        self.opt.step()

    def backward_and_sparse_update(self, loss, spars=0.05, topK=False,
                                   corr=True):
        """Gradient sparsification with error feedback (reference
        opt.py:994+ / Communicator::sparsification). On TPU the transport
        stays dense (masked values + psum ride the ICI all-reduce) while the
        semantics — threshold or top-K selection, residual accumulation —
        match the reference."""
        self._decline_zero('backward_and_sparse_update')
        self._warn_driver_skips_bucketing('backward_and_sparse_update')
        for p, g in autograd.backward(loss):
            name = p.name or f"param/{id(p)}"
            grad = g.data
            if corr:
                res = self._residuals.get(name)
                if res is None:
                    res = Tensor(shape=p.shape, device=p.device,
                                 requires_grad=False)
                    res.spec = p.spec   # error feedback shards like p
                    self._residuals[name] = res
                grad = grad + res.data
            absg = jnp.abs(grad)
            if topK:
                k = max(1, int(spars * grad.size))
                thresh = jax.lax.top_k(absg.ravel(), k)[0][-1]
                mask = absg >= thresh
            else:
                mask = absg >= spars
            sparse = jnp.where(mask, grad, 0.0)
            if corr:
                self._residuals[name].data = grad - sparse
            g.data = self.all_reduce(sparse,
                                     exclude=self._shard_axes(p))
            self.update(p, g)
        self.opt.step()
