"""Amax calibration: observe N batches, freeze scales into the policy.

Dynamic (per-batch amax) quantization makes every step's numerics
depend on that step's data. For serving and fp8 compute the scales
should instead be a COMPILE-TIME contract: run a handful of
representative batches through the model once, record the activation
ranges each op actually sees, and freeze the resulting scales into the
:class:`~singa_tpu.mixed_precision.QuantPolicy` — from then on the
traced program bakes them in as constants.

The observation point is the one chokepoint every matmul / conv /
attention / RNN operand already flows through:
``mixed_precision.cast_compute``. While a :class:`Calibrator` scope is
active, each floating operand is reported to the calibrator tagged by
its POSITION in the forward's op order (``act0, act1, ...`` — reset at
every policy-scope entry). Position tags are what make freezing
line up with execution: the traced step replays ops in the same order
the eager calibration pass ran them, so ``act{i}``'s frozen scale lands
on exactly the operand it was measured from. Two calibration runs over
the same batches therefore produce BIT-IDENTICAL scales (pinned by
``tests/test_quant.py``): the record is a plain running max of exact
device amaxes, no averaging, no randomness.

Observed ranges are published as ``quant_amax``/``quant_scale`` gauges
(label ``tensor``) so a calibration run is inspectable through the
normal telemetry spine.
"""

from __future__ import annotations

import contextlib

import numpy as np
import jax

from .. import mixed_precision as mp
from . import core


class Calibrator:
    """Record per-op-position activation amaxes over calibration
    batches; ``freeze(policy)`` turns them into a scale-frozen policy.

        cal = Calibrator()
        cal.run(model, batches)             # eager forwards, observed
        policy = cal.freeze(mp.resolve("fp8_mixed"))
        model.compile([x], policy=policy)   # scales are now constants
    """

    def __init__(self, registry=None):
        self.amax = {}          # tag -> running max |activation|
        self.batches_seen = 0
        self._registry = registry

    # -- observation --------------------------------------------------------
    def record(self, tag, arr):
        """One observed operand. Tracers are ignored: calibration is an
        EAGER pass by design (a traced abstract value has no amax)."""
        if isinstance(arr, jax.core.Tracer):
            return
        v = float(np.max(np.abs(np.asarray(arr)))) if np.size(arr) \
            else 0.0
        prev = self.amax.get(tag, 0.0)
        if v > prev:
            self.amax[tag] = v
        else:
            self.amax.setdefault(tag, prev)

    @contextlib.contextmanager
    def observe(self):
        """Scope under which ``cast_compute`` reports every floating
        operand here (nests with any active policy scope)."""
        token = mp._observer.set(self.record)
        # a fresh op-position counter even without an active policy
        # (calibration usually runs BEFORE compile(policy=...)); an
        # inner policy scope resets it again per forward body
        qtok = mp._qpos.set([0])
        try:
            yield self
        finally:
            mp._qpos.reset(qtok)
            mp._observer.reset(token)

    def run(self, model, batches):
        """Observe eager forwards of ``model`` over ``batches`` (each a
        Tensor or tuple of Tensors). The model's own policy scope is
        entered by its ``__call__``; op positions reset per forward, so
        every batch lands on the same tags."""
        was_training = getattr(model, "_train", False)
        model.eval()
        try:
            for b in batches:
                args = b if isinstance(b, (tuple, list)) else (b,)
                with self.observe():
                    model(*args)
                self.batches_seen += 1
        finally:
            model.train(was_training)
        return self

    # -- freezing -----------------------------------------------------------
    def scales(self, qmax):
        """tag -> frozen scale for a grid whose largest magnitude is
        ``qmax``; an op that only ever saw zeros gets scale 1."""
        return {tag: (a / float(qmax) if a > 0 else 1.0)
                for tag, a in sorted(self.amax.items())}

    def freeze(self, policy):
        """Return ``policy`` with this calibration's scales frozen in
        (:meth:`QuantPolicy.with_scales`), publishing the observed
        ranges as registry gauges. Raises if nothing was observed — a
        zero-batch calibration silently freezing nothing is exactly the
        bug this loud path prevents."""
        if not self.amax:
            raise ValueError(
                "no activations observed: run(model, batches) (or an "
                "observe() scope around forwards) before freeze()")
        pol = mp.resolve(policy)
        kind = getattr(pol, "compute_quant", None) or "e4m3"
        qmax = core.INT8_QMAX if kind == "int8" else core.FP8_MAX[kind]
        scales = self.scales(qmax)
        from ..observability import metrics as _metrics
        reg = self._registry if self._registry is not None \
            else _metrics.default_registry()
        g_amax = reg.gauge(
            "quant_amax", "calibration-observed max |activation| per "
            "op position", labels=("tensor",))
        g_scale = reg.gauge(
            "quant_scale", "frozen quantization scale per op position",
            labels=("tensor",))
        for tag, a in self.amax.items():
            g_amax.set(a, tensor=tag)
            g_scale.set(scales[tag], tensor=tag)
        reg.gauge("quant_calibration_batches",
                  "batches observed by the newest calibration run"
                  ).set(self.batches_seen)
        return pol.with_scales(scales)


def calibrate(model, batches, policy="fp8_mixed", registry=None):
    """One-call form: observe ``batches`` and return the scale-frozen
    policy (see :class:`Calibrator`)."""
    return Calibrator(registry=registry).run(model, batches).freeze(
        mp.resolve(policy))


__all__ = ["Calibrator", "calibrate"]
