"""int8/fp8 quantization primitives: the numerics layer of
``singa_tpu.quant``.

Everything here is a pure function over arrays, jit-safe by
construction, so the SAME code quantizes concretely (checkpoint
conversion, ``quantize_params``) and symbolically (in-graph dequant /
fake-quant inside the one compiled step — the ``n_traces == 1`` pin
survives because quantization adds ops, never shapes).

Two numeric families:

- **int8, symmetric, per-channel** — the weight-only inference format.
  ``quantize_int8`` maps a float tensor to an int8 payload plus an fp32
  scale sidecar with ``scale = amax / 127`` per channel; the scale keeps
  the payload's rank (size-1 on non-channel dims), so dequantization is
  a bare broadcast multiply with no axis metadata to carry around —
  checkpoints, the serving adapter and the ring KV cache all ride this
  one convention.
- **fp8 (e4m3 / e5m2 via ml_dtypes)** — the compute/grad emulation
  format. ``fake_cast`` rounds a tensor through the fp8 grid and back
  (weights/activations take e4m3's 3 mantissa bits, gradients e5m2's
  wide exponent), optionally pre-scaled by a calibrated per-tensor
  scale so the representable window sits on the observed amax.

Fake-quant (``fake_quant_int8`` / ``fake_quant_fp8``) is the QAT form:
forward sees quantized numerics, backward sees identity (the
straight-through estimator, expressed as ``x + stop_gradient(q(x)-x)``
so it is correct under BOTH the tape autograd and ``jax.grad``).

``quantize_params`` is the model-level pass: fp32 masters become int8
payloads in place (4x less parameter memory), scales join the model's
threaded state, and every forward — eager, compiled eval, the batch
serving engine — dequantizes IN GRAPH at the top of the traced body
(``dequant_params_scope``), where XLA fuses the convert+multiply into
the consuming matmul/conv.
"""

from __future__ import annotations

import contextlib

import numpy as np
import jax.numpy as jnp
from jax import lax

INT8_QMAX = 127.0
# largest finite magnitude of each fp8 grid (ml_dtypes finfo)
FP8_MAX = {"e4m3": 448.0, "e5m2": 57344.0}
FP8_DTYPES = {"e4m3": jnp.float8_e4m3fn, "e5m2": jnp.float8_e5m2}

# the smallest shapes worth quantizing: 1-D leaves (biases, norm
# scales, BN stats) stay fp32 — they are a rounding error of the byte
# budget and the most numerically fragile
MIN_QUANT_DIM = 2
MIN_QUANT_SIZE = 16

# checkpoint key prefix for scale sidecars written beside an fp32
# model's payloads (a LIVE quantized model's scales instead ride
# get_states under model-local names — see quantize_params)
SCALE_PREFIX = "quant-scale/"


def channel_axis(shape):
    """The per-channel axis for a weight of ``shape``: the output
    features of a 2-D matmul weight (last dim — both the layer.Linear
    ``(in, out)`` and the decode-adapter block weights use that
    layout), the leading (output-channel) dim for conv-style >2-D
    weights, None (per-tensor) for anything 1-D."""
    n = len(shape)
    if n < 2:
        return None
    return n - 1 if n == 2 else 0


def _amax(x, axis):
    """Per-channel absolute max, rank preserved (size-1 elsewhere)."""
    if axis is None:
        axes = tuple(range(x.ndim))
    else:
        axes = tuple(i for i in range(x.ndim) if i != axis % x.ndim)
    return jnp.max(jnp.abs(x), axis=axes, keepdims=True)


def quantize_int8(arr, axis=None, scale=None):
    """Symmetric int8 quantization: ``(payload int8, scale fp32)`` with
    ``scale = amax / 127`` per channel (``axis``; None = per-tensor).
    The scale keeps the payload's rank so ``payload * scale``
    broadcasts without metadata. All-zero channels get scale 1 (their
    payload is zero either way — never a divide-by-zero). A frozen
    (calibrated) ``scale`` overrides the amax derivation."""
    f = jnp.asarray(arr).astype(jnp.float32)
    if scale is None:
        amax = _amax(f, axis)
        scale = jnp.where(amax > 0, amax / INT8_QMAX,
                          jnp.ones_like(amax))
    else:
        scale = jnp.asarray(scale, jnp.float32)
    q = jnp.clip(jnp.round(f / scale), -INT8_QMAX, INT8_QMAX) \
        .astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_int8(q, scale, dtype=jnp.float32):
    """Inverse of :func:`quantize_int8` (up to the quantization error:
    at most ``scale/2`` per element). In a traced body this is the
    in-graph dequant XLA fuses into the consuming matmul/conv."""
    return (q.astype(jnp.float32) * jnp.asarray(scale).astype(
        jnp.float32)).astype(dtype)


def quantize_int8_rows(x, axes):
    """Symmetric int8 with the amax reduced over ``axes`` (a tuple) and
    one scale per REMAINING index, ``axes`` squeezed out of the scale —
    the per-row form the serving KV cache uses (one scale per written
    token row, reduced over heads × head_dim). Same numerics contract
    as :func:`quantize_int8`: ``scale = amax / 127``, all-zero rows get
    scale 1, payload clipped to ±127."""
    axes = tuple(axes)
    f = jnp.asarray(x).astype(jnp.float32)
    amax = jnp.max(jnp.abs(f), axis=axes, keepdims=True)
    scale = jnp.where(amax > 0, amax / INT8_QMAX, jnp.ones_like(amax))
    q = jnp.clip(jnp.round(f / scale), -INT8_QMAX, INT8_QMAX) \
        .astype(jnp.int8)
    return q, jnp.squeeze(scale, axis=axes).astype(jnp.float32)


def quantize_fp8(arr, kind="e4m3", scale=None):
    """Per-tensor scaled fp8 cast: ``(payload fp8, scale fp32)``. With
    ``scale=None`` the scale is derived from the tensor's own amax so
    the fp8 window covers it exactly (dynamic quantization); a
    calibration-frozen scale makes the cast batch-independent."""
    if kind not in FP8_DTYPES:
        raise ValueError(f"unknown fp8 kind {kind!r}; expected one of "
                         f"{sorted(FP8_DTYPES)}")
    f = jnp.asarray(arr).astype(jnp.float32)
    if scale is None:
        amax = jnp.max(jnp.abs(f))
        scale = jnp.where(amax > 0, amax / FP8_MAX[kind],
                          jnp.ones_like(amax))
    scale = jnp.asarray(scale, jnp.float32)
    # SATURATING cast: e4m3fn has no inf, so an unclipped overflow
    # (a value outside a calibration-frozen window) would land as NaN
    # and poison the whole step — clamp to the grid's edge instead,
    # like every hardware fp8 cast does. No-op for the dynamic scale.
    m = FP8_MAX[kind]
    return (jnp.clip(f / scale, -m, m).astype(FP8_DTYPES[kind]),
            scale)


def dequantize_fp8(q, scale, dtype=jnp.float32):
    return (q.astype(jnp.float32) * jnp.asarray(scale).astype(
        jnp.float32)).astype(dtype)


def fake_cast(x, kind="e4m3", scale=None):
    """Round ``x`` through the fp8 grid and back to its own dtype —
    fp8 numerics without fp8 storage (the emulation form every fp8
    training recipe bootstraps from). No gradient trickery: callers on
    a backward path get the rounded values (e5m2 gradient emulation),
    callers needing STE use :func:`fake_quant_fp8`."""
    q, s = quantize_fp8(x, kind, scale)
    return dequantize_fp8(q, s, x.dtype)


def _ste(x, quantized):
    """Straight-through estimator: forward = quantized, backward =
    identity. ``stop_gradient`` makes it exact under jax.grad; the tape
    autograd never differentiates through op-internal casts, so the
    form is correct under both engines."""
    return x + lax.stop_gradient(quantized - x)


def fake_quant_int8(x, axis=None, scale=None):
    """QAT int8 fake-quant with STE (per-channel when ``axis``; a
    calibrated ``scale`` freezes the grid)."""
    q, s = quantize_int8(x, axis, scale)
    return _ste(x, dequantize_int8(q, s, x.dtype))


def fake_quant_fp8(x, kind="e4m3", scale=None):
    """QAT fp8 fake-quant with STE (per-tensor; calibrated ``scale``
    freezes the window)."""
    return _ste(x, fake_cast(x, kind, scale))


# ---------------------------------------------------------------------------
# model / state-dict passes
# ---------------------------------------------------------------------------

def eligible(tensor_or_arr, require_grad=True):
    """Whether one state entry is a weight-only-quantization candidate:
    a trainable (when the entry knows) floating tensor of >= 2 dims and
    non-trivial size. Biases, norm scales and BN running stats stay
    fp32 by this rule — they are tiny and fragile."""
    rg = getattr(tensor_or_arr, "requires_grad", None)
    if require_grad and rg is False:
        return False
    dt = getattr(tensor_or_arr, "dtype", None)
    if dt is None or not jnp.issubdtype(jnp.dtype(dt), jnp.floating):
        return False
    shape = tuple(getattr(tensor_or_arr, "shape", ()))
    return len(shape) >= MIN_QUANT_DIM and \
        int(np.prod(shape)) >= MIN_QUANT_SIZE


def quantize_state_arrays(arrays, prefix="model/", live=None):
    """Quantize a flat checkpoint state dict: every eligible ``prefix``
    entry becomes an int8 payload at its own key plus an fp32 scale at
    ``quant-scale/<key>``; everything else passes through untouched.
    ``live`` (optional name -> Tensor of the same keys) contributes
    requires_grad knowledge — without it any >=2-D float under the
    prefix is quantized (the offline-tool case, where BN running stats
    are 1-D and therefore already excluded).

    This is the ~4x-smaller on-disk form: restore detects the scale
    sidecar key and dequantizes into fp32 masters
    (``checkpoint._apply_restored`` / ``Model.load_states``)."""
    out = {}
    for k, a in arrays.items():
        cand = a if live is None or k not in live else live[k]
        if k.startswith(prefix) and SCALE_PREFIX not in k and \
                not jnp.issubdtype(jnp.dtype(getattr(a, "dtype", "O")),
                                   jnp.integer) and \
                eligible(cand, require_grad=live is not None):
            q, s = quantize_int8(np.asarray(a),
                                 channel_axis(np.shape(a)))
            out[k] = np.asarray(q)
            out[SCALE_PREFIX + k] = np.asarray(s)
        else:
            out[k] = a
    return out


def dequantize_entry(payload, scale, dtype=np.float32):
    """The ONE host-side payload × scale fold every checkpoint-restore
    site shares (``dequantize_state_arrays``, ``checkpoint
    ._apply_restored``, ``Model.load_states``) — a format change (int4,
    NF4, ...) lands here once."""
    return (np.asarray(payload, np.float32)
            * np.asarray(scale, np.float32)).astype(dtype)


def dequantize_state_arrays(arrays, dtype=np.float32):
    """Inverse of :func:`quantize_state_arrays`: fold every
    ``quant-scale/`` sidecar back into its payload and drop the scale
    keys. Non-quantized entries pass through untouched."""
    scales = {k[len(SCALE_PREFIX):]: a for k, a in arrays.items()
              if k.startswith(SCALE_PREFIX)}
    out = {}
    for k, a in arrays.items():
        if k.startswith(SCALE_PREFIX):
            continue
        if k in scales:
            a = dequantize_entry(a, scales[k], dtype)
        out[k] = a
    return out


def quantize_params(model, policy="int8_weight_only"):
    """Weight-only int8 pass over a live model: every eligible fp32
    master becomes an int8 payload IN PLACE (4x less parameter memory,
    and every checkpoint route — save_states, CheckpointManager,
    digests — now persists the int8 bytes), with its per-channel scale
    joining the model's threaded state as ``quant-scale/<name>``.

    The model becomes an inference model: quantized params stop
    requiring grads, and every forward — eager, the compiled eval step,
    ``BatchServingEngine`` — dequantizes in graph at the top of the
    traced body (:func:`dequant_params_scope`, entered by
    ``Model._policy_scope``), so the one-jitted-program contract and
    the ``n_traces == 1`` pin survive untouched.

    Returns a per-param report ``{name: {"bytes_fp": .., "bytes_q": ..}}``.
    """
    from .. import mixed_precision as mp
    from ..tensor import Tensor
    if getattr(model, "_quant_pairs", None):
        raise RuntimeError(
            "model is already weight-quantized (quantize_params is a "
            "one-way inference pass; reload fp32 masters to redo it)")
    pol = mp.resolve(policy)
    pairs, scales, report = [], {}, {}
    for name, t in model.get_states().items():
        if not eligible(t):
            continue
        q, s = quantize_int8(t.data, channel_axis(t.shape))
        report[name] = {
            "bytes_fp": int(np.prod(t.shape)) *
            jnp.dtype(t.dtype).itemsize,
            "bytes_q": int(np.prod(t.shape)) + int(np.prod(s.shape)) * 4,
        }
        t.data = q
        t.requires_grad = False
        t.stores_grad = False
        st = Tensor(data=s, device=t.device, requires_grad=False)
        st.name = SCALE_PREFIX + name
        scales[SCALE_PREFIX + name] = st
        pairs.append((name, t, st))
    model._quant_pairs = pairs
    model._quant_scales = scales
    model._policy = pol
    # compiled steps/evals close over the old fp32 state identities
    model._invalidate_compiled()
    return report


@contextlib.contextmanager
def dequant_params_scope(model):
    """Rebind every weight-quantized param to its dequantized (fp32)
    value for the duration of a forward/step body, restoring the int8
    payload binding on exit. Entered INSIDE traced bodies
    (``Model._policy_scope``), so the dequant is part of the one
    compiled program — XLA fuses the convert+multiply into each
    weight's consumer — while the threaded/donated state stays int8.
    No-op for unquantized models.

    Rebinding mutates shared ``Tensor.data``, so the scope is guarded:
    a per-model RLock serializes concurrent eager forwards (a second
    thread waits, it never double-dequantizes), and a depth counter
    makes nested entries (adapter build inside an engine scope, the
    batch engine's jitted body under ``_policy_scope``) no-ops past
    the first — only the outermost exit restores the int8 binding."""
    pairs = getattr(model, "_quant_pairs", None)
    if not pairs:
        yield
        return
    lock = getattr(model, "_quant_scope_lock", None)
    if lock is None:
        import threading
        lock = model._quant_scope_lock = threading.RLock()
    with lock:
        depth = getattr(model, "_quant_scope_depth", 0)
        model._quant_scope_depth = depth + 1
        saved = None
        try:
            if depth == 0:
                saved = [(t, t.data) for _name, t, _s in pairs]
                for _name, t, st in pairs:
                    t.data = dequantize_int8(t.data, st.data)
            yield
        finally:
            model._quant_scope_depth = depth
            if saved is not None:
                for t, d in saved:
                    t.data = d


__all__ = [
    "INT8_QMAX", "FP8_MAX", "FP8_DTYPES", "SCALE_PREFIX",
    "MIN_QUANT_DIM", "MIN_QUANT_SIZE", "channel_axis",
    "quantize_int8", "quantize_int8_rows", "dequantize_int8",
    "dequantize_entry", "quantize_fp8",
    "dequantize_fp8", "fake_cast", "fake_quant_int8", "fake_quant_fp8",
    "eligible", "quantize_state_arrays", "dequantize_state_arrays",
    "quantize_params", "dequant_params_scope",
]
