"""singa_tpu.quant — int8/fp8 quantization subsystem.

Extends the :mod:`singa_tpu.mixed_precision` policy axis beyond
bf16/fp16 into integer and fp8 numerics, end to end:

- **weight-only int8** (:func:`quantize_params`): fp32 masters become
  int8 payloads + per-channel fp32 scale sidecars, dequantized IN
  GRAPH at the matmul/conv boundary — the one-jitted-program contract
  and the ``n_traces == 1`` pin survive;
- **fp8 compute / QAT** (``QuantPolicy("fp8_mixed")`` /
  ``("int8_qat")``): e4m3 weight/activation fake-quant with the
  straight-through estimator inside the compiled step, e5m2 gradient
  emulation riding the ``GuardedOptimizer`` loss-scaling driver;
- **calibration** (:class:`Calibrator`): observe N batches, record
  activation ranges as registry gauges, freeze scales into the policy;
- **quantized serving**: ``Model.compile_serving(
  policy="int8_weight_only" | "fp8_serving")`` quantizes weights at
  engine build and runs the ring KV cache in int8 (per-slot scale
  rows, f32 softmax unchanged);
- **quantized checkpoints**: ``save_states`` / ``CheckpointManager``
  persist int8 payload + scales with the normal digest sidecars (~4x
  smaller); ``tools/quantize_checkpoint.py`` converts an existing fp32
  checkpoint offline.

See ``docs/quantization.md`` for the policy table and workflow.
"""

from . import core                                   # noqa: F401
from . import calibrate as calibrate_mod             # noqa: F401
from .core import (                                  # noqa: F401
    SCALE_PREFIX, channel_axis, dequant_params_scope,
    dequantize_fp8, dequantize_int8, dequantize_state_arrays,
    fake_cast, fake_quant_fp8, fake_quant_int8, quantize_fp8,
    quantize_int8, quantize_int8_rows, quantize_params,
    quantize_state_arrays,
)
from .calibrate import Calibrator, calibrate         # noqa: F401

__all__ = [
    "core", "SCALE_PREFIX", "channel_axis", "dequant_params_scope",
    "dequantize_fp8", "dequantize_int8", "dequantize_state_arrays",
    "fake_cast", "fake_quant_fp8", "fake_quant_int8", "quantize_fp8",
    "quantize_int8", "quantize_int8_rows", "quantize_params",
    "quantize_state_arrays", "Calibrator", "calibrate",
]
