"""Data loading pipeline: checkpointable, deterministic, fault-isolating.

Capability parity with the reference pipeline (python/singa/data.py:60-124):
:class:`ImageBatchIter` streams (image, label) batches from an image-list
file through a worker process and a bounded queue, overlapping JPEG decode +
augmentation with device compute. On TPU this hides host-side input cost
behind the XLA step, the same role the reference's prefetch plays for CUDA.

On top of that parity, every iterator here implements the **state
protocol** the resilience stack (``singa_tpu/resilience``) rides on::

    state = it.state_dict()        # tiny JSON-able dict
    it2.load_state_dict(state)     # resume the EXACT sample stream

The protocol's contract is *exactly-once*: shuffles are **stateless**
(an epoch's sample order is a pure function of ``(seed, epoch)`` via
:func:`epoch_permutation` — never stored), so state is just counters
``{epoch, position, ...}`` and a restored iterator reproduces the exact
order from any offset. A preempted-and-resumed run therefore consumes a
sample sequence bit-identical to a fault-free one — the reproducibility
bar pod-scale TPU fine-tuning holds itself to. ``state_dict()`` always
reflects batches the CONSUMER has taken (never batches merely sitting in
a prefetch queue), so a prefetched-but-unstepped batch is replayed after
a restore, not dropped.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
import warnings
from multiprocessing import Process, Queue
from queue import Empty, Full, Queue as _TQueue
from threading import Thread

import numpy as np

from .observability import metrics as _obs_metrics


def _quarantine_counter():
    return _obs_metrics.default_registry().counter(
        "data_quarantined_total",
        "corrupt samples skipped and attributed by the pipeline")


def epoch_permutation(seed, epoch, n):
    """The stateless shuffle every checkpointable iterator shares: the
    sample order of epoch ``epoch`` is a pure function of
    ``(seed, epoch)`` — derived on demand, never stored — so iterator
    state stays ``{epoch, position}`` and any rank (or a restarted
    process, or a re-sharded elastic world) reproduces the exact same
    global order."""
    ss = np.random.SeedSequence([int(seed) & 0xFFFFFFFF, int(epoch)])
    return np.random.Generator(np.random.PCG64(ss)).permutation(int(n))


def can_load_state(obj):
    """True when ``obj`` can actually LOAD a saved data state. Plain
    ``callable(obj.load_state_dict)`` lies for delegating wrappers — a
    :class:`DevicePrefetcher` around a plain generator has the method
    but nothing to apply it to — so wrappers expose their own
    ``can_load_state()`` answering for the inner source, and the
    resilience runtime probes through this helper before committing to
    a rewind (falling back to its loud not-checkpointable warning
    instead of crashing mid-restore)."""
    probe = getattr(obj, "can_load_state", None)
    if callable(probe):
        return bool(probe())
    return callable(getattr(obj, "load_state_dict", None))


def raise_retried_failure(failed):
    """The ONE closed-generator-after-retry rule
    (:class:`RetryingIterator` and ``resilience.runtime._next_batch``
    both fetch through it): a ``StopIteration`` that immediately
    follows a retried error on a non-rebuildable source is the corpse
    of the closed generator, not exhaustion — re-raise the original
    failure instead of silently truncating the stream. A no-op when no
    retried failure is pending."""
    if failed is not None:
        raise failed from None


class DataWorkerKilled(BaseException):
    """Fault injection only (``FaultPlan.kill_data_worker``): kills the
    prefetch worker abruptly — no error record, no goodbye — so the
    consumer's died-worker attribution path is what gets exercised.
    BaseException so the worker's skip/error handlers cannot absorb it."""


class DataSampleError(RuntimeError):
    """A data pipeline failure attributed to a NAMED sample: carries
    ``sample`` (the ``{epoch, index, path, error}`` record of the
    offending sample, when known) and ``quarantined`` (every skipped
    sample so far) so a dead worker or an exhausted skip budget
    surfaces *which* bytes are bad, not just that something died."""

    def __init__(self, message, sample=None, quarantined=None):
        super().__init__(message)
        self.sample = sample
        self.quarantined = list(quarantined or [])


class ImageBatchIter:
    """Iterate over (images, labels) batches from an image list file.

    ``img_list_file``: each line is ``<relative path><delimiter><label>``.
    ``image_transform``: path -> list of augmented numpy images (multiple
    augmentations multiply the effective batch, like the reference).

    Deterministic + checkpointable: shuffling uses the stateless
    :func:`epoch_permutation` keyed by ``(seed, epoch)``, and
    ``state_dict()/load_state_dict()`` resume the exact stream from the
    last CONSUMED batch (batches still sitting in the prefetch queue at
    a crash are re-decoded by the restarted worker — replayed, never
    dropped).

    Fault isolation: a sample whose decode/transform raises is skipped,
    counted, and recorded in ``self.quarantined`` with full attribution
    (epoch, list index, path, error) instead of killing the worker —
    bounded by ``skip_budget`` total skips, beyond which the iterator
    raises :class:`DataSampleError` loudly (the default budget of 0
    keeps fail-fast semantics, now with the sample named). A worker
    that dies outright surfaces the sample it was decoding.
    """

    def __init__(self, img_list_file, batch_size, image_transform,
                 shuffle=True, delimiter=" ", image_folder=None,
                 capacity=10, use_process=False, seed=0,
                 skip_budget=0, faults=None):
        """``use_process=False`` (default) prefetches on a daemon thread —
        fork()ing a multi-threaded XLA process is deadlock-prone, and PIL /
        numpy release the GIL for the heavy work. ``use_process=True``
        matches the reference's separate-process behaviour."""
        self.img_list_file = img_list_file
        self.use_process = use_process
        self.capacity = capacity
        self.queue = Queue(capacity) if use_process else _TQueue(capacity)
        self.batch_size = batch_size
        self.image_transform = image_transform
        self.shuffle = shuffle
        self.delimiter = delimiter
        self.image_folder = image_folder or ""
        self.seed = int(seed)
        self.skip_budget = int(skip_budget)
        self.faults = faults
        self.stop = False
        self.p = None
        # CONSUMED state (advances only when __next__ hands a batch out)
        self._epoch = 0
        self._position = 0
        self.skip_count = 0
        self.quarantined = []
        self.last_batch_ids = None
        # worker-side attribution: the sample being decoded right now.
        # Thread mode shares memory; process mode writes it through a
        # black-box-recorder file (_attr_path) the parent reads on
        # death — a segfaulting decoder can't say goodbye, but the
        # record it wrote just before survives it.
        self._current_sample = None
        self._attr_path = None
        self._gen_id = 0
        with open(img_list_file, "r") as fd:
            self.num_samples = sum(1 for line in fd if line.strip())

    # -- state protocol ----------------------------------------------------
    def state_dict(self):
        """JSON-able consumed-stream state. ``seed`` and ``num_samples``
        ride along for verification only — the shuffle itself is
        stateless (:func:`epoch_permutation`)."""
        return {"kind": "ImageBatchIter", "epoch": int(self._epoch),
                "position": int(self._position), "seed": self.seed,
                "num_samples": int(self.num_samples),
                "skip_count": int(self.skip_count),
                "quarantined": [dict(q) for q in self.quarantined]}

    def load_state_dict(self, state):
        """Rewind/fast-forward to ``state`` (a running worker is ended
        and restarts from the loaded offset on the next fetch)."""
        if self.p is not None:
            self.end()
        _check_state_source(self, state)
        self._epoch = int(state.get("epoch", 0))
        self._position = int(state.get("position", 0))
        self.skip_count = int(state.get("skip_count", 0))
        self.quarantined = [dict(q)
                           for q in state.get("quarantined", [])]
        self.last_batch_ids = None

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self.stop = False
        # fresh queue + generation per worker: a batch a dying worker
        # managed to put during the end() drain race can never leak
        # into a restarted iterator (and a stale-generation record that
        # somehow survives is discarded by __next__)
        self._gen_id += 1
        self.queue = Queue(self.capacity) if self.use_process \
            else _TQueue(self.capacity)
        start_state = (self._epoch, self._position, self.skip_count)
        if self.use_process:
            self._remove_attr_file()
            self._attr_path = os.path.join(
                tempfile.gettempdir(),
                f"singa-data-attr-{os.getpid()}-{id(self)}-"
                f"{self._gen_id}.json")
            self.p = Process(target=self.run,
                             args=(self._gen_id, start_state,
                                   self._attr_path))
        else:
            self.p = Thread(target=self.run,
                            args=(self._gen_id, start_state))
        self.p.daemon = True
        self.p.start()

    def _remove_attr_file(self):
        if self._attr_path is not None:
            try:
                os.remove(self._attr_path)
            except OSError:
                pass
            self._attr_path = None

    def _worker_death_error(self):
        sample = self._current_sample
        if sample is None and self._attr_path is not None:
            # process mode: the child's memory is gone, but its
            # black-box record of the sample it was decoding survives
            try:
                with open(self._attr_path) as f:
                    sample = json.load(f)
            except (OSError, ValueError):
                pass
        if sample is not None:
            return DataSampleError(
                f"ImageBatchIter worker died while decoding sample "
                f"{sample.get('path')!r} (epoch {sample.get('epoch')}, "
                f"list index {sample.get('index')})", sample=sample,
                quarantined=self.quarantined)
        return DataSampleError(
            "ImageBatchIter worker died (bad image path or malformed "
            "list line?)", quarantined=self.quarantined)

    def __next__(self):
        assert self.p is not None, "call start() before next()"
        while True:
            try:
                item = self.queue.get(timeout=1.0)
            except Empty:
                if not self.p.is_alive():
                    raise self._worker_death_error() from None
                continue
            if not isinstance(item, dict) or \
                    item.get("gen") != self._gen_id:
                continue                    # stale worker generation
            kind = item.get("kind")
            if kind == "error":
                # the worker attributed its own death (skip budget
                # exhausted, unreadable list, ...): adopt its
                # bookkeeping and raise with the sample named
                self.skip_count = int(item.get("skip_count",
                                               self.skip_count))
                for q in item.get("quarantined", []):
                    self.quarantined.append(dict(q))
                raise DataSampleError(item.get("message", "data worker "
                                                          "failure"),
                                      sample=item.get("sample"),
                                      quarantined=self.quarantined)
            if kind != "batch":
                continue                    # clean-stop sentinel
            # consumed-at-hand-out accounting: state reflects THIS
            # batch only once the caller actually has it
            self._epoch = int(item["epoch"])
            self._position = int(item["position"])
            self.skip_count = int(item["skip_count"])
            if item["skipped"]:
                self.quarantined.extend(item["skipped"])
                _quarantine_counter().inc(len(item["skipped"]))
                first = item["skipped"][0]
                warnings.warn(
                    f"ImageBatchIter: skipped {len(item['skipped'])} "
                    f"corrupt sample(s) (first: {first.get('path')!r}, "
                    f"{first.get('error')}); {self.skip_count}/"
                    f"{self.skip_budget} of the skip budget used",
                    stacklevel=2)
            self.last_batch_ids = np.asarray(item["ids"], np.int64)
            return item["batch"]

    next = __next__

    def __iter__(self):
        if self.p is None:
            self.start()
        return self

    def end(self):
        if self.p is None:
            return
        self.stop = True
        if self.use_process:
            self.p.terminate()
            self.p.join(timeout=5.0)    # reap: no zombie child left
        else:
            # drain WHILE joining: a worker blocked mid-put frees up,
            # sees the stop flag, enqueues its end sentinel and exits —
            # the join (not the drain) is what guarantees no worker
            # survives into a restarted iterator
            deadline = time.monotonic() + 5.0
            while self.p.is_alive() and time.monotonic() < deadline:
                try:
                    self.queue.get_nowait()
                except Empty:
                    pass
                self.p.join(timeout=0.05)
            if self.p.is_alive():
                warnings.warn(
                    "ImageBatchIter worker did not exit within the "
                    "end() grace (a transform hung?); its queue is "
                    "abandoned", stacklevel=2)
        self.p = None
        self._remove_attr_file()

    # -- worker ------------------------------------------------------------
    def _put(self, item):
        """Stop-aware bounded put; returns False when stopped first."""
        while not self.stop:
            try:
                self.queue.put(item, timeout=0.1)
                return True
            except Full:
                continue
        return False

    def run(self, gen=0, start_state=None, attr_path=None):
        epoch, pos, skip_count = start_state or (0, 0, 0)
        try:
            with open(self.img_list_file, "r") as fd:
                samples = [line.strip().split(self.delimiter, 1)
                           for line in fd if line.strip()]
        except OSError as e:
            self._put({"kind": "error", "gen": gen,
                       "skip_count": skip_count, "quarantined": [],
                       "message": f"cannot read image list "
                                  f"{self.img_list_file!r}: {e}"})
            return
        n = len(samples)
        pending_skips = []   # skip records awaiting a batch to ride on
        while not self.stop:
            order = epoch_permutation(self.seed, epoch, n) \
                if self.shuffle else np.arange(n)
            while pos < n and not self.stop:
                images, labels, ids = [], [], []
                skips = pending_skips
                pending_skips = []
                while len(images) < self.batch_size and pos < n:
                    i = int(order[pos])
                    path, label = samples[i]
                    full = os.path.join(self.image_folder, path)
                    self._current_sample = {"epoch": epoch, "index": i,
                                            "path": full}
                    if attr_path is not None:
                        # black-box recorder (process mode): written
                        # BEFORE the decode so an abrupt death leaves
                        # the sample's name behind (best effort — an
                        # unwritable tmpdir degrades to the generic
                        # death message, never kills the worker)
                        try:
                            with open(attr_path, "w") as f:
                                json.dump(self._current_sample, f)
                        except OSError:
                            attr_path = None
                    pos += 1
                    try:
                        if self.faults is not None:
                            self.faults.on_sample(pos - 1, full)
                        augmented = self.image_transform(full)
                        for img in augmented:
                            images.append(np.asarray(img, np.float32))
                            labels.append(int(float(label)))
                            ids.append(i)
                    except (KeyboardInterrupt, SystemExit):
                        raise
                    except DataWorkerKilled:
                        return      # abrupt death: no record, no goodbye
                    except Exception as e:
                        skip_count += 1
                        rec = {"epoch": int(epoch), "index": i,
                               "path": full,
                               "error": f"{type(e).__name__}: {e}"}
                        skips.append(rec)
                        if skip_count > self.skip_budget:
                            self._put({
                                "kind": "error", "gen": gen,
                                "sample": rec, "quarantined": skips,
                                "skip_count": skip_count,
                                "message":
                                    f"data skip budget exhausted: "
                                    f"{skip_count} corrupt sample(s) "
                                    f"with a budget of "
                                    f"{self.skip_budget} (last: "
                                    f"{full!r}, {rec['error']}) — "
                                    "the dataset needs attention, not "
                                    "more skipping"})
                            return
                if not images:
                    # the whole tail of the epoch was corrupt: its skip
                    # records ride on the next REAL batch (the records
                    # carry their own epoch/index attribution, so
                    # arriving late loses nothing)
                    pending_skips = skips
                    break
                batch = (np.stack(images), np.asarray(labels, np.int32))
                if not self._put({"kind": "batch", "gen": gen,
                                  "epoch": int(epoch),
                                  "position": int(pos),
                                  "skipped": skips,
                                  "skip_count": int(skip_count),
                                  "ids": ids, "batch": batch}):
                    break
            if self.stop:
                break
            epoch += 1
            pos = 0
        # clean-stop sentinel (best effort: the queue may be full and
        # the consumer gone; generation tags make a missed sentinel
        # harmless)
        try:
            self.queue.put_nowait({"kind": "end", "gen": gen})
        except Full:
            pass


def backoff_delay(attempt, base, cap, jitter, rng):
    """The one retry-delay formula every resilient component shares:
    ``min(cap, base * 2**attempt)`` stretched by up to ``jitter`` drawn
    from the caller's (seeded, hence deterministic) RNG."""
    return min(cap, base * (2.0 ** attempt)) * (1.0 + jitter * rng.random())


def _check_state_source(it, state):
    """Shared load_state_dict sanity: a state saved against a different
    dataset size cannot resume the same stream; a different seed CAN —
    by adopting the saved one (the permutation is derived from the
    state's seed, which is the whole point of carrying it)."""
    n = state.get("num_samples")
    if n is not None and int(n) != int(it.num_samples):
        warnings.warn(
            f"data state was saved over {n} samples but this iterator "
            f"holds {it.num_samples}; the resumed stream will NOT "
            "match the saved one (did the dataset change?)",
            stacklevel=3)
    seed = state.get("seed")
    if seed is not None and int(seed) != int(it.seed):
        warnings.warn(
            f"data state carries seed {seed} but this iterator was "
            f"built with seed {it.seed}; adopting the SAVED seed so "
            "the resumed stream matches the checkpoint", stacklevel=3)
        it.seed = int(seed)


class RetryingIterator:
    """Retry transient data-source failures with exponential backoff +
    jitter — the input-pipeline arm of the resilient training runtime
    (singa_tpu/resilience): a flaky network filesystem or a dying
    worker costs a delayed batch, not the job.

    ``source`` is an iterable OR a zero-arg factory returning a fresh
    iterator; with a factory, a failure REBUILDS the source (the right
    move when the underlying worker/socket is dead). A rebuilt source
    that supports the state protocol is FAST-FORWARDED to the state of
    the last delivered batch, so the rebuilt stream continues exactly
    where the dead one left off — no replayed, no skipped samples.
    ``StopIteration`` passes through untouched — exhaustion is not a
    failure — EXCEPT when it immediately follows a retried error on a
    non-factory source: a generator that raised is permanently closed,
    so its retry yields StopIteration, and passing that through would
    silently truncate the stream; :func:`raise_retried_failure` (the
    rule's one home, shared with ``resilience.runtime._next_batch``)
    re-raises the original error instead.

    A factory-backed RetryingIterator is also RE-ITERABLE: calling
    ``iter()`` on an exhausted one rebuilds a fresh epoch from the
    factory, so it drops straight into ResilientTrainer's epoch-wrap
    (and the trainer's run summary surfaces ``counters()``).

        for batch in RetryingIterator(lambda: ImageBatchIter(...)):
            ...
    """

    def __init__(self, source, max_retries=3, backoff_base=0.1,
                 backoff_cap=5.0, jitter=0.25, seed=0, sleep=None):
        import random
        import time
        self._source = source
        self._factory = source if callable(source) else None
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self.jitter = float(jitter)
        # observability: surfaced in the ResilientTrainer run summary
        # (data-pipeline flakiness must be visible, not silent)
        self.attempts = 0           # total fetch attempts, incl. retries
        self.retries = 0            # failed attempts that were retried
        self.rebuilds = 0           # factory-source rebuilds after failure
        self._rng = random.Random(seed)
        self._sleep = sleep if sleep is not None else time.sleep
        self._it = None
        self._src_obj = None
        self._last_state = None     # state as of the last DELIVERED batch
        self._pending_state = None  # explicit load, applied on (re)build
        self._exhausted = False

    def _iterator(self):
        if self._it is None:
            src = self._factory() if self._factory is not None \
                else self._source
            self._src_obj = src
            state = self._pending_state if self._pending_state is not None \
                else self._last_state
            if state is not None and hasattr(src, "load_state_dict"):
                src.load_state_dict(state)
            self._pending_state = None
            self._it = iter(src)
        return self._it

    def __iter__(self):
        # epoch wrap for factory sources: a fresh iterator per epoch
        # (a plain-iterable source keeps passthrough exhaustion)
        if self._factory is not None and self._exhausted:
            self._it = None
            self._exhausted = False
        return self

    def counters(self) -> dict:
        """Flakiness counters: ``attempts`` (every fetch attempt,
        retries included), ``retries`` (attempts that failed and were
        retried), ``rebuilds`` (factory-source rebuilds). The
        ResilientTrainer run summary embeds this dict."""
        return {"attempts": self.attempts, "retries": self.retries,
                "rebuilds": self.rebuilds}

    # -- state protocol ----------------------------------------------------
    def state_dict(self):
        """Delegates to the underlying source: the state of the last
        DELIVERED batch (a batch lost to an in-flight failure was never
        delivered, so resume regenerates it — replay, not drop).
        Returns None when the source predates the protocol."""
        if self._last_state is not None:
            return dict(self._last_state)
        src = self._src_obj if self._src_obj is not None \
            else (None if self._factory is not None else self._source)
        sd = getattr(src, "state_dict", None)
        return sd() if callable(sd) else None

    def can_load_state(self):
        """Delegating wrapper: a factory source is trusted (the state
        is applied to whatever it builds); a plain source answers for
        itself (see :func:`can_load_state`)."""
        if self._factory is not None:
            return True
        return can_load_state(self._source)

    def load_state_dict(self, state):
        self._pending_state = dict(state)
        self._last_state = dict(state)
        self._exhausted = False
        self._it = None        # applied when the source is (re)built

    def __next__(self):
        attempt = 0
        failed = None
        while True:
            try:
                self.attempts += 1
                item = next(self._iterator())
            except StopIteration:
                # a failed generator is closed, not exhausted: surface
                # the failure instead of truncating the stream (the one
                # shared rule — see raise_retried_failure)
                raise_retried_failure(failed)
                self._exhausted = True
                raise
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                if attempt >= self.max_retries:
                    raise
                self._sleep(backoff_delay(attempt, self.backoff_base,
                                          self.backoff_cap, self.jitter,
                                          self._rng))
                self.retries += 1
                _obs_metrics.default_registry().counter(
                    "data_retries_total",
                    "transient data-source failures retried").inc()
                attempt += 1
                if self._factory is not None:
                    self._it = None     # rebuild a (likely dead) source
                    self.rebuilds += 1
                    _obs_metrics.default_registry().counter(
                        "data_rebuilds_total",
                        "factory data sources rebuilt after failure"
                    ).inc()
                else:
                    failed = e
            else:
                sd = getattr(self._src_obj, "state_dict", None)
                if callable(sd):
                    self._last_state = sd()
                return item

    next = __next__


class NumpyBatchIter:
    """Batches over in-memory arrays with a stateless epoch shuffle —
    the synthetic / pre-loaded data path used by examples (reference
    examples load cifar into numpy then slice batches in the train
    loop).

    ``batch_size`` is the PER-RANK batch; with ``world > 1`` the
    deterministic global stream (epoch ``e`` is
    ``epoch_permutation(seed, e, n)``) is consumed ``batch_size *
    world`` samples per step, rank ``r`` reading the ``r``-th slice of
    each global batch. State (``{epoch, position}``) counts GLOBAL
    samples and is therefore rank-agnostic: any rank's saved state
    resumes any other rank — or a *different* world size — at the same
    point of the same stream, which is what makes elastic resume
    exactly-once (the consumed set is always a prefix of the global
    permutation).

    ``pad_last=True`` (implies ``drop_last=False``) pads the ragged
    last batch up to ``batch_size`` and yields ``(x, y, mask)`` with a
    float32 validity mask for EVERY batch — constant shapes and arity,
    so a fixed-shape compiled step never retraces on the tail.

    ``last_batch_ids`` holds the dataset indices of the most recently
    yielded batch (this rank's slice) — the sample-attribution probe
    the exactly-once chaos scenario asserts on.
    """

    def __init__(self, x, y, batch_size, shuffle=True, drop_last=True,
                 seed=0, pad_last=False, rank=0, world=1):
        self.x = np.asarray(x)
        self.y = np.asarray(y)
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.pad_last = bool(pad_last)
        self.drop_last = False if pad_last else drop_last
        self.seed = int(seed)
        self.rank = int(rank)
        self.world = max(1, int(world))
        if not 0 <= self.rank < self.world:
            raise ValueError(f"rank {rank} outside world {world}")
        if self.world > 1 and not self.drop_last and not self.pad_last:
            # a ragged last GLOBAL batch would hand high ranks a short
            # (possibly empty) slice — divergent per-rank shapes desync
            # every collective; padding is the constant-shape answer
            raise ValueError(
                "NumpyBatchIter with world > 1 and drop_last=False "
                "requires pad_last=True (the ragged last global batch "
                "would yield rank-divergent, possibly empty, slices)")
        self._epoch = 0
        self._position = 0      # GLOBAL samples consumed this epoch
        self.last_batch_ids = None

    @property
    def num_samples(self):
        return len(self.x)

    @property
    def global_batch(self):
        return self.batch_size * self.world

    @property
    def num_batches(self):
        n = len(self.x) // self.global_batch
        if not self.drop_last and len(self.x) % self.global_batch:
            n += 1
        return n

    def _epoch_samples(self):
        """Global samples one epoch consumes."""
        n = len(self.x)
        if self.drop_last:
            return (n // self.global_batch) * self.global_batch
        return n

    # -- state protocol ----------------------------------------------------
    def state_dict(self):
        return {"kind": "NumpyBatchIter", "epoch": int(self._epoch),
                "position": int(self._position), "seed": self.seed,
                "num_samples": int(len(self.x))}

    def load_state_dict(self, state):
        _check_state_source(self, state)
        self._epoch = int(state.get("epoch", 0))
        self._position = int(state.get("position", 0))
        self.last_batch_ids = None

    def __iter__(self):
        n = len(self.x)
        end = self._epoch_samples()
        if end <= 0:
            return
        if self._position >= end:
            # the previous epoch was fully consumed (possibly noticed
            # only now, at re-iteration): wrap
            self._epoch += 1
            self._position = 0
        epoch = self._epoch
        idx = epoch_permutation(self.seed, epoch, n) if self.shuffle \
            else np.arange(n)
        while self._position < end and self._epoch == epoch:
            pos = self._position
            take = min(self.global_batch, end - pos)
            lo = pos + self.rank * self.batch_size
            hi = min(pos + (self.rank + 1) * self.batch_size, pos + take)
            sel = idx[lo:hi] if lo < pos + take else idx[:0]
            # consumed-at-yield accounting: the GLOBAL position advances
            # before the batch is handed out, so state captured after
            # the caller's step counts this batch exactly once
            self._position = pos + take
            self.last_batch_ids = np.asarray(sel, np.int64)
            bx, by = self.x[sel], self.y[sel]
            if self.pad_last:
                mask = np.zeros(self.batch_size, np.float32)
                mask[:len(sel)] = 1.0
                if len(sel) < self.batch_size:
                    pad = self.batch_size - len(sel)
                    bx = np.concatenate(
                        [bx, np.zeros((pad,) + bx.shape[1:], bx.dtype)])
                    by = np.concatenate(
                        [by, np.zeros((pad,) + by.shape[1:], by.dtype)])
                yield bx, by, mask
            else:
                yield bx, by


class DevicePrefetcher:
    """Keep the NEXT batch's host-to-device transfer in flight while the
    current batch computes.

    Wraps any iterator of numpy-array tuples and yields Tensors already
    resident on ``device``. ``jax.device_put`` is asynchronous, so holding
    ``depth`` batches ahead overlaps the H2D copies (PCIe/DMA) with the
    compiled step — the TPU-side counterpart of the host-side prefetch
    thread above (reference ImageBatchIter, python/singa/data.py:60-124,
    prefetches into host memory only; there is no device staging in the
    reference because CUDA streams hide it).

    State protocol: ``state_dict()`` snapshots the inner iterator's
    state *as of the last batch this prefetcher YIELDED* — never the
    batches merely staged in flight — so a resume replays the staged-
    but-unconsumed window instead of dropping it, and a consumed batch
    is never yielded twice.

    Usage::

        for tx, ty in DevicePrefetcher(batches, dev):
            out, loss = model(tx, ty)
    """

    def __init__(self, iterator, device, depth=2, background=False):
        from .tensor import Tensor
        self._Tensor = Tensor
        self.iterator = iterator       # re-iterated per epoch in __iter__
        self.device = device
        self.depth = max(1, int(depth))
        self.background = bool(background)
        self._consumed_state = None

    # -- state protocol ----------------------------------------------------
    def state_dict(self):
        if self._consumed_state is not None:
            return dict(self._consumed_state)
        sd = getattr(self.iterator, "state_dict", None)
        return sd() if callable(sd) else None

    def can_load_state(self):
        """Delegating wrapper: loadable iff the INNER iterator is (see
        :func:`can_load_state`)."""
        return can_load_state(self.iterator)

    def load_state_dict(self, state):
        ld = getattr(self.iterator, "load_state_dict", None)
        if ld is None:
            raise TypeError(
                "DevicePrefetcher's inner iterator does not implement "
                "the state protocol (no load_state_dict)")
        ld(state)
        self._consumed_state = dict(state)

    def _stage(self, batch):
        if not isinstance(batch, (tuple, list)):
            batch = (batch,)
        # Tensor.__init__ routes numpy input through device.put (async)
        return tuple(
            self._Tensor(data=np.asarray(a), device=self.device,
                         requires_grad=False)
            for a in batch)

    def _source(self):
        import types
        src = iter(self.iterator)
        if isinstance(self.iterator, types.GeneratorType):
            # an exhausted generator silently yields nothing — make a
            # second epoch over it an actionable error. (Live streaming
            # iterators like ImageBatchIter also return self from
            # __iter__ but keep producing, so only generators are
            # flagged.)
            if getattr(self, "_consumed_oneshot", False):
                raise RuntimeError(
                    "DevicePrefetcher wrapped a generator that is "
                    "already exhausted; pass a re-iterable (e.g. "
                    "NumpyBatchIter) for multi-epoch use")
            self._consumed_oneshot = True
        return src

    def __iter__(self):
        if self.background:
            yield from self._iter_background()
            return
        from collections import deque
        src = self._source()
        sd = getattr(self.iterator, "state_dict", None)
        pending = deque()   # (staged batch, inner state AFTER that batch)

        def emit():
            staged, st = pending.popleft()
            if st is not None:
                self._consumed_state = st
            return staged

        for batch in src:
            pending.append((self._stage(batch),
                            sd() if callable(sd) else None))
            if len(pending) >= self.depth:
                yield emit()
        while pending:
            yield emit()

    def _iter_background(self):
        """Double-buffered staging on a worker thread: while step N
        computes, the worker pulls batch N+1 from the source (host
        decode/augment) AND issues its asynchronous ``device_put`` —
        the consumer never blocks on either, so the step loop's host
        gap (``timeline_mfu_loss{host}``) collapses to a queue get.

        Exactly-once semantics are IDENTICAL to the synchronous path:
        the inner state is snapshotted per staged batch on the worker
        (the worker is the only thread driving the source, so the
        snapshot is race-free) and only becomes ``state_dict()``'s
        answer when that batch is HANDED OUT — staged-but-unconsumed
        batches replay after a resume, a consumed batch never does. A
        source failure re-raises at the hand-out point, and abandoning
        the generator (break / GC) stops and joins the worker."""
        import queue as _queue
        import threading

        src = self._source()
        sd = getattr(self.iterator, "state_dict", None)
        q = _queue.Queue(maxsize=self.depth)
        stop = threading.Event()

        def _put(item):
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except _queue.Full:
                    continue
            return False

        def worker():
            try:
                for batch in src:
                    staged = self._stage(batch)
                    st = sd() if callable(sd) else None
                    if not _put(("item", (staged, st))):
                        return
            except BaseException as e:  # noqa: BLE001 — re-raised at get
                _put(("error", e))
                return
            _put(("end", None))

        t = threading.Thread(target=worker, daemon=True,
                             name="singa-prefetch")
        t.start()
        try:
            while True:
                kind, payload = q.get()
                if kind == "end":
                    break
                if kind == "error":
                    raise payload
                staged, st = payload
                if st is not None:
                    self._consumed_state = st
                yield staged
        finally:
            stop.set()
            try:                # unblock a worker stuck on a full queue
                while True:
                    q.get_nowait()
            except _queue.Empty:
                pass
            t.join(timeout=5)
