"""Data loading pipeline with background prefetch.

Capability parity with the reference pipeline (python/singa/data.py:60-124):
:class:`ImageBatchIter` streams (image, label) batches from an image-list
file through a worker process and a bounded queue, overlapping JPEG decode +
augmentation with device compute. On TPU this hides host-side input cost
behind the XLA step, the same role the reference's prefetch plays for CUDA.
"""

from __future__ import annotations

import os
import random
from multiprocessing import Process, Queue
from queue import Empty, Full, Queue as _TQueue
from threading import Thread

import numpy as np


class ImageBatchIter:
    """Iterate over (images, labels) batches from an image list file.

    ``img_list_file``: each line is ``<relative path><delimiter><label>``.
    ``image_transform``: path -> list of augmented numpy images (multiple
    augmentations multiply the effective batch, like the reference).
    """

    def __init__(self, img_list_file, batch_size, image_transform,
                 shuffle=True, delimiter=" ", image_folder=None,
                 capacity=10, use_process=False):
        """``use_process=False`` (default) prefetches on a daemon thread —
        fork()ing a multi-threaded XLA process is deadlock-prone, and PIL /
        numpy release the GIL for the heavy work. ``use_process=True``
        matches the reference's separate-process behaviour."""
        self.img_list_file = img_list_file
        self.use_process = use_process
        self.queue = Queue(capacity) if use_process else _TQueue(capacity)
        self.batch_size = batch_size
        self.image_transform = image_transform
        self.shuffle = shuffle
        self.delimiter = delimiter
        self.image_folder = image_folder or ""
        self.stop = False
        self.p = None
        with open(img_list_file, "r") as fd:
            self.num_samples = sum(1 for line in fd if line.strip())

    def start(self):
        if self.use_process:
            self.p = Process(target=self.run)
        else:
            self.p = Thread(target=self.run)
        self.p.daemon = True
        self.p.start()

    def __next__(self):
        assert self.p is not None, "call start() before next()"
        while True:
            try:
                return self.queue.get(timeout=1.0)
            except Empty:
                if not self.p.is_alive():
                    raise RuntimeError(
                        "ImageBatchIter worker died (bad image path or "
                        "malformed list line?)") from None

    next = __next__

    def __iter__(self):
        if self.p is None:
            self.start()
        return self

    def end(self):
        if self.p is not None:
            if self.use_process:
                self.p.terminate()
            else:
                self.stop = True
                # unblock a queue.put-blocked worker
                try:
                    while True:
                        self.queue.get_nowait()
                except Empty:
                    pass
            self.p = None

    def run(self):
        with open(self.img_list_file, "r") as fd:
            samples = [line.strip().split(self.delimiter, 1)
                       for line in fd if line.strip()]
        while not self.stop:
            if self.shuffle:
                random.shuffle(samples)
            pos = 0
            while pos < len(samples):
                images, labels = [], []
                while len(images) < self.batch_size and pos < len(samples):
                    path, label = samples[pos]
                    pos += 1
                    full = os.path.join(self.image_folder, path)
                    augmented = self.image_transform(full)
                    for img in augmented:
                        images.append(np.asarray(img, np.float32))
                        labels.append(int(float(label)))
                if not images:
                    continue
                batch = (np.stack(images), np.asarray(labels, np.int32))
                while not self.stop:
                    try:
                        self.queue.put(batch, timeout=0.1)
                        break
                    except Full:
                        continue


def backoff_delay(attempt, base, cap, jitter, rng):
    """The one retry-delay formula every resilient component shares:
    ``min(cap, base * 2**attempt)`` stretched by up to ``jitter`` drawn
    from the caller's (seeded, hence deterministic) RNG."""
    return min(cap, base * (2.0 ** attempt)) * (1.0 + jitter * rng.random())


class RetryingIterator:
    """Retry transient data-source failures with exponential backoff +
    jitter — the input-pipeline arm of the resilient training runtime
    (singa_tpu/resilience): a flaky network filesystem or a dying
    worker costs a delayed batch, not the job.

    ``source`` is an iterable OR a zero-arg factory returning a fresh
    iterator; with a factory, a failure REBUILDS the source (the right
    move when the underlying worker/socket is dead) and iteration
    continues from the rebuilt stream. ``StopIteration`` passes through
    untouched — exhaustion is not a failure — EXCEPT when it
    immediately follows a retried error on a non-factory source: a
    generator that raised is permanently closed, so its retry yields
    StopIteration, and passing that through would silently truncate the
    stream; the original error is re-raised instead.

    A factory-backed RetryingIterator is also RE-ITERABLE: calling
    ``iter()`` on an exhausted one rebuilds a fresh epoch from the
    factory, so it drops straight into ResilientTrainer's epoch-wrap
    (and the trainer's run summary surfaces ``counters()``).

        for batch in RetryingIterator(lambda: ImageBatchIter(...)):
            ...
    """

    def __init__(self, source, max_retries=3, backoff_base=0.1,
                 backoff_cap=5.0, jitter=0.25, seed=0, sleep=None):
        import random
        import time
        self._source = source
        self._factory = source if callable(source) else None
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self.jitter = float(jitter)
        # observability: surfaced in the ResilientTrainer run summary
        # (data-pipeline flakiness must be visible, not silent)
        self.attempts = 0           # total fetch attempts, incl. retries
        self.retries = 0            # failed attempts that were retried
        self.rebuilds = 0           # factory-source rebuilds after failure
        self._rng = random.Random(seed)
        self._sleep = sleep if sleep is not None else time.sleep
        self._it = None
        self._exhausted = False

    def _iterator(self):
        if self._it is None:
            src = self._factory() if self._factory is not None \
                else self._source
            self._it = iter(src)
        return self._it

    def __iter__(self):
        # epoch wrap for factory sources: a fresh iterator per epoch
        # (a plain-iterable source keeps passthrough exhaustion)
        if self._factory is not None and self._exhausted:
            self._it = None
            self._exhausted = False
        return self

    def counters(self) -> dict:
        """Flakiness counters: ``attempts`` (every fetch attempt,
        retries included), ``retries`` (attempts that failed and were
        retried), ``rebuilds`` (factory-source rebuilds). The
        ResilientTrainer run summary embeds this dict."""
        return {"attempts": self.attempts, "retries": self.retries,
                "rebuilds": self.rebuilds}

    def __next__(self):
        attempt = 0
        failed = None
        while True:
            try:
                self.attempts += 1
                item = next(self._iterator())
            except StopIteration:
                if failed is not None:
                    # a failed generator is closed, not exhausted:
                    # surface the failure, don't truncate the stream
                    # (resilience.runtime._next_batch applies the same
                    # rule around its epoch-wrap; keep them in sync)
                    raise failed from None
                self._exhausted = True
                raise
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                if attempt >= self.max_retries:
                    raise
                self._sleep(backoff_delay(attempt, self.backoff_base,
                                          self.backoff_cap, self.jitter,
                                          self._rng))
                self.retries += 1
                attempt += 1
                if self._factory is not None:
                    self._it = None     # rebuild a (likely dead) source
                    self.rebuilds += 1
                else:
                    failed = e
            else:
                return item

    next = __next__


class NumpyBatchIter:
    """Batches over in-memory arrays with epoch shuffle — the synthetic /
    pre-loaded data path used by examples (reference examples load cifar
    into numpy then slice batches in the train loop)."""

    def __init__(self, x, y, batch_size, shuffle=True, drop_last=True,
                 seed=0):
        self.x = np.asarray(x)
        self.y = np.asarray(y)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._rng = np.random.RandomState(seed)

    @property
    def num_batches(self):
        n = len(self.x) // self.batch_size
        if not self.drop_last and len(self.x) % self.batch_size:
            n += 1
        return n

    def __iter__(self):
        idx = np.arange(len(self.x))
        if self.shuffle:
            self._rng.shuffle(idx)
        for b in range(self.num_batches):
            sel = idx[b * self.batch_size:(b + 1) * self.batch_size]
            yield self.x[sel], self.y[sel]


class DevicePrefetcher:
    """Keep the NEXT batch's host-to-device transfer in flight while the
    current batch computes.

    Wraps any iterator of numpy-array tuples and yields Tensors already
    resident on ``device``. ``jax.device_put`` is asynchronous, so holding
    ``depth`` batches ahead overlaps the H2D copies (PCIe/DMA) with the
    compiled step — the TPU-side counterpart of the host-side prefetch
    thread above (reference ImageBatchIter, python/singa/data.py:60-124,
    prefetches into host memory only; there is no device staging in the
    reference because CUDA streams hide it).

    Usage::

        for tx, ty in DevicePrefetcher(batches, dev):
            out, loss = model(tx, ty)
    """

    def __init__(self, iterator, device, depth=2):
        from .tensor import Tensor
        self._Tensor = Tensor
        self.iterator = iterator       # re-iterated per epoch in __iter__
        self.device = device
        self.depth = max(1, int(depth))

    def _stage(self, batch):
        if not isinstance(batch, (tuple, list)):
            batch = (batch,)
        # Tensor.__init__ routes numpy input through device.put (async)
        return tuple(
            self._Tensor(data=np.asarray(a), device=self.device,
                         requires_grad=False)
            for a in batch)

    def __iter__(self):
        import types
        from collections import deque
        src = iter(self.iterator)
        if isinstance(self.iterator, types.GeneratorType):
            # an exhausted generator silently yields nothing — make a
            # second epoch over it an actionable error. (Live streaming
            # iterators like ImageBatchIter also return self from
            # __iter__ but keep producing, so only generators are
            # flagged.)
            if getattr(self, "_consumed_oneshot", False):
                raise RuntimeError(
                    "DevicePrefetcher wrapped a generator that is "
                    "already exhausted; pass a re-iterable (e.g. "
                    "NumpyBatchIter) for multi-epoch use")
            self._consumed_oneshot = True
        pending = deque()
        for batch in src:
            pending.append(self._stage(batch))
            if len(pending) >= self.depth:
                yield pending.popleft()
        while pending:
            yield pending.popleft()
