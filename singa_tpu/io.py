"""IO: record files, text files, LMDB (optional), codecs, image transformer.

Capability parity with the reference IO stack (src/io/): BinFile/TextFile
readers and writers (reference include/singa/io/reader.h:70, writer.h),
LMDB reader/writer gated on the lmdb package, JPG and CSV codecs
(src/io/{jpg,csv}_{encoder,decoder}.cc — PIL replaces OpenCV), and the
crop/resize/flip ImageTransformer (src/io/image_transformer.cc). The byte
paths run in the native C++ runtime (native/singa_native.cc) via ctypes.
"""

from __future__ import annotations

import io as _stdio
import os

import numpy as np

from . import native
from .integrity import (IntegrityError, read_digest_sidecar,
                        record_digest, write_digest_sidecar)
from .tensor import Tensor


# ---------------------------------------------------------------------------
# binary record files (native)
# ---------------------------------------------------------------------------

class BinFileWriter:
    """KV record-file writer (reference src/io/binfile_writer.cc).

    ``digest=True`` accumulates a per-record content digest and writes
    a ``<path>.digest`` sidecar on Close — ``verify_record_file`` (or
    ``BinFileReader(..., verify=True)``) re-checks every record against
    it, so bit-rot in an at-rest dataset/checkpoint record file is
    caught at read time instead of training on garbage."""

    def __init__(self, path=None, mode="create", digest=False):
        self._w = None
        self._digest = bool(digest)
        self._records = {}
        self._count = 0
        self._path = None
        if path is not None:
            self.Open(path, mode)

    def Open(self, path, mode="create"):
        if mode == "append" and self._digest:
            # continue the EXISTING sidecar's numbering, or the rewrite
            # on Close would describe only the appended tail and a
            # healthy file would fail verification. Checked BEFORE the
            # writer opens so a refusal never leaks an open handle.
            prior = read_digest_sidecar(path + ".digest")
            if prior is None:
                raise ValueError(
                    f"append with digest=True needs {path}.digest from "
                    "the original writer (was it written with "
                    "digest=True?)")
            self._records = dict(prior["records"])
            self._count = int(prior.get("count", len(self._records)))
        elif mode != "append":
            self._records, self._count = {}, 0
        self._w = native.RecordWriter(path, append=(mode == "append"))
        self._path = path
        if mode == "append" and not self._digest and \
                os.path.exists(path + ".digest"):
            # appending UNVERIFIED records invalidates the old sidecar
            # — left behind, it would flag the healthy grown file as
            # corrupt ("sidecar out of sync"). The file is knowingly
            # unverified from here on; say so.
            import warnings
            warnings.warn(
                f"appending to {path} without digest=True: removing "
                "its digest sidecar (the file is no longer "
                "verifiable)", stacklevel=3)
            try:
                os.remove(path + ".digest")
            except OSError:
                pass
        if mode != "append":
            # a rewrite invalidates any previous writer's sidecar; left
            # behind it would make verification flag the healthy new
            # records as corrupt (Close rewrites it when digest=True).
            # Removed only AFTER the writer opened: a failed open must
            # not strip a still-valid file of its verifiability.
            try:
                os.remove(path + ".digest")
            except OSError:
                pass
        return True

    def Write(self, key, value):
        self._w.write(key, value)
        if self._digest:
            value = value.encode() if isinstance(value, str) else value
            kb = key.encode() if isinstance(key, str) else bytes(key)
            # index-qualified (record files may repeat keys), and named
            # by the DECODED key — exactly how verify_record_file will
            # look the record up when it reads the file back
            name = f"{self._count}:{kb.decode('utf-8', 'replace')}"
            self._records[name] = record_digest(kb, value)
        self._count += 1
        return True

    def Flush(self):
        self._w.flush()

    def Close(self):
        if self._w:
            self._w.close()
            self._w = None
            if self._digest and self._path:
                write_digest_sidecar(self._path + ".digest",
                                     self._records, count=self._count)

    write = Write
    flush = Flush
    close = Close

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.Close()


def verify_record_file(path):
    """Re-verify every record of ``path`` against its ``<path>.digest``
    sidecar. Returns the number of records verified; raises
    :class:`~singa_tpu.integrity.IntegrityError` on the first mismatch
    (or on a record count that disagrees — truncation), and
    ``FileNotFoundError`` when no sidecar exists to verify against."""
    sidecar = read_digest_sidecar(path + ".digest")
    if sidecar is None:
        raise FileNotFoundError(f"{path}.digest: no digest sidecar")
    records = sidecar["records"]
    reader = native.RecordReader(path)
    n = 0
    try:
        while True:
            rec = reader.read()
            if rec is None:
                break
            key, value = rec
            name = f"{n}:{key.decode('utf-8', 'replace')}"
            want = records.get(name)
            if want is None:
                raise IntegrityError(
                    f"{path}: record #{n} ({key!r}) has no digest "
                    "entry — sidecar out of sync with the file")
            if record_digest(key, value) != want:
                raise IntegrityError(
                    f"{path}: record #{n} ({key!r}) failed its content "
                    "digest — corrupt record file")
            n += 1
    finally:
        reader.close()
    count = sidecar.get("count")
    if count is not None and n != int(count):
        raise IntegrityError(
            f"{path}: {n} records on disk but the sidecar digested "
            f"{count} — truncated or appended-to record file")
    return n


class BinFileReader:
    """KV record-file reader w/ optional background prefetch thread
    (reference src/io/binfile_reader.cc). ``verify=True`` re-checks the
    whole file against its ``<path>.digest`` sidecar (written by
    ``BinFileWriter(digest=True)``) before the first record is handed
    out."""

    def __init__(self, path=None, prefetch=64, verify=False):
        self._r = None
        self._prefetch = prefetch
        self._verify = bool(verify)
        if path is not None:
            self.Open(path)

    def Open(self, path, capacity=None):
        if self._verify:
            verify_record_file(path)
        self._r = native.RecordReader(path, prefetch=self._prefetch)
        return True

    def Read(self):
        """(key, value) bytes or None at end."""
        return self._r.read()

    def Count(self):
        return self._r.count()

    def SeekToFirst(self):
        self._r.seek_to_first()

    def Close(self):
        if self._r:
            self._r.close()
            self._r = None

    read = Read
    count = Count
    seek_to_first = SeekToFirst
    close = Close

    def __iter__(self):
        return iter(self._r)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.Close()


# ---------------------------------------------------------------------------
# text files
# ---------------------------------------------------------------------------

class TextFileWriter:
    """Line-per-record writer (reference src/io/textfile_writer.cc)."""

    def __init__(self, path=None, mode="create"):
        self._f = None
        if path is not None:
            self.Open(path, mode)

    def Open(self, path, mode="create"):
        self._f = open(path, "a" if mode == "append" else "w")
        return True

    def Write(self, key, value):
        if isinstance(value, bytes):
            value = value.decode("utf-8")
        self._f.write(value.rstrip("\n") + "\n")
        return True

    def Flush(self):
        self._f.flush()

    def Close(self):
        if self._f:
            self._f.close()
            self._f = None


class TextFileReader:
    """Line-per-record reader; key is the line number
    (reference src/io/textfile_reader.cc)."""

    def __init__(self, path=None):
        self._f = None
        self._lineno = 0
        if path is not None:
            self.Open(path)

    def Open(self, path, capacity=None):
        self._f = open(path, "r")
        self._lineno = 0
        return True

    def Read(self):
        line = self._f.readline()
        if not line:
            return None
        key = str(self._lineno)
        self._lineno += 1
        return key, line.rstrip("\n")

    def Count(self):
        pos = self._f.tell()
        self._f.seek(0)
        n = sum(1 for _ in self._f)
        self._f.seek(pos)
        return n

    def SeekToFirst(self):
        self._f.seek(0)
        self._lineno = 0

    def Close(self):
        if self._f:
            self._f.close()
            self._f = None


# ---------------------------------------------------------------------------
# LMDB (optional dependency, like the reference's USE_LMDB build flag)
# ---------------------------------------------------------------------------

try:
    import lmdb as _lmdb
    HAS_LMDB = True
except ImportError:
    _lmdb = None
    HAS_LMDB = False


class LMDBWriter:
    """(reference src/io/lmdb_writer.cc; requires the lmdb package)"""

    def __init__(self, path=None, mode="create"):
        if not HAS_LMDB:
            raise ImportError("LMDBWriter requires the 'lmdb' package")
        self._env = None
        if path is not None:
            self.Open(path, mode)

    def Open(self, path, mode="create"):
        self._env = _lmdb.open(path, map_size=1 << 30)
        self._txn = self._env.begin(write=True)
        return True

    def Write(self, key, value):
        key = key.encode() if isinstance(key, str) else key
        value = value.encode() if isinstance(value, str) else value
        # one long-lived write txn; commit happens in Flush/Close (a txn
        # per record would fsync per record)
        self._txn.put(key, value)
        return True

    def Flush(self):
        self._txn.commit()
        self._env.sync()
        self._txn = self._env.begin(write=True)

    def Close(self):
        if self._env:
            self._txn.commit()
            self._env.close()
            self._env = None


class LMDBReader:
    """(reference src/io/lmdb_reader.cc; requires the lmdb package)"""

    def __init__(self, path=None):
        if not HAS_LMDB:
            raise ImportError("LMDBReader requires the 'lmdb' package")
        self._env = None
        self._cursor = None
        if path is not None:
            self.Open(path)

    def Open(self, path, capacity=None):
        self._env = _lmdb.open(path, readonly=True, lock=False)
        self._txn = self._env.begin()
        self._cursor = self._txn.cursor()
        self._cursor.first()
        self._exhausted = not self._cursor.key()
        return True

    def Read(self):
        if self._exhausted:
            return None
        key, value = self._cursor.key(), self._cursor.value()
        if not self._cursor.next():
            self._exhausted = True
        return bytes(key), bytes(value)

    def Count(self):
        return self._env.stat()["entries"]

    def SeekToFirst(self):
        self._cursor.first()
        self._exhausted = not self._cursor.key()

    def Close(self):
        if self._env:
            self._env.close()
            self._env = None


# ---------------------------------------------------------------------------
# codecs
# ---------------------------------------------------------------------------

class CSVEncoder:
    """label,feature,... -> csv line (reference src/io/csv_encoder.cc)."""

    def Encode(self, data, label=None):
        arr = np.asarray(data.numpy() if isinstance(data, Tensor)
                         else data).ravel()
        parts = [] if label is None else [str(int(label))]
        parts += [repr(float(v)) for v in arr]
        return ",".join(parts)


class CSVDecoder:
    """csv line -> (label, features) (reference src/io/csv_decoder.cc)."""

    def __init__(self, has_label=True):
        self.has_label = has_label

    def Decode(self, line):
        if isinstance(line, bytes):
            line = line.decode("utf-8")
        vals = [v for v in line.strip().split(",") if v != ""]
        if self.has_label:
            return int(float(vals[0])), np.asarray(
                [float(v) for v in vals[1:]], np.float32)
        return None, np.asarray([float(v) for v in vals], np.float32)


class JPGEncoder:
    """image array -> jpeg bytes (reference src/io/jpg_encoder.cc;
    PIL replaces OpenCV)."""

    def __init__(self, quality=95):
        self.quality = quality

    def Encode(self, image):
        from PIL import Image
        arr = np.asarray(image)
        if arr.ndim == 3 and arr.shape[0] in (1, 3) and \
                arr.shape[0] < arr.shape[2]:
            arr = np.transpose(arr, (1, 2, 0))  # CHW -> HWC
        arr = np.clip(arr, 0, 255).astype(np.uint8)
        if arr.ndim == 3 and arr.shape[2] == 1:
            arr = arr[:, :, 0]
        buf = _stdio.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG", quality=self.quality)
        return buf.getvalue()


class JPGDecoder:
    """jpeg bytes -> float32 CHW array (reference src/io/jpg_decoder.cc)."""

    def Decode(self, raw):
        from PIL import Image
        img = Image.open(_stdio.BytesIO(raw))
        arr = np.asarray(img, dtype=np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return native.hwc_to_chw(arr)


# ---------------------------------------------------------------------------
# image transformer
# ---------------------------------------------------------------------------

class ImageTransformer:
    """Crop/resize/flip augmentation (reference src/io/image_transformer.cc).

    Operates on float32 images; accepts HWC or CHW via ``image_dim_order``.
    ``Apply(flag, image)``: flag "train" randomises crop offset and flip,
    "eval"/"test" center-crops deterministically, like the reference.
    """

    def __init__(self, resize_height=0, resize_width=0, crop_shape=(),
                 horizontal_mirror=False, image_dim_order="CHW",
                 rescale=0.0):
        self.resize_height = resize_height
        self.resize_width = resize_width
        self.crop_shape = tuple(crop_shape)
        self.horizontal_mirror = horizontal_mirror
        self.image_dim_order = image_dim_order
        self.rescale = rescale
        self._rng = np.random.RandomState()

    def Apply(self, flag, image):
        arr = np.asarray(image, np.float32)
        if self.image_dim_order == "CHW":
            arr = native.chw_to_hwc(arr)
        if self.resize_height and self.resize_width:
            arr = native.resize_bilinear(arr, self.resize_height,
                                         self.resize_width)
        if self.crop_shape:
            ch, cw = self.crop_shape
            h, w = arr.shape[:2]
            if flag in ("train", 1, "kTrain"):
                top = self._rng.randint(0, max(1, h - ch + 1))
                left = self._rng.randint(0, max(1, w - cw + 1))
            else:
                top, left = (h - ch) // 2, (w - cw) // 2
            arr = native.crop(arr, top, left, ch, cw)
        if self.horizontal_mirror and flag in ("train", 1, "kTrain") \
                and self._rng.rand() < 0.5:
            arr = native.hflip(arr)
        if self.rescale:
            arr = arr * self.rescale
        if self.image_dim_order == "CHW":
            arr = native.hwc_to_chw(arr)
        return arr

    apply = Apply
