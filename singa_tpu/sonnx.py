"""ONNX import/export for the TPU-native framework.

Capability parity with the reference ONNX bridge (python/singa/sonnx.py):

- :class:`SingaFrontend` — export a taped computation to an ONNX
  ``ModelProto`` (reference SingaFrontend, sonnx.py:75-1035);
- :class:`SingaBackend` / :class:`SingaRep` — import an ONNX model and run
  (or fine-tune) it on our ops (reference SingaBackend.prepare sonnx.py:1911,
  SingaRep.run :1951);
- :class:`SONNXModel` — wrap an imported graph as a trainable
  :class:`~singa_tpu.model.Model` (reference SONNXModel sonnx.py:2196).

TPU-first redesign: the reference converts node-by-node into SWIG handles;
here every imported node lowers to our jax-backed autograd ops, so an
imported graph jits into a single XLA computation exactly like a native
model. Works against the real ``onnx`` package when installed, else the
bundled wire-compatible protos (singa_tpu/onnx_proto).
"""

from __future__ import annotations

import warnings
from collections import OrderedDict, deque

import numpy as np

from . import autograd
from .autograd_base import CTX, Dummy, Operator
from .tensor import Tensor
from . import device as device_mod
from .onnx_compat import (TensorProto, helper, numpy_helper, load, save,
                          attribute_dict)
from .ops.conv import (ConvHandle, conv2d, ConvTransposeHandle,
                       conv_transpose2d)
from .ops.pooling import PoolingHandle, pooling_2d, globalaveragepool
from .ops.batchnorm import BatchNormHandle, batchnorm_2d


def _sanitize(name):
    return name.replace("#", "_").replace(":", "_")


_DTYPE_TO_ONNX = {
    "float32": TensorProto.FLOAT, "float64": TensorProto.DOUBLE,
    "float16": TensorProto.FLOAT16, "bfloat16": TensorProto.BFLOAT16,
    "int32": TensorProto.INT32, "int64": TensorProto.INT64,
    "int8": TensorProto.INT8, "uint8": TensorProto.UINT8,
    "bool": TensorProto.BOOL,
}


def _onnx_dtype(t):
    return _DTYPE_TO_ONNX.get(str(np.dtype(t.dtype)), TensorProto.FLOAT)


# ===========================================================================
# Frontend: tape -> ONNX
# ===========================================================================

class SingaFrontend:
    """Exports a taped forward computation to ONNX (reference sonnx.py:75).

    Usage::

        x.requires_grad = True      # record input edges on the tape
        autograd.training = True
        y = model.forward(x)
        onnx_model = SingaFrontend.singa_to_onnx_model([x], [y], "net")
    """

    _target_opset_version = 11

    # our Operator class name -> onnx op_type
    _rename_operators = {
        "_Conv2d": "Conv",
        "ReLU": "Relu",
        "_Pooling2d": None,  # resolved to MaxPool/AveragePool per handle
        "SoftMax": "Softmax",
        "Sigmoid": "Sigmoid",
        "Add": "Add",
        "Matmul": "MatMul",
        "_BatchNorm2d": "BatchNormalization",
        "_BatchNorm2dInference": "BatchNormalization",
        "Concat": "Concat",
        "Flatten": "Flatten",
        "AddBias": "Add",
        "Gemm": "Gemm",
        "Reshape": "Reshape",
        "Sum": "Sum",
        "Cos": "Cos", "Cosh": "Cosh", "Sin": "Sin", "Sinh": "Sinh",
        "Tan": "Tan", "Tanh": "Tanh", "Acos": "Acos", "Acosh": "Acosh",
        "Asin": "Asin", "Asinh": "Asinh", "Atan": "Atan", "Atanh": "Atanh",
        "SeLU": "Selu", "Elu": "Elu", "Equal": "Equal", "Less": "Less",
        "Sign": "Sign", "Div": "Div", "Sub": "Sub", "Sqrt": "Sqrt",
        "Log": "Log", "Greater": "Greater", "HardSigmoid": "HardSigmoid",
        "Identity": "Identity", "SoftPlus": "Softplus",
        "SoftSign": "Softsign", "Mean": "Mean", "Pow": "Pow",
        "Clip": "Clip", "PRelu": "PRelu", "Mul": "Mul",
        "Transpose": "Transpose", "Max": "Max", "Min": "Min",
        "Shape": "Shape", "And": "And", "Or": "Or", "Xor": "Xor",
        "Not": "Not", "Negative": "Neg", "Reciprocal": "Reciprocal",
        "ConstantOfShape": "ConstantOfShape", "Dropout": "Dropout",
        "ReduceSum": "ReduceSum", "ReduceMean": "ReduceMean",
        "ReduceMax": "ReduceMax", "ReduceProd": "ReduceProd",
        "LeakyRelu": "LeakyRelu", "GlobalAveragePool": "GlobalAveragePool",
        "Squeeze": "Squeeze", "Unsqueeze": "Unsqueeze", "Slice": "Slice",
        "Ceil": "Ceil", "Floor": "Floor", "Abs": "Abs", "Split": "Split",
        "Gather": "Gather", "Tile": "Tile", "NonZero": "NonZero",
        "Cast": "Cast", "OneHot": "OneHot", "Erf": "Erf",
        "Where": "Where", "Expand": "Expand", "Pad": "Pad",
        "UpSample": "Upsample", "DepthToSpace": "DepthToSpace",
        "SpaceToDepth": "SpaceToDepth", "Embedding": "Gather",
        "ScatterElements": "ScatterElements",
        # mesh-collective ops are identity in a single-program export
        # (their collectives only act inside an active shard_map region)
        "CopyToParallel": "Identity", "AllReduce": "Identity",
        "PMean": "Identity",
    }

    @classmethod
    def _topo_ops(cls, ys):
        """Reverse tape -> topological op order (inputs first)."""
        visited = set()
        order = []

        for y in ys:
            stack = [(y.creator, False)]
            while stack:
                op, expanded = stack.pop()
                if op is None:
                    continue
                if expanded:
                    order.append(op)
                    continue
                if id(op) in visited:
                    continue
                visited.add(id(op))
                stack.append((op, True))
                for (src_op, _xid, _t, _req) in op.src:
                    if src_op is not None and id(src_op) not in visited:
                        stack.append((src_op, False))
        return order

    @classmethod
    def _node_attrs_and_extra(cls, op, op_name, input_names, extras):
        """(op_type, attrs dict); may append extra initializer inputs."""
        ty = type(op).__name__
        attrs = {}

        def extra_int64(suffix, values):
            nm = f"{op_name}_{suffix}"
            extras.append(numpy_helper.from_array(
                np.asarray(values, np.int64), nm))
            input_names.append(nm)

        if ty == "_Conv2d":
            h = op.handle
            if getattr(h, "layout", "NCHW") != "NCHW":
                # ONNX Conv is NCHW-only; exporting NHWC activations as
                # a Conv node would be silently wrong. Checkpoints are
                # layout-independent, so the fix is a rebuild.
                raise NotImplementedError(
                    "ONNX export of an NHWC-mode conv is not supported "
                    "— rebuild the model with layout='NCHW' (weights/"
                    "checkpoints are identical across layouts) and "
                    "export that")
            (p0, p1), (q0, q1) = h.padding
            attrs = {"kernel_shape": list(h.kernel_size),
                     "strides": list(h.stride),
                     "dilations": list(h.dilation),
                     "group": h.group,
                     "pads": [p0, q0, p1, q1]}
            return "Conv", attrs
        if ty == "_ConvTranspose2d":
            h = op.handle
            (p0, p1), (q0, q1) = h.padding
            attrs = {"kernel_shape": list(h.kernel_size),
                     "strides": list(h.stride),
                     "dilations": list(h.dilation),
                     "group": h.group,
                     "pads": [p0, q0, p1, q1],
                     "output_padding": list(h.output_padding)}
            return "ConvTranspose", attrs
        if ty == "_Pooling2d":
            h = op.handle
            (p0, p1), (q0, q1) = h.pad_pairs
            attrs = {"kernel_shape": list(h.kernel_size),
                     "strides": list(h.stride),
                     "pads": [p0, q0, p1, q1]}
            if h.is_max_pooling:
                return "MaxPool", attrs
            # mirror the handle's divisor mode, not a hardcoded 1 —
            # exclude-pad pools must survive the round-trip
            attrs["count_include_pad"] = int(
                getattr(h, "count_include_pad", True))
            return "AveragePool", attrs
        if ty in ("_BatchNorm2d", "_BatchNorm2dInference"):
            h = op.handle
            return "BatchNormalization", {"epsilon": float(h.eps),
                                          "momentum": float(h.factor)}
        if ty == "Gemm":
            return "Gemm", {"alpha": float(op.alpha), "beta": float(op.beta),
                            "transA": int(op.transA),
                            "transB": int(op.transB)}
        if ty == "SoftMax":
            return "Softmax", {"axis": op.axis}
        if ty == "Concat":
            return "Concat", {"axis": op.axis}
        if ty == "Flatten":
            return "Flatten", {"axis": op.axis}
        if ty == "Reshape":
            extra_int64("shape", op.shape)
            return "Reshape", {}
        if ty == "Transpose":
            return "Transpose", {"perm": list(op.perm)} if op.perm else {}
        if ty == "Squeeze":
            ax = op.axis
            if ax is None:
                return "Squeeze", {}
            return "Squeeze", {"axes": list(ax) if isinstance(
                ax, (tuple, list)) else [ax]}
        if ty == "Unsqueeze":
            return "Unsqueeze", {"axes": list(op.axis)}
        if ty == "Slice":
            extra_int64("starts", op.starts)
            extra_int64("ends", op.ends)
            if op.axes is not None:
                extra_int64("axes", op.axes)
            if op.steps is not None:
                if op.axes is None:
                    extra_int64("axes", list(range(len(op.starts))))
                extra_int64("steps", op.steps)
            return "Slice", {}
        if ty == "Clip":
            for suffix, v in (("min", op.min), ("max", op.max)):
                if v is not None:
                    nm = f"{op_name}_{suffix}"
                    extras.append(numpy_helper.from_array(
                        np.asarray(v, np.float32), nm))
                    input_names.append(nm)
                else:
                    input_names.append("")
            return "Clip", {}
        if ty in ("ReduceSum", "ReduceMean", "ReduceMax", "ReduceProd"):
            attrs = {"keepdims": int(op.keepdims)}
            if op.axes is not None:
                attrs["axes"] = list(op.axes)
            return ty, attrs
        if ty == "LeakyRelu":
            return "LeakyRelu", {"alpha": float(op.a)}
        if ty == "Elu":
            return "Elu", {"alpha": float(op.alpha)}
        if ty == "SeLU":
            return "Selu", {"alpha": float(op.alpha),
                            "gamma": float(op.gamma)}
        if ty == "HardSigmoid":
            return "HardSigmoid", {"alpha": float(op.alpha),
                                   "beta": float(op.gamma)}
        if ty == "Dropout":
            return "Dropout", {"ratio": float(op.ratio)}
        if ty == "Split":
            attrs = {"axis": op.axis}
            if op.parts is not None:
                attrs["split"] = list(op.parts)
            return "Split", attrs
        if ty == "Gather":
            return "Gather", {"axis": op.axis}
        if ty in ("Embedding", "_MaskedLookup"):
            # our Embedding(x_ids, W) == onnx Gather(W, ids) on axis 0.
            # _MaskedLookup (VocabParallelEmbedding's local lookup) is
            # exported from host/eager tapes where W is full-width, so
            # for in-range ids it IS a plain embedding. Out-of-range ids
            # diverge at the edges: Embedding clips, _MaskedLookup
            # returns zeros, ONNX Gather wraps negatives — exported
            # models are exact only for ids in [0, V), the universal
            # embedding contract.
            input_names.reverse()
            return "Gather", {"axis": 0}
        if ty == "Tile":
            extra_int64("repeats", op.repeats)
            return "Tile", {}
        if ty == "Expand":
            extra_int64("shape", op.shape)
            return "Expand", {}
        if ty == "Pad":
            extra_int64("pads", op.pads)
            if op.mode == "constant":
                nm = f"{op_name}_value"
                extras.append(numpy_helper.from_array(
                    np.asarray(op.constant, np.float32), nm))
                input_names.append(nm)
            return "Pad", {"mode": op.mode}
        if ty == "UpSample":
            nm = f"{op_name}_scales"
            extras.append(numpy_helper.from_array(
                np.asarray(op.scales, np.float32), nm))
            input_names.append(nm)
            return "Upsample", {"mode": "nearest"}
        if ty == "ConstantOfShape":
            attrs["value"] = numpy_helper.from_array(
                np.asarray([op.value], np.float32), "value")
            return "ConstantOfShape", attrs
        if ty == "Cast":
            return "Cast", {
                "to": int(helper.np_dtype_to_tensor_dtype(np.dtype(op.to)))}
        if ty == "OneHot":
            extra_int64("depth", op.depth)
            nm = f"{op_name}_values"
            extras.append(numpy_helper.from_array(
                np.asarray(op.values, np.float32), nm))
            input_names.append(nm)
            return "OneHot", {"axis": op.axis}
        if ty in ("DepthToSpace", "SpaceToDepth"):
            attrs = {"blocksize": op.b}
            if ty == "DepthToSpace":
                attrs["mode"] = op.mode
            return ty, attrs
        if ty == "ScatterElements":
            return "ScatterElements", {"axis": op.axis}
        if ty == "LRN":
            # ONNX LRN uses the same alpha/size pre-division as ours
            return "LRN", {"size": op.size, "alpha": float(op.alpha),
                           "beta": float(op.beta), "bias": float(op.k)}
        onnx_ty = cls._rename_operators.get(ty)
        if onnx_ty is None:
            raise NotImplementedError(
                f"cannot export op {ty} to ONNX")
        return onnx_ty, attrs

    # our gate-block order -> onnx gate-block order
    _rnn_perm_to_onnx = {"lstm": [0, 3, 1, 2],   # ifgo -> iofc
                         "gru": [1, 0, 2]}        # rzn  -> zrh

    @classmethod
    def _export_rnn(cls, op, op_name, in_names, out_names, nodes,
                    initializers):
        """Emit one ONNX RNN/LSTM/GRU node per layer for a (possibly
        multi-layer, bidirectional) `_RNN` op, slicing its flat packed
        weight vector into the per-layer W/R/B initializers the ONNX spec
        expects (reference frontend RNN export, python/singa/sonnx.py)."""
        h = op.handle
        Wt = op.src[3][2]
        if Wt is None:
            raise ValueError(
                f"RNN {op_name}: flat weights must be a parameter or "
                "constant to export")
        flat = np.asarray(Wt.numpy()).ravel()
        G, H, D, L = h.gates, h.hidden_size, h.num_directions, h.num_layers
        perm = cls._rnn_perm_to_onnx.get(h.mode, [0])
        node_ty = {"lstm": "LSTM", "gru": "GRU"}.get(h.mode, "RNN")

        def reorder(mat):
            return np.concatenate([mat[g * H:(g + 1) * H] for g in perm], 0)

        seq_name = ""
        if op.use_mask and len(in_names) > 4:
            seq_name = f"{op_name}_seq_i32"
            nodes.append(helper.make_node(
                "Cast", [in_names[4]], [seq_name],
                name=f"{op_name}_seqcast", to=int(TensorProto.INT32)))

        def state_slice(src, l, which):
            """initial_h/c rows for layer l: src[(l*D):(l+1)*D]."""
            if L == 1:
                return src
            nm = f"{op_name}_l{l}_{which}"
            for suffix, vals in (("starts", [l * D]), ("ends", [(l + 1) * D]),
                                 ("axes", [0])):
                initializers.append(numpy_helper.from_array(
                    np.asarray(vals, np.int64), f"{nm}_{suffix}"))
            nodes.append(helper.make_node(
                "Slice", [src, f"{nm}_starts", f"{nm}_ends", f"{nm}_axes"],
                [nm], name=nm))
            return nm

        x_name = in_names[0]
        yh_names, yc_names = [], []
        for l in range(L):
            Ws, Rs, bihs, bhhs = [], [], [], []
            for d in range(D):
                sl = h.offsets[l][d]
                parts = [flat[a:b].reshape(s) for a, b, s in sl]
                Ws.append(reorder(parts[0]))
                Rs.append(reorder(parts[1]))
                bihs.append(reorder(parts[2][:, None])[:, 0])
                bhhs.append(reorder(parts[3][:, None])[:, 0])
            prefix = f"{op_name}_l{l}"
            for nm, arr in ((f"{prefix}_W", np.stack(Ws)),
                            (f"{prefix}_R", np.stack(Rs)),
                            (f"{prefix}_B", np.stack(
                                [np.concatenate([bi, bh]) for bi, bh
                                 in zip(bihs, bhhs)]))):
                initializers.append(
                    numpy_helper.from_array(arr.astype(np.float32), nm))

            attrs = {"hidden_size": H,
                     "direction": "bidirectional" if D == 2 else "forward"}
            if node_ty == "GRU":
                attrs["linear_before_reset"] = \
                    int(h.gru_linear_before_reset)
            if node_ty == "RNN":
                attrs["activations"] = \
                    ["Relu" if h.mode == "relu" else "Tanh"] * D
            node_ins = [x_name, f"{prefix}_W", f"{prefix}_R",
                        f"{prefix}_B", seq_name,
                        state_slice(in_names[1], l, "h0")]
            node_outs = [f"{prefix}_Y", f"{prefix}_Yh"]
            if node_ty == "LSTM":
                node_ins.append(state_slice(in_names[2], l, "c0"))
                node_outs.append(f"{prefix}_Yc")
                yc_names.append(f"{prefix}_Yc")
            yh_names.append(f"{prefix}_Yh")
            nodes.append(helper.make_node(node_ty, node_ins, node_outs,
                                          name=f"{prefix}_{node_ty}",
                                          **attrs))
            # (T, D, B, H) -> (T, B, D*H) for the next layer / final y
            is_last = (l == L - 1)
            tr = f"{prefix}_Ytr"
            nodes.append(helper.make_node(
                "Transpose", [f"{prefix}_Y"], [tr], name=tr,
                perm=[0, 2, 1, 3]))
            flat_nm = out_names[0] if is_last else f"{prefix}_Yflat"
            shape_nm = f"{prefix}_yshape"
            initializers.append(numpy_helper.from_array(
                np.asarray([0, 0, D * H], np.int64), shape_nm))
            nodes.append(helper.make_node(
                "Reshape", [tr, shape_nm], [flat_nm],
                name=f"{prefix}_reshape"))
            x_name = flat_nm

        # hy / cy tape outputs: stack of per-layer final states
        if L == 1:
            # rename by aliasing: emit Identity to the tape output names
            nodes.append(helper.make_node(
                "Identity", [yh_names[0]], [out_names[1]],
                name=f"{op_name}_hy"))
        else:
            nodes.append(helper.make_node(
                "Concat", yh_names, [out_names[1]],
                name=f"{op_name}_hy", axis=0))
        if node_ty == "LSTM":
            if L == 1:
                nodes.append(helper.make_node(
                    "Identity", [yc_names[0]], [out_names[2]],
                    name=f"{op_name}_cy"))
            else:
                nodes.append(helper.make_node(
                    "Concat", yc_names, [out_names[2]],
                    name=f"{op_name}_cy", axis=0))
        else:
            # non-LSTM modes carry c through unchanged: cy == cx
            nodes.append(helper.make_node(
                "Identity", [in_names[2]], [out_names[2]],
                name=f"{op_name}_cy"))

    @classmethod
    def _export_layernorm(cls, op, op_name, in_names, out_names, nodes,
                          initializers):
        """Decompose `_LayerNorm` into primitive ONNX nodes (opset 11 has
        no LayerNormalization): (x-mean)/sqrt(var+eps)*scale+bias."""
        x, scale, bias = in_names[:3]
        eps_nm = f"{op_name}_eps"
        initializers.append(numpy_helper.from_array(
            np.asarray(op.eps, np.float32), eps_nm))

        def n(op_ty, ins, out, **attrs):
            nodes.append(helper.make_node(op_ty, ins, [out], name=out,
                                          **attrs))
            return out

        mean = n("ReduceMean", [x], f"{op_name}_mean", axes=[-1],
                 keepdims=1)
        cen = n("Sub", [x, mean], f"{op_name}_cen")
        sq = n("Mul", [cen, cen], f"{op_name}_sq")
        var = n("ReduceMean", [sq], f"{op_name}_var", axes=[-1], keepdims=1)
        veps = n("Add", [var, eps_nm], f"{op_name}_veps")
        std = n("Sqrt", [veps], f"{op_name}_std")
        norm = n("Div", [cen, std], f"{op_name}_norm")
        scaled = n("Mul", [norm, scale], f"{op_name}_scaled")
        n("Add", [scaled, bias], out_names[0])

    @classmethod
    def _export_attention(cls, op, op_name, in_names, out_names, nodes,
                          initializers):
        """Decompose fused attention into ONNX matmul/softmax nodes:
        softmax(q·kᵀ·scale [+ causal mask])·v. The fused kernel is a
        runtime optimisation; on the wire the semantics are primitive."""
        q_nm, k_nm, v_nm = in_names[:3]
        q = op._export_refs[0]
        S = int(q.shape[-2])
        scale = op.scale if op.scale is not None \
            else 1.0 / float(np.sqrt(q.shape[-1]))

        def n(op_ty, ins, out, **attrs):
            nodes.append(helper.make_node(op_ty, ins, [out], name=out,
                                          **attrs))
            return out

        scale_nm = f"{op_name}_scale"
        initializers.append(numpy_helper.from_array(
            np.asarray(scale, np.float32), scale_nm))
        kt = n("Transpose", [k_nm], f"{op_name}_kT", perm=[0, 1, 3, 2])
        logits = n("MatMul", [q_nm, kt], f"{op_name}_qk")
        scaled = n("Mul", [logits, scale_nm], f"{op_name}_qks")
        if op.causal:
            mask = np.triu(np.full((S, S), -1e9, np.float32), k=1)
            mask_nm = f"{op_name}_mask"
            initializers.append(numpy_helper.from_array(mask, mask_nm))
            scaled = n("Add", [scaled, mask_nm], f"{op_name}_masked")
        probs = n("Softmax", [scaled], f"{op_name}_p", axis=3)
        n("MatMul", [probs, v_nm], out_names[0])

    @classmethod
    def _export_cossim(cls, op, op_name, in_names, out_names, nodes,
                       initializers):
        """Decompose CosSim into primitive ONNX nodes (no CosineSimilarity
        op exists in ONNX): sum(a*b,-1) / (|a|*|b| + eps)."""
        a_nm, b_nm = in_names[:2]
        eps_nm = f"{op_name}_eps"
        initializers.append(numpy_helper.from_array(
            np.asarray(1e-12, np.float32), eps_nm))

        def n(op_ty, ins, out, **attrs):
            nodes.append(helper.make_node(op_ty, ins, [out], name=out,
                                          **attrs))
            return out

        ab = n("Mul", [a_nm, b_nm], f"{op_name}_ab")
        num = n("ReduceSum", [ab], f"{op_name}_num", axes=[-1],
                keepdims=0)
        aa = n("Mul", [a_nm, a_nm], f"{op_name}_aa")
        bb = n("Mul", [b_nm, b_nm], f"{op_name}_bb")
        na = n("Sqrt", [n("ReduceSum", [aa], f"{op_name}_sa", axes=[-1],
                          keepdims=0)], f"{op_name}_na")
        nb = n("Sqrt", [n("ReduceSum", [bb], f"{op_name}_sb", axes=[-1],
                          keepdims=0)], f"{op_name}_nb")
        den = n("Add", [n("Mul", [na, nb], f"{op_name}_nanb"), eps_nm],
                f"{op_name}_den")
        n("Div", [num, den], out_names[0])

    @classmethod
    def _export_gelu(cls, op, op_name, in_names, out_names, nodes,
                     initializers):
        """Decompose GELU (tanh approximation, matching jax.nn.gelu's
        default) into primitive nodes — opset 11 has no Gelu:
        0.5*x*(1 + tanh(sqrt(2/pi)*(x + 0.044715*x^3)))."""
        x = in_names[0]

        def const(suffix, v):
            nm = f"{op_name}_{suffix}"
            initializers.append(numpy_helper.from_array(
                np.asarray(v, np.float32), nm))
            return nm

        def n(op_ty, ins, out):
            nodes.append(helper.make_node(op_ty, ins, [out], name=out))
            return out

        x2 = n("Mul", [x, x], f"{op_name}_x2")
        x3 = n("Mul", [x2, x], f"{op_name}_x3")
        cx3 = n("Mul", [const("c2", 0.044715), x3], f"{op_name}_cx3")
        inner = n("Mul", [const("c1", float(np.sqrt(2.0 / np.pi))),
                          n("Add", [x, cx3], f"{op_name}_xpc")],
                  f"{op_name}_inner")
        t = n("Tanh", [inner], f"{op_name}_t")
        onept = n("Add", [const("one", 1.0), t], f"{op_name}_1pt")
        halfx = n("Mul", [const("half", 0.5), x], f"{op_name}_hx")
        n("Mul", [halfx, onept], out_names[0])

    @classmethod
    def singa_to_onnx_graph(cls, inputs, y, model_name="sonnx"):
        ys = y if isinstance(y, (list, tuple)) else [y]
        ops = cls._topo_ops(ys)

        input_ids = {id(t): i for i, t in enumerate(inputs)}
        names = {}          # tensor-id -> value name
        initializers = []
        graph_inputs = []
        nodes = []

        # Dummy leaves: user inputs, params (stores_grad), or constants
        for op in ops:
            if not isinstance(op, Dummy):
                continue
            t = op.tensor
            if id(t) in input_ids:
                nm = t.name or f"input_{input_ids[id(t)]}"
                names[id(t)] = nm
            else:
                nm = _sanitize(t.name or f"const_{len(initializers)}")
                names[id(t)] = nm
                initializers.append(numpy_helper.from_array(
                    np.asarray(t.numpy()), nm))
        # ALL caller inputs, in the caller's order (run() binds
        # positionally; unused inputs stay declared so positions hold)
        for i, t in enumerate(inputs):
            if id(t) not in names:
                names[id(t)] = t.name or f"input_{i}"
            graph_inputs.append(helper.make_tensor_value_info(
                names[id(t)], _onnx_dtype(t), list(t.shape)))

        # BN running stats are referenced by the node but live off-tape
        def bn_state_name(op, which):
            t = getattr(op, which)
            if id(t) not in names:
                nm = _sanitize(t.name or f"{_sanitize(op.name)}_{which}")
                names[id(t)] = nm
                initializers.append(numpy_helper.from_array(
                    np.asarray(t.numpy()), nm))
            return names[id(t)]

        for op in ops:
            if isinstance(op, Dummy):
                continue
            op_name = _sanitize(op.name)
            in_names = []
            for (src_op, x_id, t_ref, _req) in op.src:
                if x_id not in names:
                    if src_op is None and t_ref is not None:
                        # constant consumed by the op: emit an initializer
                        nm = _sanitize(t_ref.name or
                                       f"const_{len(initializers)}")
                        names[x_id] = nm
                        initializers.append(numpy_helper.from_array(
                            np.asarray(t_ref.numpy()), nm))
                    else:
                        raise ValueError(
                            f"op {op.name}: input tensor not on the tape — "
                            "mark graph inputs requires_grad=True before "
                            "export")
                in_names.append(names[x_id])
            out_names = []
            for pos, yid in enumerate(op.y_ids):
                nm = f"{op_name}_out{pos}" if len(op.y_ids) > 1 \
                    else op_name
                names[yid] = nm
                out_names.append(nm)

            ty = type(op).__name__
            if ty in ("_BatchNorm2d", "_BatchNorm2dInference"):
                # onnx BatchNormalization: X, scale, B, mean, var
                in_names = in_names[:3] + [bn_state_name(op, "running_mean"),
                                           bn_state_name(op, "running_var")]
            if ty in ("Embedding", "_MaskedLookup"):
                # ONNX Gather requires int32/int64 indices; our ids tensor
                # is float-typed on the tape, so cast it in-graph
                cast_nm = f"{op_name}_ids_i64"
                nodes.append(helper.make_node(
                    "Cast", [in_names[0]], [cast_nm],
                    name=f"{op_name}_cast", to=int(TensorProto.INT64)))
                in_names[0] = cast_nm
            if ty == "_RNN":
                cls._export_rnn(op, op_name, in_names, out_names, nodes,
                                initializers)
                continue
            if ty == "_LayerNorm":
                cls._export_layernorm(op, op_name, in_names, out_names,
                                      nodes, initializers)
                continue
            if ty == "_FlashAttention":
                cls._export_attention(op, op_name, in_names, out_names,
                                      nodes, initializers)
                continue
            if ty == "GELU":
                cls._export_gelu(op, op_name, in_names, out_names,
                                 nodes, initializers)
                continue
            if ty == "CosSim":
                cls._export_cossim(op, op_name, in_names, out_names,
                                   nodes, initializers)
                continue
            if ty == "SoftMax":
                refs = getattr(op, "_export_refs", None)
                nd = len(refs[0].shape) if refs else 2
                ax = op.axis + nd if op.axis < 0 else op.axis
                if nd > 2 and ax < nd - 1:
                    # our softmax is per-axis; opset-11 Softmax coerces
                    # to 2D at `axis`, so an INNER axis must be exported
                    # as transpose -> last-axis softmax -> transpose
                    # (semantics-preserving at any opset)
                    perm = [i for i in range(nd) if i != ax] + [ax]
                    inv = [perm.index(i) for i in range(nd)]
                    tnm = f"{op_name}_t"
                    nodes.append(helper.make_node(
                        "Transpose", [in_names[0]], [tnm], name=tnm,
                        perm=perm))
                    snm = f"{op_name}_sm"
                    nodes.append(helper.make_node(
                        "Softmax", [tnm], [snm], name=snm, axis=nd - 1))
                    nodes.append(helper.make_node(
                        "Transpose", [snm], out_names, name=op_name,
                        perm=inv))
                    continue
            onnx_ty, attrs = cls._node_attrs_and_extra(
                op, op_name, in_names, initializers)
            nodes.append(helper.make_node(onnx_ty, in_names, out_names,
                                          name=op_name, **attrs))

        graph_outputs = []
        for i, yy in enumerate(ys):
            graph_outputs.append(helper.make_tensor_value_info(
                names[id(yy)], _onnx_dtype(yy), list(yy.shape)))

        # drop unreferenced initializers: multi-node decompositions
        # (e.g. _export_rnn's per-layer W/R/B) replace the raw leaf
        # tensors, which would otherwise ship as dead payload
        used = {o.name for o in graph_outputs}
        for n in nodes:
            used.update(n.input)
        initializers = [i for i in initializers if i.name in used]

        return helper.make_graph(nodes, model_name, graph_inputs,
                                 graph_outputs, initializer=initializers)

    @classmethod
    def singa_to_onnx_model(cls, inputs, y, model_name="sonnx"):
        graph = cls.singa_to_onnx_graph(inputs, y, model_name)
        return helper.make_model(
            graph, producer_name="singa_tpu",
            opset_imports=[helper.make_operatorsetid(
                "", cls._target_opset_version)]
            if hasattr(helper, "make_operatorsetid") else None)


def to_onnx(model, inputs, model_name="sonnx"):
    """Trace ``model.forward(*inputs)`` and export it
    (reference sonnx.to_onnx, sonnx.py:2227)."""
    tape_inputs = []
    for i, t in enumerate(inputs):
        ti = Tensor(data=t.data if isinstance(t, Tensor) else np.asarray(t),
                    device=getattr(t, "device", None), requires_grad=True,
                    stores_grad=False)
        ti.name = t.name if isinstance(t, Tensor) and t.name else f"input_{i}"
        tape_inputs.append(ti)
    # after mesh-sharded training the live params span the mesh while the
    # tape inputs are single-device — gather them first (same path eval's
    # eager fallback uses), or the eager tape walk below device-mismatches
    if hasattr(model, "_unshard_state"):
        model._unshard_state()
    # record the tape with INFERENCE semantics: BN reads (and must not
    # mutate) running stats, dropout is identity — the exported graph
    # reproduces model.eval() behaviour
    prev_t, prev_r = CTX.training, CTX.recording
    CTX.training, CTX.recording = False, True
    try:
        y = model.forward(*tape_inputs)
    finally:
        CTX.training, CTX.recording = prev_t, prev_r
    if hasattr(model, "get_states"):
        # stable initializer names (params are anonymous until compile())
        for name, st in model.get_states().items():
            st.name = st.name or name
    return SingaFrontend.singa_to_onnx_model(tape_inputs, y, model_name)


# ===========================================================================
# Backend: ONNX -> our ops
# ===========================================================================

class OnnxNode:
    """Light view of a NodeProto (reference sonnx.OnnxNode)."""

    def __init__(self, node, opset=None):
        self.node = node
        self.name = _sanitize(node.name) or _sanitize("_".join(node.output))
        self.op_type = node.op_type
        self.inputs = list(node.input)
        self.outputs = list(node.output)
        self.attrs = attribute_dict(node)
        # default-domain opset of the containing model: ops whose
        # SEMANTICS changed across opsets (Softmax's coerce-to-2D vs
        # per-axis) dispatch on it
        self.opset = opset
        self.cache = {}  # shape-specialised handles, filled on first run


def _arr(t: Tensor):
    return np.asarray(t.numpy())


def _ints(t: Tensor):
    return [int(v) for v in np.asarray(t.numpy()).ravel()]


class SingaBackend:
    """ONNX graph -> executable ops (reference SingaBackend sonnx.py:1037).

    Each handler is ``(node, tensors) -> output Tensor(s)``; ``tensors``
    maps value names to Tensors (initializers included). Handles for
    shape-specialised ops (Conv/Pool/BN) are cached per node on first run.
    """

    _opset_version = 11
    _ir_version = 8

    # onnx op_type -> our functional op (simple 1:1 cases)
    _direct = {
        "Relu": autograd.relu, "Sigmoid": autograd.sigmoid,
        "Add": autograd.add, "MatMul": autograd.matmul,
        "Cos": autograd.cos, "Cosh": autograd.cosh, "Sin": autograd.sin,
        "Sinh": autograd.sinh, "Tan": autograd.tan, "Tanh": autograd.tanh,
        "Acos": autograd.acos, "Acosh": autograd.acosh,
        "Asin": autograd.asin, "Asinh": autograd.asinh,
        "Atan": autograd.atan, "Atanh": autograd.atanh,
        "Equal": autograd.equal, "Less": autograd.less,
        "Sign": autograd.sign, "Div": autograd.div, "Sub": autograd.sub,
        "Sqrt": autograd.sqrt, "Log": autograd.log,
        "Greater": autograd.greater, "Identity": autograd.identity,
        "Softplus": autograd.softplus, "Softsign": autograd.softsign,
        "Mean": autograd.mean, "Pow": autograd.pow,
        "PRelu": autograd.prelu, "Mul": autograd.mul,
        "Max": autograd.max, "Min": autograd.min,
        "Shape": autograd.shape, "And": autograd._and,
        "Or": autograd._or, "Xor": autograd._xor, "Not": autograd._not,
        "Neg": autograd.negative, "Reciprocal": autograd.reciprocal,
        "Exp": autograd.exp,
        "Sum": autograd.sum, "NonZero": autograd.nonzero,
        "Ceil": autograd.ceil, "Floor": autograd.floor,
        "Abs": autograd.abs, "Erf": autograd.erf, "Where": autograd.where,
    }

    @classmethod
    def _handle(cls, node: OnnxNode, ins, tensors):
        ty = node.op_type
        a = node.attrs
        if ty in cls._direct:
            return cls._direct[ty](*ins)
        if ty == "Conv":
            handle = node.cache.get("handle")
            if handle is None:
                ks = a["kernel_shape"]
                pads = a.get("pads", [0] * 4)
                handle = ConvHandle(
                    ins[0], tuple(ks),
                    tuple(a.get("strides", [1] * len(ks))),
                    ((pads[0], pads[2]), (pads[1], pads[3])),
                    in_channels=ins[0].shape[1],
                    out_channels=ins[1].shape[0],
                    bias=len(ins) > 2, group=a.get("group", 1),
                    dilation=tuple(a.get("dilations", [1] * len(ks))),
                    layout="NCHW")
                node.cache["handle"] = handle
            return conv2d(handle, ins[0], ins[1],
                          ins[2] if len(ins) > 2 else None)
        if ty == "ConvTranspose":
            handle = node.cache.get("handle")
            if handle is None:
                ks = a["kernel_shape"]
                pads = list(a.get("pads", [0] * 4))
                group = a.get("group", 1)
                strides = tuple(a.get("strides", [1] * len(ks)))
                dil = tuple(a.get("dilations", [1] * len(ks)))
                opad = list(a.get("output_padding", [0] * len(ks)))
                if "output_shape" in a:
                    # spec: total_padding[i] = stride[i]*(in[i]-1)
                    #   + output_padding[i] + ((k[i]-1)*dilation[i]+1)
                    #   - output_shape[i]. Split: SAME_UPPER puts the
                    #   smaller half first; the default (NOTSET) puts
                    #   the LARGER half first (begin = total - total//2)
                    upper = a.get("auto_pad", "NOTSET") == "SAME_UPPER"
                    pads = []
                    for i, want in enumerate(a["output_shape"]):
                        total = (strides[i] * (ins[0].shape[2 + i] - 1)
                                 + opad[i] + ((ks[i] - 1) * dil[i] + 1)
                                 - int(want))
                        small, big = total // 2, total - total // 2
                        pads.append(small if upper else big)   # begin
                        pads.append(big if upper else small)   # end
                    pads = [pads[0], pads[2], pads[1], pads[3]]
                handle = ConvTransposeHandle(
                    ins[0], tuple(ks), strides,
                    ((pads[0], pads[2]), (pads[1], pads[3])),
                    in_channels=ins[0].shape[1],
                    out_channels=ins[1].shape[1] * group,
                    bias=len(ins) > 2, group=group,
                    dilation=dil,
                    output_padding=tuple(opad),
                    layout="NCHW")
                node.cache["handle"] = handle
            return conv_transpose2d(handle, ins[0], ins[1],
                                    ins[2] if len(ins) > 2 else None)
        if ty in ("MaxPool", "AveragePool"):
            handle = node.cache.get("handle")
            if handle is None:
                ks = a["kernel_shape"]
                pads = a.get("pads", [0] * 4)
                # ONNX spec: absent strides default to 1 per spatial
                # axis (NOT to the kernel shape)
                handle = PoolingHandle(
                    ins[0], tuple(ks),
                    tuple(a.get("strides", [1] * len(ks))),
                    ((pads[0], pads[2]), (pads[1], pads[3])),
                    is_max=(ty == "MaxPool"), layout="NCHW",
                    # ONNX AveragePool defaults to EXCLUDING padding
                    # from the divisor (count_include_pad=0)
                    count_include_pad=bool(
                        a.get("count_include_pad", 0)))
                node.cache["handle"] = handle
            return pooling_2d(handle, ins[0])
        if ty == "GlobalAveragePool":
            return globalaveragepool(ins[0])
        if ty == "BatchNormalization":
            handle = node.cache.get("handle")
            if handle is None:
                handle = BatchNormHandle(a.get("momentum", 0.9), ins[0],
                                         a.get("epsilon", 1e-5),
                                         layout="NCHW")
                node.cache["handle"] = handle
            x, scale, bias, mean, var = ins
            return batchnorm_2d(handle, x, scale, bias, mean, var)
        if ty == "Gemm":
            C = ins[2] if len(ins) > 2 else None
            return autograd.gemm(ins[0], ins[1], C,
                                 a.get("alpha", 1.0), a.get("beta", 1.0),
                                 a.get("transA", 0), a.get("transB", 0))
        if ty == "Softmax":
            opset = node.opset or cls._opset_version
            if opset >= 13:
                # opset-13 redefined Softmax as single-axis, default -1
                return autograd.softmax(ins[0], a.get("axis", -1))
            # opset<=12: coerce to 2D at `axis`, softmax the rows —
            # identical to per-axis only when `axis` is the last dim
            axis = a.get("axis", 1)
            x = ins[0]
            nd = len(x.shape)
            if axis < 0:
                axis += nd
            if axis >= nd - 1:
                return autograd.softmax(x, -1)
            shape = list(x.shape)
            lead = 1
            for s in shape[:axis]:
                lead *= s
            flat = autograd.reshape(x, (lead, -1))
            return autograd.reshape(autograd.softmax(flat, -1), shape)
        if ty == "Concat":
            return autograd.cat(list(ins), a.get("axis", 0))
        if ty == "Flatten":
            return autograd.flatten(ins[0], a.get("axis", 1))
        if ty == "Reshape":
            shape = _ints(ins[1])
            # ONNX spec (allowzero=0 default): 0 copies the input dim
            shape = [ins[0].shape[i] if v == 0 and i < len(ins[0].shape)
                     else v for i, v in enumerate(shape)]
            return autograd.reshape(ins[0], shape)
        if ty == "Transpose":
            return autograd.transpose(ins[0], a.get("perm"))
        if ty == "Squeeze":
            # opset<=12: axes attribute; opset-13: axes as a second input
            axes = tuple(a["axes"]) if "axes" in a else \
                (tuple(_ints(ins[1])) if len(ins) > 1 and ins[1] is not None
                 else None)
            return autograd.squeeze(ins[0], axes)
        if ty == "Unsqueeze":
            axes = list(a["axes"]) if "axes" in a else _ints(ins[1])
            return autograd.unsqueeze(ins[0], axes)
        if ty == "Slice":
            starts = _ints(ins[1])
            ends = _ints(ins[2])
            axes = _ints(ins[3]) if len(ins) > 3 else None
            steps = _ints(ins[4]) if len(ins) > 4 else None
            return autograd.slice(ins[0], starts, ends, axes, steps)
        if ty == "Clip":
            # min/max arrive as 0-d or 1-element initializers
            mn = float(np.asarray(_arr(ins[1])).reshape(-1)[0]) \
                if len(ins) > 1 and ins[1] is not None else None
            mx = float(np.asarray(_arr(ins[2])).reshape(-1)[0]) \
                if len(ins) > 2 and ins[2] is not None else None
            return autograd.clip(ins[0], mn, mx)
        if ty in ("ReduceSum", "ReduceMean", "ReduceMax", "ReduceMin",
                  "ReduceProd", "ReduceL1", "ReduceL2", "ReduceLogSum",
                  "ReduceLogSumExp"):
            # opset-13 ReduceSum moved axes to a second input. An EMPTY
            # axes tensor means reduce over ALL axes (the spec default)
            # unless noop_with_empty_axes=1 asks for identity.
            axes = a.get("axes")
            if axes is None and len(ins) > 1 and ins[1] is not None:
                axes = _ints(ins[1]) or None
                if axes is None and a.get("noop_with_empty_axes", 0):
                    return autograd.identity(ins[0])
            keep = a.get("keepdims", 1)
            rsum = autograd.reduce_sum
            if ty == "ReduceSum":
                return rsum(ins[0], axes, keep)
            if ty == "ReduceMean":
                return autograd.reduce_mean(ins[0], axes, keep)
            if ty == "ReduceMax":
                return autograd.reduce_max(ins[0], axes, keep)
            if ty == "ReduceMin":
                # min = -max(-x): one extra fused negation, no new op
                return autograd.negative(
                    autograd.reduce_max(autograd.negative(ins[0]),
                                        axes, keep))
            if ty == "ReduceL1":
                return rsum(autograd.abs(ins[0]), axes, keep)
            if ty == "ReduceL2":
                return autograd.sqrt(rsum(autograd.mul(ins[0], ins[0]),
                                          axes, keep))
            if ty == "ReduceLogSum":
                return autograd.log(rsum(ins[0], axes, keep))
            if ty == "ReduceLogSumExp":
                # shift by the max for stability (spec result identical)
                m = autograd.reduce_max(ins[0], axes, 1)
                s = autograd.log(rsum(autograd.exp(
                    autograd.sub(ins[0], m)), axes, keep))
                mk = m if keep else autograd.reshape(m, list(s.shape))
                return autograd.add(s, mk)
            # ReduceProd: log/exp trick breaks on non-positive values —
            # do it as a real product reduction over the named axes
            return autograd.reduce_prod(ins[0], axes, keep)
        if ty == "LeakyRelu":
            return autograd.leakyrelu(ins[0], a.get("alpha", 0.01))
        if ty == "Elu":
            return autograd.elu(ins[0], a.get("alpha", 1.0))
        if ty == "Selu":
            return autograd.selu(ins[0], a.get("alpha", 1.67326),
                                 a.get("gamma", 1.0507))
        if ty == "HardSigmoid":
            return autograd.hardsigmoid(ins[0], a.get("alpha", 0.2),
                                        a.get("beta", 0.5))
        if ty == "Dropout":
            return autograd.dropout(ins[0], a.get("ratio", 0.5))
        if ty == "Split":
            # opset<=12: split attribute; opset-13: split as second input
            parts = list(a["split"]) if "split" in a else \
                (_ints(ins[1]) if len(ins) > 1 and ins[1] is not None
                 else None)
            return autograd.split(ins[0], a.get("axis", 0), parts,
                                  num_output=len(node.outputs)
                                  if parts is None else None)
        if ty == "Gather":
            return autograd.gather(ins[0], a.get("axis", 0),
                                   _arr(ins[1]).astype(np.int32))
        if ty == "Tile":
            return autograd.tile(ins[0], _ints(ins[1]))
        if ty == "Expand":
            return autograd.expand(ins[0], _ints(ins[1]))
        if ty == "Pad":
            pads = _ints(ins[1])
            # reshape(-1)[0]: the constant may arrive as a 0-d OR 1-elem
            # array; float() of an ndim>0 array is a numpy deprecation
            const = float(_arr(ins[2]).reshape(-1)[0]) \
                if len(ins) > 2 and ins[2] is not None else 0.0
            return autograd.pad(ins[0], a.get("mode", "constant"), pads,
                                const)
        if ty in ("Upsample", "Resize"):
            from .ops.resize import resize as _resize
            if ty == "Resize":
                # Resize(X, roi, scales[, sizes]): prefer scales; derive
                # them from sizes when only sizes is given. The spec maps
                # coordinates with the ORIGINAL scales (out=floor(in*s)),
                # so both are threaded through.
                scales_t = ins[2] if len(ins) > 2 else None
                if scales_t is not None and scales_t.size():
                    scales = [float(s) for s in _arr(scales_t).ravel()]
                    out_shape = [int(np.floor(d * s))
                                 for d, s in zip(ins[0].shape, scales)]
                elif len(ins) > 3 and ins[3] is not None:
                    out_shape = [int(v) for v in _arr(ins[3]).ravel()]
                    scales = [o / d for o, d in zip(out_shape,
                                                    ins[0].shape)]
                else:
                    raise ValueError("Resize needs scales or sizes")
                mode = a.get("mode", "nearest")
                coord = a.get("coordinate_transformation_mode",
                              "half_pixel")
            else:
                scales = [float(s) for s in _arr(ins[-1]).ravel()]
                out_shape = [int(np.floor(d * s))
                             for d, s in zip(ins[0].shape, scales)]
                mode = a.get("mode", "nearest")
                # the legacy Upsample op used asymmetric+floor sampling
                coord = "asymmetric"
            int_scales = [int(round(float(s))) for s in scales]
            if mode == "nearest" and coord == "asymmetric" and \
                    all(abs(i - float(s)) <= 1e-6
                        for i, s in zip(int_scales, scales)):
                # integer nearest upsample: the one-op repeat fast path
                return autograd.upsample(ins[0], "nearest", int_scales)
            nearest = a.get("nearest_mode", "round_prefer_floor") \
                if ty == "Resize" else "floor"
            # sampling tables are static per node: compute once, cache
            # the handle (same pattern as the Conv/Pool handles above)
            handle = node.cache.get("resize")
            if handle is None:
                from .ops.resize import ResizeHandle
                handle = ResizeHandle(
                    ins[0].shape, out_shape,
                    mode={"nearest": "nearest", "linear": "linear",
                          "cubic": "cubic"}[mode],
                    coord_mode=coord, nearest_mode=nearest,
                    cubic_a=a.get("cubic_coeff_a", -0.75),
                    scales=scales)
                node.cache["resize"] = handle
            return _resize(ins[0], handle=handle)
        if ty == "ConstantOfShape":
            v = a.get("value")
            val = float(numpy_helper.to_array(v).ravel()[0]) \
                if v is not None else 0.0
            return autograd.constant_of_shape(ins[0], val)
        if ty == "Cast":
            return autograd.cast(
                ins[0], helper.tensor_dtype_to_np_dtype(a["to"]))
        if ty == "OneHot":
            depth = int(_arr(ins[1]).ravel()[0])
            values = tuple(float(v) for v in _arr(ins[2]).ravel())
            return autograd.onehot(a.get("axis", -1), ins[0], depth, values)
        if ty == "DepthToSpace":
            return autograd.depth_to_space(ins[0], a["blocksize"],
                                           a.get("mode", "DCR"))
        if ty == "SpaceToDepth":
            return autograd.space_to_depth(ins[0], a["blocksize"])
        if ty == "ScatterElements":
            return autograd.scatter_elements(ins[0], ins[1], ins[2],
                                             a.get("axis", 0))
        if ty == "Constant":
            v = a["value"]
            return Tensor(data=numpy_helper.to_array(v),
                          requires_grad=False)
        if ty in ("RNN", "LSTM", "GRU"):
            return cls._handle_rnn_family(node, ins)
        if ty == "LRN":
            return autograd.lrn(ins[0], a.get("size", 5),
                                a.get("alpha", 1e-4), a.get("beta", 0.75),
                                a.get("bias", 1.0))
        raise NotImplementedError(f"ONNX op {ty} is not supported")

    # onnx gate-block order -> our gate order (rows of W/R in H-blocks):
    # LSTM onnx iofc -> ours ifgo (g==c); GRU onnx zrh -> ours rzn
    _rnn_gate_perm = {"LSTM": [0, 2, 3, 1], "GRU": [1, 0, 2], "RNN": [0]}

    @classmethod
    def _handle_rnn_family(cls, node, ins):
        """ONNX RNN/LSTM/GRU node -> our scan-based RNN op (reference
        python/singa/sonnx.py RNN-family backend handling; semantics from
        the ONNX operator spec).

        W/R/B are repacked into the op's flat-weight layout WITH taped
        autograd ops, so gradients flow back to the original initializers
        and an imported model fine-tunes like a native one.
        """
        from .ops.rnn import CudnnRNNHandle, rnn_op

        ty, a = node.op_type, node.attrs
        X, W, R = ins[0], ins[1], ins[2]
        B = ins[3] if len(ins) > 3 else None
        seq_lens = ins[4] if len(ins) > 4 else None
        init_h = ins[5] if len(ins) > 5 else None
        init_c = ins[6] if len(ins) > 6 else None
        if ty == "LSTM" and len(ins) > 7 and ins[7] is not None:
            raise NotImplementedError("LSTM peephole input P")

        H = int(a["hidden_size"])
        direction = a.get("direction", "forward")
        if isinstance(direction, bytes):
            direction = direction.decode()
        D = 2 if direction == "bidirectional" else 1
        perm = cls._rnn_gate_perm[ty]
        G = len(perm)
        acts = [v.decode() if isinstance(v, bytes) else v
                for v in a.get("activations", [])]
        if ty == "RNN":
            base = acts[0] if acts else "Tanh"
            if any(v != base for v in acts):
                raise NotImplementedError(f"mixed RNN activations {acts}")
            mode = {"Tanh": "tanh", "Relu": "relu"}.get(base)
            if mode is None:
                raise NotImplementedError(f"RNN activation {base}")
        else:
            defaults = {"LSTM": ["Sigmoid", "Tanh", "Tanh"],
                        "GRU": ["Sigmoid", "Tanh"]}[ty]
            # spec-default activation lists come per direction (len 3*D)
            # or abbreviated (len 3); both mean "defaults"
            if acts and acts != defaults and acts != defaults * D:
                raise NotImplementedError(
                    f"non-default {ty} activations {acts}")
            mode = ty.lower()
        lbr = bool(a.get("linear_before_reset", 0)) if ty == "GRU" else True

        if direction == "reverse":
            if seq_lens is not None:
                raise NotImplementedError(
                    "direction=reverse with sequence_lens")
            T = X.shape[0]
            X = autograd.slice(X, [T - 1], [-(T + 1)], [0], [-1])

        def rows(mat2d, g):
            return autograd.slice(mat2d, [g * H], [(g + 1) * H], [0])

        def vec(v1d, base, g):
            return autograd.slice(v1d, [base + g * H], [base + (g + 1) * H],
                                  [0])

        Bsz = X.shape[1]
        pieces = []
        for d in range(D):
            Wd = autograd.reshape(
                autograd.slice(W, [d], [d + 1], [0]), (G * H, W.shape[2]))
            Rd = autograd.reshape(
                autograd.slice(R, [d], [d + 1], [0]), (G * H, H))
            Wih = autograd.cat([rows(Wd, g) for g in perm], 0)
            Whh = autograd.cat([rows(Rd, g) for g in perm], 0)
            if B is not None:
                Bd = autograd.reshape(
                    autograd.slice(B, [d], [d + 1], [0]), (2 * G * H,))
                bih = autograd.cat([vec(Bd, 0, g) for g in perm], 0)
                bhh = autograd.cat([vec(Bd, G * H, g) for g in perm], 0)
            else:
                zz = Tensor(data=np.zeros(G * H, np.float32),
                            device=X.device, requires_grad=False)
                bih = bhh = zz
            pieces += [autograd.reshape(Wih, (G * H * W.shape[2],)),
                       autograd.reshape(Whh, (G * H * H,)), bih, bhh]
        flatW = autograd.cat(pieces, 0) if len(pieces) > 1 else pieces[0]

        handle = node.cache.get("handle")
        if handle is None:
            handle = CudnnRNNHandle(
                X, H, mode=mode, num_layers=1,
                bidirectional=(direction == "bidirectional"),
                gru_linear_before_reset=lbr)
            node.cache["handle"] = handle

        def state(t):
            if t is None:
                return Tensor(data=np.zeros((D, Bsz, H), np.float32),
                              device=X.device, requires_grad=False)
            return t

        lens = None
        if seq_lens is not None:
            lens = autograd.cast(seq_lens, np.int32)
        y, hy, cy = rnn_op(handle, X, state(init_h), state(init_c), flatW,
                           seq_lengths=lens)
        # ours: y (T, B, D*H); ONNX: Y (T, D, B, H), Y_h/Y_c (D, B, H)
        T = X.shape[0]
        Y = autograd.transpose(
            autograd.reshape(y, (T, Bsz, D, H)), (0, 2, 1, 3))
        if direction == "reverse":
            Y = autograd.slice(Y, [T - 1], [-(T + 1)], [0], [-1])
        if ty == "LSTM":
            return Y, hy, cy
        return Y, hy

    @classmethod
    def prepare(cls, model, device="CPU", init_inputs=None, **kwargs):
        """Parse an ONNX ModelProto into a runnable :class:`SingaRep`
        (reference SingaBackend.prepare sonnx.py:1911)."""
        opset = None
        for imp in model.opset_import:
            if imp.domain == "":
                opset = imp.version
                if imp.version > cls._opset_version:
                    warnings.warn(
                        f"opset {imp.version} is newer than supported "
                        f"({cls._opset_version})")
        if model.ir_version > cls._ir_version:
            warnings.warn(
                f"ir_version {model.ir_version} is newer than supported "
                f"({cls._ir_version})")
        graph = model.graph
        dev = device_mod.create_tpu_device() if device in ("TPU", "GPU",
                                                           "CUDA") \
            else device_mod.create_cpu_device()

        # initializers that are op configuration, not learned weights:
        # BN running stats and the "attribute-as-input" operands of
        # shape-manipulating ops must never be marked trainable
        non_weight = set()
        for n in graph.node:
            if n.op_type == "BatchNormalization":
                non_weight.update(n.input[3:5])
            elif n.op_type in ("Reshape", "Expand", "Tile", "Pad", "Slice",
                               "Clip", "OneHot", "Upsample", "Resize",
                               "Gather", "ConstantOfShape", "Split",
                               "Squeeze", "Unsqueeze", "ReduceSum"):
                non_weight.update(n.input[1:])
            elif n.op_type in ("RNN", "LSTM", "GRU"):
                # sequence_lens / initial states are config, not weights
                non_weight.update(n.input[4:7])

        params = OrderedDict()
        for init in graph.initializer:
            arr = numpy_helper.to_array(init)
            trainable = (arr.dtype == np.float32 and arr.ndim >= 1
                         and init.name not in non_weight)
            t = Tensor(data=np.ascontiguousarray(arr), device=dev,
                       requires_grad=trainable, stores_grad=trainable)
            t.name = init.name
            params[init.name] = t

        inputs = [vi for vi in graph.input if vi.name not in params]
        outputs = list(graph.output)
        nodes = [OnnxNode(n, opset=opset) for n in graph.node]
        return SingaRep(params, inputs, outputs, nodes, dev)


class SingaRep:
    """Executable representation of an imported graph
    (reference SingaRep sonnx.py:1951)."""

    def __init__(self, params, inputs, outputs, nodes, dev):
        self.states = params
        self.inputs = inputs
        self.outputs = outputs
        self.nodes = nodes
        self.dev = dev
        self.is_graph = False

    # reference API: layers is [(node, operator)]
    @property
    def layers(self):
        return [(n, None) for n in self.nodes]

    def get_states(self):
        return dict(self.states)

    def run(self, input, aux_output=(), **kwargs):  # noqa: A002
        """Topologically execute the graph
        (reference SingaRep.run sonnx.py:1998)."""
        tensors = dict(self.states)
        ins = list(input)
        for vi, t in zip(self.inputs, ins):
            if not isinstance(t, Tensor):
                t = Tensor(data=np.asarray(t), device=self.dev,
                           requires_grad=False)
            tensors[vi.name] = t
        for node in self.nodes:
            resolved = [tensors[nm] if nm else None for nm in node.inputs]
            out = SingaBackend._handle(node, resolved, tensors)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            for nm, t in zip(node.outputs, outs):
                if nm:  # optional outputs may be omitted as ""
                    tensors[nm] = t
        result = [tensors[o.name] for o in self.outputs]
        for nm in aux_output:
            result.append(tensors[nm])
        return result


from .model import Model as _Model  # noqa: E402  (after backend defs)


class SONNXModel(_Model):
    """Imported ONNX graph as a trainable Model
    (reference SONNXModel sonnx.py:2196). Subclass and override
    ``train_one_batch`` to fine-tune; the imported weights are parameters.

    Serving: an imported graph serves through the SAME engine as the
    zoo models — ``SONNXModel(m).compile_serving(input_shape=...)``
    returns a fixed-width :class:`~singa_tpu.serving.BatchServingEngine`
    (the inherited :meth:`~singa_tpu.model.Model.compile_serving` routes
    stateless models there); see ``docs/serving.md``.
    """

    def __init__(self, onnx_model, device="CPU"):
        super().__init__()
        self.sg_ir = prepare(onnx_model, device=device)

    def forward(self, *input, aux_output=(), **kwargs):  # noqa: A002
        outs = self.sg_ir.run(list(input), aux_output=aux_output, **kwargs)
        return outs if len(outs) > 1 else outs[0]

    def get_params(self):
        return {k: v for k, v in self.sg_ir.states.items()
                if v.requires_grad}

    def set_params(self, params):
        for k, v in params.items():
            if k in self.sg_ir.states:
                self.sg_ir.states[k].copy_from(v)

    def get_states(self):
        return dict(self.sg_ir.states)

    def set_states(self, states):
        for k, v in states.items():
            if k in self.sg_ir.states:
                self.sg_ir.states[k].copy_from(v)


# reference-parity module-level API (sonnx.py:2223-2228)
prepare = SingaBackend.prepare
get_op = SingaBackend._handle
run_node = None  # per-node execution happens through SingaRep
