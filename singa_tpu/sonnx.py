"""ONNX import/export for the TPU-native framework.

Capability parity with the reference ONNX bridge (python/singa/sonnx.py):

- :class:`SingaFrontend` — export a taped computation to an ONNX
  ``ModelProto`` (reference SingaFrontend, sonnx.py:75-1035);
- :class:`SingaBackend` / :class:`SingaRep` — import an ONNX model and run
  (or fine-tune) it on our ops (reference SingaBackend.prepare sonnx.py:1911,
  SingaRep.run :1951);
- :class:`SONNXModel` — wrap an imported graph as a trainable
  :class:`~singa_tpu.model.Model` (reference SONNXModel sonnx.py:2196).

TPU-first redesign: the reference converts node-by-node into SWIG handles;
here every imported node lowers to our jax-backed autograd ops, so an
imported graph jits into a single XLA computation exactly like a native
model. Works against the real ``onnx`` package when installed, else the
bundled wire-compatible protos (singa_tpu/onnx_proto).
"""

from __future__ import annotations

import warnings
from collections import OrderedDict, deque

import numpy as np

from . import autograd
from .autograd_base import CTX, Dummy, Operator
from .tensor import Tensor
from . import device as device_mod
from .onnx_compat import (TensorProto, helper, numpy_helper, load, save,
                          attribute_dict)
from .ops.conv import ConvHandle, conv2d
from .ops.pooling import PoolingHandle, pooling_2d, globalaveragepool
from .ops.batchnorm import BatchNormHandle, batchnorm_2d


def _sanitize(name):
    return name.replace("#", "_").replace(":", "_")


_DTYPE_TO_ONNX = {
    "float32": TensorProto.FLOAT, "float64": TensorProto.DOUBLE,
    "float16": TensorProto.FLOAT16, "bfloat16": TensorProto.BFLOAT16,
    "int32": TensorProto.INT32, "int64": TensorProto.INT64,
    "int8": TensorProto.INT8, "uint8": TensorProto.UINT8,
    "bool": TensorProto.BOOL,
}


def _onnx_dtype(t):
    return _DTYPE_TO_ONNX.get(str(np.dtype(t.dtype)), TensorProto.FLOAT)


# ===========================================================================
# Frontend: tape -> ONNX
# ===========================================================================

class SingaFrontend:
    """Exports a taped forward computation to ONNX (reference sonnx.py:75).

    Usage::

        x.requires_grad = True      # record input edges on the tape
        autograd.training = True
        y = model.forward(x)
        onnx_model = SingaFrontend.singa_to_onnx_model([x], [y], "net")
    """

    _target_opset_version = 11

    # our Operator class name -> onnx op_type
    _rename_operators = {
        "_Conv2d": "Conv",
        "ReLU": "Relu",
        "_Pooling2d": None,  # resolved to MaxPool/AveragePool per handle
        "SoftMax": "Softmax",
        "Sigmoid": "Sigmoid",
        "Add": "Add",
        "Matmul": "MatMul",
        "_BatchNorm2d": "BatchNormalization",
        "_BatchNorm2dInference": "BatchNormalization",
        "Concat": "Concat",
        "Flatten": "Flatten",
        "AddBias": "Add",
        "Gemm": "Gemm",
        "Reshape": "Reshape",
        "Sum": "Sum",
        "Cos": "Cos", "Cosh": "Cosh", "Sin": "Sin", "Sinh": "Sinh",
        "Tan": "Tan", "Tanh": "Tanh", "Acos": "Acos", "Acosh": "Acosh",
        "Asin": "Asin", "Asinh": "Asinh", "Atan": "Atan", "Atanh": "Atanh",
        "SeLU": "Selu", "Elu": "Elu", "Equal": "Equal", "Less": "Less",
        "Sign": "Sign", "Div": "Div", "Sub": "Sub", "Sqrt": "Sqrt",
        "Log": "Log", "Greater": "Greater", "HardSigmoid": "HardSigmoid",
        "Identity": "Identity", "SoftPlus": "Softplus",
        "SoftSign": "Softsign", "Mean": "Mean", "Pow": "Pow",
        "Clip": "Clip", "PRelu": "PRelu", "Mul": "Mul",
        "Transpose": "Transpose", "Max": "Max", "Min": "Min",
        "Shape": "Shape", "And": "And", "Or": "Or", "Xor": "Xor",
        "Not": "Not", "Negative": "Neg", "Reciprocal": "Reciprocal",
        "ConstantOfShape": "ConstantOfShape", "Dropout": "Dropout",
        "ReduceSum": "ReduceSum", "ReduceMean": "ReduceMean",
        "LeakyRelu": "LeakyRelu", "GlobalAveragePool": "GlobalAveragePool",
        "Squeeze": "Squeeze", "Unsqueeze": "Unsqueeze", "Slice": "Slice",
        "Ceil": "Ceil", "Floor": "Floor", "Abs": "Abs", "Split": "Split",
        "Gather": "Gather", "Tile": "Tile", "NonZero": "NonZero",
        "Cast": "Cast", "OneHot": "OneHot", "Erf": "Erf",
        "Where": "Where", "Expand": "Expand", "Pad": "Pad",
        "UpSample": "Upsample", "DepthToSpace": "DepthToSpace",
        "SpaceToDepth": "SpaceToDepth", "Embedding": "Gather",
        "ScatterElements": "ScatterElements",
    }

    @classmethod
    def _topo_ops(cls, ys):
        """Reverse tape -> topological op order (inputs first)."""
        visited = set()
        order = []

        for y in ys:
            stack = [(y.creator, False)]
            while stack:
                op, expanded = stack.pop()
                if op is None:
                    continue
                if expanded:
                    order.append(op)
                    continue
                if id(op) in visited:
                    continue
                visited.add(id(op))
                stack.append((op, True))
                for (src_op, _xid, _t, _req) in op.src:
                    if src_op is not None and id(src_op) not in visited:
                        stack.append((src_op, False))
        return order

    @classmethod
    def _node_attrs_and_extra(cls, op, op_name, input_names, extras):
        """(op_type, attrs dict); may append extra initializer inputs."""
        ty = type(op).__name__
        attrs = {}

        def extra_int64(suffix, values):
            nm = f"{op_name}_{suffix}"
            extras.append(numpy_helper.from_array(
                np.asarray(values, np.int64), nm))
            input_names.append(nm)

        if ty == "_Conv2d":
            h = op.handle
            (p0, p1), (q0, q1) = h.padding
            attrs = {"kernel_shape": list(h.kernel_size),
                     "strides": list(h.stride),
                     "dilations": list(h.dilation),
                     "group": h.group,
                     "pads": [p0, q0, p1, q1]}
            return "Conv", attrs
        if ty == "_Pooling2d":
            h = op.handle
            (p0, p1), (q0, q1) = h.pad_pairs
            attrs = {"kernel_shape": list(h.kernel_size),
                     "strides": list(h.stride),
                     "pads": [p0, q0, p1, q1]}
            if h.is_max_pooling:
                return "MaxPool", attrs
            attrs["count_include_pad"] = 1
            return "AveragePool", attrs
        if ty in ("_BatchNorm2d", "_BatchNorm2dInference"):
            h = op.handle
            return "BatchNormalization", {"epsilon": float(h.eps),
                                          "momentum": float(h.factor)}
        if ty == "Gemm":
            return "Gemm", {"alpha": float(op.alpha), "beta": float(op.beta),
                            "transA": int(op.transA),
                            "transB": int(op.transB)}
        if ty == "SoftMax":
            return "Softmax", {"axis": op.axis}
        if ty == "Concat":
            return "Concat", {"axis": op.axis}
        if ty == "Flatten":
            return "Flatten", {"axis": op.axis}
        if ty == "Reshape":
            extra_int64("shape", op.shape)
            return "Reshape", {}
        if ty == "Transpose":
            return "Transpose", {"perm": list(op.perm)} if op.perm else {}
        if ty == "Squeeze":
            ax = op.axis
            if ax is None:
                return "Squeeze", {}
            return "Squeeze", {"axes": list(ax) if isinstance(
                ax, (tuple, list)) else [ax]}
        if ty == "Unsqueeze":
            return "Unsqueeze", {"axes": list(op.axis)}
        if ty == "Slice":
            extra_int64("starts", op.starts)
            extra_int64("ends", op.ends)
            if op.axes is not None:
                extra_int64("axes", op.axes)
            if op.steps is not None:
                if op.axes is None:
                    extra_int64("axes", list(range(len(op.starts))))
                extra_int64("steps", op.steps)
            return "Slice", {}
        if ty == "Clip":
            for suffix, v in (("min", op.min), ("max", op.max)):
                if v is not None:
                    nm = f"{op_name}_{suffix}"
                    extras.append(numpy_helper.from_array(
                        np.asarray(v, np.float32), nm))
                    input_names.append(nm)
                else:
                    input_names.append("")
            return "Clip", {}
        if ty in ("ReduceSum", "ReduceMean"):
            attrs = {"keepdims": int(op.keepdims)}
            if op.axes is not None:
                attrs["axes"] = list(op.axes)
            return ty, attrs
        if ty == "LeakyRelu":
            return "LeakyRelu", {"alpha": float(op.a)}
        if ty == "Elu":
            return "Elu", {"alpha": float(op.alpha)}
        if ty == "SeLU":
            return "Selu", {"alpha": float(op.alpha),
                            "gamma": float(op.gamma)}
        if ty == "HardSigmoid":
            return "HardSigmoid", {"alpha": float(op.alpha),
                                   "beta": float(op.gamma)}
        if ty == "Dropout":
            return "Dropout", {"ratio": float(op.ratio)}
        if ty == "Split":
            attrs = {"axis": op.axis}
            if op.parts is not None:
                attrs["split"] = list(op.parts)
            return "Split", attrs
        if ty == "Gather":
            return "Gather", {"axis": op.axis}
        if ty == "Embedding":
            # our Embedding(x_ids, W) == onnx Gather(W, ids) on axis 0
            input_names.reverse()
            return "Gather", {"axis": 0}
        if ty == "Tile":
            extra_int64("repeats", op.repeats)
            return "Tile", {}
        if ty == "Expand":
            extra_int64("shape", op.shape)
            return "Expand", {}
        if ty == "Pad":
            extra_int64("pads", op.pads)
            if op.mode == "constant":
                nm = f"{op_name}_value"
                extras.append(numpy_helper.from_array(
                    np.asarray(op.constant, np.float32), nm))
                input_names.append(nm)
            return "Pad", {"mode": op.mode}
        if ty == "UpSample":
            nm = f"{op_name}_scales"
            extras.append(numpy_helper.from_array(
                np.asarray(op.scales, np.float32), nm))
            input_names.append(nm)
            return "Upsample", {"mode": "nearest"}
        if ty == "ConstantOfShape":
            attrs["value"] = numpy_helper.from_array(
                np.asarray([op.value], np.float32), "value")
            return "ConstantOfShape", attrs
        if ty == "Cast":
            return "Cast", {
                "to": int(helper.np_dtype_to_tensor_dtype(np.dtype(op.to)))}
        if ty == "OneHot":
            extra_int64("depth", op.depth)
            nm = f"{op_name}_values"
            extras.append(numpy_helper.from_array(
                np.asarray(op.values, np.float32), nm))
            input_names.append(nm)
            return "OneHot", {"axis": op.axis}
        if ty in ("DepthToSpace", "SpaceToDepth"):
            attrs = {"blocksize": op.b}
            if ty == "DepthToSpace":
                attrs["mode"] = op.mode
            return ty, attrs
        if ty == "ScatterElements":
            return "ScatterElements", {"axis": op.axis}
        onnx_ty = cls._rename_operators.get(ty)
        if onnx_ty is None:
            raise NotImplementedError(
                f"cannot export op {ty} to ONNX")
        return onnx_ty, attrs

    @classmethod
    def singa_to_onnx_graph(cls, inputs, y, model_name="sonnx"):
        ys = y if isinstance(y, (list, tuple)) else [y]
        ops = cls._topo_ops(ys)

        input_ids = {id(t): i for i, t in enumerate(inputs)}
        names = {}          # tensor-id -> value name
        initializers = []
        graph_inputs = []
        nodes = []

        # Dummy leaves: user inputs, params (stores_grad), or constants
        for op in ops:
            if not isinstance(op, Dummy):
                continue
            t = op.tensor
            if id(t) in input_ids:
                nm = t.name or f"input_{input_ids[id(t)]}"
                names[id(t)] = nm
            else:
                nm = _sanitize(t.name or f"const_{len(initializers)}")
                names[id(t)] = nm
                initializers.append(numpy_helper.from_array(
                    np.asarray(t.numpy()), nm))
        # ALL caller inputs, in the caller's order (run() binds
        # positionally; unused inputs stay declared so positions hold)
        for i, t in enumerate(inputs):
            if id(t) not in names:
                names[id(t)] = t.name or f"input_{i}"
            graph_inputs.append(helper.make_tensor_value_info(
                names[id(t)], _onnx_dtype(t), list(t.shape)))

        # BN running stats are referenced by the node but live off-tape
        def bn_state_name(op, which):
            t = getattr(op, which)
            if id(t) not in names:
                nm = _sanitize(t.name or f"{_sanitize(op.name)}_{which}")
                names[id(t)] = nm
                initializers.append(numpy_helper.from_array(
                    np.asarray(t.numpy()), nm))
            return names[id(t)]

        for op in ops:
            if isinstance(op, Dummy):
                continue
            op_name = _sanitize(op.name)
            in_names = []
            for (src_op, x_id, t_ref, _req) in op.src:
                if x_id not in names:
                    if src_op is None and t_ref is not None:
                        # constant consumed by the op: emit an initializer
                        nm = _sanitize(t_ref.name or
                                       f"const_{len(initializers)}")
                        names[x_id] = nm
                        initializers.append(numpy_helper.from_array(
                            np.asarray(t_ref.numpy()), nm))
                    else:
                        raise ValueError(
                            f"op {op.name}: input tensor not on the tape — "
                            "mark graph inputs requires_grad=True before "
                            "export")
                in_names.append(names[x_id])
            out_names = []
            for pos, yid in enumerate(op.y_ids):
                nm = f"{op_name}_out{pos}" if len(op.y_ids) > 1 \
                    else op_name
                names[yid] = nm
                out_names.append(nm)

            ty = type(op).__name__
            if ty in ("_BatchNorm2d", "_BatchNorm2dInference"):
                # onnx BatchNormalization: X, scale, B, mean, var
                in_names = in_names[:3] + [bn_state_name(op, "running_mean"),
                                           bn_state_name(op, "running_var")]
            if ty == "Embedding":
                # ONNX Gather requires int32/int64 indices; our ids tensor
                # is float-typed on the tape, so cast it in-graph
                cast_nm = f"{op_name}_ids_i64"
                nodes.append(helper.make_node(
                    "Cast", [in_names[0]], [cast_nm],
                    name=f"{op_name}_cast", to=int(TensorProto.INT64)))
                in_names[0] = cast_nm
            onnx_ty, attrs = cls._node_attrs_and_extra(
                op, op_name, in_names, initializers)
            nodes.append(helper.make_node(onnx_ty, in_names, out_names,
                                          name=op_name, **attrs))

        graph_outputs = []
        for i, yy in enumerate(ys):
            graph_outputs.append(helper.make_tensor_value_info(
                names[id(yy)], _onnx_dtype(yy), list(yy.shape)))

        return helper.make_graph(nodes, model_name, graph_inputs,
                                 graph_outputs, initializer=initializers)

    @classmethod
    def singa_to_onnx_model(cls, inputs, y, model_name="sonnx"):
        graph = cls.singa_to_onnx_graph(inputs, y, model_name)
        return helper.make_model(
            graph, producer_name="singa_tpu",
            opset_imports=[helper.make_operatorsetid(
                "", cls._target_opset_version)]
            if hasattr(helper, "make_operatorsetid") else None)


def to_onnx(model, inputs, model_name="sonnx"):
    """Trace ``model.forward(*inputs)`` and export it
    (reference sonnx.to_onnx, sonnx.py:2227)."""
    tape_inputs = []
    for i, t in enumerate(inputs):
        ti = Tensor(data=t.data if isinstance(t, Tensor) else np.asarray(t),
                    device=getattr(t, "device", None), requires_grad=True,
                    stores_grad=False)
        ti.name = t.name if isinstance(t, Tensor) and t.name else f"input_{i}"
        tape_inputs.append(ti)
    # record the tape with INFERENCE semantics: BN reads (and must not
    # mutate) running stats, dropout is identity — the exported graph
    # reproduces model.eval() behaviour
    prev_t, prev_r = CTX.training, CTX.recording
    CTX.training, CTX.recording = False, True
    try:
        y = model.forward(*tape_inputs)
    finally:
        CTX.training, CTX.recording = prev_t, prev_r
    if hasattr(model, "get_states"):
        # stable initializer names (params are anonymous until compile())
        for name, st in model.get_states().items():
            st.name = st.name or name
    return SingaFrontend.singa_to_onnx_model(tape_inputs, y, model_name)


# ===========================================================================
# Backend: ONNX -> our ops
# ===========================================================================

class OnnxNode:
    """Light view of a NodeProto (reference sonnx.OnnxNode)."""

    def __init__(self, node):
        self.node = node
        self.name = _sanitize(node.name) or _sanitize("_".join(node.output))
        self.op_type = node.op_type
        self.inputs = list(node.input)
        self.outputs = list(node.output)
        self.attrs = attribute_dict(node)
        self.cache = {}  # shape-specialised handles, filled on first run


def _arr(t: Tensor):
    return np.asarray(t.numpy())


def _ints(t: Tensor):
    return [int(v) for v in np.asarray(t.numpy()).ravel()]


class SingaBackend:
    """ONNX graph -> executable ops (reference SingaBackend sonnx.py:1037).

    Each handler is ``(node, tensors) -> output Tensor(s)``; ``tensors``
    maps value names to Tensors (initializers included). Handles for
    shape-specialised ops (Conv/Pool/BN) are cached per node on first run.
    """

    _opset_version = 11
    _ir_version = 8

    # onnx op_type -> our functional op (simple 1:1 cases)
    _direct = {
        "Relu": autograd.relu, "Sigmoid": autograd.sigmoid,
        "Add": autograd.add, "MatMul": autograd.matmul,
        "Cos": autograd.cos, "Cosh": autograd.cosh, "Sin": autograd.sin,
        "Sinh": autograd.sinh, "Tan": autograd.tan, "Tanh": autograd.tanh,
        "Acos": autograd.acos, "Acosh": autograd.acosh,
        "Asin": autograd.asin, "Asinh": autograd.asinh,
        "Atan": autograd.atan, "Atanh": autograd.atanh,
        "Equal": autograd.equal, "Less": autograd.less,
        "Sign": autograd.sign, "Div": autograd.div, "Sub": autograd.sub,
        "Sqrt": autograd.sqrt, "Log": autograd.log,
        "Greater": autograd.greater, "Identity": autograd.identity,
        "Softplus": autograd.softplus, "Softsign": autograd.softsign,
        "Mean": autograd.mean, "Pow": autograd.pow,
        "PRelu": autograd.prelu, "Mul": autograd.mul,
        "Max": autograd.max, "Min": autograd.min,
        "Shape": autograd.shape, "And": autograd._and,
        "Or": autograd._or, "Xor": autograd._xor, "Not": autograd._not,
        "Neg": autograd.negative, "Reciprocal": autograd.reciprocal,
        "Sum": autograd.sum, "NonZero": autograd.nonzero,
        "Ceil": autograd.ceil, "Floor": autograd.floor,
        "Abs": autograd.abs, "Erf": autograd.erf, "Where": autograd.where,
    }

    @classmethod
    def _handle(cls, node: OnnxNode, ins, tensors):
        ty = node.op_type
        a = node.attrs
        if ty in cls._direct:
            return cls._direct[ty](*ins)
        if ty == "Conv":
            handle = node.cache.get("handle")
            if handle is None:
                ks = a["kernel_shape"]
                pads = a.get("pads", [0] * 4)
                handle = ConvHandle(
                    ins[0], tuple(ks),
                    tuple(a.get("strides", [1] * len(ks))),
                    ((pads[0], pads[2]), (pads[1], pads[3])),
                    in_channels=ins[0].shape[1],
                    out_channels=ins[1].shape[0],
                    bias=len(ins) > 2, group=a.get("group", 1),
                    dilation=tuple(a.get("dilations", [1] * len(ks))))
                node.cache["handle"] = handle
            return conv2d(handle, ins[0], ins[1],
                          ins[2] if len(ins) > 2 else None)
        if ty in ("MaxPool", "AveragePool"):
            handle = node.cache.get("handle")
            if handle is None:
                ks = a["kernel_shape"]
                pads = a.get("pads", [0] * 4)
                handle = PoolingHandle(
                    ins[0], tuple(ks),
                    tuple(a.get("strides", ks)),
                    ((pads[0], pads[2]), (pads[1], pads[3])),
                    is_max=(ty == "MaxPool"))
                node.cache["handle"] = handle
            return pooling_2d(handle, ins[0])
        if ty == "GlobalAveragePool":
            return globalaveragepool(ins[0])
        if ty == "BatchNormalization":
            handle = node.cache.get("handle")
            if handle is None:
                handle = BatchNormHandle(a.get("momentum", 0.9), ins[0],
                                         a.get("epsilon", 1e-5))
                node.cache["handle"] = handle
            x, scale, bias, mean, var = ins
            return batchnorm_2d(handle, x, scale, bias, mean, var)
        if ty == "Gemm":
            C = ins[2] if len(ins) > 2 else None
            return autograd.gemm(ins[0], ins[1], C,
                                 a.get("alpha", 1.0), a.get("beta", 1.0),
                                 a.get("transA", 0), a.get("transB", 0))
        if ty == "Softmax":
            return autograd.softmax(ins[0], a.get("axis", 1))
        if ty == "Concat":
            return autograd.cat(list(ins), a.get("axis", 0))
        if ty == "Flatten":
            return autograd.flatten(ins[0], a.get("axis", 1))
        if ty == "Reshape":
            return autograd.reshape(ins[0], _ints(ins[1]))
        if ty == "Transpose":
            return autograd.transpose(ins[0], a.get("perm"))
        if ty == "Squeeze":
            return autograd.squeeze(ins[0], tuple(a["axes"])
                                    if "axes" in a else None)
        if ty == "Unsqueeze":
            return autograd.unsqueeze(ins[0], list(a["axes"]))
        if ty == "Slice":
            starts = _ints(ins[1])
            ends = _ints(ins[2])
            axes = _ints(ins[3]) if len(ins) > 3 else None
            steps = _ints(ins[4]) if len(ins) > 4 else None
            return autograd.slice(ins[0], starts, ends, axes, steps)
        if ty == "Clip":
            mn = float(_arr(ins[1])) if len(ins) > 1 and ins[1] is not None \
                else None
            mx = float(_arr(ins[2])) if len(ins) > 2 and ins[2] is not None \
                else None
            return autograd.clip(ins[0], mn, mx)
        if ty in ("ReduceSum", "ReduceMean"):
            fn = autograd.reduce_sum if ty == "ReduceSum" \
                else autograd.reduce_mean
            return fn(ins[0], a.get("axes"), a.get("keepdims", 1))
        if ty == "LeakyRelu":
            return autograd.leakyrelu(ins[0], a.get("alpha", 0.01))
        if ty == "Elu":
            return autograd.elu(ins[0], a.get("alpha", 1.0))
        if ty == "Selu":
            return autograd.selu(ins[0], a.get("alpha", 1.67326),
                                 a.get("gamma", 1.0507))
        if ty == "HardSigmoid":
            return autograd.hardsigmoid(ins[0], a.get("alpha", 0.2),
                                        a.get("beta", 0.5))
        if ty == "Dropout":
            return autograd.dropout(ins[0], a.get("ratio", 0.5))
        if ty == "Split":
            return autograd.split(ins[0], a.get("axis", 0),
                                  list(a["split"]) if "split" in a else None,
                                  num_output=len(node.outputs)
                                  if "split" not in a else None)
        if ty == "Gather":
            return autograd.gather(ins[0], a.get("axis", 0),
                                   _arr(ins[1]).astype(np.int32))
        if ty == "Tile":
            return autograd.tile(ins[0], _ints(ins[1]))
        if ty == "Expand":
            return autograd.expand(ins[0], _ints(ins[1]))
        if ty == "Pad":
            pads = _ints(ins[1])
            const = float(_arr(ins[2])) \
                if len(ins) > 2 and ins[2] is not None else 0.0
            return autograd.pad(ins[0], a.get("mode", "constant"), pads,
                                const)
        if ty in ("Upsample", "Resize"):
            if ty == "Resize":
                # Resize(X, roi, scales[, sizes]): prefer scales; derive
                # them from sizes when only sizes is given
                scales_t = ins[2] if len(ins) > 2 else None
                if scales_t is not None and scales_t.size():
                    scales = _arr(scales_t).ravel()
                elif len(ins) > 3 and ins[3] is not None:
                    sizes = _arr(ins[3]).ravel()
                    scales = [s / d for s, d in zip(sizes, ins[0].shape)]
                else:
                    raise ValueError("Resize needs scales or sizes")
            else:
                scales = _arr(ins[-1]).ravel()
            int_scales = [int(round(float(s))) for s in scales]
            if any(abs(i - float(s)) > 1e-6 for i, s in zip(int_scales,
                                                            scales)):
                raise NotImplementedError(
                    f"{ty}: only integer nearest-neighbour scales are "
                    f"supported, got {list(map(float, scales))}")
            return autograd.upsample(ins[0], "nearest", int_scales)
        if ty == "ConstantOfShape":
            v = a.get("value")
            val = float(numpy_helper.to_array(v).ravel()[0]) \
                if v is not None else 0.0
            return autograd.constant_of_shape(ins[0], val)
        if ty == "Cast":
            return autograd.cast(
                ins[0], helper.tensor_dtype_to_np_dtype(a["to"]))
        if ty == "OneHot":
            depth = int(_arr(ins[1]).ravel()[0])
            values = tuple(float(v) for v in _arr(ins[2]).ravel())
            return autograd.onehot(a.get("axis", -1), ins[0], depth, values)
        if ty == "DepthToSpace":
            return autograd.depth_to_space(ins[0], a["blocksize"],
                                           a.get("mode", "DCR"))
        if ty == "SpaceToDepth":
            return autograd.space_to_depth(ins[0], a["blocksize"])
        if ty == "ScatterElements":
            return autograd.scatter_elements(ins[0], ins[1], ins[2],
                                             a.get("axis", 0))
        if ty == "Constant":
            v = a["value"]
            return Tensor(data=numpy_helper.to_array(v),
                          requires_grad=False)
        raise NotImplementedError(f"ONNX op {ty} is not supported")

    @classmethod
    def prepare(cls, model, device="CPU", init_inputs=None, **kwargs):
        """Parse an ONNX ModelProto into a runnable :class:`SingaRep`
        (reference SingaBackend.prepare sonnx.py:1911)."""
        for imp in model.opset_import:
            if imp.domain == "" and imp.version > cls._opset_version:
                warnings.warn(
                    f"opset {imp.version} is newer than supported "
                    f"({cls._opset_version})")
        if model.ir_version > cls._ir_version:
            warnings.warn(
                f"ir_version {model.ir_version} is newer than supported "
                f"({cls._ir_version})")
        graph = model.graph
        dev = device_mod.create_tpu_device() if device in ("TPU", "GPU",
                                                           "CUDA") \
            else device_mod.create_cpu_device()

        # initializers that are op configuration, not learned weights:
        # BN running stats and the "attribute-as-input" operands of
        # shape-manipulating ops must never be marked trainable
        non_weight = set()
        for n in graph.node:
            if n.op_type == "BatchNormalization":
                non_weight.update(n.input[3:5])
            elif n.op_type in ("Reshape", "Expand", "Tile", "Pad", "Slice",
                               "Clip", "OneHot", "Upsample", "Resize",
                               "Gather", "ConstantOfShape"):
                non_weight.update(n.input[1:])

        params = OrderedDict()
        for init in graph.initializer:
            arr = numpy_helper.to_array(init)
            trainable = (arr.dtype == np.float32 and arr.ndim >= 1
                         and init.name not in non_weight)
            t = Tensor(data=np.ascontiguousarray(arr), device=dev,
                       requires_grad=trainable, stores_grad=trainable)
            t.name = init.name
            params[init.name] = t

        inputs = [vi for vi in graph.input if vi.name not in params]
        outputs = list(graph.output)
        nodes = [OnnxNode(n) for n in graph.node]
        return SingaRep(params, inputs, outputs, nodes, dev)


class SingaRep:
    """Executable representation of an imported graph
    (reference SingaRep sonnx.py:1951)."""

    def __init__(self, params, inputs, outputs, nodes, dev):
        self.states = params
        self.inputs = inputs
        self.outputs = outputs
        self.nodes = nodes
        self.dev = dev
        self.is_graph = False

    # reference API: layers is [(node, operator)]
    @property
    def layers(self):
        return [(n, None) for n in self.nodes]

    def get_states(self):
        return dict(self.states)

    def run(self, input, aux_output=(), **kwargs):  # noqa: A002
        """Topologically execute the graph
        (reference SingaRep.run sonnx.py:1998)."""
        tensors = dict(self.states)
        ins = list(input)
        for vi, t in zip(self.inputs, ins):
            if not isinstance(t, Tensor):
                t = Tensor(data=np.asarray(t), device=self.dev,
                           requires_grad=False)
            tensors[vi.name] = t
        for node in self.nodes:
            resolved = [tensors[nm] if nm else None for nm in node.inputs]
            out = SingaBackend._handle(node, resolved, tensors)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            for nm, t in zip(node.outputs, outs):
                tensors[nm] = t
        result = [tensors[o.name] for o in self.outputs]
        for nm in aux_output:
            result.append(tensors[nm])
        return result


from .model import Model as _Model  # noqa: E402  (after backend defs)


class SONNXModel(_Model):
    """Imported ONNX graph as a trainable Model
    (reference SONNXModel sonnx.py:2196). Subclass and override
    ``train_one_batch`` to fine-tune; the imported weights are parameters.
    """

    def __init__(self, onnx_model, device="CPU"):
        super().__init__()
        self.sg_ir = prepare(onnx_model, device=device)

    def forward(self, *input, aux_output=(), **kwargs):  # noqa: A002
        outs = self.sg_ir.run(list(input), aux_output=aux_output, **kwargs)
        return outs if len(outs) > 1 else outs[0]

    def get_params(self):
        return {k: v for k, v in self.sg_ir.states.items()
                if v.requires_grad}

    def set_params(self, params):
        for k, v in params.items():
            if k in self.sg_ir.states:
                self.sg_ir.states[k].copy_from(v)

    def get_states(self):
        return dict(self.sg_ir.states)

    def set_states(self, states):
        for k, v in states.items():
            if k in self.sg_ir.states:
                self.sg_ir.states[k].copy_from(v)


# reference-parity module-level API (sonnx.py:2223-2228)
prepare = SingaBackend.prepare
get_op = SingaBackend._handle
run_node = None  # per-node execution happens through SingaRep
