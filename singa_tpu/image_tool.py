"""Image augmentation toolkit.

Capability parity with the reference image tool (python/singa/image_tool.py):
free functions (load_img, crop, crop_and_resize, resize, color_cast,
enhance, flip, ...) plus the chainable :class:`ImageTool` whose ops either
sample one random case (``inplace=True``, training) or enumerate all cases
(``num_case=n``, test-time augmentation). PIL is the backend, as in the
reference.
"""

from __future__ import annotations

import random

import numpy as np
from PIL import Image, ImageEnhance


def load_img(path, grayscale=False):
    img = Image.open(path)
    return img.convert("L" if grayscale else "RGB")


def crop(img, patch, position):
    """Crop a (pw, ph) patch at one of five positions
    (left_top/left_bottom/right_top/right_bottom/center)."""
    w, h = img.size
    pw, ph = patch
    if pw > w or ph > h:
        raise ValueError(f"patch {patch} larger than image {img.size}")
    boxes = {
        "left_top": (0, 0),
        "left_bottom": (0, h - ph),
        "right_top": (w - pw, 0),
        "right_bottom": (w - pw, h - ph),
        "center": ((w - pw) // 2, (h - ph) // 2),
    }
    if position not in boxes:
        raise ValueError(f"unknown crop position {position}")
    left, top = boxes[position]
    return img.crop((left, top, left + pw, top + ph))


def crop_and_resize(img, patch, position):
    """Crop a full-height (or full-width) strip whose aspect matches the
    patch, at left/center/right (or top/middle/bottom), then resize."""
    w, h = img.size
    pw, ph = patch
    if position in ("left", "center", "right"):
        strip = min(w, int(h * pw / ph)) if ph else w
        offs = {"left": 0, "center": (w - strip) // 2,
                "right": w - strip}[position]
        box = (offs, 0, offs + strip, h)
    elif position in ("top", "middle", "bottom"):
        strip = min(h, int(w * ph / pw)) if pw else h
        offs = {"top": 0, "middle": (h - strip) // 2,
                "bottom": h - strip}[position]
        box = (0, offs, w, offs + strip)
    else:
        raise ValueError(f"unknown crop_and_resize position {position}")
    return img.crop(box).resize(patch)


def resize(img, small_size):
    """Resize so the shorter side equals small_size, keeping aspect."""
    w, h = img.size
    if w < h:
        return img.resize((small_size, int(h * small_size / w)))
    return img.resize((int(w * small_size / h), small_size))


def scale(img, small_size):
    return resize(img, small_size)


def resize_by_hw(img, size):
    """size = (height, width)."""
    return img.resize((size[1], size[0]))


def color_cast(img, offset=20):
    """Add a random offset in [-offset, offset] to a random channel."""
    arr = np.asarray(img, np.int32).copy()
    if arr.ndim == 2:
        arr = arr[:, :, None]
    ch = random.randint(0, arr.shape[2] - 1)
    delta = random.randint(-offset, offset)
    arr[:, :, ch] = np.clip(arr[:, :, ch] + delta, 0, 255)
    return Image.fromarray(arr.squeeze().astype(np.uint8))


def enhance(img, scale=0.2):  # noqa: A002
    """Random brightness/contrast/color/sharpness jitter of +-scale."""
    for enh in (ImageEnhance.Brightness, ImageEnhance.Contrast,
                ImageEnhance.Color, ImageEnhance.Sharpness):
        factor = 1.0 + random.uniform(-scale, scale)
        img = enh(img).enhance(factor)
    return img


def flip(img):
    return img.transpose(Image.FLIP_LEFT_RIGHT)


def flip_down(img):
    return img.transpose(Image.FLIP_TOP_BOTTOM)


def get_list_sample(lst, sample_size):
    return random.sample(list(lst), sample_size)


class ImageTool:
    """Chainable augmentation pipeline (reference image_tool.ImageTool:214).

    Each op transforms every held image; ``inplace=True`` keeps the chain
    going with one random case per image, ``inplace=False`` returns the
    augmented list without touching the chain. ``num_case>1`` enumerates
    multiple augmentation cases per image (test-time augmentation).
    """

    def __init__(self):
        self.imgs = []

    def load(self, path, grayscale=False):
        self.imgs = [load_img(path, grayscale)]
        return self

    def set(self, imgs):  # noqa: A003
        self.imgs = list(imgs)
        return self

    def append(self, img):
        self.imgs.append(img)
        return self

    def get(self):
        return self.imgs

    def _apply(self, cases, num_case, inplace):
        """cases: list of (callable, case_id); sample num_case per image."""
        out = []
        for img in self.imgs:
            chosen = get_list_sample(cases, min(num_case, len(cases)))
            out.extend(fn(img) for fn in chosen)
        if inplace:
            self.imgs = out
            return self
        return out

    # ---- resize family ---------------------------------------------------
    def resize_by_range(self, rng, inplace=True):
        size = random.randint(rng[0], rng[1] - 1) if rng[1] > rng[0] \
            else rng[0]
        return self.resize_by_list([size], 1, inplace)

    def resize_by_list(self, size_list, num_case=1, inplace=True):
        return self._apply([lambda im, s=s: resize(im, s)
                            for s in size_list], num_case, inplace)

    scale_by_range = resize_by_range
    scale_by_list = resize_by_list

    def resize_by_hw_range(self, rng, inplace=True):
        h = random.randint(rng[0][0], rng[0][1])
        w = random.randint(rng[1][0], rng[1][1])
        return self.resize_by_hw_list([(h, w)], 1, inplace)

    def resize_by_hw_list(self, size_list, num_case=1, inplace=True):
        return self._apply([lambda im, s=s: resize_by_hw(im, s)
                            for s in size_list], num_case, inplace)

    # ---- rotate ----------------------------------------------------------
    def rotate_by_range(self, rng, inplace=True):
        angle = random.uniform(rng[0], rng[1])
        return self.rotate_by_list([angle], 1, inplace)

    def rotate_by_list(self, angle_list, num_case=1, inplace=True):
        return self._apply([lambda im, a=a: im.rotate(a)
                            for a in angle_list], num_case, inplace)

    # ---- crops -----------------------------------------------------------
    def crop5(self, patch, num_case=1, inplace=True):
        """Corners + center crop (reference crop5:377)."""
        positions = ["left_top", "left_bottom", "right_top",
                     "right_bottom", "center"]
        return self._apply([lambda im, p=p: crop(im, patch, p)
                            for p in positions], num_case, inplace)

    @staticmethod
    def _strip_crop(im, patch, idx):
        """idx 0/1/2 -> orientation-appropriate strip position, decided
        per image like the reference (crop3 image_tool.py:426-437)."""
        w, h = im.size
        positions = ["left", "center", "right"] if w >= h \
            else ["top", "middle", "bottom"]
        return crop_and_resize(im, patch, positions[idx])

    def crop3(self, patch, num_case=1, inplace=True):
        """Strip crops + resize (reference crop3:407)."""
        return self._apply(
            [lambda im, i=i: self._strip_crop(im, patch, i)
             for i in range(3)], num_case, inplace)

    def crop8(self, patch, num_case=1, inplace=True):
        """crop5 + crop3 cases (reference crop8:449)."""
        five = ["left_top", "left_bottom", "right_top", "right_bottom",
                "center"]
        cases = [lambda im, p=p: crop(im, patch, p) for p in five] + \
            [lambda im, i=i: self._strip_crop(im, patch, i)
             for i in range(3)]
        return self._apply(cases, num_case, inplace)

    def random_crop(self, patch, inplace=True):
        def fn(im):
            w, h = im.size
            left = random.randint(0, w - patch[0])
            top = random.randint(0, h - patch[1])
            return im.crop((left, top, left + patch[0], top + patch[1]))
        return self._apply([fn], 1, inplace)

    def random_crop_resize(self, patch, inplace=True):
        """Random-area crop then resize to patch (reference :504)."""
        def fn(im):
            w, h = im.size
            area_frac = random.uniform(0.08, 1.0)
            cw = max(1, int(w * np.sqrt(area_frac)))
            ch = max(1, int(h * np.sqrt(area_frac)))
            left = random.randint(0, w - cw)
            top = random.randint(0, h - ch)
            return im.crop((left, top, left + cw, top + ch)).resize(patch)
        return self._apply([fn], 1, inplace)

    # ---- photometric -----------------------------------------------------
    def flip(self, num_case=1, inplace=True):
        cases = [lambda im: im, flip]
        return self._apply(cases, num_case, inplace)

    def flip_down(self, num_case=1, inplace=True):
        cases = [lambda im: im, flip_down]
        return self._apply(cases, num_case, inplace)

    def color_cast(self, offset=20, inplace=True):
        return self._apply([lambda im: color_cast(im, offset)], 1, inplace)

    def enhance(self, scale=0.2, inplace=True):  # noqa: A002
        return self._apply([lambda im: enhance(im, scale)], 1, inplace)

    def num_augmentation(self):
        return len(self.imgs)
