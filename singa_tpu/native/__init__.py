"""ctypes binding to the native IO runtime (native/singa_native.cc).

Replaces the reference's SWIG layer (src/api/*.i) for the components the
reference implements natively: record-file IO, prefetching reader, image
transforms, logging, timer. The library is built lazily with the in-tree
Makefile on first import and cached; when no C++ toolchain is available
every entry point gets a numpy/pure-python fallback so the package still
works (``AVAILABLE`` tells which path is active).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(_HERE)),
                           "native")
# a wheel build (setup.py) ships the .so inside the package; a source
# checkout builds it in-tree via the Makefile
_PACKAGED_LIB = os.path.join(_HERE, "libsinga_native.so")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libsinga_native.so")

_lib = None


def _build():
    src = os.path.join(_NATIVE_DIR, "singa_native.cc")
    if not os.path.exists(src):
        return False
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, timeout=300)
        return os.path.exists(_LIB_PATH)
    except (subprocess.SubprocessError, OSError):
        return False


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if os.path.exists(_PACKAGED_LIB):
        path = _PACKAGED_LIB
    else:
        # source checkout: always invoke make — a no-op when the .so is
        # fresh, and it rebuilds a stale one (the target depends on the
        # source), so a new ABI symbol is never missing from an old build
        _build()
        path = _LIB_PATH
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None

    u32 = ctypes.c_uint32
    lib.sg_recwriter_open.restype = ctypes.c_void_p
    lib.sg_recwriter_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.sg_recwriter_write.restype = ctypes.c_int
    lib.sg_recwriter_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       u32, ctypes.c_char_p, u32]
    lib.sg_recwriter_flush.argtypes = [ctypes.c_void_p]
    lib.sg_recwriter_close.argtypes = [ctypes.c_void_p]

    lib.sg_recreader_open.restype = ctypes.c_void_p
    lib.sg_recreader_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.sg_recreader_read.restype = ctypes.c_int
    lib.sg_recreader_read.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(u32), ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(u32)]
    lib.sg_recreader_count.restype = ctypes.c_int
    lib.sg_recreader_count.argtypes = [ctypes.c_char_p]
    lib.sg_recreader_seek_to_first.argtypes = [ctypes.c_void_p]
    lib.sg_recreader_close.argtypes = [ctypes.c_void_p]
    lib.sg_free.argtypes = [ctypes.c_void_p]

    fptr = ctypes.POINTER(ctypes.c_float)
    lib.sg_image_resize_bilinear.restype = ctypes.c_int
    lib.sg_image_resize_bilinear.argtypes = [fptr] + [ctypes.c_int] * 3 + \
        [fptr] + [ctypes.c_int] * 2
    lib.sg_image_crop.restype = ctypes.c_int
    lib.sg_image_crop.argtypes = [fptr] + [ctypes.c_int] * 3 + [fptr] + \
        [ctypes.c_int] * 4
    lib.sg_image_hflip.restype = ctypes.c_int
    lib.sg_image_hflip.argtypes = [fptr] + [ctypes.c_int] * 3 + [fptr]
    lib.sg_image_hwc_to_chw.argtypes = [fptr] + [ctypes.c_int] * 3 + [fptr]
    lib.sg_image_chw_to_hwc.argtypes = [fptr] + [ctypes.c_int] * 3 + [fptr]

    lib.sg_log.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.sg_set_log_level.argtypes = [ctypes.c_int]
    lib.sg_monotonic_seconds.restype = ctypes.c_double
    lib.sg_version.restype = ctypes.c_char_p

    lib.sg_set_channel_directory.argtypes = [ctypes.c_char_p]
    lib.sg_channel_get.restype = ctypes.c_void_p
    lib.sg_channel_get.argtypes = [ctypes.c_char_p]
    lib.sg_channel_enable_stderr.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.sg_channel_enable_file.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.sg_channel_set_dest_file.argtypes = [ctypes.c_void_p,
                                             ctypes.c_char_p]
    lib.sg_channel_send.argtypes = [ctypes.c_void_p, ctypes.c_char_p]

    _lib = lib
    return lib


_load()
AVAILABLE = _lib is not None


def _as_f32(arr):
    return np.ascontiguousarray(arr, dtype=np.float32)


def _fp(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class RecordWriter:
    """Key/value record-file writer (reference BinFileWriter,
    src/io/binfile_writer.cc). Native when available, else pure python
    with the identical on-disk format."""

    MAGIC = b"SGTPREC0"

    def __init__(self, path, append=False):
        self.path = path
        self._h = None
        self._f = None
        if AVAILABLE:
            self._h = _lib.sg_recwriter_open(path.encode(),
                                             1 if append else 0)
            if not self._h:
                raise IOError(f"cannot open {path}")
        else:
            mode = "ab" if append else "wb"
            self._f = open(path, mode)
            if not append or self._f.tell() == 0:
                self._f.write(self.MAGIC)

    def write(self, key, value):
        key = key.encode() if isinstance(key, str) else bytes(key)
        value = value.encode() if isinstance(value, str) else bytes(value)
        if self._h:
            ok = _lib.sg_recwriter_write(self._h, key, len(key), value,
                                         len(value))
            if not ok:
                raise IOError(f"write failed on {self.path}")
        else:
            self._f.write(len(key).to_bytes(4, "little"))
            self._f.write(key)
            self._f.write(len(value).to_bytes(4, "little"))
            self._f.write(value)

    def flush(self):
        if self._h:
            _lib.sg_recwriter_flush(self._h)
        else:
            self._f.flush()

    def close(self):
        if self._h:
            _lib.sg_recwriter_close(self._h)
            self._h = None
        elif self._f:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class RecordReader:
    """Key/value record-file reader with optional background prefetch
    (reference BinFileReader src/io/binfile_reader.cc + SafeQueue)."""

    def __init__(self, path, prefetch=0):
        self.path = path
        self._h = None
        self._f = None
        if AVAILABLE:
            self._h = _lib.sg_recreader_open(path.encode(), int(prefetch))
            if not self._h:
                raise IOError(f"cannot open {path}")
        else:
            self._f = open(path, "rb")
            if self._f.read(8) != RecordWriter.MAGIC:
                raise IOError(f"bad record-file magic in {path}")

    def read(self):
        """Next (key, value) bytes pair, or None at end of file."""
        if self._h:
            key_p = ctypes.c_void_p()
            val_p = ctypes.c_void_p()
            klen = ctypes.c_uint32()
            vlen = ctypes.c_uint32()
            ok = _lib.sg_recreader_read(self._h, ctypes.byref(key_p),
                                        ctypes.byref(klen),
                                        ctypes.byref(val_p),
                                        ctypes.byref(vlen))
            if not ok:
                return None
            key = ctypes.string_at(key_p, klen.value)
            val = ctypes.string_at(val_p, vlen.value)
            _lib.sg_free(key_p)
            _lib.sg_free(val_p)
            return key, val
        raw = self._f.read(4)
        if len(raw) < 4:
            return None
        klen = int.from_bytes(raw, "little")
        key = self._f.read(klen)
        vraw = self._f.read(4)
        if len(key) < klen or len(vraw) < 4:
            raise IOError(f"truncated record file {self.path}")
        vlen = int.from_bytes(vraw, "little")
        val = self._f.read(vlen)
        if len(val) < vlen:
            raise IOError(f"truncated record file {self.path}")
        return key, val

    def seek_to_first(self):
        if self._h:
            _lib.sg_recreader_seek_to_first(self._h)
        else:
            self._f.seek(8)

    def count(self):
        if AVAILABLE:
            return _lib.sg_recreader_count(self.path.encode())
        pos = self._f.tell()
        self._f.seek(8)
        n = 0
        while self.read() is not None:
            n += 1
        self._f.seek(pos)
        return n

    def close(self):
        if self._h:
            _lib.sg_recreader_close(self._h)
            self._h = None
        elif self._f:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    def __iter__(self):
        while True:
            rec = self.read()
            if rec is None:
                return
            yield rec


# ---------------------------------------------------------------------------
# image transforms (float32 HWC)
# ---------------------------------------------------------------------------

def resize_bilinear(img, out_h, out_w):
    """(H, W, C) float32 -> (out_h, out_w, C) bilinear resize."""
    img = _as_f32(img)
    h, w, c = img.shape
    out = np.empty((out_h, out_w, c), np.float32)
    if AVAILABLE:
        if not _lib.sg_image_resize_bilinear(_fp(img), h, w, c, _fp(out),
                                             out_h, out_w):
            raise ValueError("resize failed")
        return out
    ys = np.linspace(0, h - 1, out_h)
    xs = np.linspace(0, w - 1, out_w)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    return (img[y0][:, x0] * (1 - wy) * (1 - wx) +
            img[y0][:, x1] * (1 - wy) * wx +
            img[y1][:, x0] * wy * (1 - wx) +
            img[y1][:, x1] * wy * wx).astype(np.float32)


def crop(img, top, left, ch, cw):
    img = _as_f32(img)
    h, w, c = img.shape
    if AVAILABLE:
        out = np.empty((ch, cw, c), np.float32)
        if not _lib.sg_image_crop(_fp(img), h, w, c, _fp(out), top, left,
                                  ch, cw):
            raise ValueError("crop out of bounds")
        return out
    if top < 0 or left < 0 or top + ch > h or left + cw > w:
        raise ValueError("crop out of bounds")
    return img[top:top + ch, left:left + cw].copy()


def hflip(img):
    img = _as_f32(img)
    h, w, c = img.shape
    if AVAILABLE:
        out = np.empty_like(img)
        _lib.sg_image_hflip(_fp(img), h, w, c, _fp(out))
        return out
    return img[:, ::-1].copy()


def hwc_to_chw(img):
    img = _as_f32(img)
    h, w, c = img.shape
    if AVAILABLE:
        out = np.empty((c, h, w), np.float32)
        _lib.sg_image_hwc_to_chw(_fp(img), h, w, c, _fp(out))
        return out
    return np.transpose(img, (2, 0, 1)).copy()


def chw_to_hwc(img):
    img = _as_f32(img)
    c, h, w = img.shape
    if AVAILABLE:
        out = np.empty((h, w, c), np.float32)
        _lib.sg_image_chw_to_hwc(_fp(img), c, h, w, out.ctypes.data_as(
            ctypes.POINTER(ctypes.c_float)))
        return out
    return np.transpose(img, (1, 2, 0)).copy()


# ---------------------------------------------------------------------------
# logging / timer
# ---------------------------------------------------------------------------

DEBUG, INFO, WARNING, ERROR = 0, 1, 2, 3


def log(severity, msg):
    if AVAILABLE:
        _lib.sg_log(severity, str(msg).encode())
    else:
        import sys
        names = ["DEBUG", "INFO", "WARNING", "ERROR"]
        print(f"[singa_native {names[severity]}] {msg}", file=sys.stderr)


def set_log_level(level):
    if AVAILABLE:
        _lib.sg_set_log_level(int(level))


def monotonic_seconds():
    if AVAILABLE:
        return float(_lib.sg_monotonic_seconds())
    import time
    return time.monotonic()
